"""Central metric registry: counters, gauges, bounded-reservoir histograms.

Five generations of ad-hoc telemetry (``StepTimer`` summaries,
``ServingMetrics`` lists, ``ReadStats`` dataclasses, anomaly-ladder
event logs, per-drill JSON dumps) each invented their own accumulator
and snapshot shape.  This module is the one substrate they register
into: every metric is named, typed, and serialized through ONE snapshot
schema, so drills, exporters, and the future autoscaler (ROADMAP item 3
consumes metric snapshots) read the same structure everywhere.

Design constraints, in order:

- **Bounded memory.**  Histograms keep a fixed-size reservoir
  (Vitter's Algorithm R) plus O(1) moments — a million-request drill
  costs the same RAM as a thousand-request one.  This is the fix for
  ``ServingMetrics``' unbounded per-tier latency lists.
- **Deterministic.**  Reservoir eviction draws from a ``random.Random``
  seeded by the metric name (and the registry seed), so the same
  observation stream yields byte-identical snapshots — the property
  every committed drill artifact leans on.
- **Cheap on the hot path.**  ``observe``/``inc``/``set`` are a few
  attribute ops; percentile sorting happens only at snapshot time and
  only over the bounded reservoir (the old ``ServingMetrics.percentile``
  full-sorted the complete history on every snapshot).
"""

from __future__ import annotations

import math
import random
import zlib
from typing import Any, Dict, List, Optional

#: default reservoir size — large enough that drill-scale streams
#: (≲ a few thousand observations per metric) are recorded EXACTLY
#: (reservoir never evicts below capacity), small enough that a
#: million-request run stays O(1) per metric
DEFAULT_RESERVOIR = 2048


def nearest_rank(sorted_xs: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile over an already-sorted list
    (deterministic, no interpolation noise across numpy versions);
    None on empty."""
    if not sorted_xs:
        return None
    n = len(sorted_xs)
    k = min(n - 1, max(0, math.ceil(q / 100.0 * n) - 1))
    return float(sorted_xs[k])


class Counter:
    """Monotonically increasing count."""

    kind = "counter"

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: inc({n}) < 0")
        self.value += n

    def snapshot(self) -> Any:
        return self.value


class Gauge:
    """Last-written value (None until first set)."""

    kind = "gauge"

    def __init__(self, name: str):
        self.name = name
        self.value: Optional[float] = None

    def set(self, v: float) -> None:
        self.value = float(v)

    def snapshot(self) -> Any:
        return self.value


class ReservoirHistogram:
    """Bounded-memory distribution sketch: exact count/sum/min/max plus
    a uniform sample of ``max_samples`` observations (Algorithm R).

    Below capacity the reservoir holds EVERY observation, so
    percentiles are exact; past capacity each kept value is a uniform
    draw over the whole stream.  Eviction randomness is seeded from the
    metric name, so snapshots are reproducible from the observation
    stream alone."""

    kind = "histogram"

    def __init__(self, name: str, max_samples: int = DEFAULT_RESERVOIR,
                 seed: int = 0):
        if max_samples < 1:
            raise ValueError(f"histogram {name}: max_samples must be >= 1")
        self.name = name
        self.max_samples = int(max_samples)
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.samples: List[float] = []
        self._rng = random.Random(zlib.crc32(name.encode()) ^ seed)

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.total += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        if len(self.samples) < self.max_samples:
            self.samples.append(v)
        else:
            j = self._rng.randrange(self.count)
            if j < self.max_samples:
                self.samples[j] = v

    def percentile(self, q: float) -> Optional[float]:
        return nearest_rank(sorted(self.samples), q)

    @property
    def saturated(self) -> bool:
        """True once the reservoir has evicted (percentiles are now
        sampled estimates, not exact)."""
        return self.count > self.max_samples

    def snapshot(self) -> Dict[str, Any]:
        s = sorted(self.samples)
        return {
            "count": self.count,
            "sum": self.total,
            "mean": (self.total / self.count) if self.count else None,
            "min": self.min,
            "max": self.max,
            "p50": nearest_rank(s, 50),
            "p99": nearest_rank(s, 99),
            "reservoir": len(s),
            "sampled": self.saturated,
        }


class MetricRegistry:
    """Name → metric, with get-or-create accessors and one snapshot.

    Names are free-form strings; the repo convention is
    ``<subsystem>/<metric>[/k=v...]`` (e.g. ``serve/latency_s/tier=0``,
    ``train/step_s``, ``data/read/skipped_records``) so the Prometheus
    exporter can turn trailing ``k=v`` segments into labels.
    Re-requesting a name with a different metric type raises — two
    subsystems silently sharing a name was exactly the ad-hoc mess this
    replaces."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._metrics: Dict[str, Any] = {}

    def _get(self, name: str, cls, **kw):
        m = self._metrics.get(name)
        if m is None:
            m = cls(name, **kw)
            self._metrics[name] = m
        elif not isinstance(m, cls):
            raise TypeError(f"metric {name!r} already registered as "
                            f"{m.kind}, requested {cls.kind}")
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str,
                  max_samples: int = DEFAULT_RESERVOIR
                  ) -> ReservoirHistogram:
        h = self._get(name, ReservoirHistogram, max_samples=max_samples,
                      seed=self.seed)
        if h.max_samples != int(max_samples):
            # same discipline as the type check: two subsystems silently
            # sharing a name with different bounds would break one
            # side's memory/exactness expectations without an error
            raise ValueError(
                f"histogram {name!r} already registered with "
                f"max_samples={h.max_samples}, requested {max_samples}")
        return h

    def __contains__(self, name: str) -> bool:
        return name in self._metrics

    def __len__(self) -> int:
        return len(self._metrics)

    def metrics(self) -> Dict[str, Any]:
        """Name → metric object, sorted by name."""
        return dict(sorted(self._metrics.items()))

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """THE snapshot schema: ``{"counters": {...}, "gauges": {...},
        "histograms": {...}}``, keys sorted — every consumer (drill
        artifacts, Prometheus rendering, the TensorBoard bridge, the
        ROADMAP-item-3 autoscaler) reads this one shape."""
        out: Dict[str, Dict[str, Any]] = {
            "counters": {}, "gauges": {}, "histograms": {}}
        for name, m in sorted(self._metrics.items()):
            out[m.kind + "s"][name] = m.snapshot()
        return out
