"""Bounded ring-buffer flight recorder with a deterministic JSONL dump.

The black box: every finished span and every point event lands in a
fixed-capacity ring (oldest entries overwritten, never unbounded
growth), and on a terminal condition — ``TrainingDiverged``, a replica
fence, drill completion — the ring is dumped as deterministic JSONL so
the last N seconds of system behavior survive the crash.  Clockwork's
per-request action logs and the PR-3 forensics bundles are the pattern:
the evidence must already be in memory WHEN the failure happens; you
cannot start recording after the fact.

Determinism contract: events are serialized with sorted keys and a
monotonically increasing ``seq``; all timestamps come from the injected
clock.  Under a :class:`~analytics_zoo_tpu.utils.clock.VirtualClock`
two runs from the same seed produce byte-identical dumps —
``OBS_r01.json`` pins the sha256.
"""

from __future__ import annotations

import collections
import json
import os
from typing import Any, Dict, Iterable, List, Optional

from analytics_zoo_tpu.utils.clock import TimeSource, as_now_fn

DEFAULT_CAPACITY = 8192


def events_to_jsonl(events: Iterable[Dict[str, Any]]) -> str:
    """THE flight-recording serialization: one sorted-keys JSON object
    per line, in the given order.  Shared by the recorder's dump and
    ``obs.trace.TraceStore.to_jsonl`` so their byte-identity (the
    ingest↔export inverse every replay-sha pipeline leans on) holds by
    construction, not by parallel copies."""
    return "".join(json.dumps(e, sort_keys=True) + "\n" for e in events)


class FlightRecorder:
    """Fixed-capacity event ring.

    ``record`` appends a dict (a ``seq`` is stamped; the caller supplies
    ``kind`` and, conventionally, ``t``).  ``note`` is the point-event
    convenience (stamps ``t`` from the recorder clock).  ``dump``
    serializes the live ring to JSONL, optionally to ``dump_path`` —
    callers wire it to their terminal conditions."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 clock: TimeSource = None,
                 dump_path: Optional[str] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.now = as_now_fn(clock)
        self.dump_path = dump_path
        self.dropped = 0          # events overwritten by the ring bound
        self.dumps: List[Dict[str, Any]] = []   # (reason, path) log
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._seq = 0

    def __len__(self) -> int:
        return len(self._ring)

    # -- feed ----------------------------------------------------------------
    def record(self, event: Dict[str, Any]) -> None:
        if len(self._ring) == self.capacity:
            self.dropped += 1
        event = dict(event)
        event["seq"] = self._seq
        self._seq += 1
        self._ring.append(event)

    def note(self, kind: str, **fields: Any) -> None:
        """Record one point event (``kind`` + fields, ``t`` stamped from
        the recorder clock unless the caller provided one)."""
        fields.setdefault("t", round(self.now(), 6))
        fields["kind"] = kind
        self.record(fields)

    # -- read ----------------------------------------------------------------
    def events(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        evs: Iterable[Dict[str, Any]] = self._ring
        if kind is not None:
            evs = (e for e in evs if e.get("kind") == kind)
        return list(evs)

    def to_jsonl(self) -> str:
        """The ring as JSONL text, in seq order (the deque is already
        oldest→newest) — via the shared :func:`events_to_jsonl`."""
        return events_to_jsonl(self._ring)

    def dump(self, reason: str, path: Optional[str] = None) -> str:
        """Serialize the ring; write to ``path`` (or the configured
        ``dump_path``) when one is set.  Returns the JSONL text either
        way.  Every dump is logged in ``dumps`` so drills can assert
        WHICH terminal condition tripped the black box."""
        text = self.to_jsonl()
        target = path or self.dump_path
        if target:
            os.makedirs(os.path.dirname(os.path.abspath(target)),
                        exist_ok=True)
            with open(target, "w") as f:
                f.write(text)
        self.dumps.append({"reason": reason, "path": target,
                           "events": len(self._ring),
                           "dropped": self.dropped})
        return text
