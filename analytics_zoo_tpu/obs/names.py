"""Central metric-name catalog: every registry name, declared once.

The registry accepts free-form names, which is how five generations of
ad-hoc telemetry drifted apart in the first place.  This module is the
single source of truth: every ``registry.counter/gauge/histogram`` name
used anywhere in the package is declared here with its kind and one
line of meaning.  Three consumers pin against it:

- the ``registered-metric-names`` az-analyze source rule
  (``analysis/source.py``) — a call site registering an undeclared
  name fails tier-1 (dynamic, caller-parameterized names carry a
  reasoned ``# az-allow:`` waiver at the call site and declare their
  canonical families here);
- the docs table (``docs/OBSERVABILITY.md`` "What registers into it
  today") — ``tests/test_obs.py`` pins table ⇄ catalog equality, so
  the documentation cannot drift from the declaration;
- humans adding a metric: declare it here first, with the name
  convention ``<subsystem>/<metric>[/k=v...]`` (trailing ``k=v``
  segments become Prometheus labels; a trailing ``*`` in a catalog
  entry marks the labeled-family wildcard).

Entries map name (or ``...=*`` family pattern) → ``"<kind> · <doc>"``.
"""

from __future__ import annotations

from typing import Dict

CATALOG: Dict[str, str] = {
    # -- serving (ServingMetrics, fed by ServingRuntime) --------------------
    "serve/submitted":
        "counter · requests submitted to the runtime (admitted or shed "
        "at the door)",
    "serve/completed":
        "counter · requests that reached a device and returned a result",
    "serve/failed":
        "counter · requests failed after exhausting replica failover",
    "serve/batches":
        "counter · batches dispatched to the replica pool",
    "serve/redispatches":
        "counter · batches re-dispatched exactly once after a replica "
        "fence",
    "serve/deadline_misses_completed_late":
        "counter · completed requests whose result landed past the "
        "deadline",
    "serve/shed/cause=*":
        "counter · requests shed before device dispatch, by cause "
        "(queue_full | deadline)",
    "serve/latency_s/tier=*":
        "histogram · end-to-end request latency per degradation tier",
    "serve/batch_fill":
        "histogram · dispatched-batch fill fraction (n_valid/max_batch)",
    "serve/queue_depth":
        "histogram · admission-queue depth sampled at each dispatch",
    # -- multiplexed fleet (ServingRuntime(models=...), ISSUE 14) -----------
    "serve/submitted/model=*":
        "counter · requests submitted per multiplexed model",
    "serve/completed/model=*":
        "counter · requests completed per multiplexed model",
    "serve/failed/model=*":
        "counter · requests failed per multiplexed model",
    "serve/shed/model=*":
        "counter · requests shed per multiplexed model, by cause "
        "(model= then cause= labels)",
    "serve/deadline_misses_completed_late/model=*":
        "counter · completed-late requests per multiplexed model",
    "serve/latency_s/model=*":
        "histogram · end-to-end request latency per (model, tier)",
    "serve/model_weight/model=*":
        "gauge · weighted-EDF dispatch weight per model (1 = plain EDF; "
        "follows the model's worst fast-window SLO burn)",
    "serve/sessions/opened":
        "counter · streaming sessions opened (session-affine scheduling)",
    "serve/sessions/closed":
        "counter · streaming sessions closed (final chunk or state loss)",
    "serve/sessions_open":
        "gauge · streaming sessions currently open",
    "serve/cold_compiles":
        "counter · dispatches that paid the cold-compile tax (a replica "
        "served a geometry it had never compiled — what pre-warm deletes)",
    # -- live-weight hot-swap + canary (ServingRuntime.hot_swap) ------------
    "serve/swap/rollouts":
        "counter · hot-swap rollouts started (checkpoint verified, "
        "canary stage armed)",
    "serve/swap/replicas_swapped":
        "counter · replicas drained, re-installed with new weights and "
        "rejoined during rollouts",
    "serve/swap/rollbacks":
        "counter · rollouts reverted to the serve-lkg checkpoint tier "
        "(tripped canary or mid-rollout anomaly; exactly once each)",
    "serve/swap/lkg_promotions":
        "counter · serving last-known-good promotions after fully "
        "healthy rollouts (the hysteresis mirror of train LKG)",
    "serve/canary/mirrored/model=*":
        "counter · live requests mirrored to the canary weights per "
        "model (seeded fraction; never counted in accounting())",
    "serve/canary/divergence/model=*":
        "histogram · per-row output divergence between live and canary "
        "weights, labeled model= and swap= (rollout index)",
    "serve/canary/latency_s/model=*":
        "histogram · modeled service latency of the canary tier, "
        "labeled model= and swap= (rollout index)",
    "serve/canary/trips":
        "counter · canary stages tripped over their divergence/latency "
        "budgets (each one triggers a rollback)",
    # -- autoscaler (serving.autoscale.Autoscaler) --------------------------
    "autoscale/replicas":
        "gauge · current (or just-actuated target) replica-pool size",
    "autoscale/grow":
        "counter · pool-growth actuations taken by the policy loop",
    "autoscale/shrink":
        "counter · drain-then-retire shrink actuations taken",
    "autoscale/reshape":
        "counter · width-vs-count reshape actuations: a batch-saturated "
        "model's tier ladder swapped onto wider mesh slices instead of "
        "adding replicas (the B/128 occupancy-knee rationale)",
    # -- elastic mesh (parallel.train Optimizer elastic resume) -------------
    "elastic/restores":
        "counter · checkpoint restores re-placed onto a different world "
        "width than they were saved at",
    "elastic/world_width":
        "gauge · data-axis width the last elastic restore re-placed "
        "onto",
    # -- device health (resilience.health.HealthSentinel(registry=)) --------
    "health/audits":
        "counter · cross-replica parity audits run (per-replica param "
        "fingerprints compared at the decision boundary)",
    "health/audit_divergences":
        "counter · audits whose replica fingerprints disagreed (proven "
        "silent data corruption)",
    "health/shadow_checks":
        "counter · shadow recomputes run (sampled microbatch forward "
        "re-executed on a second device)",
    "health/shadow_mismatches":
        "counter · shadow recomputes disagreeing with the primary",
    "health/straggler_flags":
        "counter · devices flagged by the step-time EWMA hysteresis "
        "ladder as persistent stragglers",
    "health/quarantines":
        "counter · devices quarantined (training eviction raised or "
        "serving replica drained with device_budget decremented)",
    # -- SLO engine (obs.slo.SloEvaluator(registry=)) -----------------------
    "slo/fast_burn/slo=*":
        "gauge · latest fast-window burn rate per SLO (1.0 = budget "
        "consumed exactly at the sustainable rate)",
    "slo/slow_burn/slo=*":
        "gauge · latest slow-window burn rate per SLO",
    "slo/trips/slo=*":
        "counter · rising-edge transitions into burning per SLO (the "
        "fast-window trips the drill banks)",
    # -- training (Optimizer.set_observability) -----------------------------
    "train/dispatch/step_s":
        "histogram · host interval of the train-step call (async "
        "dispatch latency, not fenced device wall)",
    "train/dispatch/steps":
        "counter · train steps dispatched",
    "train/dispatch/records":
        "counter · training records dispatched",
    "train/anomaly/bad_steps":
        "counter · steps the anomaly sentinel discarded in-graph",
    "train/anomaly/rollbacks":
        "counter · last-known-good rollbacks the anomaly ladder took",
    "checkpoint/save_s":
        "histogram · checkpoint save wall seconds (sha256-manifested "
        "atomic publish)",
    "checkpoint/restore_s":
        "histogram · checkpoint restore wall seconds",
    # -- embedding lookups (ops.embedding.publish_lookup_stats) -------------
    "embed/lookups":
        "counter · id batches whose dedup stats were published",
    "embed/rows_touched":
        "gauge · unique table rows the last id batch gathered (what the "
        "dedup'd lookup actually fetches; the sparse apply's row count)",
    "embed/unique_fraction":
        "gauge · unique/total id ratio of the last batch (the dedup "
        "win: Zipfian traffic sits well below 1.0)",
    # -- data loading (ReadStats.publish) -----------------------------------
    "data/read/records":
        "gauge · records successfully yielded by resilient shard reads",
    "data/read/retries":
        "gauge · transient I/O errors retried",
    "data/read/skipped_records":
        "gauge · undecodable records dropped (skip-and-count)",
    "data/read/skipped_shards":
        "gauge · whole shards dropped after retry exhaustion",
    # -- step decomposition probe (obs.StepProbe) ---------------------------
    "probe/input_wait_s":
        "histogram · per-step blocking time on the input pipeline",
    "probe/dispatch_s":
        "histogram · per-step host dispatch time (call until return)",
    "probe/device_s":
        "histogram · per-step device wait (return until "
        "block_until_ready)",
}


def lookup(name: str) -> bool:
    """Whether a concrete registry name is covered by the catalog —
    exact entry, or a ``...=*`` family whose prefix matches."""
    if name in CATALOG:
        return True
    for pattern in CATALOG:
        if pattern.endswith("*") and name.startswith(pattern[:-1]):
            return True
    return False
