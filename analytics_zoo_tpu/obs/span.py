"""Structured spans: named, timed, parented intervals under a trace id.

The unit of the telemetry spine.  A *trace* is every span sharing one
``trace_id`` — e.g. one serving request's life (``request`` root →
``queue`` → ``dispatch`` children) or one train step at its loader
coordinates.  Trace ids are DERIVED from domain identity (request rid,
``(epoch, batch)``), never random, so the same seeded run produces the
same trace ids and the flight-recorder dump replays byte-identically.

Spans are recorded into the flight recorder when they END (one event
per span, carrying start/end/duration), which keeps the hot path to two
clock reads and one deque append — the cost the ``bench.py
obs_overhead`` phase banks.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional

from analytics_zoo_tpu.obs.recorder import FlightRecorder
from analytics_zoo_tpu.utils.clock import TimeSource, as_now_fn


class Span:
    """One in-flight interval.  Created by :meth:`Tracer.start`; call
    :meth:`end` exactly once (idempotent-guarded) with the terminal
    status.  ``attrs`` merge across start and end."""

    __slots__ = ("tracer", "name", "trace_id", "span_id", "parent_id",
                 "t_start", "t_end", "status", "attrs")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: int, parent_id: Optional[int], t_start: float,
                 attrs: Dict[str, Any]):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t_start = t_start
        self.t_end: Optional[float] = None
        self.status: Optional[str] = None
        self.attrs = attrs

    @property
    def ended(self) -> bool:
        return self.t_end is not None

    def end(self, status: str = "ok", at: Optional[float] = None,
            **attrs: Any) -> None:
        """Close the span and emit it to the recorder.  A second call is
        a no-op (the serving shed paths can race a drain force-flush for
        who closes a request; first writer wins).  ``at`` stamps an
        explicit end instant instead of the clock read — the parallel
        service model computes each batch's completion on its replica's
        busy horizon, a future instant the clock has not reached when the
        dispatch bookkeeping runs."""
        if self.ended:
            return
        self.attrs.update(attrs)
        self.t_end = self.tracer.now() if at is None else float(at)
        self.status = status
        self.tracer._emit(self)

    def event(self) -> Dict[str, Any]:
        ev: Dict[str, Any] = {
            "kind": "span",
            "name": self.name,
            "trace": self.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "t0": round(self.t_start, 6),
            "t1": round(self.t_end, 6) if self.t_end is not None else None,
            "dur": (round(self.t_end - self.t_start, 6)
                    if self.t_end is not None else None),
            "status": self.status,
        }
        if self.attrs:
            ev["attrs"] = dict(sorted(self.attrs.items()))
        return ev


class Tracer:
    """Span factory over one clock + recorder.

    Span ids are a per-tracer counter (deterministic); parenting is
    explicit — pass ``parent=`` (a :class:`Span`) rather than relying on
    an ambient context stack, because the serving scheduler interleaves
    many requests' spans in one thread and an implicit stack would
    mis-parent them.  The ``span`` context manager covers the common
    fully-nested case."""

    def __init__(self, clock: TimeSource = None,
                 recorder: Optional[FlightRecorder] = None):
        self.now = as_now_fn(clock)
        self.recorder = recorder
        self._next_id = 0
        self.spans_started = 0
        self.spans_ended = 0

    def start(self, name: str, trace_id: str,
              parent: Optional[Span] = None, **attrs: Any) -> Span:
        sid = self._next_id
        self._next_id += 1
        self.spans_started += 1
        if parent is not None and parent.trace_id != trace_id:
            raise ValueError(
                f"span {name!r}: parent belongs to trace "
                f"{parent.trace_id!r}, not {trace_id!r}")
        return Span(self, name, trace_id, sid,
                    parent.span_id if parent is not None else None,
                    self.now(), dict(attrs))

    @contextlib.contextmanager
    def span(self, name: str, trace_id: str,
             parent: Optional[Span] = None, **attrs: Any):
        s = self.start(name, trace_id, parent=parent, **attrs)
        try:
            yield s
        except BaseException as e:
            s.end(status="error", error=f"{type(e).__name__}: {e}")
            raise
        else:
            s.end(status=s.status or "ok")

    def _emit(self, span: Span) -> None:
        self.spans_ended += 1
        if self.recorder is not None:
            self.recorder.record(span.event())


def span_conservation(events: List[Dict[str, Any]],
                      trace_prefix: str = "req-") -> Dict[str, Any]:
    """Structural check over a flight recording: every trace whose id
    starts with ``trace_prefix`` must form ONE rooted tree — exactly one
    parentless root span, every other span's parent present in the same
    trace, and every span ended.  Returns counts the caller reconciles
    against ground truth (e.g. ``ServingRuntime.accounting()``):
    ``roots_by_status`` maps root status → count."""
    traces: Dict[str, List[Dict[str, Any]]] = {}
    for e in events:
        if e.get("kind") != "span":
            continue
        tid = e.get("trace", "")
        if isinstance(tid, str) and tid.startswith(trace_prefix):
            traces.setdefault(tid, []).append(e)
    violations: List[str] = []
    roots_by_status: Dict[str, int] = {}
    total_spans = 0
    for tid, spans in sorted(traces.items()):
        total_spans += len(spans)
        ids = {s["span"] for s in spans}
        roots = [s for s in spans if s["parent"] is None]
        if len(roots) != 1:
            violations.append(f"{tid}: {len(roots)} roots")
            continue
        for s in spans:
            if s["parent"] is not None and s["parent"] not in ids:
                violations.append(
                    f"{tid}: span {s['span']} ({s['name']}) parent "
                    f"{s['parent']} missing from trace")
            if s["t1"] is None:
                violations.append(
                    f"{tid}: span {s['span']} ({s['name']}) never ended")
        st = str(roots[0]["status"])
        roots_by_status[st] = roots_by_status.get(st, 0) + 1
    return {
        "traces": len(traces),
        "spans": total_spans,
        "roots_by_status": dict(sorted(roots_by_status.items())),
        "violations": violations,
        "ok": not violations,
    }
