"""Host/device step decomposition probe.

The SERVE_PROFILE / ``host_wall`` methodology promoted into a reusable
API: a step's host wall time splits into

- **input wait** — blocking on the data pipeline (``next(iterator)``);
- **dispatch** — the Python/jax call until the step function RETURNS
  (async dispatch: tracing/lowering on first call, argument transfer
  staging, program launch);
- **device** — from dispatch return until ``block_until_ready`` on the
  result (actual accelerator execution the host then waits out).

``host_bound_fraction = (input_wait + dispatch) / total`` is the number
the PR-2 loader work moved (0.826 → 0.545); this probe turns the
one-off bench arithmetic into something any loop can wear.  The fence
(``block_until_ready``) is part of the measurement by design — the
probe answers "where does the wall time go", not "what is peak
overlapped throughput"; an overlapped pipeline should probe a WINDOW of
steps, not each one.

Usage::

    probe = StepProbe(registry=reg)          # registry optional
    for _ in range(steps):
        with probe.input_wait():
            batch = next(it)
        out = probe.step(step_fn, state, batch)   # fenced
    probe.summary()
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Dict, Optional

from analytics_zoo_tpu.obs.registry import MetricRegistry


class StepProbe:
    """Accumulates the three-way decomposition over a run of steps.

    ``registry`` (optional): observations are mirrored into
    ``<prefix>/input_wait_s`` / ``<prefix>/dispatch_s`` /
    ``<prefix>/device_s`` reservoir histograms.  The probe uses real
    ``perf_counter`` time on purpose — it measures the actual host,
    not a virtual schedule."""

    def __init__(self, registry: Optional[MetricRegistry] = None,
                 prefix: str = "probe"):
        self.registry = registry
        self.prefix = prefix
        self.steps = 0
        self.input_wait_s = 0.0
        self.dispatch_s = 0.0
        self.device_s = 0.0

    def _observe(self, metric: str, v: float) -> None:
        if self.registry is not None:
            # az-allow: registered-metric-names — prefix-parameterized probe; the canonical probe/* family is declared in obs/names.py
            self.registry.histogram(f"{self.prefix}/{metric}").observe(v)

    @contextlib.contextmanager
    def input_wait(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.input_wait_s += dt
            self._observe("input_wait_s", dt)

    def step(self, step_fn: Callable, *args: Any, **kwargs: Any) -> Any:
        """Run one step: time the dispatch, then fence the result and
        time the device wait.  Returns the (ready) step output."""
        import jax

        t0 = time.perf_counter()
        out = step_fn(*args, **kwargs)
        t1 = time.perf_counter()
        jax.block_until_ready(out)
        t2 = time.perf_counter()
        self.steps += 1
        self.dispatch_s += t1 - t0
        self.device_s += t2 - t1
        self._observe("dispatch_s", t1 - t0)
        self._observe("device_s", t2 - t1)
        return out

    def summary(self) -> Dict[str, Any]:
        total = self.input_wait_s + self.dispatch_s + self.device_s
        host = self.input_wait_s + self.dispatch_s
        return {
            "steps": self.steps,
            "input_wait_s": round(self.input_wait_s, 6),
            "dispatch_s": round(self.dispatch_s, 6),
            "device_s": round(self.device_s, 6),
            "total_s": round(total, 6),
            "host_bound_fraction": round(host / total, 4) if total else None,
            "input_wait_fraction": (round(self.input_wait_s / total, 4)
                                    if total else None),
        }
