"""Exporters: one registry/recorder, three output surfaces.

- :func:`dump_flight_jsonl` — the black-box JSONL file (the recorder's
  own ``dump`` with an explicit path);
- :func:`render_prometheus` — Prometheus text exposition of a
  :class:`~analytics_zoo_tpu.obs.registry.MetricRegistry` snapshot (what
  a scrape endpoint would serve; drills bank it as a string so the
  format itself is pinned by tests);
- :class:`SummaryBridge` — pushes registry values into the existing
  ``parallel/summary.py`` TensorBoard writers, so training metrics land
  next to the Loss/LearningRate curves the Optimizer already writes,
  reusing the per-tag ``Trigger`` gating.

Name convention: trailing ``k=v`` path segments become Prometheus
labels — ``serve/latency_s/tier=0`` renders as
``serve_latency_s{tier="0"}``.
"""

from __future__ import annotations

import re
from typing import List, Tuple

from analytics_zoo_tpu.obs.recorder import FlightRecorder
from analytics_zoo_tpu.obs.registry import MetricRegistry

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def dump_flight_jsonl(recorder: FlightRecorder, path: str,
                      reason: str = "export") -> str:
    """Write the recorder ring to ``path`` as JSONL; returns the text."""
    return recorder.dump(reason, path=path)


def _escape_label(v: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline are the three characters the spec escapes —
    anything else passes through verbatim."""
    return (v.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_name(name: str) -> Tuple[str, str]:
    """Split a registry name into (prometheus_name, label_block)."""
    parts = name.split("/")
    labels = []
    while parts and "=" in parts[-1]:
        k, v = parts.pop().split("=", 1)
        labels.append((_NAME_RE.sub("_", k), _escape_label(v)))
    base = _NAME_RE.sub("_", "_".join(parts)) or "metric"
    if base[0].isdigit():
        base = "_" + base
    block = ("{" + ",".join(f'{k}="{v}"' for k, v in sorted(labels)) + "}"
             if labels else "")
    return base, block


def render_prometheus(registry: MetricRegistry) -> str:
    """Prometheus text format: counters and gauges as single samples,
    histograms as ``_count``/``_sum`` plus p50/p99 quantile gauges
    (reservoir summaries, not cumulative buckets — the registry keeps a
    sample, not a bucket vector).  Registry names differing only in
    their trailing ``k=v`` segments are one metric FAMILY: the format
    requires exactly one ``# TYPE`` line per family with all labeled
    samples contiguous under it, so metrics are grouped by family
    first."""
    def fmt(v) -> str:
        if v is None:
            return "NaN"
        return repr(float(v))

    # family (base, kind) -> sample lines, first-seen order (registry
    # iteration is name-sorted, so label variants arrive together)
    families: "dict[tuple, List[str]]" = {}
    # sanitization is lossy ("-" and "_" both become "_") and the
    # per-kind suffixes (_total/_sum/_count) can alias a neighbor's
    # base: two DISTINCT registry names landing on the same EMITTED
    # series would merge silently on the scrape side, so collisions are
    # checked on the sample names each metric actually emits
    _EMITTED = {"counter": ("_total",), "gauge": ("",),
                "histogram": ("", "_sum", "_count")}
    seen: "dict[Tuple[str, str], str]" = {}
    for name, m in registry.metrics().items():
        base, labels = _prom_name(name)
        for suffix in _EMITTED[m.kind]:
            prior = seen.setdefault((base + suffix, labels), name)
            if prior != name:
                raise ValueError(
                    f"prometheus name collision: registry names "
                    f"{prior!r} and {name!r} both emit the series "
                    f"{base + suffix}{labels or ''} — rename one "
                    f"(sanitization must stay injective per sample)")
        fam = families.setdefault((base, m.kind), [])
        if m.kind == "counter":
            fam.append(f"{base}_total{labels} {m.value}")
        elif m.kind == "gauge":
            fam.append(f"{base}{labels} {fmt(m.value)}")
        else:
            snap = m.snapshot()
            inner = labels[1:-1] if labels else ""
            for q, key in (("0.5", "p50"), ("0.99", "p99")):
                lab = "{" + (inner + "," if inner else "") + \
                    f'quantile="{q}"' + "}"
                fam.append(f"{base}{lab} {fmt(snap[key])}")
            fam.append(f"{base}_sum{labels} {fmt(snap['sum'])}")
            fam.append(f"{base}_count{labels} {snap['count']}")
    lines: List[str] = []
    for (base, kind), fam in families.items():
        # the counter family's exposition name is the _total series
        tname = base + "_total" if kind == "counter" else base
        ttype = "summary" if kind == "histogram" else kind
        lines.append(f"# TYPE {tname} {ttype}")
        lines.extend(fam)
    return "\n".join(lines) + ("\n" if lines else "")


class SummaryBridge:
    """Feed a registry snapshot into a ``parallel.summary`` writer.

    ``export(registry, iteration)`` writes every counter/gauge as a
    scalar and every histogram's mean/p99 — tags are the registry names
    (slashes kept: TensorBoard groups on them).  Trigger gating is the
    summary's own (``set_summary_trigger`` per tag), so high-frequency
    export calls stay cheap for gated-off tags."""

    def __init__(self, summary):
        self.summary = summary

    def export(self, registry: MetricRegistry, iteration: int) -> None:
        for name, m in registry.metrics().items():
            if m.kind in ("counter", "gauge"):
                if m.value is not None:
                    self.summary.add_scalar(name, m.value, iteration)
            else:
                snap = m.snapshot()
                if snap["count"]:
                    self.summary.add_scalar(f"{name}/mean", snap["mean"],
                                            iteration)
                    self.summary.add_scalar(f"{name}/p99", snap["p99"],
                                            iteration)
