"""Run metadata stamping for committed artifacts.

Every drill/bench artifact the repo banks (``RESILIENCE_r0*.json``,
``OBS_*.json``, …) must be traceable to the code, seed, and environment
that produced it — the r0* files predating this helper cannot be tied
to a commit, which is exactly the gap ``tools/check_artifacts.py``
lints against.  One shared helper so every tool stamps the SAME block::

    report["run_metadata"] = run_metadata("serve_drill", seed=args.seed)

Note the sha is HEAD at generation time — for a committed artifact that
is the PARENT of the commit adding it (the artifact cannot contain its
own hash).  ``git_dirty`` records whether the working tree had
uncommitted changes beyond the artifact itself.
"""

from __future__ import annotations

import os
import platform
import subprocess
from typing import Any, Dict, Optional

#: keys every stamped artifact must carry (the check_artifacts lint)
REQUIRED_KEYS = ("tool", "seed", "git_sha", "backend", "jax_version")


def _git(args, cwd: str) -> Optional[str]:
    try:
        out = subprocess.run(["git"] + args, cwd=cwd, capture_output=True,
                             text=True, timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        return None
    return out.stdout.strip() if out.returncode == 0 else None


def run_metadata(tool: str, seed: Optional[int] = None,
                 extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The shared metadata block: tool name, seed, git sha/dirty flag,
    jax backend + version, python version.  ``extra`` merges on top
    (e.g. ``{"smoke": True}``).  Never raises — outside a git checkout
    the sha fields degrade to ``None``."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sha = _git(["rev-parse", "HEAD"], root)
    status = _git(["status", "--porcelain"], root)
    import jax

    meta: Dict[str, Any] = {
        "tool": tool,
        "seed": seed,
        "git_sha": sha,
        "git_dirty": bool(status) if status is not None else None,
        "backend": jax.default_backend(),
        "jax_version": jax.__version__,
        "python": platform.python_version(),
    }
    meta.update(extra or {})
    return meta
