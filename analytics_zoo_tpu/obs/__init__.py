"""Unified telemetry spine: spans, metrics, flight recorder, exporters.

Before PR 7 the repo had five generations of ad-hoc telemetry —
``utils/profiling.StepTimer``, ``serving/metrics.ServingMetrics``,
``data/records.ReadStats``, the PR-3 health-word decodes, and per-drill
JSON dumps — with no shared substrate.  This package is that substrate
(Clockwork's bottom-up action logs and Clipper's per-decision
instrumentation are the pattern sources):

- :mod:`span` — :class:`Span`/:class:`Tracer`: trace-ids threaded
  end-to-end (loader epoch/batch → train step → checkpoint; serving
  submit → queue → batch → dispatch → response) plus the
  :func:`span_conservation` structural check;
- :mod:`registry` — :class:`MetricRegistry`: counters, gauges,
  bounded-reservoir histograms, one snapshot schema;
- :mod:`recorder` — :class:`FlightRecorder`: bounded ring buffer,
  deterministic JSONL black-box dump on terminal conditions;
- :mod:`exporters` — JSONL dump, Prometheus text rendering,
  :class:`SummaryBridge` into the TensorBoard writers;
- :mod:`probe` — :class:`StepProbe`: the dispatch / device /
  input-wait step decomposition as a reusable API;
- :mod:`runmeta` — :func:`run_metadata`: the artifact-stamping block
  ``tools/check_artifacts.py`` lints for;
- :mod:`trace` — :class:`TraceStore`: indexed span trees over a flight
  recording, critical-path extraction, p99-vs-p50 tail attribution
  (``tools/az_trace.py`` is the CLI);
- :mod:`slo` — :class:`SLO`/:class:`SloEvaluator`: declarative
  objectives over registry snapshots with multi-window burn-rate
  alerting; drives the serving DegradationLadder and the ROADMAP
  item-1 autoscaler hook;
- :mod:`names` — :data:`CATALOG`: every registry metric name declared
  once (the ``registered-metric-names`` az-analyze rule pins usage
  against it).

Everything runs on the injected clock (``utils.clock``), so drills on a
``VirtualClock`` produce byte-identical traces from a seed
(``OBS_r01.json`` pins the sha256), and the layer's hot-path cost is
banked, not assumed (``bench.py obs_overhead``).  Docs:
``docs/OBSERVABILITY.md``.
"""

from __future__ import annotations

from typing import Optional

from analytics_zoo_tpu.obs.exporters import (SummaryBridge,
                                             dump_flight_jsonl,
                                             render_prometheus)
from analytics_zoo_tpu.obs.probe import StepProbe
from analytics_zoo_tpu.obs.recorder import DEFAULT_CAPACITY, FlightRecorder
from analytics_zoo_tpu.obs.registry import (Counter, Gauge, MetricRegistry,
                                            ReservoirHistogram)
from analytics_zoo_tpu.obs.names import CATALOG
from analytics_zoo_tpu.obs.runmeta import run_metadata
from analytics_zoo_tpu.obs.slo import (SLO, SloDecision, SloEvaluator,
                                       deadline_miss_slo,
                                       default_serving_slos,
                                       model_deadline_miss_slo,
                                       model_shed_rate_slo, model_slos,
                                       p99_latency_slo, shed_rate_slo)
from analytics_zoo_tpu.obs.span import Span, Tracer, span_conservation
from analytics_zoo_tpu.obs.trace import (SEGMENTS, TraceStore,
                                         attribution_rows,
                                         format_critical_path)
from analytics_zoo_tpu.utils.clock import TimeSource


class Observability:
    """The convenience bundle most call sites take: one clock, one
    registry, one flight recorder, one tracer, wired together.

    ``dump_path`` arms the black box: terminal conditions
    (``TrainingDiverged``, replica fences, drill completion) call
    :meth:`dump` and the ring lands there as JSONL.  Subsystems that
    own a clock (the serving runtime) call :meth:`adopt_clock` so the
    whole bundle follows their time source unless one was injected
    explicitly."""

    def __init__(self, clock: TimeSource = None,
                 capacity: int = DEFAULT_CAPACITY,
                 registry: Optional[MetricRegistry] = None,
                 dump_path: Optional[str] = None,
                 seed: int = 0):
        self._clock_pinned = clock is not None
        self.registry = registry if registry is not None \
            else MetricRegistry(seed=seed)
        self.recorder = FlightRecorder(capacity=capacity, clock=clock,
                                       dump_path=dump_path)
        self.tracer = Tracer(clock=clock, recorder=self.recorder)

    @property
    def dump_path(self) -> Optional[str]:
        return self.recorder.dump_path

    def adopt_clock(self, clock: TimeSource) -> None:
        """Follow ``clock`` unless one was injected at construction."""
        if self._clock_pinned or clock is None:
            return
        from analytics_zoo_tpu.utils.clock import as_now_fn

        now = as_now_fn(clock)
        self.recorder.now = now
        self.tracer.now = now

    def dump(self, reason: str, path: Optional[str] = None) -> str:
        return self.recorder.dump(reason, path=path)


__all__ = [
    "CATALOG",
    "Counter",
    "DEFAULT_CAPACITY",
    "FlightRecorder",
    "Gauge",
    "MetricRegistry",
    "Observability",
    "ReservoirHistogram",
    "SEGMENTS",
    "SLO",
    "SloDecision",
    "SloEvaluator",
    "Span",
    "StepProbe",
    "SummaryBridge",
    "TraceStore",
    "Tracer",
    "attribution_rows",
    "deadline_miss_slo",
    "default_serving_slos",
    "model_deadline_miss_slo",
    "model_shed_rate_slo",
    "model_slos",
    "dump_flight_jsonl",
    "format_critical_path",
    "p99_latency_slo",
    "render_prometheus",
    "run_metadata",
    "shed_rate_slo",
    "span_conservation",
]
