"""Trace analytics: indexed span trees, critical paths, tail attribution.

PR 7's flight recorder can *dump* evidence; this module *answers
questions with it*.  A :class:`TraceStore` ingests a flight recording
(the recorder's JSONL, or its live event list) into per-trace span
trees with a query API, then two analyses ride on top:

- **critical-path extraction** (:meth:`TraceStore.critical_path`) —
  decompose one request's end-to-end latency into the segments the
  serving scheduler actually spent it in: ``queue_wait`` (submit →
  assembled into a batch), ``batch_assembly`` (assembled → device
  dispatch), ``dispatch`` (device service, shared with the batch's
  other members), ``failover_redispatch`` (the wasted first attempt +
  wedge detection when the batch failed over).  The segments TILE the
  root span exactly — their sum reconciles with the root duration for
  every completed request (``critical_path_conservation`` is the
  structural check, the span-tree analogue of ``span_conservation``).
- **tail attribution** (:meth:`TraceStore.tail_attribution`) — the
  Clockwork question: *where does the p99 come from?*  Compare the p99
  latency cohort against the p50 cohort segment by segment and report
  which segment grew; under overload that is almost always
  ``queue_wait``, under a replica failure ``failover_redispatch`` — the
  report says so with numbers instead of a guess.

Batch spans (``batch-<n>`` traces) belong to N requests at once; their
shared device interval fans back to every member through the member's
own ``dispatch`` span (each request *experiences* the full batch
service time — the interval is attributed whole, not divided, because
a request's latency does not shrink when it shares a batch).  Failover
timing comes from the pool's ``failover`` events in the same recording
(Clockwork's action log: the decision evidence is already in the black
box).

Everything is plain dict/list processing over the recorder's event
schema — no clock reads, no jax — so the store runs identically over a
live ring, a dumped file, or a committed artifact's recording.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Tuple

from analytics_zoo_tpu.obs.recorder import events_to_jsonl
from analytics_zoo_tpu.obs.registry import nearest_rank

#: critical-path segment names, in request-lifecycle order
SEGMENTS = ("queue_wait", "batch_assembly", "dispatch",
            "failover_redispatch")

#: |sum(segments) - root span extent| tolerance: boundaries telescope
#: over the same rounded-to-1µs timestamps, so only float-add noise
#: plus the root's independently rounded ``dur`` field remain
CONSERVATION_TOL_S = 2e-6


class TraceStore:
    """Indexed, queryable view over one flight recording.

    ``events`` is the recorder's event list (dicts carrying ``kind``;
    spans carry ``trace``/``span``/``parent``/``t0``/``t1``/``status``),
    in ``seq`` order.  The store never mutates the events, and
    :meth:`to_jsonl` re-serializes them byte-identically to
    ``FlightRecorder.to_jsonl`` — ingest and export are inverses, which
    is what lets a committed artifact's recording round-trip through
    analysis without drift (pinned in ``tests/test_trace.py``).
    """

    def __init__(self, events: Iterable[Dict[str, Any]]):
        self.events: List[Dict[str, Any]] = list(events)
        self._spans_by_trace: Dict[str, List[Dict[str, Any]]] = {}
        self._by_kind: Dict[str, List[Dict[str, Any]]] = {}
        self._failovers_by_rid: Dict[int, List[float]] = {}
        # the store is a read-only view, so decompositions memoize:
        # conservation, attribution, and the CLI all walk the same
        # requests — each trace is decomposed once, not once per caller
        self._cp_cache: Dict[str, Dict[str, Any]] = {}
        for e in self.events:
            kind = e.get("kind")
            self._by_kind.setdefault(kind, []).append(e)
            if kind == "span":
                self._spans_by_trace.setdefault(
                    e.get("trace", ""), []).append(e)
            elif kind == "failover":
                for rid in e.get("requests", ()):
                    self._failovers_by_rid.setdefault(rid, []).append(
                        float(e["t"]))

    # -- construction --------------------------------------------------------
    @classmethod
    def from_jsonl(cls, text: str) -> "TraceStore":
        """Parse a flight-recorder JSONL dump (one object per line)."""
        return cls(json.loads(line) for line in text.splitlines() if line)

    @classmethod
    def from_file(cls, path: str) -> "TraceStore":
        with open(path, encoding="utf-8") as f:
            return cls.from_jsonl(f.read())

    @classmethod
    def from_recorder(cls, recorder) -> "TraceStore":
        """Snapshot a live :class:`~analytics_zoo_tpu.obs.recorder.
        FlightRecorder` ring."""
        return cls(recorder.events())

    def to_jsonl(self) -> str:
        """Inverse of :meth:`from_jsonl`: byte-identical to the
        recorder dump it was built from (the SAME serializer,
        :func:`~analytics_zoo_tpu.obs.recorder.events_to_jsonl` — the
        inverse holds by construction)."""
        return events_to_jsonl(self.events)

    # -- queries -------------------------------------------------------------
    def trace_ids(self, prefix: Optional[str] = None) -> List[str]:
        """Trace ids in first-seen order, optionally prefix-filtered."""
        return [t for t in self._spans_by_trace
                if prefix is None or t.startswith(prefix)]

    def trace(self, trace_id: str) -> List[Dict[str, Any]]:
        """All spans of one trace, in span-id order (parents first —
        the tracer allocates ids monotonically)."""
        return sorted(self._spans_by_trace.get(trace_id, ()),
                      key=lambda s: s["span"])

    def root(self, trace_id: str) -> Optional[Dict[str, Any]]:
        for s in self.trace(trace_id):
            if s.get("parent") is None:
                return s
        return None

    def spans(self, name: Optional[str] = None,
              trace_prefix: Optional[str] = None,
              status: Optional[str] = None,
              t0: Optional[float] = None,
              t1: Optional[float] = None) -> List[Dict[str, Any]]:
        """Filtered span query: by span ``name``, trace-id prefix,
        terminal ``status``, and/or time window (a span matches when
        its own ``[t0, t1]`` interval intersects the queried window; a
        still-open span — ``t1`` null, as in a mid-run black-box dump —
        extends to the end of the recording, because the wedged span
        that never ended is exactly the one a dump query wants)."""
        out = []
        for s in self._by_kind.get("span", ()):
            if name is not None and s.get("name") != name:
                continue
            if trace_prefix is not None and not str(
                    s.get("trace", "")).startswith(trace_prefix):
                continue
            if status is not None and s.get("status") != status:
                continue
            if t1 is not None and s["t0"] > t1:
                continue
            if t0 is not None and s["t1"] is not None and s["t1"] < t0:
                continue
            out.append(s)
        return out

    def events_of(self, kind: str) -> List[Dict[str, Any]]:
        """Non-span point events by kind (``failover``,
        ``replica_fenced``, ``slo_decision``, ...)."""
        return list(self._by_kind.get(kind, ()))

    def requests(self, status: Optional[str] = None) -> List[str]:
        """``req-*`` trace ids whose ROOT span carries ``status``
        (any status when ``None``)."""
        out = []
        for tid in self.trace_ids(prefix="req-"):
            r = self.root(tid)
            if r is not None and (status is None
                                  or r.get("status") == status):
                out.append(tid)
        return out

    # -- critical path -------------------------------------------------------
    def _named(self, trace_id: str) -> Dict[str, Dict[str, Any]]:
        """First span of each name in the trace (the runtime opens at
        most one queue/dispatch span per request)."""
        named: Dict[str, Dict[str, Any]] = {}
        for s in self.trace(trace_id):
            named.setdefault(s["name"], s)
        return named

    def _failover_t(self, rid: Optional[int], lo: float,
                    hi: float) -> Optional[float]:
        if rid is None:
            return None
        for t in self._failovers_by_rid.get(rid, ()):
            if lo <= t <= hi:
                return t
        return None

    def critical_path(self, trace_id: str) -> Dict[str, Any]:
        """Segment decomposition of one request trace.

        For a dispatched request the four :data:`SEGMENTS` tile
        ``[root.t0, root.t1]`` exactly (boundaries are the queue span's
        assembly instant, the dispatch span's endpoints, and the pool's
        ``failover`` event when the batch was redispatched); a request
        shed or timed out before dispatch spent its whole life in
        ``queue_wait``.  ``residual_s`` is the tiling error —
        :meth:`critical_path_conservation` pins it ≈0 for every
        completed request.  Memoized (the store is an immutable view);
        callers must not mutate the returned dict.
        """
        cached = self._cp_cache.get(trace_id)
        if cached is not None:
            return cached
        root = self.root(trace_id)
        if root is None:
            raise KeyError(f"no root span for trace {trace_id!r}")
        if root["t1"] is None:
            raise ValueError(f"trace {trace_id!r}: root span never ended")
        named = self._named(trace_id)
        queue = named.get("queue")
        disp = named.get("dispatch")
        e2e = root["t1"] - root["t0"]
        seg = {name: 0.0 for name in SEGMENTS}
        batch = None
        tier = None
        if disp is not None and disp.get("t1") is not None:
            attrs = disp.get("attrs", {})
            if "batch" in attrs:
                batch = f"batch-{attrs['batch']}"
            tier = attrs.get("tier")
            q_end = queue["t1"] if queue is not None and \
                queue.get("t1") is not None else disp["t0"]
            seg["queue_wait"] = q_end - root["t0"]
            seg["batch_assembly"] = disp["t0"] - q_end
            rid = root.get("attrs", {}).get("rid")
            fo_t = self._failover_t(rid, disp["t0"], disp["t1"])
            if fo_t is not None:
                seg["failover_redispatch"] = fo_t - disp["t0"]
                seg["dispatch"] = disp["t1"] - fo_t
            else:
                seg["dispatch"] = disp["t1"] - disp["t0"]
        else:
            seg["queue_wait"] = e2e
        cp = {
            "trace": trace_id,
            "status": root.get("status"),
            "latency_s": e2e,
            "segments": seg,
            "residual_s": e2e - sum(seg.values()),
            "batch": batch,
            "tier": tier,
        }
        self._cp_cache[trace_id] = cp
        return cp

    def critical_path_conservation(
            self, tol_s: float = CONSERVATION_TOL_S) -> Dict[str, Any]:
        """Structural check: for EVERY completed (``done``) request the
        segment sum reconciles with the root span duration within
        ``tol_s`` (timestamp-rounding float noise only).  A violation
        means the decomposition dropped or double-counted time — the
        attribution report would be lying."""
        violations: List[str] = []
        checked = 0
        for tid in self.requests(status="done"):
            cp = self.critical_path(tid)
            checked += 1
            if abs(cp["residual_s"]) > tol_s:
                violations.append(
                    f"{tid}: segments sum to "
                    f"{sum(cp['segments'].values()):.6f}s but root span "
                    f"is {cp['latency_s']:.6f}s "
                    f"(residual {cp['residual_s']:+.2e}s)")
        return {"checked": checked, "violations": violations,
                "ok": checked > 0 and not violations}

    # -- tail attribution ----------------------------------------------------
    def tail_attribution(self, p_lo: float = 50.0,
                         p_hi: float = 99.0) -> Dict[str, Any]:
        """Clockwork-style tail explanation: which segment makes the
        tail the tail?

        Over all completed requests, the ``p_hi`` cohort (latency ≥ the
        p_hi latency) is compared with the ``p_lo`` cohort (latency ≤
        the p_lo latency) segment by segment: per-cohort mean seconds,
        the delta, and each segment's share of the total cohort gap.
        ``dominant_segment`` is the one that grew most — the answer to
        "where is the p99 coming from".  Requests that never completed
        (shed / timeout / failed) are counted by status alongside: they
        are the tail beyond the tail.
        """
        paths = [self.critical_path(t) for t in self.requests("done")]
        by_status: Dict[str, int] = {}
        for tid in self.requests():
            st = str(self.root(tid).get("status"))
            by_status[st] = by_status.get(st, 0) + 1
        if not paths:
            return {"n_done": 0, "by_status": by_status,
                    "note": "no completed requests to attribute"}
        lat_sorted = sorted(p["latency_s"] for p in paths)
        lo_cut = nearest_rank(lat_sorted, p_lo)
        hi_cut = nearest_rank(lat_sorted, p_hi)
        lo = [p for p in paths if p["latency_s"] <= lo_cut]
        hi = [p for p in paths if p["latency_s"] >= hi_cut]

        def mean(xs: List[float]) -> float:
            return sum(xs) / len(xs)

        lo_mean = mean([p["latency_s"] for p in lo])
        hi_mean = mean([p["latency_s"] for p in hi])
        gap = hi_mean - lo_mean
        segments: Dict[str, Dict[str, float]] = {}
        for name in SEGMENTS:
            m_lo = mean([p["segments"][name] for p in lo])
            m_hi = mean([p["segments"][name] for p in hi])
            segments[name] = {
                f"p{p_lo:g}_mean_s": round(m_lo, 6),
                f"p{p_hi:g}_mean_s": round(m_hi, 6),
                "delta_s": round(m_hi - m_lo, 6),
                "share_of_gap": (round((m_hi - m_lo) / gap, 4)
                                 if gap > 0 else None),
            }
        dominant = max(SEGMENTS, key=lambda n: segments[n]["delta_s"])
        return {
            "n_done": len(paths),
            "by_status": dict(sorted(by_status.items())),
            "percentiles": {f"p{p_lo:g}_s": round(lo_cut, 6),
                            f"p{p_hi:g}_s": round(hi_cut, 6)},
            "cohorts": {
                f"p{p_lo:g}": {"n": len(lo),
                               "mean_latency_s": round(lo_mean, 6)},
                f"p{p_hi:g}": {"n": len(hi),
                               "mean_latency_s": round(hi_mean, 6)},
            },
            "cohort_gap_s": round(gap, 6),
            "segments": segments,
            "dominant_segment": dominant,
        }

    # -- summaries -----------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        spans = self._by_kind.get("span", [])
        kinds = {k: len(v) for k, v in sorted(self._by_kind.items())}
        return {"events": len(self.events), "spans": len(spans),
                "traces": len(self._spans_by_trace),
                "requests": len(self.trace_ids("req-")),
                "events_by_kind": kinds}


def format_critical_path(cp: Dict[str, Any]) -> str:
    """One human-readable block for the CLI's ``--critical-path``."""
    lines = [f"trace {cp['trace']}  status={cp['status']}  "
             f"latency={cp['latency_s'] * 1e3:.3f}ms  "
             f"tier={cp['tier']}  batch={cp['batch']}"]
    total = cp["latency_s"] or 1.0
    for name in SEGMENTS:
        v = cp["segments"][name]
        bar = "#" * int(round(40 * v / total)) if total > 0 else ""
        lines.append(f"  {name:<20} {v * 1e3:9.3f}ms "
                     f"{100 * v / total:5.1f}%  {bar}")
    return "\n".join(lines)


def attribution_rows(report: Dict[str, Any]) -> List[Tuple[str, str]]:
    """(segment, rendered-row) pairs for the CLI's ``--attribute``."""
    rows = []
    for name, s in report.get("segments", {}).items():
        # numeric sort on the parsed percentile — lexicographic order
        # would swap pairs like p5/p50
        lo_k, hi_k = sorted(
            (k for k in s if k.endswith("_mean_s")),
            key=lambda k: float(k[1:-len("_mean_s")]))
        share = s["share_of_gap"]
        rows.append((name, (
            f"{name:<20} {s[lo_k] * 1e3:9.3f}ms -> {s[hi_k] * 1e3:9.3f}ms"
            f"  delta {s['delta_s'] * 1e3:+9.3f}ms"
            f"  share {share if share is not None else '-'}")))
    return rows
