"""Online SLO evaluation: declarative objectives, multi-window burn rates.

The spine's :class:`~analytics_zoo_tpu.obs.registry.MetricRegistry`
snapshots say what happened; an **SLO** says what was *promised*, and a
burn rate says how fast the promise's error budget is being spent.
This module turns registry snapshots into control signals:

- :class:`SLO` — one declarative objective over registry metric names:
  a **ratio** objective (bad-event fraction ≤ ``budget``, e.g.
  deadline-miss rate, shed rate — counters, wildcard patterns allowed)
  or a **threshold** objective (an observed value ≤ ``budget``, e.g.
  per-tier p99 latency read off the reservoir histograms);
- :class:`SloEvaluator` — feeds on a *sliding window of registry
  snapshots* (``observe``) and evaluates every SLO over TWO windows at
  once (:meth:`decide`): a **fast** window (5-minute-equivalent) that
  reacts to an active burn, and a **slow** window (1-hour-equivalent)
  that confirms the burn is sustained.  An SLO is *burning* only when
  BOTH windows exceed their burn thresholds — the standard SRE
  multi-window discipline: the fast window alone would page on blips,
  the slow window alone would keep paging long after recovery (and
  would hold the degradation ladder down through an entirely idle
  tail).  ``time_scale`` maps the wall-clock-equivalent windows onto
  the virtual clock so a seconds-long seeded drill exercises the same
  window *logic* a production hour would.

Burn rate convention: for ratio SLOs, ``burn = window_bad_fraction /
budget`` — 1.0 means the error budget is being consumed exactly at the
sustainable rate, 2.0 twice as fast; for threshold SLOs, ``burn =
window_mean_value / budget``.  Counters are assumed to start at zero
when the evaluator attaches (attach it when the runtime starts, as
``ServingRuntime(slo=)`` does).

Consumers:

- **DegradationLadder** — the runtime feeds :meth:`decide` into
  :meth:`~analytics_zoo_tpu.serving.ladder.DegradationLadder.
  observe_decision`: tier step-downs are driven by *SLO burn*, not by a
  raw shed-count flag (docs/SERVING.md "SLO-driven degradation");
- **autoscaler** (ROADMAP item 1) — :attr:`SloDecision.scale_hint` is
  the documented hook: +1 while any SLO burns (grow the replica pool),
  −1 when every burn is far under budget on both windows (shrink),
  0 otherwise.  The burns are also mirrored into the registry
  (``slo/fast_burn/slo=*`` gauges, ``slo/trips/slo=*`` counters) so an
  autoscaler that only reads registry snapshots sees them.

Determinism: the evaluator does no clock reads of its own (observation
timestamps come from the caller's injected clock) and no randomness —
the burn-rate timeline in ``OBS_r02.json`` replays byte-identically
from the drill seed.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: default multi-window geometry (wall-clock-equivalent seconds) and
#: burn thresholds — fast trips at 2× budget consumption, slow confirms
#: at 1× (sustained), per the SRE multiwindow/multi-burn-rate pattern
FAST_WINDOW_S = 300.0
SLOW_WINDOW_S = 3600.0
FAST_BURN = 2.0
SLOW_BURN = 1.0


@dataclasses.dataclass(frozen=True)
class SLO:
    """One declarative objective over registry metric names.

    ``kind="ratio"``: ``bad``/``total`` are counter-name patterns
    (exact names, or ``prefix*`` wildcards summing every match, e.g.
    ``serve/shed/cause=*``); the objective is windowed
    ``Δbad / Δtotal ≤ budget``.

    ``kind="threshold"``: ``value`` selects a histogram field as
    ``<name-pattern>:<field>`` (e.g. ``serve/latency_s/tier=*:p99`` —
    the worst matching tier is taken); the objective is windowed mean
    ``≤ budget`` (budget in the value's own unit, e.g. seconds).
    """

    name: str
    kind: str                       # "ratio" | "threshold"
    budget: float
    bad: Tuple[str, ...] = ()
    total: Tuple[str, ...] = ()
    value: str = ""
    description: str = ""

    def __post_init__(self):
        if self.kind not in ("ratio", "threshold"):
            raise ValueError(f"SLO {self.name}: unknown kind {self.kind!r}")
        if self.budget <= 0:
            raise ValueError(f"SLO {self.name}: budget must be > 0")
        if self.kind == "ratio" and (not self.bad or not self.total):
            raise ValueError(
                f"SLO {self.name}: ratio kind needs bad= and total= "
                f"counter patterns")
        if self.kind == "threshold" and ":" not in self.value:
            raise ValueError(
                f"SLO {self.name}: threshold kind needs value= "
                f"'<histogram-pattern>:<field>'")


def deadline_miss_slo(budget: float = 0.2) -> SLO:
    """Deadline-miss rate ≤ ``budget`` over terminal requests — a shed,
    failed, or completed-late request all count as missed (the
    ``ServingMetrics.miss_rate`` definition, windowed)."""
    return SLO(
        name="deadline-miss-rate", kind="ratio", budget=budget,
        bad=("serve/deadline_misses_completed_late", "serve/failed",
             "serve/shed/cause=*"),
        total=("serve/completed", "serve/failed", "serve/shed/cause=*"),
        description="fraction of terminal requests that missed their "
                    "deadline (shed | failed | completed late)")


def shed_rate_slo(budget: float = 0.1) -> SLO:
    """Shed fraction of submitted requests ≤ ``budget``."""
    return SLO(
        name="shed-rate", kind="ratio", budget=budget,
        bad=("serve/shed/cause=*",), total=("serve/submitted",),
        description="fraction of submitted requests shed before "
                    "device dispatch")


def p99_latency_slo(target_s: float) -> SLO:
    """Worst-tier p99 latency ≤ ``target_s`` (read off the bounded
    reservoirs — cumulative over the reservoir, windowed over the
    snapshot stream)."""
    return SLO(
        name="p99-latency", kind="threshold", budget=target_s,
        value="serve/latency_s/tier=*:p99",
        description=f"p99 completion latency <= {target_s}s on every "
                    f"serving tier")


def default_serving_slos() -> List[SLO]:
    """The serving objectives the drill (and a default deployment)
    evaluates: miss rate, shed rate, tail latency."""
    return [deadline_miss_slo(0.2), shed_rate_slo(0.15),
            p99_latency_slo(0.5)]


def model_deadline_miss_slo(model: str, budget: float = 0.2) -> SLO:
    """Per-model deadline-miss rate ≤ ``budget`` over ONE multiplexed
    model's terminal requests (the model-labeled counters
    ``ServingRuntime(models=...)`` maintains) — the per-model SLO whose
    burn rate drives that model's ladder and weighted-EDF weight."""
    return SLO(
        name=f"deadline-miss-rate/model={model}", kind="ratio",
        budget=budget,
        bad=(f"serve/deadline_misses_completed_late/model={model}",
             f"serve/failed/model={model}",
             f"serve/shed/model={model}/cause=*"),
        total=(f"serve/completed/model={model}",
               f"serve/failed/model={model}",
               f"serve/shed/model={model}/cause=*"),
        description=f"fraction of {model} terminal requests that missed "
                    f"their deadline (shed | failed | completed late)")


def model_shed_rate_slo(model: str, budget: float = 0.1) -> SLO:
    """Per-model shed fraction of submitted requests ≤ ``budget``."""
    return SLO(
        name=f"shed-rate/model={model}", kind="ratio", budget=budget,
        bad=(f"serve/shed/model={model}/cause=*",),
        total=(f"serve/submitted/model={model}",),
        description=f"fraction of submitted {model} requests shed "
                    f"before device dispatch")


def model_slos(model: str, miss_budget: float = 0.2,
               shed_budget: float = 0.15) -> List[SLO]:
    """The per-model objective pair a multiplexed
    ``ServingRuntime(models=[ModelConfig(slos=model_slos(name))])``
    declares per family: miss rate + shed rate over the model-labeled
    counters.  SLO names embed ``model=`` so the mirrored ``slo/*``
    gauges carry the model as a label."""
    return [model_deadline_miss_slo(model, miss_budget),
            model_shed_rate_slo(model, shed_budget)]


def canary_divergence_slo(model: str, budget: float,
                          rollout: int = 0) -> SLO:
    """Canary output divergence ≤ ``budget`` — the worst per-row
    divergence between the live tier and the mirrored new-weights tier
    (``:max`` off the rollout-labeled reservoir: ONE poisoned row must
    trip, a percentile could hide it).  The name is rollout-scoped so a
    previous rollout's divergence history can never trip — or mask — the
    next canary."""
    return SLO(
        name=f"canary-divergence/model={model}", kind="threshold",
        budget=budget,
        value=f"serve/canary/divergence/model={model}/swap={rollout}:max",
        description=f"worst mirrored-output divergence of the {model} "
                    f"canary <= {budget}")


def canary_latency_slo(model: str, budget_s: float,
                       rollout: int = 0) -> SLO:
    """Canary modeled service latency p99 ≤ ``budget_s`` — catches a new
    checkpoint whose tiers got slower even when outputs match."""
    return SLO(
        name=f"canary-latency/model={model}", kind="threshold",
        budget=budget_s,
        value=f"serve/canary/latency_s/model={model}/swap={rollout}:p99",
        description=f"p99 modeled canary service latency of {model} "
                    f"<= {budget_s}s")


def canary_slos(model: str, divergence_budget: float,
                latency_budget_s: Optional[float] = None,
                rollout: int = 0) -> List[SLO]:
    """The objectives one hot-swap canary stage evaluates (a fresh
    evaluator per rollout, over rollout-labeled metric names)."""
    out = [canary_divergence_slo(model, divergence_budget, rollout)]
    if latency_budget_s is not None:
        out.append(canary_latency_slo(model, latency_budget_s, rollout))
    return out


def _match_sum(counters: Dict[str, Any],
               patterns: Sequence[str]) -> float:
    total = 0.0
    for p in patterns:
        if p.endswith("*"):
            prefix = p[:-1]
            total += sum(float(v) for k, v in counters.items()
                         if k.startswith(prefix))
        else:
            v = counters.get(p)
            if v is not None:
                total += float(v)
    return total


def _match_value(histograms: Dict[str, Any], selector: str
                 ) -> Optional[float]:
    pattern, field = selector.rsplit(":", 1)
    vals: List[float] = []
    if pattern.endswith("*"):
        names = [k for k in histograms if k.startswith(pattern[:-1])]
    else:
        names = [pattern] if pattern in histograms else []
    for n in names:
        v = histograms[n].get(field)
        if v is not None:
            vals.append(float(v))
    return max(vals) if vals else None


@dataclasses.dataclass
class SloDecision:
    """One :meth:`SloEvaluator.decide` verdict.

    ``overloaded`` is the ladder input; ``burning`` names every SLO over
    threshold on BOTH windows; ``new_trips`` the subset that just
    transitioned into burning (the fast-window trip edge the drill
    banks); ``scale_hint`` the autoscaler signal (+1 grow / 0 hold /
    −1 shrink)."""

    t: float
    overloaded: bool
    burning: List[str]
    new_trips: List[str]
    recovered: List[str]
    scale_hint: int
    per_slo: Dict[str, Dict[str, Any]]

    def as_dict(self) -> Dict[str, Any]:
        return {
            "t": round(self.t, 6),
            "overloaded": self.overloaded,
            "burning": list(self.burning),
            "new_trips": list(self.new_trips),
            "recovered": list(self.recovered),
            "scale_hint": self.scale_hint,
            "per_slo": {k: dict(v) for k, v in self.per_slo.items()},
        }


class SloEvaluator:
    """Sliding-window burn-rate evaluation over registry snapshots.

    ``observe(snapshot, t)`` ingests one
    ``MetricRegistry.snapshot()`` at clock instant ``t``;
    ``decide(t)`` evaluates every SLO over the fast and slow windows
    and appends to ``timeline``.  ``time_scale`` shrinks the
    wall-clock-equivalent windows onto the caller's (virtual) clock:
    the committed drill runs ``time_scale=1/100`` so the 5 min / 1 h
    windows become 3 s / 36 s of virtual time while the window *logic*
    (fast trips, slow confirms, fast releases) is exercised unchanged.

    ``registry`` (optional): burns/trips are mirrored into it under
    ``slo/*`` names so registry-only consumers (Prometheus scrape, the
    ROADMAP item-1 autoscaler) see the SLO state without holding the
    evaluator object.

    Memory is bounded like everything else on the spine: observations
    are pruned to the slow window, and ``timeline`` is a ring of the
    last ``timeline_cap`` decisions (evictions counted, never silent) —
    peak burns and trip counts are maintained incrementally, so
    :meth:`report` stays correct and O(cap) at any uptime (the
    unbounded-list pathology PR 7 removed from ``ServingMetrics`` must
    not come back through the SLO door).
    """

    def __init__(self, slos: Optional[Sequence[SLO]] = None,
                 fast_window_s: float = FAST_WINDOW_S,
                 slow_window_s: float = SLOW_WINDOW_S,
                 time_scale: float = 1.0,
                 fast_burn: float = FAST_BURN,
                 slow_burn: float = SLOW_BURN,
                 recover_burn: float = 0.5,
                 timeline_cap: int = 4096,
                 registry=None):
        self.slos = list(slos) if slos is not None \
            else default_serving_slos()
        if not self.slos:
            raise ValueError("need at least one SLO")
        names = [s.name for s in self.slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        if time_scale <= 0:
            raise ValueError("time_scale must be > 0")
        if fast_window_s * time_scale >= slow_window_s * time_scale:
            raise ValueError("fast window must be shorter than slow")
        self.fast_window_s = float(fast_window_s) * float(time_scale)
        self.slow_window_s = float(slow_window_s) * float(time_scale)
        self.time_scale = float(time_scale)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.recover_burn = float(recover_burn)
        self.registry = registry
        #: (t, {slo: (bad, total)}, {slo: value}) observations, t-ordered
        if timeline_cap < 1:
            raise ValueError("timeline_cap must be >= 1")
        self._obs: List[Tuple[float, Dict[str, Tuple[float, float]],
                              Dict[str, Optional[float]]]] = []
        self._burning: Dict[str, bool] = {s.name: False for s in self.slos}
        #: last ``timeline_cap`` decisions (ring; evictions counted)
        self.timeline: "collections.deque[Dict[str, Any]]" = \
            collections.deque(maxlen=int(timeline_cap))
        self.timeline_evicted = 0
        # incrementally-maintained aggregates, so report() never
        # rescans (and eviction never corrupts) the decision history
        self._decisions = 0
        self._trip_counts: Dict[str, int] = {s.name: 0 for s in self.slos}
        self._peaks: Dict[str, Dict[str, float]] = {
            s.name: {"fast": 0.0, "slow": 0.0} for s in self.slos}

    # -- feed ----------------------------------------------------------------
    def observe_registry(self, registry, t: float) -> None:
        """Ingest directly from a live :class:`MetricRegistry` with a
        PARTIAL snapshot: counters always (integer reads), histogram
        reservoirs sorted only when a threshold-kind SLO actually needs
        them — the full ``registry.snapshot()`` sorts every reservoir
        for percentiles the ratio SLOs never read, which is exactly the
        recurring dispatch-path cost PR 7's overhead budget excludes.
        The runtime's decision window calls this; offline consumers of
        stored snapshots use :meth:`observe`."""
        metrics = registry.metrics()
        counters = {name: m.value for name, m in metrics.items()
                    if m.kind == "counter"}
        hists: Dict[str, Any] = {}
        if any(s.kind == "threshold" for s in self.slos):
            hists = {name: m.snapshot() for name, m in metrics.items()
                     if m.kind == "histogram"}
        self.observe({"counters": counters, "gauges": {},
                      "histograms": hists}, t)

    def observe(self, snapshot: Dict[str, Any], t: float) -> None:
        """Ingest one registry snapshot taken at clock instant ``t``
        (monotonically non-decreasing)."""
        if self._obs and t < self._obs[-1][0]:
            raise ValueError(
                f"observation at t={t} is older than the last "
                f"({self._obs[-1][0]}) — one clock, forward only")
        counters = snapshot.get("counters", {})
        hists = snapshot.get("histograms", {})
        ratios: Dict[str, Tuple[float, float]] = {}
        values: Dict[str, Optional[float]] = {}
        for slo in self.slos:
            if slo.kind == "ratio":
                ratios[slo.name] = (_match_sum(counters, slo.bad),
                                    _match_sum(counters, slo.total))
            else:
                values[slo.name] = _match_value(hists, slo.value)
        self._obs.append((t, ratios, values))
        self._prune(t)

    def _prune(self, now: float) -> None:
        """Drop observations older than the slow window, keeping the
        newest one at-or-before the window start as the delta
        baseline."""
        cutoff = now - self.slow_window_s
        keep_from = 0
        for i, (t, _, _) in enumerate(self._obs):
            if t <= cutoff:
                keep_from = i
            else:
                break
        self._obs = self._obs[keep_from:]

    # -- windowed math -------------------------------------------------------
    def _window(self, slo: SLO, window_s: float, now: float
                ) -> Dict[str, Any]:
        """One SLO over one window ending at ``now``: the measured
        fraction/value and its burn rate.  No observations (or an empty
        total) reads as burn 0 — absence of traffic is not a burn."""
        start = now - window_s
        if slo.kind == "ratio":
            cur: Optional[Tuple[float, float]] = None
            base = (0.0, 0.0)   # counters are zero before attach
            for t, ratios, _ in self._obs:
                if t <= start:
                    base = ratios[slo.name]
                if t <= now:
                    cur = ratios[slo.name]
            if cur is None:
                return {"fraction": None, "burn": 0.0}
            d_bad = cur[0] - base[0]
            d_total = cur[1] - base[1]
            if d_total <= 0:
                return {"fraction": None, "burn": 0.0}
            frac = d_bad / d_total
            return {"fraction": round(frac, 6),
                    "burn": round(frac / slo.budget, 4)}
        vals = [values[slo.name] for t, _, values in self._obs
                if start < t <= now and values.get(slo.name) is not None]
        if not vals:
            return {"value": None, "burn": 0.0}
        mean = sum(vals) / len(vals)
        return {"value": round(mean, 6),
                "burn": round(mean / slo.budget, 4)}

    # -- verdicts ------------------------------------------------------------
    def decide(self, t: float) -> SloDecision:
        """Evaluate every SLO at instant ``t``; returns (and logs to
        ``timeline``) the multi-window verdict.  An SLO burns when
        fast-burn ≥ ``fast_burn`` AND slow-burn ≥ ``slow_burn``; it
        recovers as soon as either window drops below its threshold
        (the fast window releases first in practice — recovery is not
        held hostage by the slow window's memory)."""
        per: Dict[str, Dict[str, Any]] = {}
        burning: List[str] = []
        new_trips: List[str] = []
        recovered: List[str] = []
        for slo in self.slos:
            fast = self._window(slo, self.fast_window_s, t)
            slow = self._window(slo, self.slow_window_s, t)
            is_burning = (fast["burn"] >= self.fast_burn
                          and slow["burn"] >= self.slow_burn)
            was = self._burning[slo.name]
            if is_burning and not was:
                new_trips.append(slo.name)
            elif was and not is_burning:
                recovered.append(slo.name)
            self._burning[slo.name] = is_burning
            if is_burning:
                burning.append(slo.name)
            per[slo.name] = {"fast": fast, "slow": slow,
                             "burning": is_burning,
                             "budget": slo.budget, "kind": slo.kind}
        if burning:
            hint = 1
        elif all(p["fast"]["burn"] <= self.recover_burn
                 and p["slow"]["burn"] <= self.recover_burn
                 for p in per.values()):
            hint = -1
        else:
            hint = 0
        decision = SloDecision(t=t, overloaded=bool(burning),
                               burning=burning, new_trips=new_trips,
                               recovered=recovered, scale_hint=hint,
                               per_slo=per)
        self._decisions += 1
        for name in new_trips:
            self._trip_counts[name] += 1
        for name, p in per.items():
            pk = self._peaks[name]
            pk["fast"] = max(pk["fast"], p["fast"]["burn"])
            pk["slow"] = max(pk["slow"], p["slow"]["burn"])
        if len(self.timeline) == self.timeline.maxlen:
            self.timeline_evicted += 1
        self.timeline.append(decision.as_dict())
        self._export(decision)
        return decision

    def _export(self, d: SloDecision) -> None:
        if self.registry is None:
            return
        for name, p in d.per_slo.items():
            self.registry.gauge(
                f"slo/fast_burn/slo={name}").set(p["fast"]["burn"])
            self.registry.gauge(
                f"slo/slow_burn/slo={name}").set(p["slow"]["burn"])
        for name in d.new_trips:
            self.registry.counter(f"slo/trips/slo={name}").inc()

    # -- read ----------------------------------------------------------------
    def trips(self) -> List[Dict[str, Any]]:
        """Timeline entries that tripped at least one SLO into burning
        (the fast-window trip edges)."""
        return [e for e in self.timeline if e["new_trips"]]

    def report(self) -> Dict[str, Any]:
        """The banked SLO report: objectives, window geometry, trip
        counts, peak burns (incrementally maintained — correct past
        timeline eviction), and the retained decision timeline."""
        return {
            "slos": [{"name": s.name, "kind": s.kind, "budget": s.budget,
                      "description": s.description} for s in self.slos],
            "windows": {
                "fast_s": self.fast_window_s, "slow_s": self.slow_window_s,
                "time_scale": self.time_scale,
                "fast_equivalent_s": self.fast_window_s / self.time_scale,
                "slow_equivalent_s": self.slow_window_s / self.time_scale,
                "fast_burn_threshold": self.fast_burn,
                "slow_burn_threshold": self.slow_burn,
            },
            "decisions": self._decisions,
            "trips": dict(self._trip_counts),
            "peak_burns": {k: dict(v)
                           for k, v in sorted(self._peaks.items())},
            "timeline": list(self.timeline),
            "timeline_evicted": self.timeline_evicted,
        }
