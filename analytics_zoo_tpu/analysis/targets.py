"""The repo's program-audit suite: what ``az_analyze --program`` traces.

Coverage contract (the ISSUE-10 acceptance line): all four registered
pipelines' train + eval programs, plus every SSD and DS2 serving tier
the degradation-ladder factories hand the runtime.

Construction is ABSTRACT wherever values don't matter: parameters come
from ``jax.eval_shape`` over ``module.init`` (a shape/dtype tree, no
weight init compile, no FLOPs), batches are ``ShapeDtypeStruct`` s, and
only the SSD serving tiers get cheap filled arrays because
``quantize_params`` must read real values to compute int8 scales.  The
whole suite traces in a few seconds on the 2-core CPU host — which is
what lets the audit run inside tier-1 on every suite pass.

The serving-tier programs are NOT reconstructed here: the tier
factories (``pipelines.ssd.ssd_serving_tiers`` / ``pipelines.
deepspeech2.ds2_serving_tiers``) attach a ``device_program`` thunk to
each :class:`~analytics_zoo_tpu.serving.ladder.ServingTier`, and this
module audits exactly those — the audit covers the programs the
runtime will actually dispatch, not a parallel copy that could drift.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from analytics_zoo_tpu.analysis.program import AuditProgram, BuiltProgram


def _S(shape, dtype) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(shape, dtype)


def abstract_variables(module, *example_inputs, **init_kwargs):
    """``module.init``'s variable tree as shapes/dtypes only — traced
    under ``eval_shape``, so no RNG work and no init compile."""
    return jax.eval_shape(
        lambda rng, *args: module.init(rng, *args, **init_kwargs),
        jax.random.PRNGKey(0), *example_inputs)


def abstract_train_state(module, optim, *example_inputs, **init_kwargs
                         ) -> Tuple[Any, Any]:
    """(variables, TrainState) as abstract trees — structure-true to
    ``create_train_state`` (same leaves, same optimizer slots), value-
    free."""
    from analytics_zoo_tpu.parallel.train import TrainState

    variables = abstract_variables(module, *example_inputs, **init_kwargs)
    params = variables["params"]
    model_state = {k: v for k, v in variables.items() if k != "params"}
    state = TrainState(
        step=_S((), np.int32),
        params=params,
        model_state=model_state,
        opt_state=jax.eval_shape(optim.tx.init, params),
        rng=jax.eval_shape(jax.random.PRNGKey, 0),
    )
    return variables, state


def filled(tree) -> Any:
    """Abstract tree → cheap concrete arrays (0.5 for floats, zeros for
    ints) — for the paths that must read values (int8 quantization
    scales)."""
    return jax.tree_util.tree_map(
        lambda s: np.full(s.shape, 0.5, s.dtype)
        if np.issubdtype(s.dtype, np.floating)
        else np.zeros(s.shape, s.dtype), tree)


# ---------------------------------------------------------------------------
# Per-pipeline target builders (lazy — nothing imports models until the
# program engine actually runs)
# ---------------------------------------------------------------------------


def _fraud(mesh) -> List[AuditProgram]:
    def build_train() -> BuiltProgram:
        from analytics_zoo_tpu.core.criterion import ClassNLLCriterion
        from analytics_zoo_tpu.models import FraudMLP
        from analytics_zoo_tpu.parallel import (Adam, make_train_step,
                                                pipeline_specs)

        module = FraudMLP(in_features=29, hidden=10, n_classes=2)
        specs = pipeline_specs("fraud", mesh=mesh)
        optim = Adam(1e-3)
        _, state = abstract_train_state(module, optim,
                                        _S((1, 29), np.float32))
        step = make_train_step(module, ClassNLLCriterion(), optim,
                               specs=specs, state=state)
        B = specs.data_axis_size
        batch = {"input": _S((B, 29), np.float32),
                 "target": _S((B,), np.int32)}
        return BuiltProgram(fn=step, args=(state, batch, 1.0),
                            specs=specs, donate_state=state)

    def build_eval() -> BuiltProgram:
        from analytics_zoo_tpu.models import FraudMLP
        from analytics_zoo_tpu.parallel import (Adam, make_eval_step,
                                                pipeline_specs)

        module = FraudMLP(in_features=29, hidden=10, n_classes=2)
        specs = pipeline_specs("fraud", mesh=mesh)
        variables = abstract_variables(module, _S((1, 29), np.float32))
        ev = make_eval_step(module, specs=specs)
        B = specs.data_axis_size
        return BuiltProgram(fn=ev, args=(variables, _S((B, 29),
                                                       np.float32)),
                            specs=specs)

    return [AuditProgram("fraud/train", build_train),
            AuditProgram("fraud/eval", build_eval)]


def _rec(mesh) -> List[AuditProgram]:
    # web-scale recommendation (ISSUE 17): the dedup'd-gather train and
    # eval programs for BOTH family architectures — the sparse lookup +
    # segment-sum backward is the hot path the audit must trace
    U, I, CLS = 64, 48, 5

    def build_train() -> BuiltProgram:
        from analytics_zoo_tpu.core.criterion import ClassNLLCriterion
        from analytics_zoo_tpu.models import NeuralCF
        from analytics_zoo_tpu.parallel import (Adam, make_train_step,
                                                pipeline_specs)

        module = NeuralCF(n_users=U, n_items=I, embedding_dim=8,
                          mf_embedding_dim=4, hidden=(16, 8), n_classes=CLS)
        specs = pipeline_specs("rec", mesh=mesh)
        optim = Adam(1e-3)
        _, state = abstract_train_state(module, optim,
                                        _S((1,), np.int32),
                                        _S((1,), np.int32))
        step = make_train_step(module, ClassNLLCriterion(), optim,
                               specs=specs, state=state)
        B = specs.data_axis_size
        batch = {"input": (_S((B,), np.int32), _S((B,), np.int32)),
                 "target": _S((B,), np.int32)}
        return BuiltProgram(fn=step, args=(state, batch, 1.0),
                            specs=specs, donate_state=state)

    def build_wd_train() -> BuiltProgram:
        from analytics_zoo_tpu.core.criterion import ClassNLLCriterion
        from analytics_zoo_tpu.models import WideAndDeep
        from analytics_zoo_tpu.parallel import (Adam, make_train_step,
                                                pipeline_specs)

        module = WideAndDeep(n_users=U, n_items=I, embedding_dim=8,
                             hidden=(16, 8), n_classes=CLS,
                             cross_buckets=32)
        specs = pipeline_specs("rec", mesh=mesh)
        optim = Adam(1e-3)
        _, state = abstract_train_state(module, optim,
                                        _S((1,), np.int32),
                                        _S((1,), np.int32))
        step = make_train_step(module, ClassNLLCriterion(), optim,
                               specs=specs, state=state)
        B = specs.data_axis_size
        batch = {"input": (_S((B,), np.int32), _S((B,), np.int32)),
                 "target": _S((B,), np.int32)}
        return BuiltProgram(fn=step, args=(state, batch, 1.0),
                            specs=specs, donate_state=state)

    def build_eval() -> BuiltProgram:
        from analytics_zoo_tpu.models import NeuralCF
        from analytics_zoo_tpu.parallel import (make_eval_step,
                                                pipeline_specs)

        module = NeuralCF(n_users=U, n_items=I, embedding_dim=8,
                          mf_embedding_dim=4, hidden=(16, 8), n_classes=CLS)
        specs = pipeline_specs("rec", mesh=mesh)
        variables = abstract_variables(module, _S((1,), np.int32),
                                       _S((1,), np.int32))
        ev = make_eval_step(module, specs=specs)
        B = specs.data_axis_size
        return BuiltProgram(fn=ev,
                            args=(variables, (_S((B,), np.int32),
                                              _S((B,), np.int32))),
                            specs=specs)

    return [AuditProgram("rec/train", build_train),
            AuditProgram("rec-wd/train", build_wd_train),
            AuditProgram("rec/eval", build_eval)]


def _sentiment(mesh) -> List[AuditProgram]:
    V, D, T = 256, 16, 24

    def _module():
        from analytics_zoo_tpu.models import SentimentNet

        return SentimentNet(vocab_size=V, embedding_dim=D, hidden=8,
                            head="gru")

    def build_train() -> BuiltProgram:
        from analytics_zoo_tpu.core.criterion import BCECriterion
        from analytics_zoo_tpu.parallel import (Adam, make_train_step,
                                                pipeline_specs)

        module = _module()
        specs = pipeline_specs("sentiment", mesh=mesh)
        optim = Adam(1e-3)
        _, state = abstract_train_state(module, optim,
                                        _S((1, T), np.int32))
        step = make_train_step(module, BCECriterion(), optim,
                               specs=specs, state=state)
        B = specs.data_axis_size
        batch = {"input": _S((B, T), np.int32),
                 "target": _S((B,), np.float32)}
        return BuiltProgram(fn=step, args=(state, batch, 1.0),
                            specs=specs, donate_state=state)

    def build_eval() -> BuiltProgram:
        from analytics_zoo_tpu.parallel import (make_eval_step,
                                                pipeline_specs)

        module = _module()
        specs = pipeline_specs("sentiment", mesh=mesh)
        variables = abstract_variables(module, _S((1, T), np.int32))
        ev = make_eval_step(module, specs=specs)
        B = specs.data_axis_size
        return BuiltProgram(fn=ev, args=(variables, _S((B, T), np.int32)),
                            specs=specs)

    return [AuditProgram("sentiment/train", build_train),
            AuditProgram("sentiment/eval", build_eval)]


def _ds2(mesh) -> List[AuditProgram]:
    T, MELS, LAB = 32, 13, 4

    def _module():
        from analytics_zoo_tpu.models import DeepSpeech2

        return DeepSpeech2(hidden=16, n_rnn_layers=1, n_mels=MELS)

    def build_train() -> BuiltProgram:
        from analytics_zoo_tpu.parallel import (Adam, make_train_step,
                                                pipeline_specs)
        from analytics_zoo_tpu.pipelines.deepspeech2 import (
            ds2_ctc_criterion, ds2_padding_metric)

        module = _module()
        specs = pipeline_specs("ds2", mesh=mesh)
        optim = Adam(1e-3)
        _, state = abstract_train_state(
            module, optim, _S((1, T, MELS), np.float32))
        step = make_train_step(module, ds2_ctc_criterion(), optim,
                               specs=specs, state=state,
                               metric_fn=ds2_padding_metric)
        B = specs.data_axis_size
        # the production bucketed-batch contract: input=(features,
        # n_frames), n_frames top-level for the CTC logit mask + metric
        batch = {"input": (_S((B, T, MELS), np.float32),
                           _S((B,), np.int32)),
                 "n_frames": _S((B,), np.int32),
                 "labels": _S((B, LAB), np.int32),
                 "label_mask": _S((B, LAB), np.float32)}
        return BuiltProgram(fn=step, args=(state, batch, 1.0),
                            specs=specs, donate_state=state)

    def build_eval() -> BuiltProgram:
        from analytics_zoo_tpu.parallel import (make_eval_step,
                                                pipeline_specs)

        module = _module()
        specs = pipeline_specs("ds2", mesh=mesh)
        variables = abstract_variables(module, _S((1, T, MELS),
                                                  np.float32))
        ev = make_eval_step(module, specs=specs)
        B = specs.data_axis_size
        return BuiltProgram(fn=ev,
                            args=(variables, _S((B, T, MELS), np.float32)),
                            specs=specs)

    def build_pallas_train() -> BuiltProgram:
        # the persistent-RNN engine's TRAIN program (ISSUE 13): the
        # custom_vjp backward is the transposed persistent Pallas
        # kernel since r10, so the jaxpr audit must trace the
        # pallas-engine training pipeline — not just the default
        # blocked-scan one — or the kernel path (fwd AND bwd pallas
        # calls, the programs bench.py ds2_persistent measures) sits
        # outside the audit surface.  Traces interpret-mode off-TPU,
        # same as the program the CPU tier dispatches.
        from analytics_zoo_tpu.models import DeepSpeech2
        from analytics_zoo_tpu.parallel import (Adam, make_train_step,
                                                pipeline_specs)
        from analytics_zoo_tpu.pipelines.deepspeech2 import (
            ds2_ctc_criterion, ds2_padding_metric)

        module = DeepSpeech2(hidden=16, n_rnn_layers=1, n_mels=MELS,
                             rnn_engine="pallas")
        specs = pipeline_specs("ds2", mesh=mesh)
        optim = Adam(1e-3)
        _, state = abstract_train_state(
            module, optim, _S((1, T, MELS), np.float32))
        step = make_train_step(module, ds2_ctc_criterion(), optim,
                               specs=specs, state=state,
                               metric_fn=ds2_padding_metric)
        B = specs.data_axis_size
        batch = {"input": (_S((B, T, MELS), np.float32),
                           _S((B,), np.int32)),
                 "n_frames": _S((B,), np.int32),
                 "labels": _S((B, LAB), np.int32),
                 "label_mask": _S((B, LAB), np.float32)}
        return BuiltProgram(fn=step, args=(state, batch, 1.0),
                            specs=specs, donate_state=state)

    return [AuditProgram("ds2/train", build_train),
            AuditProgram("ds2/eval", build_eval),
            AuditProgram("ds2-pallas/train", build_pallas_train)]


def _ssd(mesh) -> List[AuditProgram]:
    RES, NCLS, G = 300, 4, 8

    def build_train() -> BuiltProgram:
        from analytics_zoo_tpu.models import (SSDVgg, build_priors,
                                              ssd300_config)
        from analytics_zoo_tpu.ops.multibox_loss import (MultiBoxLoss,
                                                         MultiBoxLossParam)
        from analytics_zoo_tpu.parallel import (SGD, make_train_step,
                                                pipeline_specs)

        module = SSDVgg(num_classes=NCLS, resolution=RES)
        specs = pipeline_specs("ssd", mesh=mesh)
        optim = SGD(1e-3, momentum=0.9)
        _, state = abstract_train_state(
            module, optim, _S((1, RES, RES, 3), np.float32))
        priors, variances = build_priors(ssd300_config())
        crit = MultiBoxLoss(priors, variances,
                            MultiBoxLossParam(n_classes=NCLS))
        step = make_train_step(module, crit, optim, specs=specs,
                               state=state, skip_loss_above=50.0)
        B = specs.data_axis_size
        batch = {"input": _S((B, RES, RES, 3), np.float32),
                 "target": {"bboxes": _S((B, G, 4), np.float32),
                            "labels": _S((B, G), np.float32),
                            "mask": _S((B, G), np.float32)}}
        return BuiltProgram(fn=step, args=(state, batch, 1.0),
                            specs=specs, donate_state=state)

    def build_eval() -> BuiltProgram:
        from analytics_zoo_tpu.models import SSDVgg
        from analytics_zoo_tpu.parallel import (make_eval_step,
                                                pipeline_specs)

        module = SSDVgg(num_classes=NCLS, resolution=RES)
        specs = pipeline_specs("ssd", mesh=mesh)
        variables = abstract_variables(module,
                                       _S((1, RES, RES, 3), np.float32))
        ev = make_eval_step(module, specs=specs)
        B = specs.data_axis_size
        return BuiltProgram(fn=ev,
                            args=(variables,
                                  _S((B, RES, RES, 3), np.float32)),
                            specs=specs)

    return [AuditProgram("ssd/train", build_train),
            AuditProgram("ssd/eval", build_eval)]


def _frcnn(mesh) -> List[AuditProgram]:
    RES, NCLS, G = 128, 4, 8

    def _module():
        from analytics_zoo_tpu.models import FasterRcnnVgg, FrcnnParam
        from analytics_zoo_tpu.ops.proposal import ProposalParam

        return FasterRcnnVgg(param=FrcnnParam(
            num_classes=NCLS,
            proposal=ProposalParam(pre_nms_topn=64, post_nms_topn=16)))

    def build_train() -> BuiltProgram:
        from analytics_zoo_tpu.ops.frcnn_train import (
            FrcnnLossParam, frcnn_training_loss)
        from analytics_zoo_tpu.parallel import (SGD, make_train_step,
                                                pipeline_specs)

        module = _module()
        specs = pipeline_specs("frcnn", mesh=mesh)
        optim = SGD(1e-3, momentum=0.9)
        _, state = abstract_train_state(
            module, optim, _S((1, RES, RES, 3), np.float32),
            _S((1, 3), np.float32))

        def forward_fn(variables, inputs, train=False, rngs=None):
            x, im_info, gt_px, gt_mask = inputs
            out = module.apply(variables, x, im_info, train=train,
                               extra_rois=gt_px, extra_rois_mask=gt_mask,
                               train_outputs=True, rngs=rngs)
            return out, None

        loss_param = FrcnnLossParam()
        step = make_train_step(
            module, lambda out, b: frcnn_training_loss(out, b, loss_param),
            optim, specs=specs, state=state, forward_fn=forward_fn,
            grad_clip_norm=10.0)
        B = specs.data_axis_size
        batch = {"input": (_S((B, RES, RES, 3), np.float32),
                           _S((B, 3), np.float32),
                           _S((B, G, 4), np.float32),
                           _S((B, G), np.float32)),
                 "im_info": _S((B, 3), np.float32),
                 "target": {"bboxes": _S((B, G, 4), np.float32),
                            "labels": _S((B, G), np.int32),
                            "mask": _S((B, G), np.float32)}}
        return BuiltProgram(fn=step, args=(state, batch, 1.0),
                            specs=specs, donate_state=state)

    def build_eval() -> BuiltProgram:
        from analytics_zoo_tpu.parallel import (make_eval_step,
                                                pipeline_specs)

        module = _module()
        specs = pipeline_specs("frcnn", mesh=mesh)
        variables = abstract_variables(module,
                                       _S((1, RES, RES, 3), np.float32),
                                       _S((1, 3), np.float32))
        ev = make_eval_step(module, specs=specs)
        B = specs.data_axis_size
        return BuiltProgram(fn=ev,
                            args=(variables,
                                  (_S((B, RES, RES, 3), np.float32),
                                   _S((B, 3), np.float32))),
                            specs=specs)

    return [AuditProgram("frcnn/train", build_train),
            AuditProgram("frcnn/eval", build_eval)]


def _tier_targets(kind: str, tiers, specs) -> List[AuditProgram]:
    """Wrap each ServingTier's attached ``device_program`` thunk as an
    audit target (a tier without one is itself a finding — the factory
    stopped exposing its program to the audit)."""
    out: List[AuditProgram] = []
    for tier in tiers:
        name = f"{kind}/serve:{tier.name}"
        if tier.device_program is None:
            def build_missing(tier_name=tier.name) -> BuiltProgram:
                raise RuntimeError(
                    f"serving tier {tier_name!r} carries no "
                    f"device_program thunk — the tier factory must "
                    f"expose its jitted program for the audit")
            out.append(AuditProgram(name, build_missing))
            continue

        def build(thunk=tier.device_program, specs=specs) -> BuiltProgram:
            fn, args, static = thunk()
            return BuiltProgram(fn=fn, args=args, static_argnums=static,
                                specs=specs)
        out.append(AuditProgram(name, build))
    return out


def _ssd_serving(mesh) -> List[AuditProgram]:
    from analytics_zoo_tpu.core.module import Model
    from analytics_zoo_tpu.models import SSDVgg
    from analytics_zoo_tpu.ops import DetectionOutputParam
    from analytics_zoo_tpu.parallel import pipeline_specs
    from analytics_zoo_tpu.pipelines.ssd import (PreProcessParam,
                                                 ssd_serving_tiers)

    RES, NCLS = 300, 4
    module = SSDVgg(num_classes=NCLS, resolution=RES)
    # int8 quantization reads weight values for its scales → filled
    # arrays (cheap constants), not eval_shape structs
    model = Model(module)
    model.variables = filled(abstract_variables(
        module, _S((1, RES, RES, 3), np.float32)))
    specs = pipeline_specs("ssd", mesh=mesh)
    param = PreProcessParam(batch_size=specs.data_axis_size,
                            resolution=RES)
    tiers = ssd_serving_tiers(model, param, n_classes=NCLS, specs=specs)
    # the FUSED post-processing programs ("auto" resolves to them on a
    # TPU backend, but this audit traces on CPU where auto is xla):
    # audit the single-kernel DetectionOutput path explicitly so the
    # exact programs the TPU serving tiers dispatch are covered like
    # every other rung
    fused = ssd_serving_tiers(
        model, param, n_classes=NCLS, specs=specs,
        post=DetectionOutputParam(n_classes=NCLS, backend="fused"))
    return (_tier_targets("ssd", tiers, specs)
            + _tier_targets("ssd-fused", fused, specs))


def _ds2_serving(mesh) -> List[AuditProgram]:
    from analytics_zoo_tpu.core.module import Model
    from analytics_zoo_tpu.models import DeepSpeech2
    from analytics_zoo_tpu.parallel import pipeline_specs
    from analytics_zoo_tpu.pipelines.deepspeech2 import (DS2Param,
                                                         ds2_serving_tiers)

    module = DeepSpeech2(hidden=16, n_rnn_layers=1, n_mels=13)
    model = Model(module)
    model.variables = abstract_variables(module,
                                         _S((1, 64, 13), np.float32))
    specs = pipeline_specs("ds2", mesh=mesh)
    tiers = ds2_serving_tiers(model, DS2Param(decoder="beam"), specs=specs)
    return _tier_targets("ds2", tiers, specs)


def _ds2_streaming_serving(mesh) -> List[AuditProgram]:
    # the ISSUE-14 first-class streaming session model: audit the
    # steady-block carry-in/carry-out program every chunk dispatches
    from analytics_zoo_tpu.core.module import Model
    from analytics_zoo_tpu.models import DeepSpeech2
    from analytics_zoo_tpu.parallel import pipeline_specs
    from analytics_zoo_tpu.pipelines.deepspeech2 import ds2_streaming_tiers

    module = DeepSpeech2(hidden=16, n_rnn_layers=1, n_mels=13,
                         bidirectional=False)
    model = Model(module)
    model.variables = abstract_variables(module,
                                         _S((1, 64, 13), np.float32))
    specs = pipeline_specs("ds2", mesh=mesh)
    tiers = ds2_streaming_tiers(model, n_mels=13, chunk_frames=50)
    return _tier_targets("ds2-stream", tiers, specs)


def _frcnn_serving(mesh) -> List[AuditProgram]:
    from analytics_zoo_tpu.models import FasterRcnnDetector, FrcnnParam
    from analytics_zoo_tpu.ops.proposal import ProposalParam
    from analytics_zoo_tpu.parallel import pipeline_specs
    from analytics_zoo_tpu.pipelines.frcnn import frcnn_serving_tiers
    from analytics_zoo_tpu.pipelines.ssd import PreProcessParam

    RES, NCLS = 128, 4
    detector = FasterRcnnDetector(param=FrcnnParam(
        num_classes=NCLS,
        proposal=ProposalParam(pre_nms_topn=64, post_nms_topn=16)))
    # int8 quantization reads weight values for its scales → filled
    variables = filled(abstract_variables(
        detector, _S((1, RES, RES, 3), np.float32),
        _S((1, 3), np.float32)))
    specs = pipeline_specs("frcnn", mesh=mesh)
    tiers = frcnn_serving_tiers(
        detector, variables,
        param=PreProcessParam(batch_size=specs.data_axis_size,
                              resolution=RES),
        specs=specs)
    return _tier_targets("frcnn", tiers, specs)


def _fraud_serving(mesh) -> List[AuditProgram]:
    from analytics_zoo_tpu.core.module import Model
    from analytics_zoo_tpu.models import FraudMLP
    from analytics_zoo_tpu.parallel import pipeline_specs
    from analytics_zoo_tpu.pipelines.fraud import fraud_serving_tiers

    module = FraudMLP(in_features=29, hidden=10, n_classes=2)
    model = Model(module)
    model.variables = filled(abstract_variables(
        module, _S((1, 29), np.float32)))
    specs = pipeline_specs("fraud", mesh=mesh)
    tiers = fraud_serving_tiers(model, specs=specs)
    return _tier_targets("fraud", tiers, specs)


def _fraud_slice_serving(mesh) -> List[AuditProgram]:
    """ISSUE 19: the width-2 :class:`ReplicaSlice` geometry — the SAME
    fraud tier ladder re-jitted against a 2-device sub-mesh via
    ``SpecSet.replace_mesh``, exactly how the runtime builds a slice's
    programs.  Auditing it pins that the slice path produces genuine
    annotated programs (donation/sharding/collectives discipline), not
    a degenerate single-device trace wearing a wide name."""
    from analytics_zoo_tpu.core.module import Model
    from analytics_zoo_tpu.models import FraudMLP
    from analytics_zoo_tpu.parallel import pipeline_specs
    from analytics_zoo_tpu.parallel import mesh as mesh_lib
    from analytics_zoo_tpu.pipelines.fraud import fraud_serving_tiers

    devs = list(mesh.devices.reshape(-1)[:2])
    sub = mesh_lib.create_mesh((len(devs),),
                               (mesh_lib.data_axis(mesh),), devices=devs)
    module = FraudMLP(in_features=29, hidden=10, n_classes=2)
    model = Model(module)
    model.variables = filled(abstract_variables(
        module, _S((1, 29), np.float32)))
    specs = pipeline_specs("fraud", mesh=mesh).replace_mesh(sub)
    tiers = fraud_serving_tiers(model, specs=specs)
    return _tier_targets("fraud-slice-w2", tiers, specs)


def _rec_serving(mesh) -> List[AuditProgram]:
    from analytics_zoo_tpu.parallel import pipeline_specs
    from analytics_zoo_tpu.pipelines.recommendation import (
        make_ncf_model, rec_serving_tiers)

    # sized like the train targets; tiny enough that a real init is
    # cheaper than the abstract+filled dance (int8 scales read values)
    model = make_ncf_model(n_users=64, n_items=48, embedding_dim=8,
                           mf_embedding_dim=4, hidden=(16, 8))
    specs = pipeline_specs("rec", mesh=mesh)
    tiers = rec_serving_tiers(model, specs=specs)
    return _tier_targets("rec", tiers, specs)


def _sentiment_serving(mesh) -> List[AuditProgram]:
    from analytics_zoo_tpu.core.module import Model
    from analytics_zoo_tpu.models import SentimentNet
    from analytics_zoo_tpu.parallel import pipeline_specs
    from analytics_zoo_tpu.pipelines.sentiment import sentiment_serving_tiers

    T = 24
    module = SentimentNet(vocab_size=256, embedding_dim=16, hidden=8,
                          head="gru")
    model = Model(module)
    model.variables = filled(abstract_variables(
        module, _S((1, T), np.int32)))
    specs = pipeline_specs("sentiment", mesh=mesh)
    tiers = sentiment_serving_tiers(model, specs=specs, seq_len=T)
    return _tier_targets("sentiment", tiers, specs)


def _fraud_swapped_serving(mesh) -> List[AuditProgram]:
    """ISSUE 18 (live weights): ``ServingRuntime.hot_swap`` rebuilds a
    family's tier stack from a RESTORED checkpoint pytree — plain
    nested dicts of host arrays (what ``checkpoint.load`` returns, not
    the boot-time FrozenDict) pushed through the declared SpecSet's
    ``place_state``.  The programs a swapped-in replica dispatches must
    stay under the audit exactly like the boot-time stack, so this
    target builds the fraud tiers through that restore → place →
    rebuild path."""
    from analytics_zoo_tpu.core.module import Model
    from analytics_zoo_tpu.models import FraudMLP
    from analytics_zoo_tpu.parallel import pipeline_specs
    from analytics_zoo_tpu.pipelines.fraud import fraud_serving_tiers

    def plain(tree):
        if hasattr(tree, "items"):
            return {k: plain(v) for k, v in tree.items()}
        return np.asarray(tree)

    module = FraudMLP(in_features=29, hidden=10, n_classes=2)
    model = Model(module)
    restored = plain(filled(abstract_variables(
        module, _S((1, 29), np.float32))))
    specs = pipeline_specs("fraud", mesh=mesh)
    model.variables = specs.place_state(restored)
    tiers = fraud_serving_tiers(model, specs=specs)
    return _tier_targets("fraud-swapped", tiers, specs)


def _guarded_tiers(kind: str, builder, mesh) -> List[AuditProgram]:
    """The serving-tier targets need the tier FACTORIES to run before
    the target names are even known (names come from the rungs).  A
    factory that explodes must surface as a finding on that family —
    not crash suite construction and take the healthy train/eval
    targets down with it (audit_program's per-target contract)."""
    try:
        return builder(mesh)
    except Exception as e:
        msg = f"{type(e).__name__}: {e}"

        def build_fail() -> BuiltProgram:
            raise RuntimeError(
                f"serving-tier factory failed before any program could "
                f"be traced: {msg}")
        return [AuditProgram(f"{kind}/serve:<factory-failed>", build_fail)]


def repo_audit_suite(mesh=None) -> List[AuditProgram]:
    """Every program the ISSUE-10 audit must cover, lazily built on
    ``mesh`` (default: 1-D data mesh over all local devices)."""
    from analytics_zoo_tpu.parallel import mesh as mesh_lib

    mesh = mesh or mesh_lib.create_mesh()
    targets: List[AuditProgram] = []
    targets += _ssd(mesh)
    targets += _frcnn(mesh)
    targets += _ds2(mesh)
    targets += _fraud(mesh)
    # the ISSUE-17 long tail: recommendation (NCF + Wide&Deep) and
    # sentiment ride the sharded-embedding substrate
    targets += _rec(mesh)
    targets += _sentiment(mesh)
    targets += _guarded_tiers("ssd", _ssd_serving, mesh)
    targets += _guarded_tiers("ds2", _ds2_serving, mesh)
    # the ISSUE-14 multiplexed fleet: every model family the shared
    # replica pool schedules exposes its serving programs to the audit
    targets += _guarded_tiers("ds2-stream", _ds2_streaming_serving, mesh)
    targets += _guarded_tiers("frcnn", _frcnn_serving, mesh)
    targets += _guarded_tiers("fraud", _fraud_serving, mesh)
    # ISSUE 18: the hot-swapped tier stack (checkpoint-restored
    # variables → place_state → tiers) audits like the boot-time one
    targets += _guarded_tiers("fraud-swapped", _fraud_swapped_serving,
                              mesh)
    # ISSUE 19: serving replicas that ARE mesh slices — the width-2
    # sub-mesh tier ladder audits alongside the full-width one
    targets += _guarded_tiers("fraud-slice-w2", _fraud_slice_serving,
                              mesh)
    targets += _guarded_tiers("rec", _rec_serving, mesh)
    targets += _guarded_tiers("sentiment", _sentiment_serving, mesh)
    return targets
