"""Program engine: jaxpr audits of the compiled-program invariants.

Source rules see what the code *says*; this engine checks what the
traced program *is*.  Every audit target (:mod:`analysis.targets`
builds the repo's suite) is traced with ``jax.make_jaxpr`` — tracing
only, no XLA compile, no FLOPs — and the resulting jaxpr is walked
recursively (pjit bodies, scan/while/cond sub-jaxprs, shard_map
bodies, custom_vjp calls) for four properties:

- **no-callbacks-in-hot-program** — ``pure_callback``/``io_callback``/
  ``debug_callback`` inside a jitted train/eval/serving program is a
  host round-trip per step hiding where no profiler attributes it (and
  pins the program to the host, breaking async dispatch overlap).
- **donation-materialized** — the train step's ``TrainState`` arg must
  actually reach the pjit with every leaf marked donated.  Donation is
  declared at one ``jax.jit(donate_argnums=...)`` site but silently
  voided by wrapper reordering (a wrapper that re-packs the state
  breaks aliasing without an error) — so the audit reads
  ``donated_invars`` off the traced pjit equation itself.
- **no-float64** — an f64 leak (a stray Python float promoted under
  x64, an np.float64 scalar) doubles bandwidth on the exact arrays the
  MFU ceiling analyses assume are f32/bf16, and TPUs emulate f64.
  Scope is honest: with ``jax_enable_x64`` OFF (this repo's every
  config) JAX canonicalizes f64 → f32 at trace time, so no leak can
  exist and the check is vacuous-but-free; it arms the moment a
  process enables x64 (a future double-precision eval config), where
  the audit traces under the same flag and catches real leaks.
  Deliberately NOT forced on for the audit itself: under x64 every
  plain Python float literal traces as weak-f64, which would flag
  every program in the repo.
- **collective-inventory** — every named-axis collective (psum /
  all_gather / ppermute / …) in the program must reference an axis the
  pipeline's declared ``SpecSet`` mesh actually has.  GSPMD-annotated
  programs carry no explicit collectives (XLA inserts them after
  SPMD partitioning), so any named axis that shows up was written by
  hand — and a hand-written axis the declaration doesn't know about is
  exactly the drift the declare-once substrate exists to prevent.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator, List, Optional, Sequence, Set, Tuple

import jax
import numpy as np

from analytics_zoo_tpu.analysis.base import Violation

#: host-callback primitives banned from hot programs
CALLBACK_PRIMS = frozenset({"pure_callback", "io_callback",
                            "debug_callback"})

#: named-axis collective primitives whose axes must be declared
COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "pmax", "pmin", "pbroadcast", "ppermute",
    "all_gather", "all_gather_invariant", "reduce_scatter",
    "all_to_all", "pgather", "axis_index",
})


@dataclasses.dataclass
class BuiltProgram:
    """One traced-and-audited program.

    ``donate_state``: the pytree passed as argument 0 whose every leaf
    must be donated (``None`` skips the donation check — eval/serving
    programs donate nothing).  ``specs``: the pipeline's declared
    :class:`~analytics_zoo_tpu.parallel.specs.SpecSet`; its mesh axis
    names are the collective-inventory ground truth.  ``hot``: callback
    primitives are violations (every repo program audited today is
    hot)."""

    fn: Callable
    args: Tuple
    static_argnums: Tuple[int, ...] = ()
    specs: Any = None
    donate_state: Any = None
    hot: bool = True


@dataclasses.dataclass(frozen=True)
class AuditProgram:
    """A named, lazily-built audit target: ``build()`` returns the
    :class:`BuiltProgram` (construction is deferred so ``--source``-only
    runs never pay for model construction)."""

    name: str
    build: Callable[[], BuiltProgram]


def _sub_jaxprs(params: dict) -> Iterator[Any]:
    for v in params.values():
        if isinstance(v, (jax.core.Jaxpr, jax.core.ClosedJaxpr)):
            yield v
        elif isinstance(v, (tuple, list)):
            for item in v:
                if isinstance(item, (jax.core.Jaxpr, jax.core.ClosedJaxpr)):
                    yield item


def iter_eqns(jaxpr) -> Iterator[Any]:
    """Every equation of ``jaxpr`` and (recursively) of every sub-jaxpr
    carried in equation params — pjit bodies, scan/while/cond branches,
    shard_map bodies, custom_jvp/vjp call jaxprs."""
    if isinstance(jaxpr, jax.core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def _named_axes(eqn) -> Set[str]:
    axes: Set[str] = set()
    for key in ("axes", "axis_name"):
        v = eqn.params.get(key)
        if v is None:
            continue
        for a in (v if isinstance(v, (tuple, list)) else (v,)):
            if isinstance(a, str):
                axes.add(a)
    return axes


def collective_inventory(jaxpr) -> Set[str]:
    """All named mesh axes referenced by collective primitives anywhere
    in the program."""
    axes: Set[str] = set()
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name in COLLECTIVE_PRIMS:
            axes |= _named_axes(eqn)
    return axes


def _avals(jaxpr) -> Iterator[Any]:
    if isinstance(jaxpr, jax.core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    for v in jaxpr.invars + jaxpr.outvars:
        if hasattr(v, "aval"):
            yield v.aval
    for eqn in iter_eqns(jaxpr):
        for v in list(eqn.invars) + list(eqn.outvars):
            if hasattr(v, "aval"):
                yield v.aval


def audit_program(target: AuditProgram) -> List[Violation]:
    """Trace one target and run every program check against it."""
    where = f"program:{target.name}"
    try:
        built = target.build()
        closed = jax.make_jaxpr(
            built.fn, static_argnums=built.static_argnums)(*built.args)
    except Exception as e:  # a target that cannot trace IS a finding
        return [Violation(
            rule="program-trace-error", file=where, line=0,
            message=f"audit target failed to trace: "
                    f"{type(e).__name__}: {e}")]
    out: List[Violation] = []

    if built.hot:
        seen = set()
        for eqn in iter_eqns(closed):
            name = eqn.primitive.name
            if name in CALLBACK_PRIMS and name not in seen:
                seen.add(name)
                out.append(Violation(
                    rule="no-callbacks-in-hot-program", file=where, line=0,
                    message=f"{name} inside the jitted program — a host "
                            f"round-trip per step; hoist it out of the "
                            f"traced body (obs hooks live host-side)"))

    if built.donate_state is not None:
        n_state = len(jax.tree_util.tree_leaves(built.donate_state))
        pjit_eqns = [e for e in closed.jaxpr.eqns
                     if e.primitive.name == "pjit"
                     and "donated_invars" in e.params]
        if not pjit_eqns:
            out.append(Violation(
                rule="donation-materialized", file=where, line=0,
                message="no pjit equation found at the top level — the "
                        "step is not the single jitted program the "
                        "donation contract assumes"))
        else:
            donated = pjit_eqns[0].params["donated_invars"]
            missing = sum(1 for d in donated[:n_state] if not d)
            if missing:
                out.append(Violation(
                    rule="donation-materialized", file=where, line=0,
                    message=f"{missing}/{n_state} TrainState leaves are "
                            f"NOT donated — the step keeps a second copy "
                            f"of params+optimizer state in HBM (check "
                            f"donate_argnums and wrapper arg order)"))

    f64 = sorted({str(a.dtype) for a in _avals(closed)
                  if getattr(a, "dtype", None) == np.dtype("float64")})
    if f64:
        out.append(Violation(
            rule="no-float64", file=where, line=0,
            message="float64 values inside the program — a leaked "
                    "double (Python float under x64, np.float64 scalar) "
                    "doubles bandwidth and TPUs emulate f64"))

    if built.specs is not None:
        declared = set(built.specs.mesh.axis_names)
        inventory = collective_inventory(closed)
        undeclared = sorted(inventory - declared)
        if undeclared:
            out.append(Violation(
                rule="collective-inventory", file=where, line=0,
                message=f"collectives over axes {undeclared} but the "
                        f"pipeline's SpecSet declares mesh axes "
                        f"{sorted(declared)} — the program communicates "
                        f"over axes the declaration doesn't know about"))
    return out


def run_program_engine(targets: Sequence[AuditProgram]
                       ) -> List[Violation]:
    out: List[Violation] = []
    for t in targets:
        out.extend(audit_program(t))
    return out
