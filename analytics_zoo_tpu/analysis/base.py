"""Shared checker vocabulary: violations, waivers, diagnostics format.

The waiver contract is the load-bearing design decision.  A static rule
that cannot express exceptions gets deleted the first time it is wrong;
a rule whose exceptions are silent (skip-lists inside the checker) rots
the other way — nobody can see what was exempted or why.  Here every
exception is declared **in the source it exempts**::

    x = jax.device_put(v, s)  # az-allow: one-placement-site — <why>

    # az-allow: one-clock — <why>
    t0 = time.monotonic()

A trailing waiver covers its own logical statement (every physical
line of a wrapped call); a standalone comment covers the statement
below it.  The reason is mandatory (a reason-less waiver is itself a
violation) and an unused waiver is a violation too, so a waiver cannot
outlive the exception it documents.  The CLI prints every applied
waiver with its reason — counted, never silent.
"""

from __future__ import annotations

import dataclasses
import io
import re
import tokenize
from typing import Dict, Iterable, List, Sequence, Tuple

#: ``# az-allow: <rule> — <reason>`` (en/em dash or ``-`` accepted).
_WAIVER_RE = re.compile(
    r"#\s*az-allow:\s*(?P<rule>[A-Za-z0-9_-]+)\s*(?P<rest>.*)$")
_DASH_RE = re.compile(r"^[\s—–-]+")


@dataclasses.dataclass(frozen=True)
class Violation:
    """One diagnostic: ``file:line rule message``.  ``waived`` marks a
    violation covered by an in-source waiver (kept in the report so the
    exception stays visible); only un-waived violations fail the run."""

    rule: str
    file: str
    line: int
    message: str
    waived: bool = False
    waiver_reason: str = ""


@dataclasses.dataclass
class Waiver:
    """One parsed ``az-allow`` comment and the lines it covers."""

    rule: str
    reason: str
    file: str
    line: int                     # line the comment sits on
    covers: Tuple[int, ...]       # lines it exempts
    used: int = 0


def format_violation(v: Violation) -> str:
    tag = f" [waived: {v.waiver_reason}]" if v.waived else ""
    return f"{v.file}:{v.line} {v.rule}{tag} {v.message}"


def parse_waivers(lines: Sequence[str], file: str
                  ) -> Tuple[List[Waiver], List[Violation]]:
    """Scan raw source lines for waiver comments.

    Returns ``(waivers, violations)`` where the violations are malformed
    waivers (rule present but no reason) — a waiver must say *why* or it
    is itself a finding (rule ``waiver-syntax``).

    Tokenizer-based on purpose: only REAL comment tokens count, so a
    docstring or string literal that merely *mentions* the syntax (this
    module's own docstring, docs examples, test fixtures as strings)
    never creates a stray waiver.  Both placements cover every physical
    line of one whole LOGICAL statement — the one the trailing comment
    sits on, or the next one below a standalone comment — because a
    violation may anchor to any line of a multi-line call (the call's
    first line for the call itself, a continuation line for a nested
    call)."""
    waivers: List[Waiver] = []
    violations: List[Violation] = []
    source = "\n".join(lines) + "\n"
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return waivers, violations      # unparsable → the engine reports
    _SKIP = {tokenize.NL, tokenize.COMMENT, tokenize.INDENT,
             tokenize.DEDENT, tokenize.ENCODING, tokenize.ENDMARKER}
    statements: List[Tuple[int, int]] = []   # logical-stmt line extents
    # (rule, reason, comment line, stmt_start-at-comment; 0=standalone)
    pending: List[Tuple[str, str, int, int]] = []
    stmt_start: int = 0                      # 0 = no code yet this stmt
    for tok in tokens:
        if tok.type == tokenize.NEWLINE:
            if stmt_start:
                statements.append((stmt_start, tok.start[0]))
            stmt_start = 0
            continue
        if tok.type not in _SKIP:
            if stmt_start == 0:
                stmt_start = tok.start[0]
            continue
        if tok.type != tokenize.COMMENT:
            continue
        m = _WAIVER_RE.search(tok.string)
        if not m:
            continue
        lineno = tok.start[0]
        rule = m.group("rule")
        reason = _DASH_RE.sub("", m.group("rest")).strip()
        if not reason:
            violations.append(Violation(
                rule="waiver-syntax", file=file, line=lineno,
                message=f"waiver for {rule!r} carries no reason — write "
                        f"'# az-allow: {rule} — <why this exception is "
                        f"sound>'"))
            continue
        pending.append((rule, reason, lineno, stmt_start))
    for rule, reason, lineno, start in pending:
        if start:
            # trailing (comment on any physical line of a statement):
            # cover that statement's FULL extent
            extent = next(((s, e) for s, e in statements
                           if s == start and e >= lineno),
                          (start, lineno))
        else:
            # standalone: the next logical statement below (a multi-
            # line one covered whole); none follows → next line only
            extent = next(((s, e) for s, e in statements if s > lineno),
                          (lineno + 1, lineno + 1))
        covers = (lineno,) + tuple(range(extent[0], extent[1] + 1))
        waivers.append(Waiver(rule=rule, reason=reason, file=file,
                              line=lineno, covers=covers))
    waivers.sort(key=lambda w: w.line)
    return waivers, violations


def apply_waivers(violations: Iterable[Violation],
                  waivers: Sequence[Waiver],
                  active_rules: Optional[Iterable[str]] = None
                  ) -> List[Violation]:
    """Mark violations covered by a matching waiver (same file, same
    rule, covered line) and surface unused waivers as violations
    (rule ``waiver-unused``) so dead exemptions cannot accumulate.

    ``active_rules``: the rule names that actually RAN.  A waiver for a
    rule outside the set is left alone instead of escalating to
    waiver-unused — a subset-rule run (tests pinning one rule, a future
    ``--rule`` CLI filter) must not report other rules' legitimate
    waivers as dead."""
    active = None if active_rules is None else set(active_rules)
    index: Dict[Tuple[str, str, int], Waiver] = {}
    for w in waivers:
        for ln in w.covers:
            index[(w.file, w.rule, ln)] = w

    out: List[Violation] = []
    for v in violations:
        w = index.get((v.file, v.rule, v.line))
        if w is not None:
            w.used += 1
            v = dataclasses.replace(v, waived=True, waiver_reason=w.reason)
        out.append(v)
    for w in waivers:
        if w.used == 0 and (active is None or w.rule in active):
            out.append(Violation(
                rule="waiver-unused", file=w.file, line=w.line,
                message=f"waiver for {w.rule!r} matched no violation — "
                        f"the exception it documented is gone; delete it"))
    return out
