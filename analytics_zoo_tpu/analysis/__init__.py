"""az-analyze: the two-engine static invariant checker.

Eight PRs of hard-won invariants — one placement site, one injected
clock, seeded-RNG-only determinism, donated step buffers, no host work
inside jitted hot paths, a complete error taxonomy — were enforced by
one grep test, convention, and reviewer memory.  This package turns
them into machine-checked rules (Clockwork's thesis restated for a
codebase: predictable systems come from *consolidating choice* and
removing nondeterminism by construction):

- **source engine** (:mod:`analysis.source`) — AST rules over the
  package source.  No file is imported or executed; a rule sees the
  parse tree, the import-alias table, and the raw lines.  Exceptions
  are declared in-source with ``# az-allow: <rule> — <reason>`` —
  visible, reasoned, and counted, never silent (:mod:`analysis.base`).
- **program engine** (:mod:`analysis.program`) — every registered
  pipeline's jitted train/eval program and the SSD/DS2 serving tiers
  are traced to jaxprs (:mod:`analysis.targets`; abstract
  ``eval_shape`` init, so the audit costs tracing, not FLOPs) and
  audited: no host callbacks in hot programs, donation materialized
  for the ``TrainState`` pytree, no float64 leaks, and the collective
  inventory confined to the mesh axes the pipeline's ``SpecSet``
  declares.

``tools/az_analyze.py --all`` runs both engines and exits non-zero on
any un-waived violation; ``tests/test_analyze.py`` wires it into
tier-1.  Rule catalog and waiver syntax: ``docs/ANALYSIS.md``.
"""

from analytics_zoo_tpu.analysis.base import (
    Violation,
    Waiver,
    apply_waivers,
    format_violation,
    parse_waivers,
)
from analytics_zoo_tpu.analysis.source import (
    SOURCE_RULES,
    default_rules,
    run_source_engine,
)

__all__ = [
    "Violation",
    "Waiver",
    "apply_waivers",
    "format_violation",
    "parse_waivers",
    "SOURCE_RULES",
    "default_rules",
    "run_source_engine",
]
