"""Source engine: AST rules encoding the repo's invariants.

Each rule is a small object with a ``name``, a one-line ``doc``, and a
``check(ctx)`` generator over :class:`~analytics_zoo_tpu.analysis.base.
Violation`.  The engine parses every package module ONCE into a
:class:`ModuleContext` (AST + import-alias table + raw lines — nothing
is imported or executed, so a rule can never be dodged by import-time
side effects) and runs every rule over it, then applies the in-source
``az-allow`` waivers.

Adding a rule (docs/ANALYSIS.md has the worked example):

1. subclass/instantiate with a unique kebab-case ``name``;
2. yield ``Violation``\\ s with the *package-relative* file path the
   engine passed in ``ctx.display``;
3. append the instance to :data:`SOURCE_RULES`;
4. add the firing + clean fixture pair in ``tests/test_analyze.py``.

The rules resolve import aliases (``import numpy as np``, ``import
time as _time``, ``from jax.sharding import NamedSharding``) so renamed
imports cannot slip past a textual match — the failure mode of the
PR-8 grep gate this engine replaces.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, FrozenSet, Iterator, List, Optional, Sequence

from analytics_zoo_tpu.analysis.base import (
    Violation,
    apply_waivers,
    parse_waivers,
)


@dataclasses.dataclass
class ModuleContext:
    """One parsed module: package-relative path, AST, raw lines, and the
    local-name → dotted-origin import table."""

    rel: str              # posix path relative to the scan root
    display: str          # path used in diagnostics (root name + rel)
    tree: ast.Module
    lines: List[str]
    aliases: Dict[str, str]

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted origin of a Name/Attribute chain, through the alias
        table: ``np.random.seed`` → ``numpy.random.seed``,
        ``_time.monotonic`` → ``time.monotonic``, a bare
        ``NamedSharding`` imported from ``jax.sharding`` →
        ``jax.sharding.NamedSharding``."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        head = parts[0]
        if head in self.aliases:
            return ".".join([self.aliases[head]] + parts[1:])
        return ".".join(parts)


def _import_aliases(tree: ast.Module) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                local = a.asname or a.name.split(".")[0]
                # `import numpy.random` binds the TOP package name
                origin = a.name if a.asname else a.name.split(".")[0]
                aliases[local] = origin
        elif isinstance(node, ast.ImportFrom):
            mod = ("." * node.level) + (node.module or "")
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{mod}.{a.name}" if mod \
                    else a.name
    return aliases


def _calls(tree: ast.Module) -> Iterator[ast.Call]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            yield node


def _last_component(ctx: ModuleContext, func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class OneClock:
    """No ``time.time()``/``time.monotonic()`` outside the injected
    clock module — every time-based decision (deadlines, shedding,
    stall detection, span timestamps, epoch/eval throughput logs) must
    read the ONE clock so drills replay deterministically under
    ``VirtualClock`` (the RESILIENCE_r03/OBS_r01 contract)."""

    name: str = "one-clock"
    allowed: FrozenSet[str] = frozenset({"utils/clock.py"})
    _BANNED = frozenset({"time.time", "time.monotonic"})

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.rel in self.allowed:
            return
        for call in _calls(ctx.tree):
            r = ctx.resolve(call.func)
            if r in self._BANNED:
                yield Violation(
                    rule=self.name, file=ctx.display, line=call.lineno,
                    message=f"{r}() read outside utils/clock.py — inject "
                            f"a Clock/now-fn (utils.clock.as_now_fn) so "
                            f"virtual-clock drills stay deterministic")


@dataclasses.dataclass
class OnePlacementSite:
    """No ``jax.device_put`` / ``NamedSharding(`` construction outside
    the declare-once substrate (``parallel/specs.py`` and the mesh/
    tensor placement engines it delegates to) — the AST generalization
    of the PR-8 grep gate, covering the WHOLE package instead of two
    directories and ignoring docstrings/comments."""

    name: str = "one-placement-site"
    allowed: FrozenSet[str] = frozenset({
        "parallel/specs.py",     # the declaration + its one payoff site
        "parallel/mesh.py",      # place/replicate engine specs delegates to
        "parallel/tensor.py",    # rule-resolved shard_tree engine
    })
    _BANNED = frozenset({"device_put", "NamedSharding"})

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.rel in self.allowed:
            return
        for call in _calls(ctx.tree):
            last = _last_component(ctx, call.func)
            if last in self._BANNED:
                yield Violation(
                    rule=self.name, file=ctx.display, line=call.lineno,
                    message=f"{last}( constructs device placement outside "
                            f"the spec layer — declare a PartitionSpec in "
                            f"parallel/specs.py and consume the SpecSet")


#: numpy.random module-level draw/state functions (the GLOBAL RNG).
_NP_MODULE_DRAWS = frozenset({
    "rand", "randn", "randint", "random", "random_sample", "ranf",
    "sample", "choice", "bytes", "shuffle", "permutation", "uniform",
    "normal", "standard_normal", "beta", "binomial", "chisquare",
    "dirichlet", "exponential", "f", "gamma", "geometric", "gumbel",
    "hypergeometric", "laplace", "logistic", "lognormal", "logseries",
    "multinomial", "multivariate_normal", "negative_binomial",
    "noncentral_chisquare", "noncentral_f", "pareto", "poisson", "power",
    "rayleigh", "standard_cauchy", "standard_exponential",
    "standard_gamma", "standard_t", "triangular", "vonmises", "wald",
    "weibull", "zipf", "get_state", "set_state",
})


@dataclasses.dataclass
class SeededRngOnly:
    """Determinism by construction: no global ``np.random.seed``, no
    module-level ``np.random.<draw>`` (both mutate/read process-global
    state any import can perturb — the exact hazard the loader's
    byte-identical-for-any-worker-count contract forbids), and no
    unseeded ``Generator``/``RandomState`` construction (randomness must
    derive from the (base_seed, epoch, index) chain, never the OS)."""

    name: str = "seeded-rng-only"
    allowed: FrozenSet[str] = frozenset()
    #: constructors that draw OS entropy when called without a seed —
    #: the Generator front door, the legacy RandomState, every stock
    #: BitGenerator, and SeedSequence itself
    _SEEDABLE_CTORS = frozenset({
        "default_rng", "RandomState", "PCG64", "PCG64DXSM", "MT19937",
        "Philox", "SFC64", "SeedSequence",
    })

    @staticmethod
    def _unseeded_call(call: ast.Call) -> bool:
        """No arguments, or an explicit ``None``/``seed=None`` first
        seed — both fall back to OS entropy."""
        if not call.args and not call.keywords:
            return True
        if call.args:
            first = call.args[0]
        else:
            seed_kw = [k for k in call.keywords
                       if k.arg in ("seed", "entropy")]
            if not seed_kw:
                return False
            first = seed_kw[0].value
        return isinstance(first, ast.Constant) and first.value is None

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.rel in self.allowed:
            return
        for call in _calls(ctx.tree):
            r = ctx.resolve(call.func)
            if r is None or not r.startswith("numpy.random."):
                continue
            tail = r.rsplit(".", 1)[1]
            if r == "numpy.random.seed":
                yield Violation(
                    rule=self.name, file=ctx.display, line=call.lineno,
                    message="np.random.seed mutates the process-global "
                            "RNG — thread a seeded np.random.Generator "
                            "instead (data.parallel seeding chain)")
            elif tail in _NP_MODULE_DRAWS:
                yield Violation(
                    rule=self.name, file=ctx.display, line=call.lineno,
                    message=f"np.random.{tail} draws from the process-"
                            f"global RNG — use a Generator seeded from "
                            f"the stream position")
            elif tail in self._SEEDABLE_CTORS and self._unseeded_call(call):
                yield Violation(
                    rule=self.name, file=ctx.display, line=call.lineno,
                    message=f"{tail}() without a seed draws OS entropy — "
                            f"derive the seed from the (base_seed, epoch, "
                            f"index) chain")


#: Modules on the step/dispatch hot path: the train step factories +
#: host loop, the serving dispatch chain, the two pipeline modules
#: whose serving programs feed the runtime, and the device-health
#: fingerprint programs (the parity audit's no-host-sync contract:
#: fingerprints fold in-graph and are fetched only at the decision
#: boundary in the host loop).
_HOT_MODULES = frozenset({
    "parallel/train.py",
    "parallel/optim.py",
    "serving/replica.py",
    "serving/runtime.py",
    "serving/batcher.py",
    "serving/request.py",
    "pipelines/ssd.py",
    "pipelines/deepspeech2.py",
    "resilience/health.py",
})


@dataclasses.dataclass
class NoHostSyncInHotPath:
    """No host synchronization inside step/dispatch modules: every
    ``block_until_ready``/``.item()`` is a full device round-trip that
    serializes the async dispatch pipeline (the overlap PR 2/PR 5 built),
    and ``np.asarray``/``np.array`` inside a jit-bound function either
    fails on tracers or silently constant-folds a batch.  The ONE
    sanctioned sync point is ``obs/probe.py`` — syncing is its
    measurement, by design."""

    name: str = "no-host-sync-in-hot-path"
    hot_modules: FrozenSet[str] = _HOT_MODULES
    allowed: FrozenSet[str] = frozenset({"obs/probe.py"})
    _HOST_MATERIALIZE = frozenset({"numpy.asarray", "numpy.array",
                                   "jax.device_get"})

    @staticmethod
    def _is_jit_name(last: Optional[str]) -> bool:
        """``jax.jit`` / ``pjit`` / repo jit-wrapper convention
        (``_serving_jit``) — deliberately NOT a bare substring match, so
        a helper that merely mentions 'jit' mid-name is not a jit
        site."""
        return last is not None and (last in ("jit", "pjit")
                                     or last.endswith("_jit"))

    def _jit_bound_spans(self, ctx: ModuleContext):
        """Line spans of functions whose body runs under trace — the
        static approximation covers both idioms: a function NAME passed
        as the first positional argument of a jit call
        (``jax.jit(step_fn, ...)``, ``self._serving_jit(detect, ...)``)
        and decorator form (``@jax.jit`` / ``@partial(jax.jit, ...)``)."""
        defs: Dict[str, List] = {}
        spans: List = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            span = (node.lineno, node.end_lineno or node.lineno)
            defs.setdefault(node.name, []).append(span)
            for deco in node.decorator_list:
                target = deco
                if isinstance(deco, ast.Call):
                    # @partial(jax.jit, ...) / @jax.jit(...)
                    if deco.args and _last_component(
                            ctx, deco.func) == "partial":
                        target = deco.args[0]
                    else:
                        target = deco.func
                if self._is_jit_name(_last_component(ctx, target)):
                    spans.append(span)
        for call in _calls(ctx.tree):
            if self._is_jit_name(_last_component(ctx, call.func)) \
                    and call.args and isinstance(call.args[0], ast.Name):
                spans.extend(defs.get(call.args[0].id, ()))
        return spans

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.rel in self.allowed or ctx.rel not in self.hot_modules:
            return
        spans = self._jit_bound_spans(ctx)
        for call in _calls(ctx.tree):
            last = _last_component(ctx, call.func)
            if last == "block_until_ready":
                yield Violation(
                    rule=self.name, file=ctx.display, line=call.lineno,
                    message="block_until_ready in a hot-path module — "
                            "syncing belongs to obs/probe.py (or waive "
                            "with the reason the sync is load-bearing)")
                continue
            if last == "item" and not call.args and not call.keywords:
                yield Violation(
                    rule=self.name, file=ctx.display, line=call.lineno,
                    message=".item() forces a device round-trip per "
                            "scalar in a hot-path module")
                continue
            r = ctx.resolve(call.func)
            if r in self._HOST_MATERIALIZE and any(
                    s <= call.lineno <= e for s, e in spans):
                yield Violation(
                    rule=self.name, file=ctx.display, line=call.lineno,
                    message=f"{last}( inside a jit-bound function — host "
                            f"materialization on a tracer (move it out of "
                            f"the traced body or keep it jnp)")


@dataclasses.dataclass
class TaxonomyComplete:
    """Every exception class in ``resilience/errors.py`` must appear in
    exactly one of ``_RETRYABLE_CLASSES``/``FATAL_ERRORS`` — an error
    class outside both falls through ``run_resilient``'s retry filter
    with unconsidered semantics (the PR-3 contract, now static: the
    check runs without importing the module)."""

    name: str = "taxonomy-complete"
    target: str = "resilience/errors.py"
    registries: Sequence[str] = ("_RETRYABLE_CLASSES", "FATAL_ERRORS")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.rel != self.target:
            return
        classes: Dict[str, int] = {}
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef) and node.bases:
                classes[node.name] = node.lineno
        registered: Dict[str, int] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                target = node.targets[0].id
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                target = node.target.id
            else:
                continue
            if target not in self.registries:
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Name):
                        registered[elt.id] = node.lineno
        for name, lineno in sorted(classes.items()):
            if name not in registered:
                yield Violation(
                    rule=self.name, file=ctx.display, line=lineno,
                    message=f"error class {name} is in neither "
                            f"_RETRYABLE_CLASSES nor FATAL_ERRORS — "
                            f"classify it so run_resilient's retry filter "
                            f"has considered semantics")
        for name, lineno in sorted(registered.items()):
            if name not in classes:
                yield Violation(
                    rule=self.name, file=ctx.display, line=lineno,
                    message=f"registry names {name}, which is not an "
                            f"exception class defined in this module")


@dataclasses.dataclass
class RegisteredMetricNames:
    """Every ``registry.counter/gauge/histogram`` name used anywhere in
    the package must be declared once in the ``obs/names.py`` catalog —
    the registry accepts free-form strings, which is exactly how five
    generations of telemetry names drifted apart before PR 7.  The rule
    resolves statically: a literal name (or an f-string whose leading
    literal prefix pins the family, e.g. ``f"serve/latency_s/tier=
    {tier}"`` → ``serve/latency_s/tier=*``) must be covered by a
    catalog entry; a fully caller-parameterized name cannot be checked
    here and needs a reasoned ``# az-allow:`` waiver naming the
    canonical family it registers under (the standard waiver contract —
    the exemption is visible at the call site, and the catalog still
    documents the family).

    The catalog is read from the INSTALLED package's ``obs/names.py``
    by AST (``CATALOG`` dict-literal keys) — never imported, per the
    engine's no-execution discipline — so fixture scans of other roots
    still check against the real declaration."""

    name: str = "registered-metric-names"
    allowed: FrozenSet[str] = frozenset({
        "obs/registry.py",   # the substrate itself (names are params)
        "obs/names.py",      # the declaration
    })
    _METHODS = frozenset({"counter", "gauge", "histogram"})

    def _catalog(self) -> FrozenSet[str]:
        cached = getattr(self, "_catalog_cache", None)
        if cached is not None:
            return cached
        path = os.path.join(package_root(), "obs", "names.py")
        patterns: List[str] = []
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
            for node in ast.walk(tree):
                target = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    target = node.targets[0].id
                elif isinstance(node, ast.AnnAssign) \
                        and isinstance(node.target, ast.Name):
                    target = node.target.id
                if target != "CATALOG" or not isinstance(node.value,
                                                         ast.Dict):
                    continue
                for key in node.value.keys:
                    if isinstance(key, ast.Constant) \
                            and isinstance(key.value, str):
                        patterns.append(key.value)
        except (OSError, SyntaxError):   # pragma: no cover - repo intact
            pass
        out = frozenset(patterns)
        self._catalog_cache = out
        return out

    @staticmethod
    def _static_name(arg: ast.AST):
        """(resolved-name-or-pattern, fully_static) from the first call
        argument; (None, False) when no literal prefix exists."""
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value, True
        if isinstance(arg, ast.JoinedStr):
            prefix: List[str] = []
            for part in arg.values:
                if isinstance(part, ast.Constant) \
                        and isinstance(part.value, str):
                    prefix.append(part.value)
                else:
                    break
            p = "".join(prefix)
            return (p + "*", False) if p else (None, False)
        return None, False

    def _covered(self, name: str) -> bool:
        cat = self._catalog()
        if name in cat:
            return True
        if name.endswith("*"):
            p = name[:-1]
            return any(c.endswith("*") and p.startswith(c[:-1])
                       for c in cat)
        return any(c.endswith("*") and name.startswith(c[:-1])
                   for c in cat)

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.rel in self.allowed:
            return
        for call in _calls(ctx.tree):
            if not isinstance(call.func, ast.Attribute) \
                    or call.func.attr not in self._METHODS:
                continue
            if not call.args:
                continue
            resolved, _ = self._static_name(call.args[0])
            if resolved is None:
                yield Violation(
                    rule=self.name, file=ctx.display, line=call.lineno,
                    message=f".{call.func.attr}( name is not statically "
                            f"resolvable — declare the canonical family "
                            f"in obs/names.py and waive this "
                            f"caller-parameterized site with the family "
                            f"it registers under")
            elif not self._covered(resolved):
                yield Violation(
                    rule=self.name, file=ctx.display, line=call.lineno,
                    message=f"metric name {resolved!r} is not declared "
                            f"in the obs/names.py catalog — declare it "
                            f"(name, kind, one-line meaning) so the "
                            f"registry namespace stays documented")


def default_rules() -> List:
    return [OneClock(), OnePlacementSite(), SeededRngOnly(),
            NoHostSyncInHotPath(), TaxonomyComplete(),
            RegisteredMetricNames()]


#: name → rule instance (the default catalog the CLI runs).
SOURCE_RULES: Dict[str, object] = {r.name: r for r in default_rules()}


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


def package_root() -> str:
    """The ``analytics_zoo_tpu`` package directory (the default scan
    root)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _iter_py_files(root: str) -> Iterator[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                yield os.path.join(dirpath, fname)


def run_source_engine(root: Optional[str] = None,
                      rules: Optional[Sequence] = None) -> List[Violation]:
    """Parse every ``.py`` under ``root`` (default: the installed
    package), run every rule, apply waivers.  Returns ALL violations —
    waived ones carry ``waived=True``; callers gate on the un-waived
    subset.

    Rule path scopes (``allowed`` / ``hot_modules`` / ``target``) are
    PACKAGE-root-relative (``utils/clock.py``), so a ``root`` that
    merely *contains* the package (e.g. the repo checkout, ``--root .``)
    is normalized down to its ``analytics_zoo_tpu/`` directory — scanning
    from the wrong altitude would silently void every exemption and
    flag the sanctioned modules themselves."""
    root = os.path.abspath(root or package_root())
    nested = os.path.join(root, "analytics_zoo_tpu")
    if os.path.basename(root) != "analytics_zoo_tpu" \
            and os.path.isdir(nested):
        root = nested
    rules = list(rules) if rules is not None else default_rules()
    rootname = os.path.basename(root)
    out: List[Violation] = []
    for path in _iter_py_files(root):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        display = f"{rootname}/{rel}"
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            out.append(Violation(rule="parse-error", file=display,
                                 line=e.lineno or 0,
                                 message=f"syntax error: {e.msg}"))
            continue
        lines = source.splitlines()
        ctx = ModuleContext(rel=rel, display=display, tree=tree,
                            lines=lines, aliases=_import_aliases(tree))
        found: List[Violation] = []
        for rule in rules:
            found.extend(rule.check(ctx))
        waivers, malformed = parse_waivers(lines, display)
        out.extend(apply_waivers(found, waivers,
                                 active_rules=[r.name for r in rules]))
        out.extend(malformed)
    out.sort(key=lambda v: (v.file, v.line, v.rule))
    return out
