"""Checkpoint/resume via orbax — replaces BigDL's ``Module.save``/``load`` +
``OptimMethod.load`` snapshot files (reference ``ssd/example/Train.scala:161-163``
checkpoint path + ``optimizer.setCheckpoint(path, Trigger.everyEpoch)``).

Snapshot lifecycle (hardened — see docs/RESILIENCE.md):

1. orbax writes the pytree into a hidden temp dir (``.tmp_<name>``);
2. a ``manifest.json`` is written beside it with per-file sha256 +
   sizes and step/epoch metadata;
3. the snapshot is *published* with an atomic directory rename — a crash
   at ANY point before the rename leaves the previous snapshot intact;
4. ``keep_last=N`` garbage-collects the oldest ``step_N`` snapshots.

Layout: ``<path>/<'latest' or step_N>/{manifest.json, data/<orbax>}``.
Pre-manifest snapshots (bare orbax dirs) remain loadable.  Restore
verifies the manifest and, when the newest snapshot is truncated or
corrupt, automatically falls back to the newest older intact one.

Multi-host safe: orbax coordinates a single logical checkpoint across
processes; the manifest + publish rename are done by process 0 with a
cross-process barrier after.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from analytics_zoo_tpu.resilience.errors import CheckpointCorrupt

logger = logging.getLogger("analytics_zoo_tpu")

MANIFEST = "manifest.json"
_DATA_SUBDIR = "data"

# Fault-injection hook (chaos drills / tests): ``fn(phase, path)`` called
# at "pre_save" (before orbax writes), "pre_publish" (snapshot fully
# written, rename NOT yet done) and "post_publish".  An exception raised
# at pre_publish simulates a crash mid-save: the temp dir is left behind
# (cleaned by the next save) and the previous snapshot stays intact.
_fault_hook: Optional[Callable[[str, str], None]] = None


def set_fault_hook(fn: Optional[Callable[[str, str], None]]):
    """Install (or clear with ``None``) the save-path fault hook.
    Returns the previous hook so tests can restore it."""
    global _fault_hook
    prev, _fault_hook = _fault_hook, fn
    return prev


def _fire(phase: str, path: str) -> None:
    if _fault_hook is not None:
        _fault_hook(phase, path)


def _checkpointer():
    return ocp.PyTreeCheckpointer()


# ---------------------------------------------------------------------------
# Manifest
# ---------------------------------------------------------------------------


def _sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return h.hexdigest()
            h.update(b)


def _build_manifest(snap_dir: str, meta: Dict[str, Any]) -> Dict[str, Any]:
    files: Dict[str, Dict[str, Any]] = {}
    for root, _dirs, names in os.walk(snap_dir):
        for n in sorted(names):
            full = os.path.join(root, n)
            rel = os.path.relpath(full, snap_dir)
            if rel == MANIFEST:
                continue
            files[rel] = {"size": os.path.getsize(full),
                          "sha256": _sha256(full)}
    return {"format": 1, "meta": meta, "files": files}


def read_manifest(snap_dir: str) -> Optional[Dict[str, Any]]:
    """The snapshot's manifest dict, or ``None`` when it has none
    (pre-manifest layout or partially-written directory)."""
    p = os.path.join(snap_dir, MANIFEST)
    if not os.path.isfile(p):
        return None
    try:
        with open(p) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def verify_snapshot(snap_dir: str) -> Dict[str, Any]:
    """Check every manifest-listed file exists with the recorded size and
    sha256.  Returns the manifest; raises :class:`CheckpointCorrupt` with
    the first discrepancy."""
    man = read_manifest(snap_dir)
    if man is None:
        raise CheckpointCorrupt(f"{snap_dir}: manifest missing or unreadable")
    for rel, info in man.get("files", {}).items():
        full = os.path.join(snap_dir, rel)
        if not os.path.isfile(full):
            raise CheckpointCorrupt(f"{snap_dir}: missing file {rel}")
        size = os.path.getsize(full)
        if size != info["size"]:
            raise CheckpointCorrupt(
                f"{snap_dir}: {rel} truncated ({size} != {info['size']} bytes)")
        if _sha256(full) != info["sha256"]:
            raise CheckpointCorrupt(f"{snap_dir}: {rel} checksum mismatch")
    return man


# ---------------------------------------------------------------------------
# Save
# ---------------------------------------------------------------------------


def _state_step(host_state: Any) -> Optional[int]:
    step = getattr(host_state, "step", None)
    if step is None and isinstance(host_state, dict):
        step = host_state.get("step")
    if step is None:
        return None
    try:
        return int(np.asarray(step))
    except (TypeError, ValueError):
        return None


#: Snapshot tiers with their own named slot beside ``latest``/``step_N``.
#: ``lkg`` (last-known-good) is written by the anomaly sentinel only
#: after the health word has been clean for ``promote_after`` steps —
#: the rollback target of the numerical-anomaly ladder.  ``serve-lkg``
#: is its serving twin: promoted by the runtime's hot-swap machinery
#: only after a rollout has served clean decision windows, and the
#: rollback target of a tripped canary.  Tier slots are deliberately
#: NOT restore candidates for the normal resume path (``_candidates``):
#: a tier snapshot is typically OLDER than ``latest`` and must never
#: silently rewind an ordinary restart.
TIERS = ("lkg", "serve-lkg")


def save(path: str, state: Any, step: Optional[int] = None,
         keep_last: Optional[int] = None,
         meta: Optional[Dict[str, Any]] = None,
         tier: Optional[str] = None) -> str:
    """Save a pytree (TrainState or raw variables) atomically.

    ``step=None`` overwrites a single 'latest' snapshot (reference
    ``overWriteCheckpoint``); an integer publishes ``step_<step>`` and,
    with ``keep_last=N``, garbage-collects all but the newest N step
    snapshots.  ``meta`` (e.g. epoch/iteration) is recorded in the
    manifest beside the train-state step.  ``tier="lkg"`` publishes into
    the named tier slot instead (single overwrite slot per tier, same
    atomic temp-write → manifest → rename lifecycle).

    Multi-host: EVERY process must call this (orbax's save has internal
    cross-process barriers); replicated leaves are read from the local
    replica so the host conversion itself never blocks on a peer."""
    from analytics_zoo_tpu.parallel.mesh import host_local_state

    if tier is not None:
        if tier not in TIERS:
            raise ValueError(f"unknown checkpoint tier {tier!r}; "
                             f"one of {TIERS}")
        name = tier
    else:
        name = "latest" if step is None else f"step_{step}"
    base = os.path.abspath(path)
    target = os.path.join(base, name)
    os.makedirs(base, exist_ok=True)
    tmp = os.path.join(base, f".tmp_{name}")
    host_state = host_local_state(state)
    _fire("pre_save", target)
    # stale temps from crashed previous saves: ONE process sweeps them
    # ALL (step-tagged saves use a fresh .tmp_step_N each time, so a
    # same-name-only cleanup would leak a snapshot-sized dir per crash),
    # with a barrier before the collective write — unsynchronized rmtree
    # on shared storage could delete a peer's in-flight files
    if jax.process_index() == 0:
        for d in os.listdir(base):
            if d.startswith(".tmp_") and os.path.isdir(os.path.join(base, d)):
                shutil.rmtree(os.path.join(base, d))
    if jax.process_count() > 1:  # pragma: no cover - multi-host only
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"azr_ckpt_clean_{name}")
    _checkpointer().save(os.path.join(tmp, _DATA_SUBDIR), host_state,
                         force=True)
    if jax.process_index() == 0:
        man_meta = {"name": name, "step": step,
                    "state_step": _state_step(host_state)}
        if tier is not None:
            man_meta["tier"] = tier
        man_meta.update(meta or {})
        manifest = _build_manifest(tmp, man_meta)
        with open(os.path.join(tmp, MANIFEST), "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        _fire("pre_publish", target)
        # atomic publish: the old snapshot (if any) moves aside first, so
        # at no instant does `target` hold a half-written mixture.  The
        # trash slot is cleared ONLY when a live target needs to move
        # into it — after a crash between the two renames, trash holds
        # the sole intact snapshot (a restore candidate) and must
        # survive until this save actually publishes a replacement.
        trash = os.path.join(base, f".trash_{name}")
        if os.path.exists(target):
            if os.path.isdir(trash):
                shutil.rmtree(trash)
            os.rename(target, trash)
        os.rename(tmp, target)
        shutil.rmtree(trash, ignore_errors=True)
        _fire("post_publish", target)
        if keep_last is not None and step is not None:
            _gc_old_steps(base, keep_last)
    if jax.process_count() > 1:  # pragma: no cover - multi-host only
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"azr_ckpt_publish_{name}")
    return target


def _gc_old_steps(base: str, keep_last: int) -> None:
    steps = _step_dirs(base, require_manifest=False)
    doomed = steps[:-keep_last] if keep_last > 0 else steps
    for _n, d in doomed:
        logger.info("checkpoint GC: removing %s (keep_last=%d)", d, keep_last)
        shutil.rmtree(d, ignore_errors=True)


# ---------------------------------------------------------------------------
# Resolve / load
# ---------------------------------------------------------------------------


def _step_dirs(path: str, require_manifest: bool = True) -> List[Tuple[int, str]]:
    """``(step, dir)`` pairs ascending by step.  ``require_manifest``
    skips partially-written ``step_N`` entries (no manifest yet) — they
    are publish leftovers, never a restore candidate."""
    out: List[Tuple[int, str]] = []
    if not os.path.isdir(path):
        return out
    for d in os.listdir(path):
        if not d.startswith("step_"):
            continue
        try:
            n = int(d.split("_", 1)[1])
        except ValueError:
            continue
        full = os.path.join(path, d)
        if require_manifest and read_manifest(full) is None:
            logger.warning("checkpoint: skipping %s (no manifest — "
                           "partially written)", full)
            continue
        out.append((n, full))
    out.sort()
    return out


def latest_step(path: str, require_manifest: bool = True) -> Optional[int]:
    steps = _step_dirs(path, require_manifest=require_manifest)
    return steps[-1][0] if steps else None


def _recency(snap_dir: str, fallback: float) -> float:
    """Training-position sort key for a snapshot: manifest iteration,
    else the state's step counter, else ``fallback``."""
    man = read_manifest(snap_dir)
    if man is not None:
        meta = man.get("meta", {})
        # loop iteration first (the training position), then the step
        # tag; state_step last — it reflects the saved pytree's counter,
        # which raw-variable saves may not advance between snapshots
        for k in ("iteration", "step", "state_step"):
            v = meta.get(k)
            if v is not None:
                return float(v)
    return fallback


def _candidates(base: str) -> List[str]:
    """Restore candidates ordered by actual training recency (manifest
    iteration/step), newest first — NOT by slot name: a stale 'latest'
    overwrite slot must not outrank newer ``step_N`` snapshots when a
    job switched checkpointing modes.  ``.trash_*`` dirs come last as a
    dead-man's fallback — a crash in the tiny window between publish's
    two renames (old → trash, tmp → target) leaves the displaced-but-
    intact old snapshot ONLY in trash, and it must stay restorable."""
    ranked: List[Tuple[float, int, str]] = []
    latest = os.path.join(base, "latest")
    if os.path.isdir(latest):
        # a legacy manifest-less 'latest' keeps its old first-place rank
        ranked.append((_recency(latest, float("inf")), 1, latest))
    for n, d in _step_dirs(base, require_manifest=False):
        ranked.append((_recency(d, float(n)), 0, d))
    ranked.sort(key=lambda t: (t[0], t[1]), reverse=True)
    cands = [d for _r, _tie, d in ranked]
    if os.path.isdir(base):
        cands.extend(os.path.join(base, d) for d in sorted(os.listdir(base))
                     if d.startswith(".trash_")
                     and os.path.isdir(os.path.join(base, d)))
    return cands


def newest_intact(path: str) -> Optional[Tuple[str, Dict[str, Any]]]:
    """``(snapshot_dir, manifest)`` of the newest snapshot that passes
    verification, or ``None``.  Used by supervisors/drills to learn where
    a restart will resume from without restoring the full pytree."""
    for c in _candidates(os.path.abspath(path)):
        try:
            return c, verify_snapshot(c)
        except CheckpointCorrupt:
            continue
    return None


def tier_snapshot(path: str, tier: str) -> Optional[Tuple[str, Dict[str, Any]]]:
    """``(snapshot_dir, manifest)`` of a named tier slot when it exists
    AND verifies, else ``None``.  Tier slots are tracked separately from
    ``latest``/``step_N`` (never a normal resume candidate); they are the
    rollback targets of the anomaly ladder (``lkg``) and of the serving
    hot-swap canary (``serve-lkg``)."""
    if tier not in TIERS:
        raise ValueError(f"unknown checkpoint tier {tier!r}; one of {TIERS}")
    snap = os.path.join(os.path.abspath(path), tier)
    if not os.path.isdir(snap):
        return None
    try:
        return snap, verify_snapshot(snap)
    except CheckpointCorrupt as e:
        logger.warning("checkpoint: %s tier slot unusable (%s)", tier, e)
        return None


def lkg_snapshot(path: str) -> Optional[Tuple[str, Dict[str, Any]]]:
    """``(snapshot_dir, manifest)`` of the last-known-good tier slot when
    it exists AND verifies, else ``None``.  The LKG tier is tracked
    separately from ``latest``/``step_N`` (it is not a normal resume
    candidate); this is the anomaly ladder's rollback target."""
    return tier_snapshot(path, "lkg")


def promote_tier(path: str, snap_dir: str, tier: str) -> str:
    """Copy an already-published (and verifying) snapshot into a named
    tier slot with the same atomic temp-write → manifest → rename
    lifecycle as :func:`save`.  Unlike ``save(tier=...)`` this never
    re-serializes the pytree — it promotes the exact bytes that served
    (or trained) clean, which is the point of a last-known-good slot.

    The promoted copy's manifest records the source slot under
    ``meta.promoted_from``.  Returns the tier slot path."""
    if tier not in TIERS:
        raise ValueError(f"unknown checkpoint tier {tier!r}; one of {TIERS}")
    src = os.path.abspath(snap_dir)
    man = verify_snapshot(src)  # never promote bytes we can't vouch for
    base = os.path.abspath(path)
    target = os.path.join(base, tier)
    if src == target:
        return target  # already the tier slot
    tmp = os.path.join(base, f".tmp_{tier}")
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    _fire("pre_save", target)
    shutil.copytree(src, tmp)
    meta = dict(man.get("meta", {}))
    meta.update({"name": tier, "tier": tier,
                 "promoted_from": os.path.basename(src)})
    manifest = _build_manifest(tmp, meta)
    with open(os.path.join(tmp, MANIFEST), "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    _fire("pre_publish", target)
    trash = os.path.join(base, f".trash_{tier}")
    if os.path.exists(target):
        if os.path.isdir(trash):
            shutil.rmtree(trash)
        os.rename(target, trash)
    os.rename(tmp, target)
    shutil.rmtree(trash, ignore_errors=True)
    _fire("post_publish", target)
    return target


class CheckpointWatcher:
    """Poll-based "new checkpoint published" watch over a checkpoint
    directory — the serving side's view of a trainer that keeps
    publishing ``latest``/``step_N`` snapshots into shared storage.

    Construction baselines the current newest intact snapshot; each
    :meth:`poll` answers "has a DIFFERENT intact snapshot been published
    since the last poll?" by fingerprinting the manifest's per-file
    sha256 map (content identity, not mtime — atomic renames and GC make
    timestamps meaningless here).  Tier slots (``lkg``/``serve-lkg``)
    are never restore candidates, so a promotion or rollback does not
    retrigger the watcher."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        self._seen = self._fingerprint()[0]

    def _fingerprint(self) -> Tuple[Optional[str],
                                    Optional[Tuple[str, Dict[str, Any]]]]:
        found = newest_intact(self.path)
        if found is None:
            return None, None
        _snap, man = found
        digest = hashlib.sha256(json.dumps(
            {rel: info["sha256"] for rel, info in man.get("files", {}).items()},
            sort_keys=True).encode()).hexdigest()
        return digest, found

    def poll(self) -> Optional[Tuple[str, Dict[str, Any]]]:
        """``(snapshot_dir, manifest)`` of a newly-published intact
        snapshot, or ``None`` when nothing changed since the last poll
        (or construction).  Marks the returned snapshot as seen."""
        digest, found = self._fingerprint()
        if digest is None or digest == self._seen:
            return None
        self._seen = digest
        return found


def _restore(snap_dir: str, target: Any, verify: bool) -> Any:
    man = read_manifest(snap_dir)
    if man is not None:
        if verify:
            verify_snapshot(snap_dir)
        data_dir = os.path.join(snap_dir, _DATA_SUBDIR)
        if not os.path.isdir(data_dir):
            data_dir = snap_dir  # manifest written beside a flat snapshot
    else:
        data_dir = snap_dir  # pre-manifest layout: bare orbax dir
    if target is not None:
        return _checkpointer().restore(data_dir, item=target)
    return _checkpointer().restore(data_dir)


def load(path: str, target: Any = None, step: Optional[int] = None,
         verify: bool = True) -> Any:
    """Restore a checkpoint.  ``target`` (a matching pytree of arrays)
    fixes leaf types/shapes; without it, raw arrays are returned.

    ``step=None`` walks the candidates newest-first ('latest' overwrite
    slot, then ``step_N`` descending) and returns the first snapshot that
    verifies AND restores — a truncated/corrupt newest snapshot falls
    back to the newest intact older one (with a warning) instead of
    aborting.  ``step=<int>`` pins one snapshot: corruption there raises.
    ``verify=False`` skips checksum verification (fast path for huge
    snapshots on trusted storage).
    """
    base = os.path.abspath(path)
    if step is not None:
        return _restore(os.path.join(base, f"step_{step}"), target, verify)
    cands = _candidates(base)
    if not cands:
        # `path` itself is the snapshot (or a bare orbax dir)
        return _restore(base, target, verify)
    errors: List[str] = []
    for c in cands:
        try:
            out = _restore(c, target, verify)
            if errors:
                logger.warning("checkpoint: restored fallback %s after "
                               "rejecting newer snapshot(s): %s", c,
                               "; ".join(errors))
            return out
        except CheckpointCorrupt as e:
            logger.warning("checkpoint: %s", e)
            errors.append(str(e))
        except Exception as e:  # orbax-level failure on an unverified dir
            logger.warning("checkpoint: restore of %s failed (%s: %s)",
                           c, type(e).__name__, e)
            errors.append(f"{c}: {type(e).__name__}: {e}")
    raise CheckpointCorrupt(
        f"no intact snapshot under {base}: " + "; ".join(errors))


def restore_elastic(path: str, target: Any, specs,
                    step: Optional[int] = None, verify: bool = True) -> Any:
    """Restore a checkpoint saved at ANY world size and place it under
    ``specs`` (a :class:`~analytics_zoo_tpu.parallel.specs.SpecSet`,
    possibly width W′ ≠ the saving run's W).

    Checkpoints hold width-agnostic HOST values by construction
    (``mesh.host_local_state`` reads the local replica of every leaf
    before the atomic write), so elastic re-placement is exactly one
    ``place_state`` under the new declaration: parameters replicate,
    optimizer slots re-shard through the same path-matched rules as
    their parameters, and the (replicated) RNG key carries over bit-
    exactly — the per-step ``fold_in(rng, step)`` is width-invariant,
    so the restored stream continues where the W-wide run left it.

    Raises :class:`~analytics_zoo_tpu.resilience.errors.
    ElasticPlacementError` when the restored tree does not structure-
    match ``specs``' resolved spec tree (a model/checkpoint mismatch
    would otherwise surface as an opaque device_put failure), and
    propagates the same error from ``place_state`` when the mesh cannot
    carry the declaration's axes.
    """
    from analytics_zoo_tpu.resilience.errors import ElasticPlacementError

    try:
        state = load(path, target=target, step=step, verify=verify)
    except CheckpointCorrupt:
        if target is None:
            raise
        # disambiguate: an orbax key/structure mismatch against `target`
        # surfaces from load's fallback walk as CheckpointCorrupt.  If
        # the snapshot restores RAW, the bytes are intact and the
        # failure is a model/checkpoint mismatch — name it.
        raw = load(path, target=None, step=step, verify=verify)
        raise ElasticPlacementError(
            f"restore_elastic: snapshot is intact but does not "
            f"structure-match the target tree (snapshot top-level keys "
            f"{sorted(raw) if isinstance(raw, dict) else type(raw)}, "
            f"target {jax.tree_util.tree_structure(target)}) — wrong "
            f"model for this checkpoint, not corruption")
    spec_tree = specs.state_specs(state)
    got = jax.tree_util.tree_structure(state)
    want = jax.tree_util.tree_structure(spec_tree)
    if got != want:  # pragma: no cover - state_specs maps over state
        raise ElasticPlacementError(
            f"restore_elastic: restored state does not structure-match "
            f"the declared spec tree (state {got}, specs {want})")
    if target is not None:
        t_struct = jax.tree_util.tree_structure(target)
        if got != t_struct:
            raise ElasticPlacementError(
                f"restore_elastic: restored state does not structure-"
                f"match the target tree (state {got}, target {t_struct})")
    return specs.place_state(state)


def has_checkpoint(path: str) -> bool:
    """True when at least one restore candidate exists under ``path``
    (it may still fail verification — ``load`` handles fallback)."""
    return bool(_candidates(os.path.abspath(path)))
