"""Checkpoint/resume via orbax — replaces BigDL's ``Module.save``/``load`` +
``OptimMethod.load`` snapshot files (reference ``ssd/example/Train.scala:161-163``
checkpoint path + ``optimizer.setCheckpoint(path, Trigger.everyEpoch)``).

Layout: ``<path>/<step or 'latest'>/`` orbax PyTree checkpoint of the full
``TrainState`` (params, model_state, opt_state, step, rng).  Multi-host
safe: orbax coordinates a single logical checkpoint across processes.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp


def _checkpointer():
    return ocp.PyTreeCheckpointer()


def save(path: str, state: Any, step: Optional[int] = None) -> str:
    """Save a pytree (TrainState or raw variables). ``step=None`` overwrites
    a single 'latest' snapshot (reference ``overWriteCheckpoint``).

    Multi-host: EVERY process must call this (orbax's save has internal
    cross-process barriers); replicated leaves are read from the local
    replica so the host conversion itself never blocks on a peer."""
    from analytics_zoo_tpu.parallel.mesh import host_local_state

    name = "latest" if step is None else f"step_{step}"
    target = os.path.join(os.path.abspath(path), name)
    host_state = host_local_state(state)
    _checkpointer().save(target, host_state, force=True)
    return target


def load(path: str, target: Any = None, step: Optional[int] = None) -> Any:
    """Restore a checkpoint.  ``target`` (a matching pytree of arrays) fixes
    leaf types/shapes; without it, raw arrays are returned.

    ``step=None`` resolves to the 'latest' overwrite snapshot if present,
    else the highest ``step_N`` directory, else treats ``path`` itself as
    the checkpoint directory.
    """
    base = os.path.abspath(path)
    if step is not None:
        full = os.path.join(base, f"step_{step}")
    elif os.path.exists(os.path.join(base, "latest")):
        full = os.path.join(base, "latest")
    else:
        newest = latest_step(base)
        full = os.path.join(base, f"step_{newest}") if newest is not None else base
    if target is not None:
        return _checkpointer().restore(full, item=target)
    return _checkpointer().restore(full)


def latest_step(path: str) -> Optional[int]:
    if not os.path.isdir(path):
        return None
    steps = []
    for d in os.listdir(path):
        if d.startswith("step_"):
            try:
                steps.append(int(d.split("_", 1)[1]))
            except ValueError:
                pass
    return max(steps) if steps else None
