"""Distributed runtime: mesh/sharding, jitted train loop, optim, checkpointing.

The TPU-native replacement for the reference's BigDL DistriOptimizer + Spark
distribution stack (SURVEY.md §2.7 "Optimizer" and §5 "Distributed
communication backend").
"""

from analytics_zoo_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    SEQUENCE_AXIS,
    batch_sharding,
    batch_spec,
    create_mesh,
    replicate,
    replicated_sharding,
    shard_batch,
)
from analytics_zoo_tpu.parallel.optim import (
    SGD,
    Adam,
    AdamW,
    OptimMethod,
    Plateau,
    TrainingState,
    Trigger,
    multistep,
    polynomial,
    warmup_linear,
)
from analytics_zoo_tpu.parallel.train import (
    MAE,
    Loss,
    Optimizer,
    Top1Accuracy,
    TrainState,
    ValidationMethod,
    ValidationResult,
    create_train_state,
    make_eval_step,
    make_train_step,
    sparse_adam_apply,
    state_to_variables,
    validate,
)
from analytics_zoo_tpu.parallel.specs import (
    SpecSet,
    pipeline_specs,
    register_pipeline,
    registered_pipelines,
)
from analytics_zoo_tpu.parallel.summary import TrainSummary, ValidationSummary
from analytics_zoo_tpu.parallel import checkpoint
from analytics_zoo_tpu.parallel.expert import (
    moe_apply_dense,
    moe_apply_expert_parallel,
    route_top1,
)
from analytics_zoo_tpu.parallel.pipeline import (
    carrier_decay_mask,
    flatten_stage_params,
    flatten_stage_params_grouped,
    pipeline_forward,
    pipeline_forward_het,
    stage_carrier_slice,
    unflatten_stage,
    split_microbatches,
    stack_stage_params,
)
from analytics_zoo_tpu.parallel.tensor import (
    default_tp_rules,
    embedding_row_rules,
    megatron_tp_rules,
    spatial_input_spec,
    ssd_tp_rules,
    shard_tree,
    sharded_param_count,
)
from analytics_zoo_tpu.parallel.elastic import (
    RETRYABLE_ERRORS,
    DivergenceDetector,
    FaultInjector,
    TrainingDiverged,
    run_resilient,
)
from analytics_zoo_tpu.resilience import (
    FATAL_ERRORS,
    AnomalyPolicy,
    CheckpointCorrupt,
    ElasticPlacementError,
    InjectedFault,
    Preempted,
    PreemptionHandler,
    PrefetchWorkerDied,
    ShardReadError,
    StallError,
    StallWatchdog,
)

__all__ = [k for k in dir() if not k.startswith("_")]
