"""Failure detection + elastic restart supervision for the training loop.

The reference's recovery story is Spark task retry (it actually sets
``spark.task.maxFailures=1`` to fail fast, ``ssd/example/Train.scala:153``)
plus data-level tolerance (corrupt images flow through as invalid
features; MultiBoxLoss skips backward when loss > 50 — both ported, see
``FeatureTransformer`` and ``make_train_step(skip_loss_above=...)``).
What Spark provides for free — a supervisor that reruns lost work — has
no JAX equivalent, so this module supplies it TPU-natively:

- :class:`DivergenceDetector` — periodic host-side health check on the
  training loss (a non-finite streak means the run is dead even though
  the device happily keeps stepping; the skip-update guard makes such a
  run *stall* silently rather than crash).
- :func:`run_resilient` — a restart supervisor around the
  :class:`~analytics_zoo_tpu.parallel.train.Optimizer`: on a retryable
  failure (device/runtime error, stall, preemption) it rebuilds the
  whole program via the caller's factory and resumes from the latest
  orbax checkpoint, up to ``max_restarts`` times.  Rebuilding matters on
  TPU: after a device reset or relay drop the old compiled executables
  and live buffers are garbage; a fresh ``Optimizer`` re-traces and
  re-replicates from the restored host-side state.

Fault injection for tests: :class:`FaultInjector` wraps a dataset and
raises a chosen exception at a chosen global batch index, once.  The
full chaos matrix (SIGTERM, mid-save kill, snapshot corruption, stalls,
transient XLA errors on a schedule) lives in
:mod:`analytics_zoo_tpu.resilience.chaos`.
"""

from __future__ import annotations

import logging
import math
from typing import Callable, Optional, Sequence, Tuple, Type

from analytics_zoo_tpu.resilience.errors import (
    InjectedFault,
    TrainingDiverged,
    retryable_errors,
)

logger = logging.getLogger("analytics_zoo_tpu")


#: Failures worth restarting for: preemption, stalls, dead input
#: pipelines, injected chaos, and jaxlib device/runtime errors.
#: Deliberately NOT ``RuntimeError`` — a bare RuntimeError is usually a
#: programming error and must propagate on attempt 1.  ``TrainingDiverged``
#: moved OUT of this tuple (resilience/errors.py classifies it fatal):
#: restarting resumes from the same checkpoint into the same divergence,
#: and the in-loop anomaly ladder (``resilience.anomaly``) already owns
#: the recoverable part of that failure class.
RETRYABLE_ERRORS: Tuple[Type[BaseException], ...] = retryable_errors()


class DivergenceDetector:
    """Checks the host-synced loss every ``check_every`` iterations; a run
    of ``max_bad_checks`` consecutive non-finite readings raises
    :class:`TrainingDiverged`.  Checking is periodic, not per-step, so the
    device pipeline is only forced to sync ~1/``check_every`` of the time."""

    def __init__(self, check_every: int = 50, max_bad_checks: int = 3):
        if check_every < 1:
            raise ValueError("check_every must be >= 1")
        self.check_every = check_every
        self.max_bad_checks = max_bad_checks
        self._bad = 0

    def should_check(self, iteration: int) -> bool:
        return iteration % self.check_every == 0

    def check(self, loss: float, iteration: int) -> None:
        if math.isfinite(loss):
            self._bad = 0
            return
        self._bad += 1
        logger.warning("non-finite loss %s at iteration %d (%d/%d strikes)",
                       loss, iteration, self._bad, self.max_bad_checks)
        if self._bad >= self.max_bad_checks:
            raise TrainingDiverged(
                f"loss non-finite for {self._bad} consecutive checks "
                f"(every {self.check_every} iterations)")

    def reset(self) -> None:
        self._bad = 0


def run_resilient(
    build_optimizer: Callable[[], "object"],
    checkpoint_path: str,
    max_restarts: int = 3,
    retry_on: Optional[Tuple[Type[BaseException], ...]] = None,
    on_restart: Optional[Callable[[int, BaseException], None]] = None,
):
    """Supervised training: ``build_optimizer()`` must return a fresh,
    fully-configured :class:`Optimizer` each attempt.  The supervisor
    forces checkpointing to ``checkpoint_path`` (every epoch with
    ``keep_last=3`` step snapshots, unless the optimizer already
    configured one) and resume-from-latest, so each restart continues
    where the last checkpoint left off rather than from scratch.
    Returns the trained model.

    ``retry_on`` filters which failures are retryable; it defaults to
    :data:`RETRYABLE_ERRORS` (preemption, stalls, device/runtime
    errors).  Programming errors — ``TypeError``, ``ValueError``, and
    notably *bare* ``RuntimeError`` — propagate on attempt 1 so real
    bugs are never masked by restart churn; ``TrainingDiverged`` is
    likewise fatal (the in-loop anomaly ladder owns numerical recovery —
    restarting into the same divergence cannot help).
    """
    from analytics_zoo_tpu.parallel.optim import Trigger

    if retry_on is None:
        retry_on = RETRYABLE_ERRORS
    attempt = 0
    while True:
        opt = build_optimizer()
        if opt.checkpoint_trigger is None:
            # step-tagged snapshots (not the single overwrite slot): a
            # corrupted newest snapshot can then fall back to an older
            # intact one instead of losing the run
            opt.set_checkpoint(checkpoint_path, Trigger.every_epoch(),
                               overwrite=False, keep_last=3)
        # resume from wherever checkpoints actually land — the optimizer
        # may have configured its own path different from the supervisor's
        opt.set_resume(opt.checkpoint_path)
        try:
            return opt.optimize()
        except retry_on as e:  # type: ignore[misc]
            attempt += 1
            if attempt > max_restarts:
                logger.error("giving up after %d restarts: %s", max_restarts, e)
                raise
            logger.warning("training attempt %d failed (%s: %s); restarting "
                           "from latest checkpoint (%d/%d)",
                           attempt, type(e).__name__, e, attempt, max_restarts)
            if on_restart is not None:
                on_restart(attempt, e)


class FaultInjector:
    """Dataset wrapper that raises ``exc`` just before yielding global
    batch index ``fail_at`` (counted across epochs), exactly once —
    simulating a mid-training device loss / preemption for tests.  The
    default exception is :class:`InjectedFault` (retryable); pass a bare
    ``ValueError``/``RuntimeError`` to simulate a genuine bug instead.
    For multi-fault schedules use ``resilience.chaos.ChaosMonkey``."""

    def __init__(self, dataset, fail_at: int,
                 exc: Optional[BaseException] = None):
        self.dataset = dataset
        self.fail_at = fail_at
        self.exc = exc or InjectedFault("injected fault")
        self._count = 0
        self._fired = False

    def __iter__(self):
        for batch in self.dataset:
            if not self._fired and self._count == self.fail_at:
                self._fired = True
                raise self.exc
            self._count += 1
            yield batch
