"""TensorBoard summaries — reference ``TrainSummary``/``ValidationSummary``
(``ssd/example/Train.scala:237-243``; notebook
``set_summary_trigger("Parameters", SeveralIteration(50))``).

Backed by tensorboardX event files; per-tag triggers gate how often a tag is
written.  Multi-host: only process 0 writes (metrics are already global
since the loss/metrics come out of the psum'd step).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax

from analytics_zoo_tpu.parallel.optim import TrainingState, Trigger


class _Summary:
    def __init__(self, log_dir: str, app_name: str, kind: str):
        self.log_dir = os.path.join(log_dir, app_name, kind)
        self._writer = None
        self.triggers: Dict[str, Trigger] = {}

    @property
    def writer(self):
        if self._writer is None and jax.process_index() == 0:
            from tensorboardX import SummaryWriter

            os.makedirs(self.log_dir, exist_ok=True)
            self._writer = SummaryWriter(self.log_dir)
        return self._writer

    def set_summary_trigger(self, tag: str, trigger: Trigger) -> "_Summary":
        self.triggers[tag] = trigger
        return self

    def _gated(self, tag: str, iteration: int) -> bool:
        t = self.triggers.get(tag)
        if t is None:
            return True
        # summary gating is iteration-granular (the reference's notebook use
        # is SeveralIteration); epoch_finished=True keeps everyEpoch-style
        # triggers from silently never firing here
        state = TrainingState(iteration=iteration, epoch_finished=True)
        return t(state)

    def add_scalar(self, tag: str, value, iteration: int) -> None:
        """``value`` may be a device array: it is only forced to a host
        float AFTER the trigger gate, so gated-off iterations never pay a
        device→host sync (expensive when the accelerator is remote)."""
        if self.writer is not None and self._gated(tag, iteration):
            self.writer.add_scalar(tag, float(value), iteration)

    def add_histogram(self, tag: str, values, iteration: int) -> None:
        if self.writer is not None and self._gated(tag, iteration):
            self.writer.add_histogram(tag, values, iteration)

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None


class TrainSummary(_Summary):
    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "train")


class ValidationSummary(_Summary):
    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "validation")
