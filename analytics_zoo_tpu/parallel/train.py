"""Train/eval step factories + the distributed Optimizer.

This is the TPU-native replacement for BigDL's ``DistriOptimizer`` stack
(reference ``Optimizer(model, trainSet, criterion).setOptimMethod
.setValidation.setCheckpoint.setTrainSummary.setEndWhen.optimize()``,
``ssd/example/Train.scala:219-252``).  Where BigDL runs a Spark job per
iteration — executor model replicas, block-manager AllReduce, driver-side
weight update — here the whole iteration is ONE jitted function: batches
arrive sharded over the mesh's ``data`` axis, parameters are replicated, and
XLA compiles the gradient mean into an ICI all-reduce.  There is no
parameter server and no explicit communication code in the loss path.

The host-side loop (this file's ``Optimizer.optimize``) only does what must
stay on host: data feeding, triggers, validation, checkpointing, summaries,
and metric-driven LR control (Plateau).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from analytics_zoo_tpu.core.module import Model, accepted_kwargs
from analytics_zoo_tpu.parallel import mesh as mesh_lib
from analytics_zoo_tpu.parallel.optim import (
    Adam,
    OptimMethod,
    TrainingState,
    Trigger,
)

logger = logging.getLogger("analytics_zoo_tpu")


class TrainState(struct.PyTreeNode):
    """Everything the jitted step mutates, as one donated pytree."""

    step: jax.Array
    params: Any
    model_state: Any          # batch_stats & friends (may be empty dict)
    opt_state: Any
    rng: jax.Array


def create_train_state(model: Model, optim: OptimMethod, rng=0) -> TrainState:
    if model.variables is None:
        raise ValueError("model.build(...) before creating a train state")
    if isinstance(rng, int):
        rng = jax.random.PRNGKey(rng)
    variables = dict(model.variables)
    params = variables.pop("params")
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        model_state=variables,
        opt_state=optim.tx.init(params),
        rng=rng,
    )


def state_to_variables(state: TrainState):
    return {"params": state.params, **state.model_state}


def _forward(module, variables, inputs, train: bool, rngs=None, mutable=False):
    args = inputs if isinstance(inputs, (tuple, list)) else (inputs,)
    kwargs = accepted_kwargs(module, {"train": train})
    if rngs:
        kwargs["rngs"] = rngs
    if mutable:
        return module.apply(variables, *args, mutable=["batch_stats"], **kwargs)
    return module.apply(variables, *args, **kwargs), None


def _call_criterion(criterion, output, batch):
    """Criterion protocol: ``crit(output, target)`` with optional ``mask``;
    plain callables instead take ``(output, batch)`` for full control."""
    from analytics_zoo_tpu.core.criterion import Criterion

    if isinstance(criterion, Criterion):
        target = batch.get("target")
        if "target_mask" in batch:
            return criterion(output, target, mask=batch["target_mask"])
        return criterion(output, target)
    return criterion(output, batch)


def cast_floating(tree: Any, dtype) -> Any:
    """Cast every floating-point leaf of a pytree to ``dtype``."""
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        tree,
    )


def resolve_compute_dtype(compute_dtype):
    """'bf16'/'fp32'/None/dtype → jnp dtype or None (no casting)."""
    if compute_dtype is None or compute_dtype in ("fp32", "float32"):
        return None
    if compute_dtype in ("bf16", "bfloat16"):
        return jnp.bfloat16
    return jnp.dtype(compute_dtype)


def make_train_step(
    module,
    criterion: Callable,
    optim: OptimMethod,
    mesh=None,  # legacy hint; pass specs= for annotated in/out shardings
    specs=None,
    state=None,
    annotate_batches: bool = True,
    loss_scale: float = 1.0,
    grad_clip_norm: Optional[float] = None,
    skip_loss_above: Optional[float] = None,
    compute_dtype=None,
    grad_accum: int = 1,
    device_transform: Optional[Callable] = None,
    forward_fn: Optional[Callable] = None,
    health_check: bool = False,
    skip_unhealthy: bool = False,
    metric_fn: Optional[Callable] = None,
):
    """Build the jitted train step.

    ``specs`` (optional, a :class:`~analytics_zoo_tpu.parallel.specs.
    SpecSet`): the pipeline's declare-once sharding.  The step is then
    jitted with explicit ``in_shardings``/``out_shardings`` — state and
    metrics carry the declared NamedShardings, and (single-process, no
    per-key batch overrides) HOST batches can be passed straight in:
    jit itself places them dim-0 over the ``data`` axis, so no pipeline
    calls ``device_put``/``shard_batch`` anywhere.  With tensor-parallel
    rules armed, pass the concrete ``state`` too (per-leaf specs need
    the tree structure).  Batch leaves must be batch-major arrays (the
    ``shard_batch`` contract); for batches carrying 0-d leaves pass
    ``annotate_batches=False`` (state/metrics keep their declared
    shardings, batches arrive pre-placed by ``specs.place_batch``,
    whose documented contract replicates scalars) — the Optimizer does
    this automatically when it meets such a batch.

    ``metric_fn`` (optional): ``metric_fn(batch) → {name: scalar}``,
    fused into the compiled step and merged into the returned metrics —
    e.g. the length-bucketed DS2 path reports ``padding_efficiency``
    (valid / padded frames) per step from the batch's ``n_frames``.

    ``device_transform`` (optional) is fused INTO the compiled step: the
    batch passes through it on-device before the loss (used for the
    device-side augmentation path — halves per-step dispatches and
    avoids materializing the transformed batch in HBM between calls).

    ``forward_fn`` (optional) replaces the default ``module.apply``
    forward: ``forward_fn(variables, inputs, train, rngs) → (output,
    new_model_state)``.  Used for parallel-forward variants whose
    program differs from the plain apply — e.g. the sequence-parallel
    DS2 forward (``models.deepspeech2.make_sequence_parallel_forward_fn``)
    that shards T over a ("data", "sequence") mesh inside the step.

    ``skip_loss_above`` reproduces MultiBoxLoss's gradient-explosion guard
    (reference ``common/nn/MultiBoxLoss.scala:546``: skip backward when
    loss > 50) — the update is zeroed when the loss exceeds the threshold,
    as a lax.cond-free masked select so the step stays a single program.

    ``health_check=True`` adds the anomaly sentinel's in-graph health
    fold (``resilience.anomaly``): one fused isfinite-and-threshold
    reduction over the loss, the (unscaled, clipped) grads, and the
    UPDATED params, emitted as ``metrics["health"]`` — an int32 word
    whose per-tree-section bits name which parameter subtree went
    non-finite (``decode_health``).  ``skip_unhealthy=True`` additionally
    discards the whole update in-graph whenever the word is non-zero —
    params, optimizer slots AND batch stats keep their pre-step values —
    subsuming ``skip_loss_above`` (which becomes the word's spike bit).

    ``compute_dtype='bf16'`` enables mixed precision: parameters stay fp32
    masters (the optimizer update is fp32), the forward/backward runs in
    bfloat16 — convs/matmuls hit the MXU at its native rate — and model
    outputs are cast back to fp32 before the criterion so softmax/log
    numerics are unaffected.  bf16 shares fp32's exponent range, so the
    default ``loss_scale=1.0`` is safe (unlike fp16); the scale hook stays
    plumbed for experimentation.  This replaces the reference's MKL-tuned
    kernels as the fast-kernel story (``pipeline/ssd/pom.xml:73-83``).

    ``grad_accum=N`` splits the batch into N microbatches and accumulates
    their gradients with a ``lax.scan`` inside the SAME jitted step —
    activation memory drops ~N× (large effective batches on one chip)
    while the update equals the full-batch step exactly for mean-reduced
    losses.  BatchNorm running stats are chained through the N
    microbatches sequentially (the EMA advances N times per step — same
    data seen, faster-moving stats than a single full-batch update).
    """

    cdtype = resolve_compute_dtype(compute_dtype)

    def loss_fn(params, model_state, batch, rng):
        if cdtype is not None:
            params_c = cast_floating(params, cdtype)
            inputs = cast_floating(batch["input"], cdtype)
        else:
            params_c, inputs = params, batch["input"]
        variables = {"params": params_c, **model_state}
        if forward_fn is not None:
            output, new_model_state = forward_fn(
                variables, inputs, train=True, rngs={"dropout": rng})
            new_model_state = new_model_state or {}
        else:
            output, new_model_state = _forward(
                module, variables, inputs, train=True,
                rngs={"dropout": rng}, mutable=True,
            )
        if cdtype is not None:
            output = cast_floating(output, jnp.float32)
            # batch stats remain fp32 masters
            new_model_state = cast_floating(new_model_state, jnp.float32)
        loss = _call_criterion(criterion, output, batch)
        return loss * loss_scale, (new_model_state, loss)

    def _grads(params, model_state, batch, rng):
        """(grads, model_state, loss) — single-shot or scan-accumulated."""
        if grad_accum <= 1:
            g, (ms, loss) = jax.grad(loss_fn, has_aux=True)(
                params, model_state, batch, rng)
            return g, ms, loss
        # every batch leaf must be batch-major with the SAME dim 0,
        # divisible by grad_accum — a silent reshape of a shared (non-
        # batch) leaf would feed each microbatch a slice of it
        sizes = {getattr(leaf, "shape", (None,))[0] if getattr(
            leaf, "ndim", 0) > 0 else None
            for leaf in jax.tree_util.tree_leaves(batch)}
        if None in sizes or len(sizes) != 1:
            raise ValueError(
                f"grad_accum needs batch-major array leaves with one "
                f"common dim 0, got leading dims {sizes}")
        (B,) = sizes
        if B % grad_accum:
            raise ValueError(f"batch size {B} not divisible by "
                             f"grad_accum={grad_accum} (pad or "
                             f"drop_remainder the tail batch)")
        micro = jax.tree_util.tree_map(
            lambda x: x.reshape((grad_accum, B // grad_accum) + x.shape[1:]),
            batch)

        # only the mutable collection rides the scan carry — constant
        # collections in model_state would mismatch the returned structure
        mut0 = ({"batch_stats": model_state["batch_stats"]}
                if "batch_stats" in model_state else {})

        def body(carry, inp):
            g_acc, loss_acc, mut = carry
            mb, j = inp
            g, (new_mut, l) = jax.grad(loss_fn, has_aux=True)(
                params, {**model_state, **mut}, mb,
                jax.random.fold_in(rng, j))
            g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
            return (g_acc, loss_acc + l, new_mut), None

        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        (g_sum, loss_sum, mut), _ = jax.lax.scan(
            body, (zeros, 0.0, mut0), (micro, jnp.arange(grad_accum)))
        inv = 1.0 / grad_accum
        return (jax.tree_util.tree_map(lambda g: g * inv, g_sum),
                mut, loss_sum * inv)

    def step_fn(state: TrainState, batch, lr_scale):
        if device_transform is not None:
            # fused in-graph (e.g. the device-side augmentation): ONE
            # compiled program and one dispatch per step instead of
            # transform + step as separate calls — a jitted transform
            # passed here simply inlines during tracing.  stop_gradient
            # marks the batch constant w.r.t. params so autodiff/remat
            # never recomputes the transform in the backward pass.
            batch = jax.lax.stop_gradient(device_transform(batch))
        rng, new_rng = jax.random.split(jax.random.fold_in(state.rng, state.step))
        grads, new_model_state, loss = _grads(
            state.params, state.model_state, batch, rng)
        if loss_scale != 1.0:
            grads = jax.tree_util.tree_map(lambda g: g / loss_scale, grads)
        gnorm = optax.global_norm(grads) if grad_clip_norm else None
        if grad_clip_norm:
            scale = jnp.minimum(1.0, grad_clip_norm / (gnorm + 1e-6))
            grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
        lr = optim.lr_for_step(state.step, lr_scale)
        opt_state = _set_lr(state.opt_state, lr)
        updates, new_opt_state = optim.tx.update(grads, opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, "lr": lr}
        if metric_fn is not None:
            metrics.update(metric_fn(batch))
        # merge: mutable apply only returns the batch_stats collection; any
        # other collection in model_state must survive untouched
        merged_model_state = {**state.model_state, **new_model_state}
        health = None
        if health_check or skip_unhealthy:
            from analytics_zoo_tpu.resilience import anomaly

            health = anomaly.tree_health_word(
                loss, grads, new_params,
                anomaly.health_sections(state.params),
                spike_loss_above=skip_loss_above)
            metrics["health"] = health
        def masked(keep, new, old):
            """Elementwise select: the update applies only where ``keep``."""
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(keep, n, o), new, old)

        if skip_unhealthy:
            # anomaly-sentinel guard: ANY non-finite loss/grad/param (or
            # a loss spike past skip_loss_above) discards the entire
            # update — params, optimizer slots and batch stats keep their
            # pre-step values, so a poison batch can never seed NaNs into
            # the training state
            keep = health == 0
            new_params = masked(keep, new_params, state.params)
            new_opt_state = masked(keep, new_opt_state, opt_state)
            merged_model_state = masked(keep, merged_model_state,
                                        state.model_state)
        elif skip_loss_above is not None:
            # reference guard (MultiBoxLoss.scala:546): a loss spike skips
            # the ENTIRE update — params and optimizer state (momentum/Adam
            # moments, counts) stay untouched, not just zeroed grads
            keep = loss <= skip_loss_above
            new_params = masked(keep, new_params, state.params)
            new_opt_state = masked(keep, new_opt_state, opt_state)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            model_state=merged_model_state,
            opt_state=new_opt_state,
            rng=new_rng,
        )
        return new_state, metrics

    donate = (0,)
    if specs is not None:
        # declare-once substrate: the ONLY sharding source is the
        # pipeline's SpecSet — state in/out carry its NamedShardings,
        # batches ride the data-axis prefix (jit transfers host arrays
        # itself on the single-process fast path), scalars (lr_scale,
        # every metric) are replicated
        state_sh = specs.state_shardings(state)
        return jax.jit(
            step_fn, donate_argnums=donate,
            in_shardings=(state_sh,
                          (specs.batch_shardings() if annotate_batches
                           else None),
                          specs.replicated),
            out_shardings=(state_sh, specs.replicated))
    return jax.jit(step_fn, donate_argnums=donate)


def _set_lr(opt_state, lr):
    """Write the traced LR into optax's injected hyperparams slot."""
    if hasattr(opt_state, "hyperparams"):
        hp = dict(opt_state.hyperparams)
        hp["learning_rate"] = lr
        return opt_state._replace(hyperparams=hp)
    return opt_state


def make_eval_step(module, compute_dtype=None, specs=None):
    """Jitted inference step: ``outputs = eval_step(variables, inputs)``.

    ``compute_dtype='bf16'`` runs the forward in bfloat16 (serving-path
    mixed precision) with outputs cast back to fp32.

    ``specs`` (a :class:`~analytics_zoo_tpu.parallel.specs.SpecSet`):
    mesh-annotated serving — jit places the variables replicated and the
    batch dim-0 over the ``data`` axis, so a serving forward scales out
    by widening the mesh with no predictor code change (the same
    declare-once substrate the train step consumes).
    """

    cdtype = resolve_compute_dtype(compute_dtype)

    def eval_fn(variables, inputs):
        if cdtype is not None:
            variables = dict(variables)
            variables["params"] = cast_floating(variables["params"], cdtype)
            inputs = cast_floating(inputs, cdtype)
        out, _ = _forward(module, variables, inputs, train=False)
        if cdtype is not None:
            out = cast_floating(out, jnp.float32)
        return out

    if specs is not None:
        # ragged tail batches (dim 0 not divisible by the data axis)
        # run the un-annotated program — validation/predict sets keep
        # their remainder batches; the routing rule lives in the spec
        # layer so every annotated serving program shares it
        return specs.ragged_dispatch(
            jax.jit(eval_fn, in_shardings=(specs.replicated,
                                           specs.batch_shardings())),
            jax.jit(eval_fn))
    return jax.jit(eval_fn)


# ---------------------------------------------------------------------------
# Validation methods (BigDL ValidationMethod monoid, SURVEY.md §2.7)
# ---------------------------------------------------------------------------


class ValidationResult:
    """Mergeable (monoid) metric accumulator — reference
    ``common/DetectionResult.scala:57`` ``+``-reduce across partitions."""

    def __init__(self, value: float, count: float, name: str):
        self.value = value
        self.count = count
        self.name = name

    def __add__(self, other: "ValidationResult") -> "ValidationResult":
        return ValidationResult(self.value + other.value, self.count + other.count,
                                self.name)

    def result(self) -> float:
        return self.value / max(self.count, 1e-12)

    def __repr__(self):
        return f"{self.name}: {self.result():.6f} ({int(self.count)} samples)"


class ValidationMethod:
    name = "validation"

    def __call__(self, output, batch) -> ValidationResult:  # pragma: no cover
        raise NotImplementedError


class Top1Accuracy(ValidationMethod):
    name = "Top1Accuracy"

    def __call__(self, output, batch):
        target = np.asarray(batch["target"]).reshape(-1)
        pred = np.asarray(jnp.argmax(output, axis=-1)).reshape(-1)
        mask = np.asarray(batch.get("target_mask", np.ones_like(target))).reshape(-1)
        correct = float(np.sum((pred == target) * mask))
        return ValidationResult(correct, float(mask.sum()), self.name)


class Loss(ValidationMethod):
    name = "Loss"

    def __init__(self, criterion):
        self.criterion = criterion

    def __call__(self, output, batch):
        n = np.asarray(batch["target"]).shape[0]
        loss = float(_call_criterion(self.criterion, output, batch))
        return ValidationResult(loss * n, n, self.name)


class MAE(ValidationMethod):
    """Mean absolute error on the argmax class (the recommender notebook's
    validation metric over 5 rating classes)."""

    name = "MAE"

    def __call__(self, output, batch):
        target = np.asarray(batch["target"]).reshape(-1).astype(np.float32)
        pred = np.asarray(jnp.argmax(output, axis=-1)).reshape(-1).astype(np.float32)
        return ValidationResult(float(np.abs(pred - target).sum()), target.size,
                                self.name)


# ---------------------------------------------------------------------------
# The Optimizer (host loop)
# ---------------------------------------------------------------------------


class Optimizer:
    """BigDL-``Optimizer``-shaped trainer over a mesh.

    Usage (mirrors ``ssd/example/Train.scala:219-252``)::

        opt = (Optimizer(model, train_set, criterion, mesh=mesh)
               .set_optim_method(SGD(lr, momentum=0.9, plateau=...))
               .set_validation(Trigger.every_epoch(), val_set, [Top1Accuracy()])
               .set_checkpoint(path, Trigger.every_epoch())
               .set_train_summary(TrainSummary(logdir, app))
               .set_end_when(Trigger.max_epoch(250)))
        trained_model = opt.optimize()
    """

    def __init__(self, model: Model, dataset, criterion, mesh=None,
                 skip_loss_above: Optional[float] = None,
                 grad_clip_norm: Optional[float] = None,
                 compute_dtype=None, device_transform=None,
                 param_rules=None, prefetch: int = 0,
                 grad_accum: int = 1, forward_fn=None,
                 batch_overrides=None, metric_fn=None, specs=None,
                 clock=None):
        from analytics_zoo_tpu.parallel.specs import SpecSet
        from analytics_zoo_tpu.utils.clock import as_now_fn

        # epoch/throughput timing reads the ONE injected clock (utils.
        # clock, az-analyze one-clock rule) — a VirtualClock makes the
        # records/s epoch log deterministic in drills
        self._now = as_now_fn(clock)

        self.model = model
        self.dataset = dataset
        self.criterion = criterion
        self.compute_dtype = compute_dtype
        # jitted on-device batch rewrite (e.g. the device-augmentation
        # program, transform/vision/device.py) applied after sharding
        self.device_transform = device_transform
        # the declare-once sharding substrate (parallel.specs): EVERY
        # placement this loop performs — state replication/TP sharding,
        # batch feeds, the step's jit in/out shardings — flows through
        # one SpecSet.  `param_rules`/`batch_overrides` remain as sugar
        # that BUILDS the SpecSet, so legacy callers land on the same
        # single path.
        if specs is not None:
            if mesh is not None and mesh is not specs.mesh:
                raise ValueError("pass mesh= OR specs= (the SpecSet "
                                 "carries its mesh), not conflicting both")
            if param_rules is not None or batch_overrides is not None:
                raise ValueError("param_rules/batch_overrides are the "
                                 "legacy sugar for building a SpecSet — "
                                 "declare them inside specs= instead")
            self.specs = specs
        else:
            self.specs = SpecSet(mesh or mesh_lib.create_mesh(),
                                 rules=param_rules,
                                 batch_overrides=batch_overrides)
        self.mesh = self.specs.mesh
        self.optim: OptimMethod = Adam(1e-3)
        self.end_when: Trigger = Trigger.max_epoch(1)
        self.val_trigger: Optional[Trigger] = None
        self.val_dataset = None
        self.val_methods: Sequence[ValidationMethod] = ()
        self.checkpoint_path: Optional[str] = None
        self.checkpoint_trigger: Optional[Trigger] = None
        self.overwrite_checkpoint = False
        self.train_summary = None
        self.val_summary = None
        self.skip_loss_above = skip_loss_above
        self.grad_clip_norm = grad_clip_norm
        # views onto the SpecSet (back-compat attribute surface)
        self.param_rules = self.specs.rules
        # > 0: shard+transfer batches on a background thread, staying
        # `prefetch` ahead of the device (data.prefetch double-buffering,
        # SURVEY.md §3.1 HOT LOOP #1 overlap)
        self.prefetch = prefetch
        # > 1: accumulate gradients over N microbatches inside the step
        self.grad_accum = grad_accum
        # custom forward (make_train_step forward_fn hook), e.g. the
        # sequence-parallel DS2 program
        self.forward_fn = forward_fn
        # in-graph extra step metrics (make_train_step metric_fn hook),
        # e.g. the bucketed DS2 padding_efficiency report
        self.metric_fn = metric_fn
        # per-key PartitionSpec overrides for shard_batch, e.g.
        # {"input": tensor.spatial_input_spec()} for spatial TP
        self.batch_overrides = self.specs.batch_overrides
        if self.batch_overrides and prefetch:
            raise ValueError("batch_overrides is not supported with "
                             "prefetch (the prefetch path shards with "
                             "the default data-axis specs)")
        self._score_name: Optional[str] = None
        self.resume_path: Optional[str] = None
        self._resume_requested = False
        self.failure_detector = None
        self.preemption_handler = None
        self.stall_watchdog = None
        self.checkpoint_keep_last: Optional[int] = None
        self.epoch_hook = None
        self._skip_batches = 0      # mid-epoch resume fast-forward
        self._iter_in_epoch = 0
        # elastic resume: GLOBAL sample offset into the current epoch.
        # Checkpoint meta records it so a restore under a DIFFERENT
        # world size / batch geometry re-seeks the deterministic stream
        # by sample coordinate instead of batch count (the PR-8 resume
        # bug generalized — see docs/PARALLELISM.md "Elastic resize").
        self._samples_in_epoch = 0
        self._skip_samples: Optional[int] = None
        self.anomaly_policy = None
        self._anomaly = None        # AnomalySentinel, built per optimize()
        self.health_policy = None
        self._health = None         # HealthSentinel, built per optimize()
        self._audit_fn = None       # jitted parity audit, built lazily
        self._shadow_fn = None      # jitted shadow forward, built lazily
        self.obs = None             # obs.Observability (set_observability)

    # -- fluent config (reference API names, snake_cased) ------------------
    def set_optim_method(self, m: OptimMethod) -> "Optimizer":
        self.optim = m
        return self

    def set_end_when(self, t: Trigger) -> "Optimizer":
        self.end_when = t
        return self

    def set_validation(self, trigger: Trigger, dataset,
                       methods: Sequence[ValidationMethod],
                       score_name: Optional[str] = None) -> "Optimizer":
        self.val_trigger = trigger
        self.val_dataset = dataset
        self.val_methods = list(methods)
        self._score_name = score_name or (methods[0].name if methods else None)
        return self

    def set_checkpoint(self, path: str, trigger: Trigger,
                       overwrite: bool = True,
                       keep_last: Optional[int] = None) -> "Optimizer":
        """``overwrite=True`` keeps one 'latest' snapshot; ``False``
        publishes ``step_N`` snapshots, with ``keep_last=N`` retention GC
        (older snapshots are fallbacks when the newest is corrupt)."""
        self.checkpoint_path = path
        self.checkpoint_trigger = trigger
        self.overwrite_checkpoint = overwrite
        self.checkpoint_keep_last = keep_last
        return self

    def set_preemption_handler(self, handler=None) -> "Optimizer":
        """Trap SIGTERM/SIGINT during ``optimize()``: the loop finishes
        the in-flight step, takes a forced checkpoint at the boundary,
        and raises a retryable ``Preempted`` (see docs/RESILIENCE.md)."""
        from analytics_zoo_tpu.resilience.preempt import PreemptionHandler
        self.preemption_handler = handler or PreemptionHandler()
        return self

    def set_stall_watchdog(self, watchdog) -> "Optimizer":
        """Raise ``StallError`` (instead of hanging forever) when the
        loop makes no progress within a deadline.  Pass a
        ``StallWatchdog`` or a float timeout in seconds; the heartbeat is
        per-phase (step / validation / checkpoint save), so size it to
        cover the slowest SINGLE legitimate phase — including the
        first-step XLA compile and the full snapshot write."""
        from analytics_zoo_tpu.resilience.watchdog import StallWatchdog
        if not hasattr(watchdog, "beat"):
            watchdog = StallWatchdog(float(watchdog))
        self.stall_watchdog = watchdog
        return self

    def set_anomaly_policy(self, policy=None) -> "Optimizer":
        """Arm the training anomaly sentinel (``resilience.anomaly``):
        the jitted step folds an in-graph health word over loss / grads /
        updated params, unhealthy updates are discarded in-graph, and
        the host ladder escalates — skip → rollback to the
        last-known-good checkpoint tier (+ deterministic re-seek past
        the bad region) → fatal ``TrainingDiverged`` after
        ``max_rollbacks``.  A forensics bundle (``anomaly_<step>.json``)
        is written on the first bad step of each episode; replay it with
        ``tools/replay_batch.py``.  Rollback needs ``set_checkpoint`` so
        the LKG tier has somewhere to live.  Costs one device→host
        round trip per step (health word + loss fetched together)."""
        from analytics_zoo_tpu.resilience.anomaly import AnomalyPolicy
        self.anomaly_policy = policy or AnomalyPolicy()
        return self

    def set_health_policy(self, policy=None) -> "Optimizer":
        """Arm the device-health sentinel (``resilience.health``): every
        ``audit_every`` steps an in-graph per-replica param fingerprint
        (one shard_map program, no per-step cost) is fetched at the
        decision boundary and compared — data-parallel replicas must be
        bit-identical post-all-reduce, so a divergence proves silent
        data corruption and the minority vote names the device; every
        ``shadow_every`` steps the current microbatch's forward is
        recomputed on a second device and the output fingerprints
        compared (a third device breaks ties when available).  A named
        suspect raises retryable ``DeviceQuarantine`` — pair with
        ``set_anomaly_policy`` + ``set_checkpoint`` so the supervisor
        can rebuild on the surviving devices from the LKG tier
        (``health.evict_device`` + elastic resume); an unattributable
        divergence raises fatal ``SdcDetected``.  Default policy audits
        every 8 steps; all knobs default off on an un-armed Optimizer."""
        from analytics_zoo_tpu.resilience.health import HealthPolicy
        self.health_policy = policy or HealthPolicy(audit_every=8)
        return self

    def set_observability(self, obs=None) -> "Optimizer":
        """Arm the telemetry spine (:class:`analytics_zoo_tpu.obs.
        Observability`): per-step spans at their loader coordinates
        (trace id ``train-e<epoch>-b<batch>``), checkpoint save/restore
        spans, ``train/dispatch/*`` metrics via
        :class:`~analytics_zoo_tpu.utils.profiling.StepTimer`, and
        anomaly-ladder counters — all in the shared registry/flight
        recorder.  On ``TrainingDiverged`` (ladder OR failure detector)
        the recorder dumps its ring (the black box) to ``obs.dump_path``
        when one is configured.

        Timing semantics: the step span and ``train/dispatch/step_s``
        cover the HOST interval of the train-step call — jax dispatch
        is asynchronous, so without a per-step sync this is dispatch
        latency, not device wall time (with the anomaly sentinel armed
        its per-step health fetch makes it ≈wall).  A deliberate
        choice: fencing every step to measure it would serialize the
        pipeline the PR-2 work overlapped.  For the fenced
        dispatch/device/input-wait decomposition use
        :class:`analytics_zoo_tpu.obs.StepProbe` on a probe run.
        Cost is banked by ``bench.py obs_overhead`` (≤ 3 % per step);
        ``None`` builds a default bundle."""
        from analytics_zoo_tpu.obs import Observability
        self.obs = obs or Observability()
        return self

    def set_resume(self, path: Optional[str] = None) -> "Optimizer":
        """Resume from the latest checkpoint under ``path`` (defaults to the
        ``set_checkpoint`` path, resolved at ``optimize()`` time so the
        fluent-call order doesn't matter) when one exists — the reference's
        ``--model``/``--state`` snapshot restart (``Train.scala:161-163``)."""
        self.resume_path = path
        self._resume_requested = True
        return self

    def set_epoch_hook(self, fn) -> "Optimizer":
        """``fn(loop, state)`` after each completed epoch (post
        validation/checkpoint) — e.g. an mAP-trajectory probe that runs a
        detector assembly the ``ValidationMethod`` protocol can't express.
        ``state`` params are live device arrays; pass them straight into a
        jitted eval to avoid a host round-trip."""
        self.epoch_hook = fn
        return self

    def set_failure_detector(self, detector) -> "Optimizer":
        """Periodic loss-health check (``parallel.elastic.DivergenceDetector``);
        raises out of ``optimize()``.  Ignored while an anomaly policy is
        armed: the sentinel discards bad updates in-graph, so the
        detector would read discarded steps' NaN losses and raise fatal
        ``TrainingDiverged`` before the ladder could roll back."""
        self.failure_detector = detector
        return self

    def set_train_summary(self, summary) -> "Optimizer":
        self.train_summary = summary
        return self

    def set_validation_summary(self, summary) -> "Optimizer":
        self.val_summary = summary
        return self

    # -- loop --------------------------------------------------------------
    def optimize(self) -> Model:
        state = create_train_state(self.model, self.optim)
        loop = TrainingState()
        if self._resume_requested:
            resume_base = self.resume_path or self.checkpoint_path
            if resume_base:
                state, loop = self._try_resume(resume_base, state, loop)
        state = self._place_state(state)
        anomaly_on = self.anomaly_policy is not None
        spike = self.skip_loss_above
        if anomaly_on and self.anomaly_policy.spike_loss_above is not None:
            spike = self.anomaly_policy.spike_loss_above
        def build_step(annotate_batches=True):
            return make_train_step(
                self.model.module, self.criterion, self.optim,
                specs=self.specs, state=state,
                annotate_batches=annotate_batches,
                skip_loss_above=spike,
                grad_clip_norm=self.grad_clip_norm,
                compute_dtype=self.compute_dtype,
                grad_accum=self.grad_accum,
                device_transform=self.device_transform,
                forward_fn=self.forward_fn,
                health_check=anomaly_on,
                skip_unhealthy=anomaly_on and self.anomaly_policy.skip,
                metric_fn=self.metric_fn,
            )

        train_step = build_step()
        # built lazily the first time a batch carries a 0-d leaf: the
        # data-axis batch annotation cannot express "replicate this
        # scalar", so such batches ride an un-annotated-batch variant
        # of the SAME step, pre-placed by specs.place_batch (whose
        # documented contract replicates scalars)
        scalar_step = [None]
        if anomaly_on:
            from analytics_zoo_tpu.resilience.anomaly import (
                AnomalySentinel, health_sections)
            self._anomaly = AnomalySentinel(
                self.anomaly_policy,
                sections=health_sections(
                    mesh_lib.host_local_state(state.params)))
            if (self.anomaly_policy.promote_initial
                    and self.checkpoint_path is not None):
                # seed the last-known-good tier with the (trivially
                # healthy) starting state so a rollback ALWAYS has a
                # target, even before the first clean-streak promotion
                from analytics_zoo_tpu.parallel import checkpoint as ckpt
                if ckpt.lkg_snapshot(self.checkpoint_path) is None:
                    self._promote_lkg(loop, state)
        eval_step = make_eval_step(
            self.model.module, compute_dtype=self.compute_dtype,
            # validation rides the same substrate: replicated variables
            # + data-axis batches via jit in_shardings.  A mesh spanning
            # processes keeps the un-annotated path (host arrays cannot
            # be jit-placed across processes), and tensor-parallel rules
            # keep theirs (a replicated prefix would all-gather the
            # sharded params every call).
            specs=(self.specs
                   if (self.specs.rules is None
                       and not mesh_lib.spans_processes(self.mesh))
                   else None))
        # telemetry spine: the tracer/StepTimer pair is None-checked on
        # the hot path so an un-instrumented loop pays nothing
        obs = self.obs
        tracer = obs.tracer if obs is not None else None
        step_timer = None
        if obs is not None:
            from analytics_zoo_tpu.utils.profiling import StepTimer
            # "dispatch" named honestly: async dispatch returns before
            # the device finishes (see set_observability docstring)
            step_timer = StepTimer("train/dispatch", registry=obs.registry)
        self._health = None
        # the jitted audit/shadow programs close over the mesh and the
        # forward fn — a reused Optimizer may have swapped either (the
        # elastic replace_mesh path), so they rebuild per optimize()
        # alongside the sentinel, never across calls
        self._audit_fn = None
        self._shadow_fn = None
        if (self.health_policy is not None
                and (self.health_policy.audit_every > 0
                     or self.health_policy.shadow_every > 0)):
            from analytics_zoo_tpu.resilience.health import HealthSentinel
            self._health = HealthSentinel(
                self.health_policy,
                registry=obs.registry if obs is not None else None)
        if self.prefetch:
            from analytics_zoo_tpu.data.prefetch import device_prefetch
        # single-process, no per-key overrides: host batches go straight
        # into the annotated jit (its in_shardings do the placement)
        jit_places = self.specs.jit_places_batches()
        batch_annotated = self.specs.batch_shardings() is not None

        def _has_scalar_leaf(b):
            return any(getattr(leaf, "ndim", 0) == 0
                       for leaf in jax.tree_util.tree_leaves(b))

        ph = self.preemption_handler
        wd = self.stall_watchdog
        if ph is not None:
            ph.stall_watchdog = wd   # stall interrupts beat preemption
            ph.install()
        if wd is not None:
            wd.start()
        t_epoch = self._now()
        records = 0
        stop = False
        sentinel = object()
        try:
            while not stop and not self.end_when(loop):
                loop.epoch_finished = False
                host_iter = iter(self.dataset)
                # mid-epoch resume: fast-forward past already-trained batches
                # ON THE HOST — never shard/transfer data that will be
                # dropped.  Elastic resume (meta carried the GLOBAL sample
                # offset): consume by sample count so a stream re-batched
                # under a different world size lands on the same global
                # coordinate; the offset must land on a batch boundary of
                # the NEW stream or the geometries are incompatible.
                while self._skip_samples is not None and self._skip_samples > 0:
                    b = next(host_iter, sentinel)
                    if b is sentinel:
                        break
                    n_skip = _batch_size(b)
                    if n_skip > self._skip_samples:
                        raise ValueError(
                            f"elastic resume: checkpointed sample offset "
                            f"leaves {self._skip_samples} samples to skip "
                            f"but the next batch holds {n_skip} — the "
                            f"offset does not land on a batch boundary of "
                            f"the resumed stream (incompatible global "
                            f"batch geometry)")
                    self._skip_samples -= n_skip
                    self._samples_in_epoch += n_skip
                    self._iter_in_epoch += 1
                self._skip_samples = None
                while self._skip_batches > 0:
                    b = next(host_iter, sentinel)
                    if b is sentinel:
                        break
                    self._skip_batches -= 1
                    self._samples_in_epoch += _batch_size(b)
                    self._iter_in_epoch += 1
                # close_source: the prefetch worker thread closes
                # host_iter itself on cancel/end — a consumer-side close
                # could land while the thread is inside next(host_iter)
                epoch_batches = (device_prefetch(host_iter, self.mesh,
                                                 self.prefetch,
                                                 close_source=True)
                                 if self.prefetch else host_iter)
                epoch_iter = iter(epoch_batches)
                try:
                    for batch in epoch_iter:
                        n = _batch_size(batch)
                        # prefetch path: already sharded on the worker
                        # thread.  jit fast path: the annotated step's
                        # in_shardings place the HOST batch (one
                        # transfer, no explicit device_put).  Otherwise
                        # (per-key overrides, multi-process mesh) the
                        # spec layer assembles the device batch.  A
                        # batch with a 0-d leaf takes the lazily-built
                        # un-annotated-batch step (the data-axis prefix
                        # is invalid for rank-0; place_batch replicates
                        # scalars, preserving the shard_batch contract).
                        step_fn = train_step
                        if batch_annotated and _has_scalar_leaf(batch):
                            if scalar_step[0] is None:
                                scalar_step[0] = build_step(
                                    annotate_batches=False)
                            step_fn = scalar_step[0]
                            dev_batch = (batch if self.prefetch
                                         else self.specs.place_batch(batch))
                        else:
                            dev_batch = (batch if (self.prefetch
                                                   or jit_places)
                                         else self.specs.place_batch(batch))
                        # device_transform is fused INSIDE train_step
                        step_span = None
                        if tracer is not None:
                            # loader coordinates ARE the trace identity:
                            # the same (epoch, batch) replays as the
                            # same trace under the PR-2 determinism
                            # contract
                            step_span = tracer.start(
                                "train_step",
                                f"train-e{loop.epoch}"
                                f"-b{self._iter_in_epoch}",
                                iteration=loop.iteration + 1,
                                epoch=loop.epoch,
                                batch=self._iter_in_epoch)
                        try:
                            if step_timer is None:
                                state, metrics = step_fn(
                                    state, dev_batch, self.optim.lr_scale)
                            else:
                                with step_timer.step(n):
                                    state, metrics = step_fn(
                                        state, dev_batch,
                                        self.optim.lr_scale)
                        except BaseException as e:
                            # an exception escaping the step (XLA error,
                            # watchdog interrupt) must still CLOSE the
                            # span — spans reach the flight recorder on
                            # end(), and the crashed step is exactly the
                            # event the black box exists to capture
                            if step_span is not None:
                                step_span.end(
                                    status="error",
                                    error=f"{type(e).__name__}: {e}")
                            raise
                        loop.iteration += 1
                        self._iter_in_epoch += 1
                        self._samples_in_epoch += n
                        records += n
                        # keep the loss as a device array — only force a host
                        # sync when something host-side actually reads it
                        loop.loss = metrics["loss"]
                        if self._anomaly is not None:
                            # skip / rollback / diverge ladder; may
                            # replace `state` (rollback restores the
                            # last-known-good tier), consume re-seek
                            # batches from epoch_iter, and reset
                            # loop.loss/health after a rollback
                            state = self._anomaly_step(
                                loop, state, metrics, dev_batch,
                                epoch_iter, step_span=step_span)
                        elif (self.failure_detector is not None
                                and self.failure_detector.should_check(
                                    loop.iteration)):
                            # detector only when NO sentinel is armed:
                            # the sentinel discards bad updates in-graph,
                            # so feeding the detector a discarded step's
                            # NaN loss would raise fatal TrainingDiverged
                            # before the ladder could roll back
                            try:
                                self.failure_detector.check(
                                    float(metrics["loss"]), loop.iteration)
                            except Exception as e:
                                # same black-box contract as the ladder
                                # path: a diverged run dumps the ring
                                # before propagating
                                if (step_span is not None
                                        and not step_span.ended):
                                    step_span.end(
                                        status="error",
                                        error=f"{type(e).__name__}: {e}")
                                if obs is not None:
                                    obs.recorder.note(
                                        "training_diverged",
                                        iteration=loop.iteration)
                                    obs.dump("training_diverged")
                                raise
                        if self._health is not None:
                            # parity audit / shadow recompute at their
                            # cadences; a confirmed bad device raises
                            # DeviceQuarantine (retryable — supervisor
                            # rebuilds on survivors), unattributable
                            # corruption raises fatal SdcDetected
                            try:
                                self._health_step(loop, state, dev_batch)
                            except Exception as e:
                                if (step_span is not None
                                        and not step_span.ended):
                                    step_span.end(
                                        status="error",
                                        error=f"{type(e).__name__}: {e}")
                                if obs is not None:
                                    obs.recorder.note(
                                        "device_health",
                                        iteration=loop.iteration)
                                    obs.dump("device_health")
                                raise
                        if step_span is not None and not step_span.ended:
                            step_span.end(status="ok")
                        if self.train_summary is not None:
                            # device arrays on purpose: add_scalar floats them
                            # only when the tag's trigger fires
                            self.train_summary.add_scalar(
                                "Loss", metrics["loss"], loop.iteration)
                            self.train_summary.add_scalar(
                                "LearningRate", metrics["lr"], loop.iteration)
                        self._boundary_checks(loop, state, eval_step,
                                              wd, ph)
                        if self.end_when(loop):
                            stop = True
                            break
                finally:
                    # early exit (end_when break / detector raise): release
                    # the prefetch worker and its HBM-pinned queued batches;
                    # close_source above hands host_iter (possibly a
                    # multiprocess loader epoch owning worker processes)
                    # to the prefetch thread for closing
                    if hasattr(epoch_batches, "close"):
                        epoch_batches.close()
                if stop:
                    break  # partial epoch: don't count or re-trigger it
                loop.epoch += 1
                loop.epoch_finished = True
                self._iter_in_epoch = 0
                self._samples_in_epoch = 0
                loop.loss = float(loop.loss)
                dt = self._now() - t_epoch
                logger.info("Epoch %d done: %d records in %.1fs (%.1f records/s), loss %.4f",
                            loop.epoch, records, dt, records / max(dt, 1e-9), loop.loss)
                t_epoch, records = self._now(), 0
                self._boundary_checks(loop, state, eval_step, wd, ph)
                if self.epoch_hook is not None:
                    self.epoch_hook(loop, state)
        except KeyboardInterrupt:
            # the stall watchdog signals via a main-thread interrupt; a
            # REAL Ctrl-C (watchdog quiet) keeps its usual meaning
            self._raise_if_stalled(wd, loop)
            raise
        finally:
            if wd is not None:
                wd.stop()
            if ph is not None:
                ph.uninstall()
        # write trained variables back into the model wrapper (local-
        # replica read: safe on a mesh spanning processes)
        host_state = mesh_lib.host_local_state(state)
        self.model.variables = state_to_variables(host_state)
        self._last_state = host_state
        return self.model

    # -- helpers -----------------------------------------------------------
    def _maybe_validate(self, loop: TrainingState, state: TrainState, eval_step):
        if self.val_trigger is None or not self.val_trigger(loop):
            return
        # iteration-based triggers stay true at the epoch boundary; don't
        # re-validate (and double-count toward Plateau patience) at the same
        # iteration the in-loop pass already handled
        if getattr(self, "_last_val_iter", None) == loop.iteration:
            return
        self._last_val_iter = loop.iteration
        variables = state_to_variables(state)
        results = validate(self.model.module, variables, self.val_dataset,
                           self.val_methods, eval_step=eval_step)
        metrics = {r.name: r.result() for r in results}
        for name, value in metrics.items():
            logger.info("Validation @ iter %d: %s = %.5f", loop.iteration, name, value)
            if self.val_summary is not None:
                self.val_summary.add_scalar(name, value, loop.iteration)
        if self._score_name and self._score_name in metrics:
            loop.score = metrics[self._score_name]
            self.optim.on_validation({"score": loop.score, **metrics})

    def _boundary_checks(self, loop: TrainingState, state: TrainState,
                         eval_step, wd, ph) -> None:
        """Everything that runs at a step/epoch boundary, in order:
        validation, checkpoint, stall classification, preemption.  Kept
        in ONE place so step and epoch boundaries cannot drift apart.

        Per-phase heartbeats: the step, the validation pass, and the
        (sha256-hashed) checkpoint save each get their own deadline
        window — size the watchdog for the slowest SINGLE phase.  Stall
        beats preempt: the watchdog's interrupt may have been absorbed
        by the signal handler as a preempt request, so it must be
        re-classified before the preemption check."""
        if wd is not None:
            wd.beat()
        self._maybe_validate(loop, state, eval_step)
        if wd is not None:
            wd.beat()
        self._maybe_checkpoint(loop, state)
        self._raise_if_stalled(wd, loop)
        if wd is not None:
            wd.beat()
        if ph is not None and self._preempt_agreed(ph, loop):
            self._graceful_preempt(loop, state)

    def _raise_if_stalled(self, wd, loop: TrainingState) -> None:
        if wd is None or not wd.stalled:
            return
        from analytics_zoo_tpu.resilience.errors import StallError

        # absorb the watchdog's simulated SIGINT if it is still pending
        # (the monitor sets `stalled` a moment before interrupt_main; a
        # boundary check landing in that window would otherwise leave a
        # stray KeyboardInterrupt to pop in unrelated code later)
        try:
            time.sleep(0.2)
        except KeyboardInterrupt:
            pass
        raise StallError(
            f"no training progress past the {wd.timeout_s:.1f}s stall "
            f"deadline at iteration {loop.iteration}")

    #: multi-host boundaries between preemption-agreement collectives —
    #: bounds the graceful-response latency to this many steps while
    #: keeping the per-step hot path free of cross-host syncs
    preempt_sync_every: int = 16

    def _preempt_agreed(self, ph, loop: TrainingState) -> bool:
        """Whether to act on a preemption request at this boundary.
        Multi-host: the request flags are OR-reduced across hosts — a
        signal landing on ANY process (single-pod eviction, per-host
        OOM-kill) makes EVERY process enter the forced final checkpoint,
        which is a COLLECTIVE save, at the same step boundary.  The
        agreement gather is itself a cross-host sync, so it runs only
        every ``preempt_sync_every`` iterations (a replicated,
        deterministic schedule), not on every step."""
        if jax.process_count() == 1:
            return ph.requested
        if loop.iteration % max(self.preempt_sync_every, 1):
            return False  # pragma: no cover - multi-host only
        from jax.experimental import multihost_utils  # pragma: no cover

        flags = multihost_utils.process_allgather(
            np.asarray([ph.requested]))  # pragma: no cover
        return bool(np.any(flags))  # pragma: no cover

    def _graceful_preempt(self, loop: TrainingState, state: TrainState):
        """Step-boundary response to SIGTERM/SIGINT: force a final
        checkpoint, then raise the retryable ``Preempted`` so a
        supervisor (or the job's next incarnation) resumes from it.
        Preemption is a terminal condition for THIS incarnation, so the
        flight recorder dumps its ring alongside the boundary
        checkpoint — the preemption drill carries a black box of the
        steps leading into the signal, same as a divergence does."""
        from analytics_zoo_tpu.resilience.errors import Preempted

        saved = False
        if self.checkpoint_path is not None:
            saved = bool(self._maybe_checkpoint(loop, state, force=True))
        if self.obs is not None:
            self.obs.recorder.note(
                "preempted", iteration=loop.iteration, epoch=loop.epoch,
                checkpoint_saved=saved)
            if self.obs.dump_path:
                self.obs.dump("preempted")
        raise Preempted(
            f"preemption signal received at iteration {loop.iteration}; "
            + ("final checkpoint written"
               if saved else
               "NO final checkpoint written (no path configured, already "
               "saved this iteration, or loss non-finite) — resume falls "
               "back to the previous snapshot"))

    def _place_state(self, state: TrainState) -> TrainState:
        """Host/state pytree → mesh placement through the declared
        SpecSet (tensor-parallel rules when declared, else full
        replication).  The ONE placement decision, shared by the initial
        `optimize()` setup and the anomaly rollback restore so they can
        never drift."""
        return self.specs.place_state(state)

    # -- anomaly sentinel (resilience.anomaly ladder) ----------------------
    def _anomaly_step(self, loop: TrainingState, state: TrainState,
                      metrics, dev_batch, epoch_iter,
                      step_span=None) -> TrainState:
        """Per-step ladder: feed the health word to the sentinel, write
        forensics on an episode's first bad step, roll back / escalate.
        Returns the (possibly restored) state.  ``step_span`` (telemetry
        spine): closed here with the ladder's verdict so the flight
        recorder names unhealthy steps; ladder actions also count into
        the shared registry, and a diverged run dumps the black box
        before raising."""
        from analytics_zoo_tpu.resilience import anomaly as anomaly_lib
        from analytics_zoo_tpu.resilience.errors import TrainingDiverged

        sent = self._anomaly
        obs = self.obs
        # ONE device->host round trip for both scalars (the sentinel's
        # documented per-step host cost)
        word, loss_host = jax.device_get((metrics["health"],
                                          metrics["loss"]))
        word = int(word)
        loop.health = word
        sent.record_loss(float(loss_host))
        action, first = sent.observe(word)
        if step_span is not None:
            step_span.end(status="ok" if word == 0 else "unhealthy",
                          **({} if word == 0
                             else {"health_word": word, "action": action}))
        if obs is not None and word:
            obs.registry.counter("train/anomaly/bad_steps").inc()
        if word:
            sent.note_skip(word, step=loop.iteration)
            logger.warning(
                "anomaly sentinel: unhealthy step at iteration %d "
                "(word %#x, %d consecutive): %s", loop.iteration, word,
                sent.consecutive_bad,
                anomaly_lib.decode_health(word, sent.sections))
        if first:
            self._write_forensics(sent, word, loop, state, dev_batch)
        if action == "rollback":
            if obs is not None:
                obs.registry.counter("train/anomaly/rollbacks").inc()
            state = self._anomaly_rollback(loop, state)
            self._reseek(epoch_iter, sent.policy.reseek)
        elif action == "diverged":
            if obs is not None:
                # terminal condition: the ring becomes the black box
                obs.recorder.note(
                    "training_diverged", iteration=loop.iteration,
                    health_word=word,
                    rollbacks=sent.rollbacks,
                    consecutive_bad=sent.consecutive_bad)
                obs.dump("training_diverged")
            raise TrainingDiverged(
                f"anomaly ladder exhausted at iteration {loop.iteration}: "
                f"{sent.consecutive_bad} consecutive unhealthy steps with "
                f"the rollback budget spent ({sent.rollbacks}/"
                f"{sent.policy.max_rollbacks}); last health "
                f"{anomaly_lib.decode_health(word, sent.sections)}; "
                f"forensics bundles: {sent.forensics_paths or 'none'}")
        elif (action == "ok" and sent.should_promote()
                and self.checkpoint_path is not None):
            self._promote_lkg(loop, state)
        if word and action != "diverged" and sent.policy.skip:
            # with in-graph skip armed the LIVE state after a bad step is
            # provably clean (the update was discarded; a rollback just
            # restored the promoted LKG tier) — clear the word and swap
            # the discarded step's (usually non-finite) loss for the last
            # finite reading, so the checkpoint guards don't refuse to
            # persist a clean state (e.g. a preemption-forced snapshot
            # landing inside a bad-data window).  Without skip the
            # update DID apply, so the guards must keep refusing.
            loop.health = 0
            finite = [v for v in sent.loss_history if np.isfinite(v)]
            if finite:
                loop.loss = finite[-1]
        return state

    # -- device-health sentinel (resilience.health) ------------------------
    def _health_step(self, loop: TrainingState, state: TrainState,
                     dev_batch) -> None:
        """Run the armed detectors at their cadences.  The audit is one
        pre-built jitted program fetched with a single ``jax.device_get``
        at the decision boundary (the ``_anomaly_step`` host-cost
        contract) — steps between audits pay nothing.  Raises
        ``DeviceQuarantine`` (named suspect, eviction budget permitting)
        or ``SdcDetected`` (proven but unattributable corruption)."""
        from analytics_zoo_tpu.resilience import health as health_lib
        from analytics_zoo_tpu.resilience.errors import (DeviceQuarantine,
                                                         SdcDetected)

        pol = self.health_policy
        sent = self._health
        step = loop.iteration
        flip = health_lib.active_bit_flip() or (-1, 0, 0)
        if pol.audit_every > 0 and step % pol.audit_every == 0:
            if self._audit_fn is None:
                self._audit_fn = health_lib.make_audit_fn(self.mesh)
            target, element, bit = flip
            fps = jax.device_get(self._audit_fn(
                state.params, jnp.int32(target), jnp.int32(element),
                jnp.int32(bit)))
            verdict = sent.observe_audit(step, [int(v) for v in fps])
            self._health_verdict(loop, verdict, "parity audit",
                                 DeviceQuarantine, SdcDetected)
        if pol.shadow_every > 0 and step % pol.shadow_every == 0:
            devices = list(self.mesh.devices.flat)
            if len(devices) >= 2:
                verdict = self._shadow_check(step, state, dev_batch,
                                             devices, flip)
                self._health_verdict(loop, verdict, "shadow recompute",
                                     DeviceQuarantine, SdcDetected)

    def _health_verdict(self, loop, verdict, what, quarantine_cls,
                        sdc_cls) -> None:
        if verdict.ok:
            return
        pol, sent = self.health_policy, self._health
        if verdict.ambiguous:
            raise sdc_cls(
                f"{what} diverged at iteration {loop.iteration} with no "
                f"attributable minority device (fingerprints "
                f"{list(verdict.fingerprints)}); corruption is proven "
                f"but eviction has no target — triage the hardware")
        if pol.evict and sent.eviction_budget_left:
            sent.note_quarantine(verdict.suspect, what.replace(" ", "_"))
            raise quarantine_cls(
                f"{what} named device {verdict.suspect} as corrupt at "
                f"iteration {loop.iteration} (fingerprints "
                f"{list(verdict.fingerprints)}); quarantining — rebuild "
                f"on the surviving devices and resume from the LKG tier",
                device=verdict.suspect)
        logger.error("health: %s named device %s at iteration %d but "
                     "eviction is %s — continuing (detect-only)", what,
                     verdict.suspect, loop.iteration,
                     "off" if not pol.evict else "budget-exhausted")

    def _shadow_check(self, step: int, state: TrainState, dev_batch,
                      devices, flip):
        """Re-execute the current microbatch's forward on the shadow
        device and fingerprint-compare against the primary (a third
        device votes on a mismatch when the mesh has one).  Host-side by
        design: the spot-check must NOT share the primary's compiled
        program or placed arrays — a corrupt device's results re-read
        from HBM would just agree with themselves."""
        from analytics_zoo_tpu.resilience import health as health_lib

        pol, sent = self.health_policy, self._health
        if self._shadow_fn is None:
            self._shadow_fn = health_lib.make_shadow_fn(
                self.model.module, forward_fn=self.forward_fn)
        variables = state_to_variables(mesh_lib.host_local_state(state))
        host_batch = jax.device_get(dev_batch)
        target, element, bit = flip
        shadow_i = min(pol.shadow_device, len(devices) - 1)

        def fp_on(i):
            with jax.default_device(devices[i]):
                return int(jax.device_get(self._shadow_fn(
                    variables, host_batch, jnp.int32(element),
                    jnp.int32(bit), jnp.bool_(target == i))))

        fp_primary, fp_shadow = fp_on(0), fp_on(shadow_i)
        tiebreak = None
        if fp_primary != fp_shadow:
            third = next((j for j in range(len(devices))
                          if j not in (0, shadow_i)), None)
            if third is not None:
                tiebreak = fp_on(third)
        return sent.observe_shadow(step, fp_primary, fp_shadow,
                                   device=shadow_i, tiebreak_fp=tiebreak)

    def _anomaly_rollback(self, loop: TrainingState,
                          state: TrainState) -> TrainState:
        """Restore the last-known-good tier (falling back to the newest
        intact regular snapshot — those are health-guarded too) and
        re-replicate it over the mesh."""
        from analytics_zoo_tpu.parallel import checkpoint as ckpt
        from analytics_zoo_tpu.resilience.errors import TrainingDiverged

        sent = self._anomaly
        found, tier = None, "lkg"
        if self.checkpoint_path is not None:
            found = ckpt.lkg_snapshot(self.checkpoint_path)
            if found is None:
                found, tier = ckpt.newest_intact(self.checkpoint_path), \
                    "regular"
        if found is None:
            raise TrainingDiverged(
                f"anomaly rollback requested at iteration {loop.iteration} "
                "but no last-known-good (or intact regular) snapshot "
                "exists — configure set_checkpoint so the ladder has a "
                "rollback target")
        snap_dir, man = found
        host_target = mesh_lib.host_local_state(state)
        restored = ckpt.load(snap_dir, target=host_target, verify=False)
        new_state = self._place_state(restored)
        # bit-identity proof: the live post-replication params equal the
        # snapshot's bytes (the chaos drill banks this check)
        live = mesh_lib.host_local_state(new_state)
        match = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree_util.tree_leaves(live.params),
                            jax.tree_util.tree_leaves(restored.params)))
        self.optim.load_state_dict(
            (man.get("meta", {}) or {}).get("optim", {}) or {})
        sent.note_rollback(
            iteration=loop.iteration, tier=tier,
            snapshot=os.path.basename(snap_dir),
            restored_step=int(np.asarray(restored.step)),
            params_match_snapshot=bool(match),
            reseek_batches=sent.policy.reseek)
        logger.warning(
            "anomaly sentinel: rollback %d/%d at iteration %d -> %s "
            "(restored step %d, params bit-identical to snapshot: %s)",
            sent.rollbacks, sent.policy.max_rollbacks, loop.iteration,
            snap_dir, int(np.asarray(restored.step)), match)
        return new_state

    def _reseek(self, epoch_iter, n: int) -> None:
        """Advance the deterministic stream past the bad region: drop the
        next ``n`` batches on the host (they count as consumed for the
        mid-epoch-resume position, but train no step)."""
        done = object()
        skipped = 0
        for _ in range(max(n, 0)):
            b = next(epoch_iter, done)
            if b is done:
                break
            skipped += 1
            self._iter_in_epoch += 1
            self._samples_in_epoch += _batch_size(b)
        if skipped:
            logger.warning("anomaly sentinel: re-sought stream past %d "
                           "batch(es) after rollback", skipped)

    def _write_forensics(self, sent, word: int, loop: TrainingState,
                         state: TrainState, dev_batch) -> None:
        from analytics_zoo_tpu.resilience import anomaly as anomaly_lib

        directory = (sent.policy.forensics_dir or self.checkpoint_path
                     or os.getcwd())
        batch_in_epoch = self._iter_in_epoch - 1
        num_workers = getattr(self.dataset, "num_workers", None)
        group_size = getattr(self.dataset, "group_size", None)
        # worker shards owning the groups this batch spans (a batch is
        # assembled in the parent from one or MORE groups; assumes no
        # upstream sample drops shifted the mapping).  Replay itself
        # needs only (base_seed, epoch, batch index).
        worker_shards = None
        if num_workers and group_size:
            B = _batch_size(dev_batch)
            first = (batch_in_epoch * B) // group_size
            last = ((batch_in_epoch + 1) * B - 1) // group_size
            worker_shards = sorted({g % num_workers
                                    for g in range(first, last + 1)})
        payload = {
            "bundle": "anomaly_forensics",
            "format": 1,
            "step": int(np.asarray(mesh_lib.host_local_state(state.step))),
            "iteration": loop.iteration,
            "epoch": loop.epoch,
            "batch_in_epoch": batch_in_epoch,
            "health_word": int(word),
            "health": anomaly_lib.decode_health(word, sent.sections),
            "sections": sent.sections,
            "batch_hash": anomaly_lib.batch_fingerprint(dev_batch),
            # strict-JSON loss history: non-finite floats become strings
            "loss_history": [v if np.isfinite(v) else repr(v)
                             for v in sent.loss_history],
            # the PR-2 determinism coordinates replay_batch.py consumes
            "rng": {
                "base_seed": getattr(self.dataset, "base_seed", None),
                "loader_epoch": getattr(self.dataset, "last_epoch", None),
                "num_workers": num_workers,
                "worker_shards": worker_shards,
            },
        }
        sent.write_forensics(directory, payload)

    def _promote_lkg(self, loop: TrainingState, state: TrainState) -> None:
        from analytics_zoo_tpu.parallel import checkpoint as ckpt

        target = ckpt.save(
            self.checkpoint_path, state, tier="lkg",
            meta={"epoch": loop.epoch, "iteration": loop.iteration,
                  "iter_in_epoch": self._iter_in_epoch,
                  "samples_in_epoch": self._samples_in_epoch,
                  "world_width": self.specs.data_axis_size,
                  "health_word": 0,
                  "optim": self.optim.state_dict()})
        self._anomaly.note_promoted(step=loop.iteration,
                                    snapshot=os.path.basename(target))
        logger.info("anomaly sentinel: promoted last-known-good snapshot "
                    "at iteration %d", loop.iteration)

    def _maybe_checkpoint(self, loop: TrainingState, state: TrainState,
                          force: bool = False) -> bool:
        """Returns True when this iteration's state is persisted (saved
        now, or already saved at this very iteration)."""
        if not force and (self.checkpoint_trigger is None
                          or not self.checkpoint_trigger(loop)):
            return False
        if getattr(self, "_last_ckpt_iter", None) == loop.iteration:
            return True
        # never snapshot a poisoned state: the anomaly health word covers
        # non-finite GRADS/PARAMS even when this step's scalar loss is
        # finite; the loss check alone remains the guard for runs without
        # an anomaly policy (loop.health then stays 0)
        loss_now = float(loop.loss)
        health_now = int(getattr(loop, "health", 0) or 0)
        if health_now or not np.isfinite(loss_now):
            logger.warning("skipping checkpoint at iteration %d: "
                           "health word %#x, loss %s", loop.iteration,
                           health_now, loss_now)
            return False
        # memoized only on an ACTUAL save: a skipped save must not make a
        # later forced call at this iteration report "already persisted"
        self._last_ckpt_iter = loop.iteration
        from analytics_zoo_tpu.parallel import checkpoint as ckpt
        tag = None if self.overwrite_checkpoint else loop.iteration
        # multi-host: EVERY process calls save (orbax has internal
        # cross-process barriers and elects the writer itself); the
        # trigger decision above is deterministic and replicated, so all
        # processes reach this point together.  Loop position + host-side
        # optim state (Plateau's learned LR scale) ride in the snapshot's
        # own manifest, so a restore can never pair params with metadata
        # from a DIFFERENT snapshot.
        import contextlib
        # with obs armed the save is both a span (trace
        # ckpt-i<iteration>) and a checkpoint/save_s histogram entry
        t0 = time.perf_counter()
        span = (self.obs.tracer.span(
                    "checkpoint_save", f"ckpt-i{loop.iteration}",
                    iteration=loop.iteration,
                    tag="latest" if tag is None else f"step_{tag}")
                if self.obs is not None else contextlib.nullcontext())
        with span:
            ckpt.save(self.checkpoint_path, state, step=tag,
                      keep_last=self.checkpoint_keep_last,
                      meta={"epoch": loop.epoch, "iteration": loop.iteration,
                            "iter_in_epoch": self._iter_in_epoch,
                            "samples_in_epoch": self._samples_in_epoch,
                            "world_width": self.specs.data_axis_size,
                            "optim": self.optim.state_dict()})
        if self.obs is not None:
            self.obs.registry.histogram("checkpoint/save_s").observe(
                time.perf_counter() - t0)
        return True

    def _apply_resume_meta(self, meta, loop: TrainingState, state) -> None:
        loop.epoch = int(meta.get("epoch", 0))
        loop.iteration = int(meta.get("iteration", int(state.step)))
        if meta.get("samples_in_epoch") is not None:
            # sample-coordinate resume (elastic-capable): the skip loop
            # consumes batches until the GLOBAL sample offset is reached,
            # valid under any world size whose stream re-batches the same
            # merged sample sequence.  Same-geometry resumes consume
            # exactly iter_in_epoch batches — bit-identical to the
            # legacy batch-count path.
            self._skip_samples = int(meta["samples_in_epoch"])
            self._skip_batches = 0
        else:
            self._skip_batches = int(meta.get("iter_in_epoch", 0))
        saved_width = meta.get("world_width")
        if (saved_width is not None
                and int(saved_width) != self.specs.data_axis_size):
            logger.info(
                "elastic resume: checkpoint saved at world width %d, "
                "re-placing at width %d (sample offset %s)",
                int(saved_width), self.specs.data_axis_size,
                meta.get("samples_in_epoch"))
            if self.obs is not None:
                self.obs.registry.counter("elastic/restores").inc()
                self.obs.registry.gauge("elastic/world_width").set(
                    float(self.specs.data_axis_size))
        self.optim.load_state_dict(meta.get("optim", {}) or {})

    def _try_resume(self, base: str, state: TrainState, loop: TrainingState):
        """Restore (state, loop, host optim state) from the newest INTACT
        checkpoint under ``base`` if one exists; otherwise return the
        fresh pair unchanged.  A corrupt/truncated newest snapshot falls
        back to the next older intact one — loop metadata comes from the
        restored snapshot's own manifest, so position and params always
        match."""
        import json

        from analytics_zoo_tpu.parallel import checkpoint as ckpt
        base = os.path.abspath(base)
        if not ckpt.has_checkpoint(base):
            return state, loop
        found = ckpt.newest_intact(base)
        if found is not None:
            snap_dir, manifest = found
            # newest_intact already checksummed this exact dir — do not
            # pay a second full read+sha256 pass on the restart hot path
            import contextlib
            t0 = time.perf_counter()
            span = (self.obs.tracer.span(
                        "checkpoint_restore", "ckpt-restore",
                        snapshot=os.path.basename(snap_dir))
                    if self.obs is not None else contextlib.nullcontext())
            with span:
                state = ckpt.load(snap_dir, target=state, verify=False)
            if self.obs is not None:
                self.obs.registry.histogram("checkpoint/restore_s").observe(
                    time.perf_counter() - t0)
            self._apply_resume_meta(manifest.get("meta", {}), loop, state)
        else:
            # legacy layout (pre-manifest snapshots): best-effort restore
            # with the loop_meta.json sidecar older builds wrote
            state = ckpt.load(base, target=state)
            meta_path = os.path.join(base, "loop_meta.json")
            if os.path.exists(meta_path):
                with open(meta_path) as f:
                    self._apply_resume_meta(json.load(f), loop, state)
            else:
                loop.iteration = int(state.step)
        if self._skip_samples is not None:
            logger.info("resumed from %s at epoch %d, iteration %d "
                        "(re-seeking %d in-epoch samples)",
                        base, loop.epoch, loop.iteration, self._skip_samples)
        else:
            logger.info("resumed from %s at epoch %d, iteration %d "
                        "(skipping %d in-epoch batches)",
                        base, loop.epoch, loop.iteration, self._skip_batches)
        return state, loop


def _batch_size(batch) -> int:
    leaf = jax.tree_util.tree_leaves(batch)[0]
    # .shape directly: np.asarray on a device-resident (prefetched) leaf
    # would device_get the whole array just to read its shape
    shape = getattr(leaf, "shape", None)
    return int(shape[0]) if shape else int(np.asarray(leaf).shape[0])


def sparse_adam_apply(table, mu, nu, count, grad, learning_rate,
                      b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8):
    """Row-sparse Adam: update ONLY the rows a batch touched, and only
    their optimizer slots — the embedding-table apply that scales past
    one chip (a full-table apply moves ``vocab × dim`` for every step no
    matter how few rows the batch referenced).

    ``grad`` is an ``ops.embedding.SparseRows`` (the (ids, segment-summed
    rows) gradient the dedup'd lookup backward produces);
    ``mu``/``nu``/``count`` are the table's Adam slots.  The math runs
    the SAME optax transforms as the full-table path
    (``scale_by_adam`` → ``scale(-lr)`` → ``p + u``) on the gathered
    rows, so touched rows bit-match a dense ``optax.adam`` apply —
    ``tests/test_embedding.py`` pins this.  Untouched rows keep stale
    moments (lazy Adam): their ``mu``/``nu`` do not decay until the next
    time they are touched, the standard sparse-trainer tradeoff.

    Padded tail entries of ``grad.ids`` are redirected OUT OF BOUNDS:
    jax gathers clamp (harmless garbage rows in dead slots) and jax
    scatters DROP out-of-bounds updates, so padding never corrupts row
    0 and valid unique ids make every scatter-set deterministic.

    Returns ``(table, mu, nu, count)`` updated."""
    vocab = table.shape[0]
    n = grad.ids.shape[0]
    valid = jnp.arange(n, dtype=jnp.int32) < grad.count
    safe_ids = jnp.where(valid, grad.ids, vocab)
    t_rows, mu_rows, nu_rows = table[safe_ids], mu[safe_ids], nu[safe_ids]
    adam = optax.scale_by_adam(b1=b1, b2=b2, eps=eps)
    row_state = optax.ScaleByAdamState(count=count, mu=mu_rows, nu=nu_rows)
    upd, new_state = adam.update(grad.rows, row_state, t_rows)
    # mirror optax.scale_by_learning_rate + apply_updates op-for-op so
    # the arithmetic is bit-identical to the dense chain
    step_size = -1 * jnp.asarray(learning_rate, dtype=jnp.float32)
    new_rows = (t_rows + step_size * upd).astype(table.dtype)
    return (table.at[safe_ids].set(new_rows),
            mu.at[safe_ids].set(new_state.mu.astype(mu.dtype)),
            nu.at[safe_ids].set(new_state.nu.astype(nu.dtype)),
            new_state.count)


def validate(module, variables, dataset, methods: Sequence[ValidationMethod],
             eval_step=None) -> List[ValidationResult]:
    """Forward a dataset and monoid-reduce validation results (reference
    ``Validator.test``, ``ssd/Validator.scala:59-86``)."""
    eval_step = eval_step or make_eval_step(module)
    totals: List[Optional[ValidationResult]] = [None] * len(methods)
    for batch in dataset:
        out = eval_step(variables, batch["input"])
        for i, m in enumerate(methods):
            r = m(out, batch)
            totals[i] = r if totals[i] is None else totals[i] + r
    return [t for t in totals if t is not None]
