"""Expert (MoE) parallelism — switch-style top-1 routing with capacity,
experts sharded one-per-device over an ``expert`` mesh axis and tokens
exchanged with ``lax.all_to_all`` over ICI.

Net-new capability (nothing MoE-shaped exists in the 2017 reference);
completes the framework's mesh-axis story alongside ``data`` / ``model``
/ ``sequence`` / ``pipe``.

Two execution paths share ONE routing implementation
(:func:`route_top1` — argmax gate, per-expert capacity positions via
one-hot cumsum, over-capacity tokens dropped to zero, switch-style gate
scaling):

- :func:`moe_apply_dense` — single-program path: dispatch/combine as
  einsums against the (N, E, C) dispatch tensor, experts vmapped.  This
  is also the numerical oracle.
- :func:`moe_apply_expert_parallel` — ``shard_map`` path: tokens arrive
  sharded over the expert axis, each device einsum-packs per-expert
  buckets, one ``all_to_all`` ships every bucket to its expert's device,
  the local expert runs once on all its tokens, a second ``all_to_all``
  ships results back.  Parity with the dense path is exact (same
  routing, same drops) and is what the tests assert.

Everything is static-shape: capacity ``C`` is a Python int, dropped
tokens are zeros, so both paths jit cleanly.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.parallel.sequence import _shard_map

EXPERT_AXIS = "expert"


def route_top1(x: jax.Array, gate_kernel: jax.Array, capacity: int
               ) -> Tuple[jax.Array, jax.Array]:
    """Top-1 routing: returns (dispatch (N, E, C) float 0/1, scale (N,)).

    ``dispatch[i, e, c] = 1`` iff token i goes to expert e at bucket slot
    c; tokens beyond an expert's ``capacity`` are dropped (all-zero row).
    ``scale[i]`` is the token's softmax gate probability for its chosen
    expert (switch-transformer output scaling).
    """
    logits = x @ gate_kernel                                # (N, E)
    gates = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(gates, axis=-1)                 # (N,)
    E = gate_kernel.shape[-1]
    oh = jax.nn.one_hot(expert_idx, E, dtype=x.dtype)       # (N, E)
    # slot within the chosen expert's bucket = how many earlier tokens
    # picked the same expert.  Counted in int32, NOT x.dtype: a bf16
    # cumsum stops incrementing at 256 and would assign duplicate slots.
    oh_i = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)
    pos_i = jnp.sum((jnp.cumsum(oh_i, axis=0) - 1) * oh_i, axis=-1)  # (N,)
    keep = pos_i < capacity
    slot_oh = jax.nn.one_hot(pos_i, capacity, dtype=x.dtype)  # (N, C)
    dispatch = (oh[:, :, None] * slot_oh[:, None, :]
                * keep[:, None, None].astype(x.dtype))      # (N, E, C)
    scale = jnp.sum(gates * oh, axis=-1) * keep.astype(x.dtype)
    return dispatch, scale


def default_capacity(n_tokens: int, n_experts: int,
                     capacity_factor: float = 1.25) -> int:
    return max(1, math.ceil(n_tokens / n_experts * capacity_factor))


def moe_apply_dense(apply_expert: Callable[[Any, jax.Array], jax.Array],
                    stacked_params: Any, gate_kernel: jax.Array,
                    x: jax.Array, capacity: Optional[int] = None
                    ) -> jax.Array:
    """Reference/single-device path: x (N, D) → (N, D)."""
    E = gate_kernel.shape[-1]
    n_experts = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_experts != E:
        raise ValueError(
            f"stacked_params has {n_experts} experts but gate_kernel "
            f"routes to {E}")
    C = capacity if capacity is not None else default_capacity(x.shape[0], E)
    if C < 1:
        raise ValueError(f"capacity must be >= 1, got {C}")
    dispatch, scale = route_top1(x, gate_kernel, C)
    xe = jnp.einsum("nec,nd->ecd", dispatch, x)             # (E, C, D)
    ye = jax.vmap(apply_expert)(stacked_params, xe)         # (E, C, D)
    y = jnp.einsum("nec,ecd->nd", dispatch, ye)
    return y * scale[:, None]


def moe_apply_expert_parallel(
    apply_expert: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any, gate_kernel: jax.Array,
    x: jax.Array, mesh: Mesh,
    axis_name: str = EXPERT_AXIS,
    capacity: Optional[int] = None,
) -> jax.Array:
    """Expert-parallel path: E == mesh.shape[axis_name], one expert per
    device; ``x`` (N, D) with N sharded over the expert axis.

    Per-device capacity applies to each (sender, expert) pair, so the
    effective global capacity per expert is ``n_devices · C_local`` —
    pass ``capacity`` computed from the LOCAL token count for parity with
    a dense run at the same per-pair capacity.
    """
    E = gate_kernel.shape[-1]
    n = mesh.shape[axis_name]
    if E != n:
        raise ValueError(f"{E} experts but {axis_name!r} axis has {n} "
                         f"devices — one expert per device required")
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_stages != E:
        raise ValueError(f"stacked_params has {n_stages} experts, expected {E}")
    if x.shape[0] % n:
        raise ValueError(f"token count {x.shape[0]} not divisible by {n}")
    C = (capacity if capacity is not None
         else default_capacity(x.shape[0] // n, E))
    if C < 1:
        raise ValueError(f"capacity must be >= 1, got {C}")

    param_spec = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    tok_spec = P(axis_name, None)

    def local(params_l, gk, x_l):
        params = jax.tree_util.tree_map(lambda p: p[0], params_l)
        dispatch, scale = route_top1(x_l, gk, C)            # (N_l, E, C)
        xe = jnp.einsum("nec,nd->ecd", dispatch, x_l)       # (E, C, D)
        # ship bucket e to device e; receive (n, C, D): row j = sender j's
        # bucket for MY expert
        recv = jax.lax.all_to_all(xe, axis_name, split_axis=0,
                                  concat_axis=0, tiled=True)
        ye = apply_expert(params, recv.reshape(n * C, -1)).reshape(n, C, -1)
        back = jax.lax.all_to_all(ye, axis_name, split_axis=0,
                                  concat_axis=0, tiled=True)  # (E, C, D)
        y = jnp.einsum("nec,ecd->nd", dispatch, back)
        return y * scale[:, None]

    fn = _shard_map(local, mesh,
                    in_specs=(param_spec, P(), tok_spec),
                    out_specs=tok_spec)
    return fn(stacked_params, gate_kernel, x)
