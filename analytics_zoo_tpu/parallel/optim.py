"""Optim methods, LR schedules, and triggers — BigDL ``OptimMethod``/``Trigger``
parity on optax.

The reference trains SSD with SGD(momentum 0.9) under a MultiStep or
plateau-on-score schedule and warms up with Adam to a target mAP
(``ssd/example/Train.scala:178-210``); the notebooks use Adam.  Triggers
drive epoch/iteration control flow (``Trigger.everyEpoch``, ``maxEpoch``,
``severalIteration``, ``maxScore``, SURVEY.md §2.7 "Optimizer").

Design: an ``OptimMethod`` owns an ``optax.GradientTransformation`` whose
learning rate is injected as a hyperparameter, so *metric-driven* schedules
(Plateau) can rescale the LR from the host between jitted steps without
recompilation.  Step-driven schedules (MultiStep, warmup, poly) are pure
functions of the step count and live inside the jitted update.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp
import numpy as np
import optax


# ---------------------------------------------------------------------------
# Schedules
# ---------------------------------------------------------------------------


def multistep(base_lr: float, milestones, gamma: float = 0.1) -> Callable:
    """MultiStep LR: multiply by ``gamma`` at each milestone iteration
    (reference SGD ``MultiStep`` branch, ``Train.scala:206-210``)."""
    # host numpy: this closure runs inside the jitted train step, and a
    # closed-over COMMITTED device array degrades the remote-TPU
    # transfer path process-wide
    ms = np.asarray(sorted(milestones))

    def schedule(step):
        n = jnp.sum(step >= ms)
        return base_lr * (gamma ** n)

    return schedule


def polynomial(base_lr: float, power: float, max_iter: int) -> Callable:
    def schedule(step):
        frac = jnp.clip(step / max_iter, 0.0, 1.0)
        return base_lr * (1.0 - frac) ** power

    return schedule


def warmup_linear(base_lr: float, warmup_steps: int, after: Optional[Callable] = None):
    def schedule(step):
        warm = base_lr * (step + 1) / max(warmup_steps, 1)
        rest = after(step - warmup_steps) if after is not None else base_lr
        return jnp.where(step < warmup_steps, warm, rest)

    return schedule


class Plateau:
    """Host-side plateau-on-metric LR controller (reference SGD ``Plateau``
    monitoring "score", factor 0.5, ``Train.scala:196-204``).

    Stateful and metric-driven, so it cannot live inside jit: call
    ``update(metric)`` once per validation; the resulting ``scale`` is fed to
    the train step as the injected LR multiplier.
    """

    def __init__(self, monitor: str = "score", factor: float = 0.5,
                 patience: int = 10, mode: str = "max", epsilon: float = 1e-4,
                 min_lr: float = 0.0, base_lr: float = 1.0):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.mode = mode
        self.epsilon = epsilon
        self.min_lr = min_lr
        self.base_lr = base_lr
        self.scale = 1.0
        self.best: Optional[float] = None
        self.num_bad = 0

    def update(self, metric: float) -> float:
        better = (
            self.best is None
            or (self.mode == "max" and metric > self.best + self.epsilon)
            or (self.mode == "min" and metric < self.best - self.epsilon)
        )
        if better:
            self.best = metric
            self.num_bad = 0
        else:
            self.num_bad += 1
            if self.num_bad > self.patience:
                new_scale = self.scale * self.factor
                if self.base_lr * new_scale >= self.min_lr:
                    self.scale = new_scale
                self.num_bad = 0
        return self.scale


# ---------------------------------------------------------------------------
# OptimMethod
# ---------------------------------------------------------------------------


class OptimMethod:
    """Wraps an optax transformation with an injected LR hyperparameter.

    ``tx.init(params)`` / ``tx.update`` are used by the train-step factory;
    ``lr_for_step`` is traced inside jit; ``lr_scale`` (host float) carries
    Plateau rescaling across steps.
    """

    def __init__(self, opt_factory: Callable[[], optax.GradientTransformation],
                 schedule: Callable, plateau: Optional[Plateau] = None):
        self._factory = opt_factory
        self.schedule = schedule
        self.plateau = plateau
        self.tx = opt_factory()

    def lr_for_step(self, step, lr_scale):
        return self.schedule(step) * lr_scale

    @property
    def lr_scale(self) -> float:
        return self.plateau.scale if self.plateau is not None else 1.0

    def on_validation(self, metrics: Dict[str, float]) -> None:
        if self.plateau is not None and self.plateau.monitor in metrics:
            self.plateau.update(metrics[self.plateau.monitor])

    def state_dict(self) -> Dict[str, Any]:
        """Host-side state that must survive a checkpoint/resume (the
        device-side opt_state lives in the TrainState; this is the rest —
        Plateau's learned LR scale and patience counters)."""
        if self.plateau is None:
            return {}
        return {"plateau": {"scale": self.plateau.scale,
                            "best": self.plateau.best,
                            "num_bad": self.plateau.num_bad}}

    def load_state_dict(self, d: Dict[str, Any]) -> None:
        p = d.get("plateau")
        if p and self.plateau is not None:
            self.plateau.scale = float(p["scale"])
            self.plateau.best = p["best"]
            self.plateau.num_bad = int(p["num_bad"])


def _with_injected_lr(inner: Callable[[float], optax.GradientTransformation]):
    return optax.inject_hyperparams(inner)(learning_rate=1.0)


class SGD(OptimMethod):
    """SGD + momentum + optional L2 weight decay (the reference's workhorse:
    ``new SGD(learningRate=lr, momentum=0.9)``, ``Train.scala:192``)."""

    def __init__(self, learning_rate: float = 1e-3, momentum: float = 0.0,
                 weight_decay: float = 0.0, nesterov: bool = False,
                 schedule: Optional[Callable] = None,
                 plateau: Optional[Plateau] = None):
        if plateau is not None:
            plateau.base_lr = learning_rate

        def factory():
            def inner(learning_rate):
                parts = []
                if weight_decay:
                    parts.append(optax.add_decayed_weights(weight_decay))
                parts.append(optax.sgd(learning_rate, momentum=momentum or None,
                                       nesterov=nesterov))
                return optax.chain(*parts)

            return _with_injected_lr(inner)

        sched = schedule or (lambda step: learning_rate)
        super().__init__(factory, sched, plateau)


class Adam(OptimMethod):
    def __init__(self, learning_rate: float = 1e-3, b1: float = 0.9,
                 b2: float = 0.999, eps: float = 1e-8,
                 schedule: Optional[Callable] = None,
                 plateau: Optional[Plateau] = None):
        if plateau is not None:
            plateau.base_lr = learning_rate

        def factory():
            return _with_injected_lr(
                lambda learning_rate: optax.adam(learning_rate, b1=b1, b2=b2, eps=eps)
            )

        sched = schedule or (lambda step: learning_rate)
        super().__init__(factory, sched, plateau)


class AdamW(OptimMethod):
    def __init__(self, learning_rate: float = 1e-3, weight_decay: float = 1e-4,
                 schedule: Optional[Callable] = None):
        def factory():
            return _with_injected_lr(
                lambda learning_rate: optax.adamw(learning_rate, weight_decay=weight_decay)
            )

        super().__init__(factory, schedule or (lambda step: learning_rate))


# ---------------------------------------------------------------------------
# Triggers
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TrainingState:
    """Host-visible loop state that triggers predicate over."""

    epoch: int = 0
    iteration: int = 0
    epoch_finished: bool = False
    loss: float = float("inf")
    score: Optional[float] = None
    #: last anomaly health word (``resilience.anomaly`` bit layout);
    #: 0 = healthy, and always 0 when no anomaly policy is armed.  The
    #: checkpoint guard refuses to snapshot while it is non-zero.
    health: int = 0


class Trigger:
    """Predicate over TrainingState (reference ``Trigger`` companion:
    everyEpoch / maxEpoch / severalIteration / maxScore / minLoss)."""

    def __init__(self, fn: Callable[[TrainingState], bool], name: str = "trigger"):
        self._fn = fn
        self.name = name

    def __call__(self, state: TrainingState) -> bool:
        return self._fn(state)

    # -- factories ---------------------------------------------------------
    @staticmethod
    def always() -> "Trigger":
        """Fires at every evaluation (per-iteration checkpointing in
        chaos drills / debugging — expensive for real jobs)."""
        return Trigger(lambda s: True, "always")

    @staticmethod
    def max_wall_time(seconds: float, clock=None) -> "Trigger":
        """Fires once ``seconds`` of wall time elapsed since the trigger
        was CREATED (host-side clock).  The bounded-run guard for drills
        and preemptible jobs: compose as ``Trigger.or_(max_epoch(n),
        max_wall_time(t))`` so a restart-looping run still terminates.
        ``clock``: injected time source (utils.clock convention) — a
        VirtualClock makes the trigger deterministic in drills."""
        from analytics_zoo_tpu.utils.clock import as_now_fn

        now = as_now_fn(clock)
        start = now()
        return Trigger(lambda s: now() - start >= seconds,
                       f"maxWallTime({seconds}s)")

    @staticmethod
    def every_epoch() -> "Trigger":
        return Trigger(lambda s: s.epoch_finished, "everyEpoch")

    @staticmethod
    def max_epoch(n: int) -> "Trigger":
        return Trigger(lambda s: s.epoch >= n, f"maxEpoch({n})")

    @staticmethod
    def max_iteration(n: int) -> "Trigger":
        return Trigger(lambda s: s.iteration >= n, f"maxIteration({n})")

    @staticmethod
    def several_iteration(n: int) -> "Trigger":
        return Trigger(lambda s: s.iteration > 0 and s.iteration % n == 0,
                       f"severalIteration({n})")

    @staticmethod
    def max_score(s: float) -> "Trigger":
        return Trigger(lambda st: st.score is not None and st.score >= s,
                       f"maxScore({s})")

    @staticmethod
    def min_loss(l: float) -> "Trigger":
        return Trigger(lambda st: st.loss <= l, f"minLoss({l})")

    @staticmethod
    def or_(*triggers: "Trigger") -> "Trigger":
        return Trigger(lambda s: any(t(s) for t in triggers),
                       " | ".join(t.name for t in triggers))

    @staticmethod
    def and_(*triggers: "Trigger") -> "Trigger":
        return Trigger(lambda s: all(t(s) for t in triggers),
                       " & ".join(t.name for t in triggers))
