"""Device mesh + sharding helpers — the framework's distributed substrate.

Replaces BigDL's Spark-executor topology (reference ``Engine.init`` +
``ParameterManager`` AllReduce over the Spark block manager, SURVEY.md §2.7
"Optimizer") with a ``jax.sharding.Mesh``.  Gradient synchronization is not
an explicit AllReduce call anywhere in this codebase: batches are sharded
over the ``data`` axis, parameters are replicated, and XLA inserts the
``all-reduce`` over ICI when it compiles the jitted train step.

Axis conventions (any subset may be size 1):
  ``data``     — data parallel (batch dim)
  ``model``    — tensor parallel (hidden dims)
  ``sequence`` — sequence/context parallel (time dim; ring attention)
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQUENCE_AXIS = "sequence"


def create_mesh(
    mesh_shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over the available devices.

    Default: 1-D pure data-parallel mesh over every device — the topology of
    the reference's synchronous data-parallel DistriOptimizer.  Pass
    ``mesh_shape=(dp, tp)`` + ``axis_names=("data", "model")`` etc. for
    hybrid parallelism.  A ``-1`` dim is inferred like numpy reshape.
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    n = len(devices)
    if mesh_shape is None:
        mesh_shape = (n,) if len(axis_names) == 1 else None
    if mesh_shape is None:
        raise ValueError("mesh_shape required for multi-axis meshes")
    shape = list(mesh_shape)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = n // known
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} does not cover {n} devices")
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axis_names))


def data_axis(mesh: Mesh) -> str:
    """The mesh axis carrying the batch dim (``data`` if present)."""
    return DATA_AXIS if DATA_AXIS in mesh.axis_names else mesh.axis_names[0]


def batch_spec(mesh: Mesh, ndim: int = 1) -> P:
    """PartitionSpec sharding dim 0 over the data axis, rest replicated."""
    return P(data_axis(mesh), *([None] * (ndim - 1)))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """NamedSharding placing dim 0 of every batch leaf on the data axis."""
    return NamedSharding(mesh, P(data_axis(mesh)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def spans_processes(mesh: Mesh) -> bool:
    """True when the mesh includes devices of OTHER processes — the
    multi-host regime where arrays must be assembled from per-process
    local shards (``jax.make_array_from_process_local_data``) instead of
    ``device_put`` onto devices this process can't address."""
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def shard_batch(batch, mesh: Mesh, overrides=None):
    """Place a host pytree of arrays onto the mesh, dim-0-sharded over
    ``data`` (the per-iteration device feed of the train loop).

    Scalars (0-d leaves) are replicated.  Dim 0 must divide the data-axis
    size — use the data layer's ``drop_remainder``/padded batching for
    ragged tails.

    On a mesh spanning multiple processes, ``batch`` is this process's
    LOCAL slice of the global batch (each host feeds only the records
    its ``local_data_slice`` selects — the DistriOptimizer
    executor-feeds-its-partition contract): the leaves are assembled
    into global arrays of dim0 = local_dim0 × process_count.

    ``overrides`` maps top-level batch keys to explicit PartitionSpecs —
    e.g. ``{"input": tensor.spatial_input_spec()}`` shards image HEIGHT
    over the model axis (spatial-partitioning tensor parallelism).
    """
    axis = data_axis(mesh)
    n_shards = mesh.shape[axis]
    multiproc = spans_processes(mesh)

    def put(x, spec=None):
        x = np.asarray(x)
        if x.ndim == 0:
            sh = NamedSharding(mesh, P())
            if multiproc:
                return jax.make_array_from_process_local_data(sh, x)
            return jax.device_put(x, sh)
        n_global = x.shape[0] * (jax.process_count() if multiproc else 1)
        if n_global % n_shards:
            raise ValueError(
                f"global batch dim {n_global} not divisible by data-axis "
                f"size {n_shards}; pad the batch or drop the remainder "
                f"(see data.batching drop_remainder)"
            )
        if spec is None:
            spec = P(*([axis] + [None] * (x.ndim - 1)))
        sh = NamedSharding(mesh, spec)
        if multiproc:
            return jax.make_array_from_process_local_data(sh, x)
        return jax.device_put(x, sh)

    if overrides:
        return {k: (jax.tree_util.tree_map(
                        lambda x, k=k: put(x, overrides[k]), v)
                    if k in overrides
                    else jax.tree_util.tree_map(put, v))
                for k, v in batch.items()}
    return jax.tree_util.tree_map(put, batch)


def replicate(tree, mesh: Mesh):
    """Replicate a pytree (params/opt state) across the whole mesh — the
    one-time weight distribution that replaces the reference's per-job
    ``ModelBroadcast`` (``common/Predictor.scala:36``).

    Multi-host: every process holds the same host values (deterministic
    seeded init), so each contributes its local replicas."""
    if spans_processes(mesh):
        sh = replicated_sharding(mesh)
        return jax.tree_util.tree_map(
            lambda leaf: jax.make_array_from_process_local_data(
                sh, np.asarray(jax.device_get(leaf))), tree)
    return jax.device_put(tree, replicated_sharding(mesh))


def host_local_state(tree):
    """Host (numpy) copy of a state pytree that may contain multi-process
    arrays.  ``jax.device_get`` on a non-fully-addressable array can
    build a cross-process gather program — which deadlocks when only one
    process runs it (e.g. a checkpoint path).  Replicated leaves instead
    read their LOCAL replica: no cross-process traffic, any process can
    call this alone.  Leaves that are genuinely sharded across processes
    (multi-host tensor parallelism) are not supported here — checkpoint
    those with a collective-aware saver."""
    import numpy as _np

    def get(leaf):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            if not leaf.sharding.is_fully_replicated:
                raise ValueError(
                    "host_local_state: leaf is sharded across processes; "
                    "a local read would return one shard, not the value")
            return _np.asarray(leaf.addressable_data(0))
        return jax.device_get(leaf)

    return jax.tree_util.tree_map(get, tree)


def local_data_slice(global_batch: int, mesh: Mesh) -> Tuple[int, int]:
    """(start, size) of this host's slice of the global batch, so each host
    feeds only its addressable shard (per-host file sharding)."""
    n_proc = jax.process_count()
    if global_batch % n_proc:
        raise ValueError(f"global batch {global_batch} not divisible by {n_proc} hosts")
    per = global_batch // n_proc
    return jax.process_index() * per, per
