"""Device mesh + sharding helpers — the framework's distributed substrate.

Replaces BigDL's Spark-executor topology (reference ``Engine.init`` +
``ParameterManager`` AllReduce over the Spark block manager, SURVEY.md §2.7
"Optimizer") with a ``jax.sharding.Mesh``.  Gradient synchronization is not
an explicit AllReduce call anywhere in this codebase: batches are sharded
over the ``data`` axis, parameters are replicated, and XLA inserts the
``all-reduce`` over ICI when it compiles the jitted train step.

Axis conventions (any subset may be size 1):
  ``data``     — data parallel (batch dim)
  ``model``    — tensor parallel (hidden dims)
  ``sequence`` — sequence/context parallel (time dim; ring attention)
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQUENCE_AXIS = "sequence"


def create_mesh(
    mesh_shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over the available devices.

    Default: 1-D pure data-parallel mesh over every device — the topology of
    the reference's synchronous data-parallel DistriOptimizer.  Pass
    ``mesh_shape=(dp, tp)`` + ``axis_names=("data", "model")`` etc. for
    hybrid parallelism.  A ``-1`` dim is inferred like numpy reshape.
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    n = len(devices)
    if mesh_shape is None:
        mesh_shape = (n,) if len(axis_names) == 1 else None
    if mesh_shape is None:
        raise ValueError("mesh_shape required for multi-axis meshes")
    shape = list(mesh_shape)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = n // known
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} does not cover {n} devices")
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axis_names))


def data_axis(mesh: Mesh) -> str:
    """The mesh axis carrying the batch dim (``data`` if present)."""
    return DATA_AXIS if DATA_AXIS in mesh.axis_names else mesh.axis_names[0]


def batch_spec(mesh: Mesh, ndim: int = 1) -> P:
    """PartitionSpec sharding dim 0 over the data axis, rest replicated."""
    return P(data_axis(mesh), *([None] * (ndim - 1)))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """NamedSharding placing dim 0 of every batch leaf on the data axis."""
    return NamedSharding(mesh, P(data_axis(mesh)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh):
    """Place a host pytree of arrays onto the mesh, dim-0-sharded over
    ``data`` (the per-iteration device feed of the train loop).

    Scalars (0-d leaves) are replicated.  Dim 0 must divide the data-axis
    size — use the data layer's ``drop_remainder``/padded batching for
    ragged tails.
    """
    axis = data_axis(mesh)
    n_shards = mesh.shape[axis]

    def put(x):
        x = np.asarray(x)
        if x.ndim == 0:
            return jax.device_put(x, NamedSharding(mesh, P()))
        if x.shape[0] % n_shards:
            raise ValueError(
                f"batch dim {x.shape[0]} not divisible by data-axis size "
                f"{n_shards}; pad the batch or drop the remainder "
                f"(see data.batching drop_remainder)"
            )
        return jax.device_put(
            x, NamedSharding(mesh, P(*([axis] + [None] * (x.ndim - 1))))
        )

    return jax.tree_util.tree_map(put, batch)


def replicate(tree, mesh: Mesh):
    """Replicate a pytree (params/opt state) across the whole mesh — the
    one-time weight distribution that replaces the reference's per-job
    ``ModelBroadcast`` (``common/Predictor.scala:36``)."""
    return jax.device_put(tree, replicated_sharding(mesh))


def local_data_slice(global_batch: int, mesh: Mesh) -> Tuple[int, int]:
    """(start, size) of this host's slice of the global batch, so each host
    feeds only its addressable shard (per-host file sharding)."""
    n_proc = jax.process_count()
    if global_batch % n_proc:
        raise ValueError(f"global batch {global_batch} not divisible by {n_proc} hosts")
    per = global_batch // n_proc
    return jax.process_index() * per, per
