"""Pipeline (stage) parallelism — GPipe-style microbatched execution of a
stack of identical blocks, one stage per device along a ``pipe`` mesh axis.

Net-new capability (the reference's only parallelism is data-parallel
replicas, SURVEY.md §2.7), completing the framework's mesh-axis story:
``data`` × ``model`` × ``sequence`` × ``pipe``.

TPU-idiomatic formulation (the praxis/T5X "pipelined scan" pattern):
stage parameters are STACKED on a leading (L, ...) axis and sharded over
``pipe`` so each device holds one stage; a ``lax.scan`` over
``M + L - 1`` ticks runs inside ``shard_map`` — every tick each device
applies its stage to its current activation, then hands the result one
hop right via ``ppermute`` (which rides ICI).  Stage 0 injects a fresh
microbatch per tick; the last stage's outputs are collected with a
static one-hot scatter so shapes stay fixed for XLA.  Being pure
``scan``+``ppermute``, the schedule is differentiable — ``jax.grad``
through :func:`pipeline_forward` yields the reverse (backward-pipelined)
schedule automatically, so the same train-step factories work unchanged.

The pipeline bubble is the usual (L-1)/(M+L-1) fraction: amortize with
more microbatches M.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.parallel.sequence import _shard_map

PIPE_AXIS = "pipe"


def stack_stage_params(params_list) -> Any:
    """[per-stage params pytree] → one pytree with leading (L, ...) axis
    (stages must share a structure — a stack of identical blocks)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_forward(apply_block: Callable[[Any, jax.Array], jax.Array],
                     stacked_params: Any,
                     microbatches: jax.Array,
                     mesh: Mesh,
                     axis_name: str = PIPE_AXIS,
                     batch_axis: Optional[str] = None) -> jax.Array:
    """Run ``y_m = block_{L-1}(... block_0(x_m))`` for every microbatch.

    ``apply_block(stage_params, x) → y`` must preserve x's shape (uniform
    inter-stage activations — the standard homogeneous-pipeline contract).
    ``stacked_params``: leading dim L == size of ``axis_name``.
    ``microbatches``: (M, B, ...) — M microbatches, replicated over the
    pipe axis (or sharded over ``batch_axis`` on dim 1 for 2-D meshes).

    Returns (M, B, ...) outputs, replicated like the input.
    """
    L = mesh.shape[axis_name]
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_stages != L:
        # shard_map would happily split a multiple-of-L stack and the [0]
        # squeeze below would then silently drop every stage but the first
        # on each device
        raise ValueError(
            f"stacked_params has {n_stages} stages but the {axis_name!r} "
            f"axis has {L} devices — one stage per device required")
    stage_spec = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params)
    mb_spec = P(None, batch_axis)

    def local(params_l, mbs):
        # params_l: (1, ...) — this device's stage;  mbs: (M, B, ...)
        params = jax.tree_util.tree_map(lambda p: p[0], params_l)
        return _gpipe_schedule(lambda x: apply_block(params, x),
                               mbs, axis_name)

    fn = _shard_map(local, mesh,
                    in_specs=(stage_spec, mb_spec),
                    out_specs=mb_spec)
    return fn(stacked_params, microbatches)


def _gpipe_schedule(apply_stage, mbs, axis_name: str):
    """The shared GPipe tick loop (call inside ``shard_map``).

    ``apply_stage(x) → y`` applies THIS device's stage (shape
    preserving); ``mbs``: (M, B, ...) local microbatches.  One schedule
    serves both the homogeneous (:func:`pipeline_forward`) and the
    heterogeneous (:func:`pipeline_forward_het`) entry points, so fixes
    to the inject/collect/ppermute logic can never diverge between them.
    """
    M = mbs.shape[0]
    stage = jax.lax.axis_index(axis_name)
    n = jax.lax.psum(1, axis_name)             # static: == pipe-axis size
    buf = jnp.zeros_like(mbs[0])               # current activation
    outs = jnp.zeros_like(mbs)                 # last stage's collection

    def tick(carry, t):
        buf, outs = carry
        # stage 0 takes microbatch t (clamped; junk ticks discarded)
        inject = mbs[jnp.clip(t, 0, M - 1)]
        x = jnp.where(stage == 0, inject, buf)
        y = apply_stage(x)
        # collect on the last stage at ticks t in [L-1, T)
        m_idx = t - (n - 1)
        keep = (stage == n - 1) & (m_idx >= 0)
        onehot = (jnp.arange(M) == jnp.clip(m_idx, 0, M - 1)) & keep
        outs = jnp.where(
            onehot.reshape((M,) + (1,) * (outs.ndim - 1)), y[None], outs)
        # hand y one hop right (last stage's send is dropped)
        nxt = jax.lax.ppermute(y, axis_name,
                               [(i, i + 1) for i in range(n - 1)])
        return (nxt, outs), None

    (_, outs), _ = jax.lax.scan(tick, (buf, outs),
                                jnp.arange(M + n - 1))
    # only the last stage collected real results; zero-mask everyone
    # else and psum to broadcast them pipe-wide (out_specs replicate
    # over the pipe axis)
    contrib = jnp.where(stage == n - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(contrib, axis_name)


def split_microbatches(x: jax.Array, n_micro: int) -> jax.Array:
    """(B, ...) → (M, B/M, ...) microbatches for the pipeline schedule."""
    B = x.shape[0]
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible by {n_micro} microbatches")
    return x.reshape((n_micro, B // n_micro) + x.shape[1:])


# ---------------------------------------------------------------------------
# Heterogeneous stages
# ---------------------------------------------------------------------------
#
# ``pipeline_forward`` requires identical blocks (stackable param trees).
# Real models are rarely that uniform — SSDVgg's stages differ, DS2 mixes
# conv/BiRNN/FC (VERDICT round-2 weak item #3).  The generalization keeps
# the same SPMD tick loop but lets every stage carry a DIFFERENT param
# structure and a DIFFERENT apply function:
#
# - each stage's params are flattened to one f32 vector, zero-padded to
#   the longest stage and stacked to (L, Pmax) — a stackable, shardable
#   carrier for arbitrary per-stage trees (each device holds only its
#   own padded vector: memory stays O(stage), not O(model));
# - inside the tick, ``lax.switch`` on the device's stage index picks the
#   stage's branch, which unflattens ITS slice of the vector back into
#   its tree (static shapes/treedef per branch) and applies its fn.
#
# The one remaining contract is the wire format: every stage maps the
# SAME activation shape to itself (pad/reshape heterogeneous activations
# into a canonical buffer at the model boundary if needed).


def flatten_stage_params(params_list):
    """[heterogeneous per-stage pytrees] → ((L, Pmax) f32 carrier, metas).

    The carrier is a single differentiable array — shard it over the pipe
    axis, hand it to an optimizer, checkpoint it — while ``metas`` (static
    treedefs/shapes/dtypes) lets each stage recover its own tree."""
    metas, vecs = [], []
    for p in params_list:
        leaves, treedef = jax.tree_util.tree_flatten(p)
        shapes = tuple(tuple(l.shape) for l in leaves)
        dtypes = tuple(l.dtype for l in leaves)
        vec = (jnp.concatenate([jnp.ravel(l).astype(jnp.float32)
                                for l in leaves])
               if leaves else jnp.zeros((0,), jnp.float32))
        metas.append((treedef, shapes, dtypes, int(vec.shape[0])))
        vecs.append(vec)
    pmax = max(v.shape[0] for v in vecs)
    stacked = jnp.stack([jnp.pad(v, (0, pmax - v.shape[0])) for v in vecs])
    return stacked, metas


def unflatten_stage(vec, meta):
    """Inverse of one stage's flattening (static meta → static shapes)."""
    treedef, shapes, dtypes, _ = meta
    out, off = [], 0
    for shp, dt in zip(shapes, dtypes):
        k = int(np.prod(shp)) if shp else 1
        out.append(vec[off:off + k].reshape(shp).astype(dt))
        off += k
    return jax.tree_util.tree_unflatten(treedef, out)


def pipeline_forward_het(stage_fns, stacked_vec, metas, microbatches,
                         mesh: Mesh, axis_name: str = PIPE_AXIS,
                         batch_axis: Optional[str] = None) -> jax.Array:
    """GPipe schedule over HETEROGENEOUS stages.

    ``stage_fns[j](params_j, x) → y`` with x and y the same shape (the
    uniform wire format); ``stacked_vec``/``metas`` from
    :func:`flatten_stage_params`.  Differentiable in ``stacked_vec`` —
    the train step treats the carrier as one parameter array.
    """
    L = mesh.shape[axis_name]
    if len(stage_fns) != L or stacked_vec.shape[0] != L:
        raise ValueError(
            f"{len(stage_fns)} stage fns / {stacked_vec.shape[0]} stage "
            f"vectors for a {L}-device {axis_name!r} axis — need exactly "
            "one stage per device")
    mb_spec = P(None, batch_axis)

    def local(vec_l, mbs):
        vec = vec_l[0]                             # this device's carrier
        stage = jax.lax.axis_index(axis_name)
        branches = [
            (lambda x, j=j: stage_fns[j](unflatten_stage(vec, metas[j]), x))
            for j in range(L)
        ]
        return _gpipe_schedule(
            lambda x: jax.lax.switch(stage, branches, x), mbs, axis_name)

    fn = _shard_map(local, mesh,
                    in_specs=(P(axis_name), mb_spec),
                    out_specs=mb_spec)
    return fn(stacked_vec, microbatches)
