"""Pipeline (stage) parallelism — GPipe-style microbatched execution of a
stack of identical blocks, one stage per device along a ``pipe`` mesh axis.

Net-new capability (the reference's only parallelism is data-parallel
replicas, SURVEY.md §2.7), completing the framework's mesh-axis story:
``data`` × ``model`` × ``sequence`` × ``pipe``.

TPU-idiomatic formulation (the praxis/T5X "pipelined scan" pattern):
stage parameters are STACKED on a leading (L, ...) axis and sharded over
``pipe`` so each device holds one stage; a ``lax.scan`` over
``M + L - 1`` ticks runs inside ``shard_map`` — every tick each device
applies its stage to its current activation, then hands the result one
hop right via ``ppermute`` (which rides ICI).  Stage 0 injects a fresh
microbatch per tick; the last stage's outputs are collected with a
static one-hot scatter so shapes stay fixed for XLA.  Being pure
``scan``+``ppermute``, the schedule is differentiable — ``jax.grad``
through :func:`pipeline_forward` yields the reverse (backward-pipelined)
schedule automatically, so the same train-step factories work unchanged.

The pipeline bubble is the usual (L-1)/(M+L-1) fraction: amortize with
more microbatches M.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.parallel.sequence import _shard_map

PIPE_AXIS = "pipe"


def stack_stage_params(params_list) -> Any:
    """[per-stage params pytree] → one pytree with leading (L, ...) axis
    (stages must share a structure — a stack of identical blocks)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_forward(apply_block: Callable[[Any, jax.Array], jax.Array],
                     stacked_params: Any,
                     microbatches: jax.Array,
                     mesh: Mesh,
                     axis_name: str = PIPE_AXIS,
                     batch_axis: Optional[str] = None) -> jax.Array:
    """Run ``y_m = block_{L-1}(... block_0(x_m))`` for every microbatch.

    ``apply_block(stage_params, x) → y`` must preserve x's shape (uniform
    inter-stage activations — the standard homogeneous-pipeline contract).
    ``stacked_params``: leading dim L == size of ``axis_name``.
    ``microbatches``: (M, B, ...) — M microbatches, replicated over the
    pipe axis (or sharded over ``batch_axis`` on dim 1 for 2-D meshes).

    Returns (M, B, ...) outputs, replicated like the input.
    """
    L = mesh.shape[axis_name]
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_stages != L:
        # shard_map would happily split a multiple-of-L stack and the [0]
        # squeeze below would then silently drop every stage but the first
        # on each device
        raise ValueError(
            f"stacked_params has {n_stages} stages but the {axis_name!r} "
            f"axis has {L} devices — one stage per device required")
    M = microbatches.shape[0]
    T = M + L - 1

    stage_spec = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params)
    mb_spec = P(None, batch_axis)

    def local(params_l, mbs):
        # params_l: (1, ...) — this device's stage;  mbs: (M, B, ...)
        params = jax.tree_util.tree_map(lambda p: p[0], params_l)
        stage = jax.lax.axis_index(axis_name)
        n = jax.lax.psum(1, axis_name)
        buf = jnp.zeros_like(mbs[0])               # current activation
        outs = jnp.zeros_like(mbs)                 # last stage's collection

        def tick(carry, t):
            buf, outs = carry
            # stage 0 takes microbatch t (clamped; junk ticks discarded)
            inject = mbs[jnp.clip(t, 0, M - 1)]
            x = jnp.where(stage == 0, inject, buf)
            y = apply_block(params, x)
            # collect on the last stage at ticks t in [L-1, T)
            m_idx = t - (n - 1)
            keep = (stage == n - 1) & (m_idx >= 0)
            onehot = (jnp.arange(M) == jnp.clip(m_idx, 0, M - 1)) & keep
            outs = jnp.where(
                onehot.reshape((M,) + (1,) * (outs.ndim - 1)), y[None], outs)
            # hand y one hop right (last stage's send is dropped)
            nxt = jax.lax.ppermute(y, axis_name,
                                   [(i, i + 1) for i in range(n - 1)])
            return (nxt, outs), None

        (_, outs), _ = jax.lax.scan(tick, (buf, outs), jnp.arange(T))
        # only the last stage collected real results; zero-mask everyone
        # else and psum to broadcast them pipe-wide (out_specs replicate
        # over the pipe axis)
        contrib = jnp.where(stage == n - 1, outs, jnp.zeros_like(outs))
        return jax.lax.psum(contrib, axis_name)

    fn = _shard_map(local, mesh,
                    in_specs=(stage_spec, mb_spec),
                    out_specs=mb_spec)
    return fn(stacked_params, microbatches)


def split_microbatches(x: jax.Array, n_micro: int) -> jax.Array:
    """(B, ...) → (M, B/M, ...) microbatches for the pipeline schedule."""
    B = x.shape[0]
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible by {n_micro} microbatches")
    return x.reshape((n_micro, B // n_micro) + x.shape[1:])
