"""Pipeline (stage) parallelism — GPipe-style microbatched execution of a
stack of identical blocks, one stage per device along a ``pipe`` mesh axis.

Net-new capability (the reference's only parallelism is data-parallel
replicas, SURVEY.md §2.7), completing the framework's mesh-axis story:
``data`` × ``model`` × ``sequence`` × ``pipe``.

TPU-idiomatic formulation (the praxis/T5X "pipelined scan" pattern):
stage parameters are STACKED on a leading (L, ...) axis and sharded over
``pipe`` so each device holds one stage; a ``lax.scan`` over
``M + L - 1`` ticks runs inside ``shard_map`` — every tick each device
applies its stage to its current activation, then hands the result one
hop right via ``ppermute`` (which rides ICI).  Stage 0 injects a fresh
microbatch per tick; the last stage's outputs are collected with a
static one-hot scatter so shapes stay fixed for XLA.  Being pure
``scan``+``ppermute``, the schedule is differentiable — ``jax.grad``
through :func:`pipeline_forward` yields the reverse (backward-pipelined)
schedule automatically, so the same train-step factories work unchanged.

The pipeline bubble is the usual (L-1)/(M+L-1) fraction: amortize with
more microbatches M.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.parallel.sequence import _shard_map

PIPE_AXIS = "pipe"


def stack_stage_params(params_list) -> Any:
    """[per-stage params pytree] → one pytree with leading (L, ...) axis
    (stages must share a structure — a stack of identical blocks)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_forward(apply_block: Callable[[Any, jax.Array], jax.Array],
                     stacked_params: Any,
                     microbatches: jax.Array,
                     mesh: Mesh,
                     axis_name: str = PIPE_AXIS,
                     batch_axis: Optional[str] = None,
                     param_specs: Optional[Any] = None) -> jax.Array:
    """Run ``y_m = block_{L-1}(... block_0(x_m))`` for every microbatch.

    ``apply_block(stage_params, x) → y`` must preserve x's shape (uniform
    inter-stage activations — the standard homogeneous-pipeline contract).
    ``stacked_params``: leading dim L == size of ``axis_name``.
    ``microbatches``: (M, B, ...) — M microbatches, replicated over the
    pipe axis (or sharded over ``batch_axis`` on dim 1 for 2-D meshes).

    ``param_specs`` (optional): a pytree of ``PartitionSpec`` matching
    ``stacked_params`` that REPLACES the default ``P(axis_name)`` —
    for composing pipeline with tensor parallelism: e.g. a Megatron
    col/row pair inside each stage uses
    ``{"w1": P("pipe", None, "model"), "w2": P("pipe", "model", None)}``
    and closes the pair with ``jax.lax.psum(..., "model")`` inside
    ``apply_block`` (which runs inside shard_map, so every mesh axis
    name is in scope).  Every spec's dim 0 must still be ``axis_name``.

    Returns (M, B, ...) outputs, replicated like the input.
    """
    L = mesh.shape[axis_name]
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_stages != L:
        # shard_map would happily split a multiple-of-L stack and the [0]
        # squeeze below would then silently drop every stage but the first
        # on each device
        raise ValueError(
            f"stacked_params has {n_stages} stages but the {axis_name!r} "
            f"axis has {L} devices — one stage per device required")
    if param_specs is None:
        stage_spec = jax.tree_util.tree_map(
            lambda _: P(axis_name), stacked_params)
    else:
        stage_spec = param_specs
        for s in jax.tree_util.tree_leaves(
                stage_spec, is_leaf=lambda x: isinstance(x, P)):
            if not s or s[0] != axis_name:
                raise ValueError(
                    f"param_specs leaf {s} must shard dim 0 over "
                    f"{axis_name!r} (one stage per pipe device)")
    mb_spec = P(None, batch_axis)

    def local(params_l, mbs):
        # params_l: (1, ...) — this device's stage;  mbs: (M, B, ...)
        params = jax.tree_util.tree_map(lambda p: p[0], params_l)
        return _gpipe_schedule(lambda x: apply_block(params, x),
                               mbs, axis_name)

    fn = _shard_map(local, mesh,
                    in_specs=(stage_spec, mb_spec),
                    out_specs=mb_spec)
    return fn(stacked_params, microbatches)


def _gpipe_schedule(apply_stage, mbs, axis_name: str):
    """The shared GPipe tick loop (call inside ``shard_map``).

    ``apply_stage(x) → y`` applies THIS device's stage (shape
    preserving); ``mbs``: (M, B, ...) local microbatches.  One schedule
    serves both the homogeneous (:func:`pipeline_forward`) and the
    heterogeneous (:func:`pipeline_forward_het`) entry points, so fixes
    to the inject/collect/ppermute logic can never diverge between them.
    """
    M = mbs.shape[0]
    stage = jax.lax.axis_index(axis_name)
    n = jax.lax.psum(1, axis_name)             # static: == pipe-axis size
    buf = jnp.zeros_like(mbs[0])               # current activation
    outs = jnp.zeros_like(mbs)                 # last stage's collection

    def tick(carry, t):
        buf, outs = carry
        # stage 0 takes microbatch t (clamped; junk ticks discarded)
        inject = mbs[jnp.clip(t, 0, M - 1)]
        x = jnp.where(stage == 0, inject, buf)
        y = apply_stage(x)
        # collect on the last stage at ticks t in [L-1, T)
        m_idx = t - (n - 1)
        keep = (stage == n - 1) & (m_idx >= 0)
        onehot = (jnp.arange(M) == jnp.clip(m_idx, 0, M - 1)) & keep
        outs = jnp.where(
            onehot.reshape((M,) + (1,) * (outs.ndim - 1)), y[None], outs)
        # hand y one hop right (last stage's send is dropped)
        nxt = jax.lax.ppermute(y, axis_name,
                               [(i, i + 1) for i in range(n - 1)])
        return (nxt, outs), None

    (_, outs), _ = jax.lax.scan(tick, (buf, outs),
                                jnp.arange(M + n - 1))
    # only the last stage collected real results; zero-mask everyone
    # else and psum to broadcast them pipe-wide (out_specs replicate
    # over the pipe axis)
    contrib = jnp.where(stage == n - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(contrib, axis_name)


def split_microbatches(x: jax.Array, n_micro: int) -> jax.Array:
    """(B, ...) → (M, B/M, ...) microbatches for the pipeline schedule."""
    B = x.shape[0]
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible by {n_micro} microbatches")
    return x.reshape((n_micro, B // n_micro) + x.shape[1:])


# ---------------------------------------------------------------------------
# Heterogeneous stages
# ---------------------------------------------------------------------------
#
# ``pipeline_forward`` requires identical blocks (stackable param trees).
# Real models are rarely that uniform — SSDVgg's stages differ, DS2 mixes
# conv/BiRNN/FC (VERDICT round-2 weak item #3).  The generalization keeps
# the same SPMD tick loop but lets every stage carry a DIFFERENT param
# structure and a DIFFERENT apply function:
#
# - each stage's params are flattened to one f32 vector, zero-padded to
#   the longest stage and stacked to (L, Pmax) — a stackable, shardable
#   carrier for arbitrary per-stage trees (each device holds only its
#   own padded vector: memory stays O(stage), not O(model));
# - inside the tick, ``lax.switch`` on the device's stage index picks the
#   stage's branch, which unflattens ITS slice of the vector back into
#   its tree (static shapes/treedef per branch) and applies its fn.
#
# The one remaining contract is the wire format: every stage maps the
# SAME activation shape to itself (pad/reshape heterogeneous activations
# into a canonical buffer at the model boundary if needed).


def default_param_group(path: str, leaf) -> str:
    """Default optimizer-hygiene classifier for the grouped carrier:
    ``decay`` for ≥2-D kernels, ``no_decay`` for biases / norm
    scales-offsets (the standard weight-decay exclusion heuristic, and
    the same rule a caller would express as an optax mask by ndim)."""
    return "decay" if getattr(leaf, "ndim", 0) >= 2 else "no_decay"


def flatten_stage_params_grouped(params_list, classify=default_param_group):
    """[heterogeneous per-stage pytrees] → (carrier DICT, metas).

    VERDICT r3 weak #3: the single flat f32 carrier below erases
    per-parameter structure — optimizer semantics that distinguish
    parameter kinds (weight-decay masks excluding biases/BN, bf16
    master-weight policies) cannot apply inside a stage.  This carrier
    keeps the stackable/shardable property but groups leaves by
    ``(classify(path, leaf), dtype)``: the result is a dict of
    ``(L, Pmax_group)`` arrays — ``{"decay:float32": ...,
    "no_decay:float32": ..., ...}`` — so

    * an optax mask over the CARRIER (see :func:`carrier_decay_mask`)
      applies weight decay to exactly the leaves a per-parameter mask
      would, and
    * non-f32 leaves ride a carrier of their own dtype (no f32
      round-trip).

    Zero-padding to the longest stage is inert under standard
    transforms (decay/momentum of an exact 0 stays 0).
    ``metas[i]`` is a dict (distinguishing it from the legacy tuple
    meta) holding the stage's treedef + per-leaf (group, offset, shape,
    dtype) entries; both :func:`unflatten_stage` and
    :func:`pipeline_forward_het` accept either carrier form."""
    staged_entries, staged_treedefs, staged_leaves, lengths = [], [], [], {}
    for p in params_list:
        leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(p)
        offsets: dict = {}
        entries = []
        for path_entries, leaf in leaves_with_path:
            path = "/".join(str(getattr(e, "key", getattr(e, "name", e)))
                            for e in path_entries)
            dt = jnp.asarray(leaf).dtype
            key = f"{classify(path, leaf)}:{dt.name}"
            off = offsets.get(key, 0)
            size = int(np.prod(leaf.shape)) if leaf.shape else 1
            entries.append((key, off, tuple(leaf.shape), dt))
            offsets[key] = off + size
        for key, used in offsets.items():
            lengths[key] = max(lengths.get(key, 0), used)
        staged_entries.append(entries)
        staged_treedefs.append(treedef)
        staged_leaves.append([l for _, l in leaves_with_path])

    carrier = {}
    for key, pmax in sorted(lengths.items()):
        dt = jnp.dtype(key.split(":", 1)[1])
        rows = []
        for entries, leaves in zip(staged_entries, staged_leaves):
            parts = [jnp.ravel(jnp.asarray(l)) for (k, _, _, _), l
                     in zip(entries, leaves) if k == key]
            vec = (jnp.concatenate(parts) if parts
                   else jnp.zeros((0,), dt))
            rows.append(jnp.pad(vec, (0, pmax - vec.shape[0])))
        carrier[key] = jnp.stack(rows)
    metas = [{"treedef": td, "entries": tuple(es)}
             for td, es in zip(staged_treedefs, staged_entries)]
    return carrier, metas


def carrier_decay_mask(carrier):
    """optax-style bool mask over a grouped carrier: ``True`` exactly on
    the ``decay:*`` components — ``optax.add_decayed_weights(wd,
    mask=carrier_decay_mask(carrier))`` then matches a per-parameter
    bias/BN-excluding mask on the unflattened trees."""
    return {k: k.startswith("decay:") for k in carrier}


def stage_carrier_slice(carrier, j: int):
    """Stage ``j``'s slice of a grouped carrier (host-side convenience —
    inside ``shard_map`` each device already holds only its own row)."""
    return {k: v[j] for k, v in carrier.items()}


def flatten_stage_params(params_list):
    """[heterogeneous per-stage pytrees] → ((L, Pmax) f32 carrier, metas).

    The carrier is a single differentiable array — shard it over the pipe
    axis, hand it to an optimizer, checkpoint it — while ``metas`` (static
    treedefs/shapes/dtypes) lets each stage recover its own tree.

    Prefer :func:`flatten_stage_params_grouped` when the optimizer needs
    per-parameter semantics (weight-decay masks, non-f32 params): this
    flat form coerces everything to one undifferentiated f32 vector."""
    metas, vecs = [], []
    for p in params_list:
        leaves, treedef = jax.tree_util.tree_flatten(p)
        shapes = tuple(tuple(l.shape) for l in leaves)
        dtypes = tuple(l.dtype for l in leaves)
        vec = (jnp.concatenate([jnp.ravel(l).astype(jnp.float32)
                                for l in leaves])
               if leaves else jnp.zeros((0,), jnp.float32))
        metas.append((treedef, shapes, dtypes, int(vec.shape[0])))
        vecs.append(vec)
    pmax = max(v.shape[0] for v in vecs)
    stacked = jnp.stack([jnp.pad(v, (0, pmax - v.shape[0])) for v in vecs])
    return stacked, metas


def unflatten_stage(vec, meta):
    """Inverse of one stage's flattening (static meta → static shapes).
    Accepts both carrier forms: grouped (``vec`` a dict of vectors +
    dict meta) and legacy flat (``vec`` one f32 vector + tuple meta)."""
    if isinstance(meta, dict):
        out = []
        for key, off, shp, dt in meta["entries"]:
            k = int(np.prod(shp)) if shp else 1
            out.append(vec[key][off:off + k].reshape(shp).astype(dt))
        return jax.tree_util.tree_unflatten(meta["treedef"], out)
    treedef, shapes, dtypes, _ = meta
    out, off = [], 0
    for shp, dt in zip(shapes, dtypes):
        k = int(np.prod(shp)) if shp else 1
        out.append(vec[off:off + k].reshape(shp).astype(dt))
        off += k
    return jax.tree_util.tree_unflatten(treedef, out)


def pipeline_forward_het(stage_fns, stacked_vec, metas, microbatches,
                         mesh: Mesh, axis_name: str = PIPE_AXIS,
                         batch_axis: Optional[str] = None) -> jax.Array:
    """GPipe schedule over HETEROGENEOUS stages.

    ``stage_fns[j](params_j, x) → y`` with x and y the same shape (the
    uniform wire format); ``stacked_vec``/``metas`` from
    :func:`flatten_stage_params_grouped` (dict carrier — optimizer
    hygiene preserved) or :func:`flatten_stage_params` (legacy flat f32
    carrier).  Differentiable in ``stacked_vec`` — the train step treats
    the carrier as parameter array(s).
    """
    L = mesh.shape[axis_name]
    grouped = isinstance(stacked_vec, dict)
    n_stage_rows = (next(iter(stacked_vec.values())).shape[0] if grouped
                    else stacked_vec.shape[0])
    if len(stage_fns) != L or n_stage_rows != L:
        raise ValueError(
            f"{len(stage_fns)} stage fns / {n_stage_rows} stage "
            f"vectors for a {L}-device {axis_name!r} axis — need exactly "
            "one stage per device")
    mb_spec = P(None, batch_axis)
    carrier_spec = ({k: P(axis_name) for k in stacked_vec} if grouped
                    else P(axis_name))

    def local(vec_l, mbs):
        # this device's carrier row(s)
        vec = ({k: v[0] for k, v in vec_l.items()} if grouped
               else vec_l[0])
        stage = jax.lax.axis_index(axis_name)
        branches = [
            (lambda x, j=j: stage_fns[j](unflatten_stage(vec, metas[j]), x))
            for j in range(L)
        ]
        return _gpipe_schedule(
            lambda x: jax.lax.switch(stage, branches, x), mbs, axis_name)

    fn = _shard_map(local, mesh,
                    in_specs=(carrier_spec, mb_spec),
                    out_specs=mb_spec)
    return fn(stacked_vec, microbatches)
