"""Sequence/context parallelism: ring attention + sequence-sharded helpers.

The reference's only long-sequence mechanism is data-level chunking
(``TimeSegmenter.scala:11``: split audio into independent rows, re-join by
``(audio_id, seq)`` — see SURVEY.md §5 "Long-context").  A TPU-native
framework needs true *sequence parallelism*: shard the time axis T across
the mesh's ``sequence`` axis and exchange blocks over ICI.

This module provides:

- :func:`ring_attention` — blockwise attention where K/V blocks rotate
  around the ring via ``lax.ppermute`` while each device keeps a running
  online-softmax (flash-attention style) over its local Q block.  Memory
  per device is O(T/n · T/n) instead of O(T²); the n-step rotation overlaps
  compute with ICI transfers.  Supports causal masking via global block
  offsets.
- :func:`shard_sequence` / :func:`unshard_sequence` — place (B, T, …)
  activations on the sequence axis.
- collective helpers (:func:`psum_mean`, :func:`ring_shift`) used by
  sequence-parallel layers.

All functions are built on ``shard_map`` over an explicit Mesh, so they
compose with the data-parallel train step (mesh axes ``("data",
"sequence")``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.parallel.mesh import SEQUENCE_AXIS

NEG_INF = -1e30


def shard_sequence(x, mesh: Mesh, axis_name: str = SEQUENCE_AXIS):
    """Place (B, T, …) on the mesh with T sharded over ``axis_name``."""
    spec = P(None, axis_name, *([None] * (np.ndim(x) - 2)))
    # az-allow: one-placement-site — T-axis staging predates the SpecSet substrate; folding sequence parallelism into specs is ROADMAP work
    return jax.device_put(x, NamedSharding(mesh, spec))


def unshard_sequence(x):
    return jax.device_get(x)


def psum_mean(x, axis_name: str):
    """Mean across an axis's devices (gradient/metric reduction helper)."""
    return jax.lax.psum(x, axis_name) / jax.lax.psum(1, axis_name)


def ring_shift(x, axis_name: str, shift: int = 1):
    """Rotate a block one hop around the ring (ppermute over ICI)."""
    n = jax.lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def _ring_attention_local(q, k, v, axis_name: str, causal: bool,
                          scale: Optional[float]):
    """Per-device body: q/k/v are LOCAL blocks (B, Tb, H, D)."""
    B, Tb, H, D = q.shape
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    scale = scale if scale is not None else 1.0 / np.sqrt(D)

    # accumulators in (B, H, Tq) layout for the online softmax
    o = jnp.zeros((B, H, Tb, D), q.dtype)
    l = jnp.zeros((B, H, Tb), jnp.float32)
    m = jnp.full((B, H, Tb), NEG_INF, jnp.float32)
    q_pos = my_idx * Tb + jnp.arange(Tb)                 # global q positions

    def step(r, carry):
        o, l, m, k_cur, v_cur = carry
        # k_cur originated on device (my_idx - r) mod n
        src = (my_idx - r) % n
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur) * scale
        scores = scores.astype(jnp.float32)
        if causal:
            k_pos = src * Tb + jnp.arange(Tb)
            mask = q_pos[:, None] >= k_pos[None, :]      # (Tq, Tk)
            scores = jnp.where(mask[None, None], scores, NEG_INF)
        blk_max = jnp.max(scores, axis=-1)               # (B, H, Tq)
        new_m = jnp.maximum(m, blk_max)
        p = jnp.exp(scores - new_m[..., None])
        # rows with no valid key yet: new_m stays NEG_INF -> p would be
        # exp(0)=1 garbage; zero them explicitly
        p = jnp.where((new_m[..., None] > NEG_INF / 2), p, 0.0)
        corr = jnp.where(m > NEG_INF / 2, jnp.exp(m - new_m), 0.0)
        l = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v_cur.dtype), v_cur)
        o = o * corr[..., None].astype(o.dtype) + pv
        # rotate K/V one hop; after n steps every device saw every block
        k_next = ring_shift(k_cur, axis_name)
        v_next = ring_shift(v_cur, axis_name)
        return o, l, m * 0 + new_m, k_next, v_next

    o, l, m, _, _ = jax.lax.fori_loop(0, n, step, (o, l, m, k, v))
    out = o / jnp.maximum(l, 1e-20)[..., None].astype(o.dtype)
    return jnp.einsum("bhqd->bqhd", out)


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = SEQUENCE_AXIS,
                   causal: bool = False, scale: Optional[float] = None):
    """Sequence-parallel attention over a T-sharded batch.

    q, k, v: (B, T, H, D) with T sharded over ``axis_name`` (use
    :func:`shard_sequence`).  Returns (B, T, H, D), same sharding.  Inside
    jit, XLA lowers the per-step ``ppermute`` to ICI sends overlapping the
    per-block matmuls — the standard ring-attention schedule.
    """
    spec = P(None, axis_name, None, None)
    body = functools.partial(_ring_attention_local, axis_name=axis_name,
                             causal=causal, scale=scale)
    fn = _shard_map(body, mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def full_attention(q, k, v, causal: bool = False,
                   scale: Optional[float] = None):
    """Single-device reference implementation (for tests and small T)."""
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(D)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        Tq, Tk = scores.shape[-2], scores.shape[-1]
        mask = jnp.arange(Tq)[:, None] >= jnp.arange(Tk)[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _shard_map(body, mesh, in_specs, out_specs):
    try:
        return shard_map(body, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except TypeError:  # older jax uses check_rep
        return shard_map(body, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)


def halo_exchange(x, axis_name: str, left: int, right: int, time_axis: int = 1):
    """Append neighbors' edge frames to a T-sharded block (non-wrapping).

    For temporal convs over a sharded time axis: each device receives the
    last ``left`` frames of its left neighbor and the first ``right``
    frames of its right neighbor.  Edge devices receive ZEROS (ppermute's
    semantics for non-receivers), which exactly emulates the zero padding
    a global SAME/padded conv would apply — so a VALID conv on the extended
    block reproduces the unsharded result.  Call inside shard_map.
    """
    n = jax.lax.psum(1, axis_name)
    parts = []
    if left:
        edge = jax.lax.slice_in_dim(x, x.shape[time_axis] - left, None,
                                    axis=time_axis)
        recv = jax.lax.ppermute(edge, axis_name,
                                [(i, i + 1) for i in range(n - 1)])
        parts.append(recv)
    parts.append(x)
    if right:
        edge = jax.lax.slice_in_dim(x, 0, right, axis=time_axis)
        recv = jax.lax.ppermute(edge, axis_name,
                                [(i + 1, i) for i in range(n - 1)])
        parts.append(recv)
    return jnp.concatenate(parts, axis=time_axis)


def sequence_sharded_scan(step_fn, h0, xs, mesh: Mesh,
                          axis_name: str = SEQUENCE_AXIS,
                          reverse: bool = False,
                          batch_axis: Optional[str] = None):
    """Exact RNN scan over a time-sharded sequence (SURVEY.md §5 north star).

    ``xs``: (B, T, D) with T sharded over ``axis_name``; ``h0``: (B, H)
    replicated; ``step_fn(h, x_t) → (h', y_t)`` with y the same shape as h.
    Returns (B, T, H), T-sharded like the input.

    Schedule: n SPMD rounds.  Every round each device scans its local
    chunk from its current boundary state, then passes its final state one
    hop along the pipeline via ``ppermute``.  Device k's input state is
    exact in round k (it has received the chained boundary states of all
    predecessors), so its outputs from that round are kept and the rest
    discarded.  Wall-clock equals the unsharded scan (the recurrence is
    inherently sequential) but per-device *activation memory* is O(T/n) —
    the enabler for sequences that do not fit one chip; the reference's
    only answer was lossy chunking (``TimeSegmenter.scala:11``).  For a
    bidirectional pair use :func:`sequence_scan_local_bidir`, which fuses
    both directions into ONE round loop (opposite pipelines sharing the
    same n rounds) instead of two sequential loops.

    ``batch_axis``: name of the mesh axis sharding B (for 2-D
    ("data","sequence") meshes) — only used to build the in/out specs.
    """
    time_spec = P(batch_axis, axis_name, None)
    h_spec = P(batch_axis, None)

    def local(h0_l, x_l):
        return sequence_scan_local(step_fn, h0_l, x_l, axis_name, reverse)

    fn = _shard_map(local, mesh, in_specs=(h_spec, time_spec),
                    out_specs=time_spec)
    return fn(h0, xs)


def sequence_scan_local(step_fn, h0_l, x_l, axis_name: str,
                        reverse: bool = False):
    """Per-device body of :func:`sequence_sharded_scan` — call inside an
    enclosing ``shard_map`` (e.g. a whole sequence-parallel model forward).
    ``x_l``: local (B, Tb, D) chunk; ``h0_l``: (B, H)."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    eff = (n - 1 - idx) if reverse else idx
    xt = jnp.moveaxis(x_l, 1, 0)                         # (Tb, B, D)
    if reverse:
        xt = jnp.flip(xt, 0)

    def chunk_scan(h):
        return jax.lax.scan(lambda c, x: step_fn(c, x), h, xt)

    # pipeline hop: forward passes state idx→idx+1; reverse idx→idx-1
    if reverse:
        perm = [(i + 1, i) for i in range(n - 1)]
    else:
        perm = [(i, i + 1) for i in range(n - 1)]

    ys_init = jnp.zeros((xt.shape[0],) + h0_l.shape, h0_l.dtype)

    def round_body(r, carry):
        h_in, ys_acc = carry
        h_fin, ys = chunk_scan(h_in)
        ys_acc = jnp.where(eff == r, ys, ys_acc)
        h_next = jax.lax.ppermute(h_fin, axis_name, perm)
        # devices at the pipeline head re-enter with the true initial
        # state (they only matter in round 0, already kept)
        h_next = jnp.where(eff == 0, h0_l, h_next)
        return h_next, ys_acc

    _, ys = jax.lax.fori_loop(0, n, round_body, (h0_l, ys_init))
    if reverse:
        ys = jnp.flip(ys, 0)
    return jnp.moveaxis(ys, 0, 1)                        # (B, Tb, H)


def sequence_scan_local_bidir(step_fwd, step_bwd, h0_l, x_l, axis_name: str):
    """Fused bidirectional pipelined scan — fwd and bwd directions share
    the SAME n rounds (one loop, two opposite ppermute pipelines), so a
    BiRNN layer costs n rounds, not 2n.  Returns (ys_fwd, ys_bwd), each
    (B, Tb, H).  Call inside shard_map."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    eff_f = idx
    eff_b = n - 1 - idx
    xt = jnp.moveaxis(x_l, 1, 0)                         # (Tb, B, D)
    xt_rev = jnp.flip(xt, 0)

    perm_f = [(i, i + 1) for i in range(n - 1)]
    perm_b = [(i + 1, i) for i in range(n - 1)]
    ys_init = jnp.zeros((xt.shape[0],) + h0_l.shape, h0_l.dtype)

    def round_body(r, carry):
        hf_in, hb_in, ysf_acc, ysb_acc = carry
        hf_fin, ysf = jax.lax.scan(lambda c, x: step_fwd(c, x), hf_in, xt)
        hb_fin, ysb = jax.lax.scan(lambda c, x: step_bwd(c, x), hb_in, xt_rev)
        ysf_acc = jnp.where(eff_f == r, ysf, ysf_acc)
        ysb_acc = jnp.where(eff_b == r, ysb, ysb_acc)
        hf_next = jax.lax.ppermute(hf_fin, axis_name, perm_f)
        hb_next = jax.lax.ppermute(hb_fin, axis_name, perm_b)
        hf_next = jnp.where(eff_f == 0, h0_l, hf_next)
        hb_next = jnp.where(eff_b == 0, h0_l, hb_next)
        return hf_next, hb_next, ysf_acc, ysb_acc

    _, _, ysf, ysb = jax.lax.fori_loop(
        0, n, round_body, (h0_l, h0_l, ys_init, ys_init))
    return (jnp.moveaxis(ysf, 0, 1),
            jnp.moveaxis(jnp.flip(ysb, 0), 0, 1))


class RingAttentionLayer:
    """Callable bundling a mesh + settings, usable as a model-side op for
    long-context attention blocks (net-new capability vs the reference)."""

    def __init__(self, mesh: Mesh, axis_name: str = SEQUENCE_AXIS,
                 causal: bool = False):
        self.mesh = mesh
        self.axis_name = axis_name
        self.causal = causal

    def __call__(self, q, k, v):
        return ring_attention(q, k, v, self.mesh, self.axis_name, self.causal)
