"""Sequence/context parallelism: ring attention + sequence-sharded helpers.

The reference's only long-sequence mechanism is data-level chunking
(``TimeSegmenter.scala:11``: split audio into independent rows, re-join by
``(audio_id, seq)`` — see SURVEY.md §5 "Long-context").  A TPU-native
framework needs true *sequence parallelism*: shard the time axis T across
the mesh's ``sequence`` axis and exchange blocks over ICI.

This module provides:

- :func:`ring_attention` — blockwise attention where K/V blocks rotate
  around the ring via ``lax.ppermute`` while each device keeps a running
  online-softmax (flash-attention style) over its local Q block.  Memory
  per device is O(T/n · T/n) instead of O(T²); the n-step rotation overlaps
  compute with ICI transfers.  Supports causal masking via global block
  offsets.
- :func:`shard_sequence` / :func:`unshard_sequence` — place (B, T, …)
  activations on the sequence axis.
- collective helpers (:func:`psum_mean`, :func:`ring_shift`) used by
  sequence-parallel layers.

All functions are built on ``shard_map`` over an explicit Mesh, so they
compose with the data-parallel train step (mesh axes ``("data",
"sequence")``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.parallel.mesh import SEQUENCE_AXIS

NEG_INF = -1e30


def shard_sequence(x, mesh: Mesh, axis_name: str = SEQUENCE_AXIS):
    """Place (B, T, …) on the mesh with T sharded over ``axis_name``."""
    spec = P(None, axis_name, *([None] * (np.ndim(x) - 2)))
    return jax.device_put(x, NamedSharding(mesh, spec))


def unshard_sequence(x):
    return jax.device_get(x)


def psum_mean(x, axis_name: str):
    """Mean across an axis's devices (gradient/metric reduction helper)."""
    return jax.lax.psum(x, axis_name) / jax.lax.psum(1, axis_name)


def ring_shift(x, axis_name: str, shift: int = 1):
    """Rotate a block one hop around the ring (ppermute over ICI)."""
    n = jax.lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def _ring_attention_local(q, k, v, axis_name: str, causal: bool,
                          scale: Optional[float]):
    """Per-device body: q/k/v are LOCAL blocks (B, Tb, H, D)."""
    B, Tb, H, D = q.shape
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    scale = scale if scale is not None else 1.0 / np.sqrt(D)

    # accumulators in (B, H, Tq) layout for the online softmax
    o = jnp.zeros((B, H, Tb, D), q.dtype)
    l = jnp.zeros((B, H, Tb), jnp.float32)
    m = jnp.full((B, H, Tb), NEG_INF, jnp.float32)
    q_pos = my_idx * Tb + jnp.arange(Tb)                 # global q positions

    def step(r, carry):
        o, l, m, k_cur, v_cur = carry
        # k_cur originated on device (my_idx - r) mod n
        src = (my_idx - r) % n
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur) * scale
        scores = scores.astype(jnp.float32)
        if causal:
            k_pos = src * Tb + jnp.arange(Tb)
            mask = q_pos[:, None] >= k_pos[None, :]      # (Tq, Tk)
            scores = jnp.where(mask[None, None], scores, NEG_INF)
        blk_max = jnp.max(scores, axis=-1)               # (B, H, Tq)
        new_m = jnp.maximum(m, blk_max)
        p = jnp.exp(scores - new_m[..., None])
        # rows with no valid key yet: new_m stays NEG_INF -> p would be
        # exp(0)=1 garbage; zero them explicitly
        p = jnp.where((new_m[..., None] > NEG_INF / 2), p, 0.0)
        corr = jnp.where(m > NEG_INF / 2, jnp.exp(m - new_m), 0.0)
        l = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v_cur.dtype), v_cur)
        o = o * corr[..., None].astype(o.dtype) + pv
        # rotate K/V one hop; after n steps every device saw every block
        k_next = ring_shift(k_cur, axis_name)
        v_next = ring_shift(v_cur, axis_name)
        return o, l, m * 0 + new_m, k_next, v_next

    o, l, m, _, _ = jax.lax.fori_loop(0, n, step, (o, l, m, k, v))
    out = o / jnp.maximum(l, 1e-20)[..., None].astype(o.dtype)
    return jnp.einsum("bhqd->bqhd", out)


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = SEQUENCE_AXIS,
                   causal: bool = False, scale: Optional[float] = None):
    """Sequence-parallel attention over a T-sharded batch.

    q, k, v: (B, T, H, D) with T sharded over ``axis_name`` (use
    :func:`shard_sequence`).  Returns (B, T, H, D), same sharding.  Inside
    jit, XLA lowers the per-step ``ppermute`` to ICI sends overlapping the
    per-block matmuls — the standard ring-attention schedule.
    """
    spec = P(None, axis_name, None, None)
    body = functools.partial(_ring_attention_local, axis_name=axis_name,
                             causal=causal, scale=scale)
    try:
        fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    except TypeError:  # older jax uses check_rep
        fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_rep=False)
    return fn(q, k, v)


def full_attention(q, k, v, causal: bool = False,
                   scale: Optional[float] = None):
    """Single-device reference implementation (for tests and small T)."""
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(D)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        Tq, Tk = scores.shape[-2], scores.shape[-1]
        mask = jnp.arange(Tq)[:, None] >= jnp.arange(Tk)[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


class RingAttentionLayer:
    """Callable bundling a mesh + settings, usable as a model-side op for
    long-context attention blocks (net-new capability vs the reference)."""

    def __init__(self, mesh: Mesh, axis_name: str = SEQUENCE_AXIS,
                 causal: bool = False):
        self.mesh = mesh
        self.axis_name = axis_name
        self.causal = causal

    def __call__(self, q, k, v):
        return ring_attention(q, k, v, self.mesh, self.axis_name, self.causal)
