"""Tensor (model) parallelism via GSPMD sharding rules.

The reference scales one way only — data-parallel replicas with a
block-manager AllReduce (SURVEY.md §2.7 "Optimizer") — because BigDL
models must fit one executor.  On TPU the idiomatic generalization is not
explicit collectives but *sharding annotations*: place weight shards on a
``model`` mesh axis with ``NamedSharding`` and let XLA's SPMD partitioner
split the matmuls/convs and insert the all-gathers/reduce-scatters over
ICI (the scaling-book recipe: pick a mesh, annotate, let XLA do the
rest).  Nothing in the train step changes — the same jitted program runs
1D data-parallel or 2D data×model depending only on where the arrays
live.

Rules are matched against the '/'-joined pytree path, so they apply
equally to ``params`` and to optimizer slots that mirror params (optax's
``mu``/``nu``/``trace`` carry the same sub-paths).  A dimension that
doesn't divide the mesh axis falls back to replicated — sharding is an
optimization, never a correctness requirement.
"""

from __future__ import annotations

import logging
import re
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

logger = logging.getLogger("analytics_zoo_tpu")

# rule: (path_regex, spec_fn(shape) -> PartitionSpec-axis-tuple)
Rule = Tuple[str, Callable[[Tuple[int, ...]], Sequence[Optional[str]]]]


def _last_dim(axis: str):
    """Shard the trailing (output-feature) dim — Dense kernels (in, out),
    Conv kernels (kh, kw, cin, cout), Embed tables (vocab, features)."""
    def spec(shape):
        return [None] * (len(shape) - 1) + [axis]
    return spec


def _contract_dim(axis: str):
    """Shard the CONTRACTION (input-feature) dim — dim 0 of a Dense
    (in, out) kernel, dim -2 of a Conv (kh, kw, cin, cout) kernel.  The
    matmul/conv then reduces over a sharded dim: each device contracts
    its channel slice locally and XLA inserts one all-reduce after
    (Megatron's "row-parallel" half)."""
    def spec(shape):
        axes: List[Optional[str]] = [None] * len(shape)
        axes[0 if len(shape) <= 2 else len(shape) - 2] = axis
        return axes
    return spec


def _row_dim(axis: str):
    """Shard dim 0 — the VOCAB dim of an Embed (vocab, features) table.
    Row sharding is what large lookup tables want: each device owns a
    contiguous id range and a lookup is a shard-local gather (the SPMD
    partitioner inserts the combine), whereas column sharding splits
    every row's features and makes EVERY lookup touch EVERY device."""
    def spec(shape):
        axes: List[Optional[str]] = [None] * len(shape)
        axes[0] = axis
        return axes
    return spec


def embedding_row_rules(axis: str = MODEL_AXIS) -> List[Rule]:
    """Row-shard every ``embedding`` table over ``axis`` (vocab dim 0).
    The rule a pipeline's ``param_rules`` prepends for large-vocab
    lookup tables; optimizer slots mirror it through their sub-paths."""
    return [
        (r"(^|.*/)embedding$", _row_dim(axis)),
    ]


def default_tp_rules(axis: str = MODEL_AXIS) -> List[Rule]:
    """Megatron-style column sharding of every learnable matrix's output
    features; biases/scales stay replicated (1-D, tiny).  Embedding
    tables take the ROW rule first: a (vocab, dim) table column-sharded
    on dim 1 (the pre-ISSUE-17 behavior of the generic rule below) puts
    a slice of every row on every device, which is the wrong axis for
    large vocabularies — first-match precedence routes them to
    ``embedding_row_rules`` instead."""
    return embedding_row_rules(axis) + [
        (r"(^|.*/)kernel$", _last_dim(axis)),
    ]


def megatron_tp_rules(col: Sequence[str], row: Sequence[str],
                      axis: str = MODEL_AXIS) -> List[Rule]:
    """Paired column/row rules from two lists of layer names.

    ``col`` layers shard output features (their activations leave
    channel-sharded); ``row`` layers shard the contraction dim (they
    consume a channel-sharded OR replicated input with zero gather cost
    and emit a replicated output after one all-reduce).  Chaining
    col→row is the Megatron MLP pattern: exactly one collective per
    pair, never an activation all-gather.  Names match any path
    component, so ``"conv1_1"`` covers ``params/vgg/conv1_1/kernel`` and
    its optimizer-slot mirrors."""
    def name_rule(names: Sequence[str], spec_fn) -> Rule:
        alt = "|".join(re.escape(n) for n in names)
        return (rf"(^|.*/)({alt})/(kernel|embedding)$", spec_fn)

    return [name_rule(col, _last_dim(axis)),
            name_rule(row, _contract_dim(axis))]


def ssd_tp_rules(axis: str = MODEL_AXIS,
                 resolution: int = 300) -> List[Rule]:
    """Tensor-parallel rules tuned to the SSDVgg topology.

    The generic ``default_tp_rules`` col-shards EVERY kernel — but the
    SSD conf/loc heads have small non-divisible cout (84/126), so their
    kernels fall back to replicated while their INPUTS arrive
    channel-sharded from the col-sharded trunk: GSPMD then has no
    efficient path and emits "Involuntary full rematerialization"
    (observed on the conf_2 conv in the 8-device dryrun).

    Here every edge is a clean Megatron pair instead: layers whose
    outputs feed another sharded conv or a detection head are column
    (cout) sharded; their consumers — including every loc_*/conf_* head,
    whose contraction dim (512/1024/256) always divides the axis — are
    row (cin) sharded.  Head outputs come back replicated (one psum),
    which is exactly what the concat + MultiBoxLoss want."""
    col = [
        # one col per VGG block boundary + the head-source producers
        "conv1_1", "conv2_1", "conv3_1", "conv4_1", "conv4_3",
        "conv5_2", "fc7",
        "conv6_2", "conv7_2", "conv8_2", "conv9_2",
    ]
    row = [
        "conv1_2", "conv2_2", "conv3_2", "conv3_3", "conv4_2",
        "conv5_1", "conv5_3", "fc6",
        "conv6_1", "conv7_1", "conv8_1", "conv9_1",
        "loc_0", "loc_1", "loc_2", "loc_3", "loc_4", "loc_5",
        "conf_0", "conf_1", "conf_2", "conf_3", "conf_4", "conf_5",
    ]
    if resolution != 300:
        # SSD512 adds one extra block + a 7th head pair, same pairing.
        # Mirror the MODEL's branch (models/ssd.py ExtraLayers builds the
        # conv10/7-source topology for any resolution != 300) — an
        # inverted guard would hand a 512-topology model the 300 rule
        # set, recreating the replicated-kernel-fed-by-sharded-input
        # rematerialization this module exists to avoid.
        col.append("conv10_2")
        row += ["conv10_1", "loc_6", "conf_6"]
    return megatron_tp_rules(col, row, axis)


def spatial_input_spec(axis: str = MODEL_AXIS,
                       data_axis_name: str = DATA_AXIS) -> P:
    """PartitionSpec for NHWC image batches with the HEIGHT axis sharded
    over the model axis — *spatial partitioning*, the conv-net tensor
    parallelism that actually pays on TPU.

    Channel (Megatron) sharding of a VGG-style trunk all-reduces FULL
    spatial activation maps once per col/row pair — measured 2.1× slower
    than this mode on the virtual-mesh microbench (TP_MICROBENCH.json).
    With H sharded and weights replicated, XLA's SPMD partitioner inserts
    only halo exchanges of kernel_h/2 edge rows per conv (communication
    O(B·W·C·halo), not O(B·H·W·C)), so each device convolves a horizontal
    stripe.  Use with ``shard_batch(..., overrides={"input":
    spatial_input_spec()})`` — parameters stay replicated (no rules).
    Keep ``ssd_tp_rules``/``megatron_tp_rules`` for models whose FLOPs
    live in dense/1×1 layers, where the activation all-reduce is small
    relative to the weight shards gained."""
    return P(data_axis_name, axis, None, None)


def rule_axes(rules: Sequence[Rule]) -> frozenset:
    """Mesh-axis names a rule set can resolve to, discovered by probing
    each spec builder across leaf ranks 1..4 (builders close over their
    axis names — there is no declarative field to read).  Used by the
    elastic boundary (``SpecSet.declared_axes``) to check whether a new
    mesh still covers what the declaration shards."""
    axes = set()
    for _, spec_fn in rules:
        for rank in (1, 2, 3, 4):
            try:
                resolved = spec_fn((2,) * rank)
            except Exception:
                continue
            for part in resolved:
                if part is None:
                    continue
                for ax in (part if isinstance(part, tuple) else (part,)):
                    axes.add(ax)
    return frozenset(axes)


def partition_spec(path: str, shape: Tuple[int, ...], mesh: Mesh,
                   rules: Sequence[Rule]) -> P:
    """Resolve the first matching rule into a PartitionSpec, degrading to
    replicated when the sharded dim doesn't divide the mesh axis."""
    for pattern, spec_fn in rules:
        if re.match(pattern, path):
            axes = list(spec_fn(shape))
            for i, ax in enumerate(axes):
                if ax is not None and (ax not in mesh.shape
                                       or shape[i] % mesh.shape[ax] != 0):
                    logger.debug("tp: %s dim %d (%d) not divisible by "
                                 "axis %r — replicating", path, i, shape[i], ax)
                    axes[i] = None
            return P(*axes)
    return P()


def spec_tree(tree: Any, mesh: Mesh,
              rules: Optional[Sequence[Rule]] = None) -> Any:
    """PartitionSpec for every leaf of ``tree``, structure-matched —
    the declare-once form the spec layer (``parallel.specs``) registers
    per pipeline.  Scalars and rule-misses resolve to replicated.
    ``shard_tree`` is exactly ``device_put`` over this tree, so the
    specs a pipeline declares and the placement it gets can't drift."""
    rules = default_tp_rules() if rules is None else rules

    def resolve(path_entries, leaf):
        path = "/".join(str(getattr(e, "key", getattr(e, "name", e)))
                        for e in path_entries)
        # read .shape where the leaf carries one (arrays AND abstract
        # ShapeDtypeStructs — the az-analyze audit resolves specs over
        # eval_shape trees); only coerce true scalars/lists through numpy
        shape = getattr(leaf, "shape", None)
        if shape is None:
            shape = np.asarray(leaf).shape
        return (partition_spec(path, tuple(shape), mesh, rules)
                if len(shape) > 0 else P())

    return jax.tree_util.tree_map_with_path(resolve, tree)


def shard_tree(tree: Any, mesh: Mesh,
               rules: Optional[Sequence[Rule]] = None) -> Any:
    """device_put every leaf with its rule-resolved NamedSharding.  Works
    on a params dict or a whole TrainState (optimizer slots that mirror
    params pick up the same specs through their matching sub-paths)."""
    specs = spec_tree(tree, mesh, rules)
    return jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        tree, specs)


def sharded_param_count(tree: Any) -> int:
    """Number of array LEAVES whose sharding actually splits data across
    more than one device (diagnostic for tests/logging).  On a full
    TrainState this counts optimizer-slot mirrors too (momentum/mu/nu
    carry the same sharding as their parameter), so it is a leaf count,
    not a distinct-parameter count — pass just the params subtree for
    the latter."""
    n = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        sh = getattr(leaf, "sharding", None)
        if sh is not None and not sh.is_fully_replicated:
            n += 1
    return n
