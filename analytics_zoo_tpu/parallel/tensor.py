"""Tensor (model) parallelism via GSPMD sharding rules.

The reference scales one way only — data-parallel replicas with a
block-manager AllReduce (SURVEY.md §2.7 "Optimizer") — because BigDL
models must fit one executor.  On TPU the idiomatic generalization is not
explicit collectives but *sharding annotations*: place weight shards on a
``model`` mesh axis with ``NamedSharding`` and let XLA's SPMD partitioner
split the matmuls/convs and insert the all-gathers/reduce-scatters over
ICI (the scaling-book recipe: pick a mesh, annotate, let XLA do the
rest).  Nothing in the train step changes — the same jitted program runs
1D data-parallel or 2D data×model depending only on where the arrays
live.

Rules are matched against the '/'-joined pytree path, so they apply
equally to ``params`` and to optimizer slots that mirror params (optax's
``mu``/``nu``/``trace`` carry the same sub-paths).  A dimension that
doesn't divide the mesh axis falls back to replicated — sharding is an
optimization, never a correctness requirement.
"""

from __future__ import annotations

import logging
import re
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.parallel.mesh import MODEL_AXIS

logger = logging.getLogger("analytics_zoo_tpu")

# rule: (path_regex, spec_fn(shape) -> PartitionSpec-axis-tuple)
Rule = Tuple[str, Callable[[Tuple[int, ...]], Sequence[Optional[str]]]]


def _last_dim(axis: str):
    """Shard the trailing (output-feature) dim — Dense kernels (in, out),
    Conv kernels (kh, kw, cin, cout), Embed tables (vocab, features)."""
    def spec(shape):
        return [None] * (len(shape) - 1) + [axis]
    return spec


def default_tp_rules(axis: str = MODEL_AXIS) -> List[Rule]:
    """Megatron-style column sharding of every learnable matrix's output
    features; biases/scales stay replicated (1-D, tiny)."""
    return [
        (r"(^|.*/)(kernel|embedding)$", _last_dim(axis)),
    ]


def partition_spec(path: str, shape: Tuple[int, ...], mesh: Mesh,
                   rules: Sequence[Rule]) -> P:
    """Resolve the first matching rule into a PartitionSpec, degrading to
    replicated when the sharded dim doesn't divide the mesh axis."""
    for pattern, spec_fn in rules:
        if re.match(pattern, path):
            axes = list(spec_fn(shape))
            for i, ax in enumerate(axes):
                if ax is not None and (ax not in mesh.shape
                                       or shape[i] % mesh.shape[ax] != 0):
                    logger.debug("tp: %s dim %d (%d) not divisible by "
                                 "axis %r — replicating", path, i, shape[i], ax)
                    axes[i] = None
            return P(*axes)
    return P()


def shard_tree(tree: Any, mesh: Mesh,
               rules: Optional[Sequence[Rule]] = None) -> Any:
    """device_put every leaf with its rule-resolved NamedSharding.  Works
    on a params dict or a whole TrainState (optimizer slots that mirror
    params pick up the same specs through their matching sub-paths)."""
    rules = default_tp_rules() if rules is None else rules

    def put(path_entries, leaf):
        path = "/".join(str(getattr(e, "key", getattr(e, "name", e)))
                        for e in path_entries)
        arr = np.asarray(leaf) if not isinstance(leaf, jax.Array) else leaf
        spec = (partition_spec(path, arr.shape, mesh, rules)
                if getattr(arr, "ndim", 0) > 0 else P())
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(put, tree)


def sharded_param_count(tree: Any) -> int:
    """Number of array LEAVES whose sharding actually splits data across
    more than one device (diagnostic for tests/logging).  On a full
    TrainState this counts optimizer-slot mirrors too (momentum/mu/nu
    carry the same sharding as their parameter), so it is a leaf count,
    not a distinct-parameter count — pass just the params subtree for
    the latter."""
    n = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        sh = getattr(leaf, "sharding", None)
        if sh is not None and not sh.is_fully_replicated:
            n += 1
    return n
