"""Declare-once PartitionSpecs: the single sharding substrate.

The reference distributes one way — synchronous data-parallel replicas
over Spark executors (``DistriOptimizer``, SURVEY.md §2.7) — and every
entry point re-implements that placement.  Before this module our TPU
port had started to mirror the same drift: ``parallel/mesh.py`` placed
data-parallel batches, ``parallel/tensor.py`` placed tensor-parallel
weights, and each pipeline picked its own combination inline.  Here the
GSPMD/pjit pattern (SNIPPETS.md [1]–[3]) is made the ONE convention:

* a pipeline declares its PartitionSpec tree **exactly once** — a
  :class:`SpecSet` built from the registry below — and everything that
  places arrays (``make_train_step``/``make_eval_step`` jit
  ``in_shardings``/``out_shardings``, ``Optimizer._place_state``, the
  serving predictors) consumes that object;
* data/tensor/pipeline parallelism then compose by changing the MESH
  SHAPE, not the pipeline: the same declared specs resolve against a
  ``(8,)`` data mesh, a ``(2, 4)`` data×model mesh, or a multi-host
  mesh, with non-divisible dims degrading to replicated
  (``tensor.partition_spec``).

Axis conventions (``parallel.mesh``): ``data`` carries dim 0 of every
batch leaf; ``model`` carries weight shards (Megatron rules) or image
height (spatial partitioning); ``sequence`` carries time.  Parameters
without a matching rule are replicated — sharding is an optimization,
never a correctness requirement.

Registry::

    specs = pipeline_specs("ds2", mesh=mesh)          # declared once
    state = specs.place_state(create_train_state(model, optim))
    step = make_train_step(model.module, crit, optim, specs=specs)
    ...                                # jit places host batches itself

``tests/test_specs.py`` pins the contract: every registered pipeline's
spec tree structure-matches its param tree, and a shard→gather
roundtrip is byte-identical.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.parallel import mesh as mesh_lib
from analytics_zoo_tpu.resilience.errors import ElasticPlacementError


def _spec_axes(spec) -> set:
    """Mesh-axis names one PartitionSpec (or axis sequence) references."""
    axes = set()
    for part in spec:
        if part is None:
            continue
        for ax in (part if isinstance(part, tuple) else (part,)):
            axes.add(ax)
    return axes


@dataclasses.dataclass(frozen=True)
class SpecSet:
    """One pipeline's declared sharding: mesh + state rules + batch specs.

    ``rules``: ``parallel.tensor`` ``(path_regex, spec_fn)`` pairs
    resolving parameter/optimizer-slot leaves (``None`` = everything
    replicated — pure data parallelism).  ``batch_overrides``: top-level
    batch keys whose leaves take an explicit PartitionSpec instead of the
    default dim-0-over-``data`` (e.g. spatial tensor parallelism's
    ``{"input": tensor.spatial_input_spec()}``).

    The object is both the *declaration* (spec trees, for tests and
    docs) and the *placement engine* (``place_state``/``place_batch``/
    jit sharding annotations) — one source of truth, so a refactor
    cannot change where arrays land without changing what the pipeline
    declared.
    """

    mesh: Mesh
    rules: Optional[Sequence] = None
    batch_overrides: Optional[Dict[str, P]] = None

    # -- spec trees (the declaration) -----------------------------------
    def state_specs(self, state: Any) -> Any:
        """PartitionSpec tree structure-matching ``state`` (a params dict
        or a whole TrainState; optimizer slots mirror their parameter's
        spec through path matching)."""
        from analytics_zoo_tpu.parallel import tensor as tensor_lib

        if self.rules is None:
            return jax.tree_util.tree_map(lambda _: P(), state)
        return tensor_lib.spec_tree(state, self.mesh, self.rules)

    def batch_specs(self, batch: Any) -> Any:
        """PartitionSpec tree for one batch pytree: dim 0 over ``data``,
        scalars replicated, ``batch_overrides`` honored per top-level
        key."""
        axis = mesh_lib.data_axis(self.mesh)

        def default(leaf):
            arr = np.asarray(leaf) if not hasattr(leaf, "ndim") else leaf
            if arr.ndim == 0:
                return P()
            return P(*([axis] + [None] * (arr.ndim - 1)))

        if not (self.batch_overrides and isinstance(batch, dict)):
            return jax.tree_util.tree_map(default, batch)
        return {k: (jax.tree_util.tree_map(
                        lambda leaf, k=k: self.batch_overrides[k], v)
                    if k in self.batch_overrides
                    else jax.tree_util.tree_map(default, v))
                for k, v in batch.items()}

    # -- jit annotations ------------------------------------------------
    @property
    def replicated(self) -> NamedSharding:
        """Replicated NamedSharding — scalars (lr, metrics) and, as a
        pytree prefix, whole replicated trees (variables, DP state)."""
        return NamedSharding(self.mesh, P())

    @property
    def data_axis_size(self) -> int:
        """Width of the batch-carrying mesh axis (replica count)."""
        return int(self.mesh.shape[mesh_lib.data_axis(self.mesh)])

    @property
    def data_sharding(self) -> NamedSharding:
        """Dim-0-over-``data`` NamedSharding; as a jit pytree PREFIX it
        broadcasts over a whole batch tree of batch-major leaves."""
        return NamedSharding(self.mesh, P(mesh_lib.data_axis(self.mesh)))

    def state_shardings(self, state: Any = None):
        """jit ``in_shardings``/``out_shardings`` entry for the train
        state.  Pure data parallelism needs no structure — a replicated
        prefix covers any state tree; with rules armed the concrete
        ``state`` is required to resolve per-leaf specs."""
        if self.rules is None:
            return self.replicated
        if state is None:
            raise ValueError("state_shardings with tensor-parallel rules "
                             "needs the concrete state tree")
        return jax.tree_util.tree_map(
            lambda spec: NamedSharding(self.mesh, spec),
            self.state_specs(state))

    def batch_shardings(self):
        """jit ``in_shardings`` entry for batches, or ``None`` when jit
        cannot place them (per-key overrides need the spec layer's own
        ``place_batch``; jit prefixes cannot express per-key specs over
        an open batch structure)."""
        if self.batch_overrides:
            return None
        return self.data_sharding

    def ragged_dispatch(self, annotated: Callable, plain: Callable
                        ) -> Callable:
        """ONE routing rule for annotated serving/eval programs, owned
        by the spec layer: ``dispatch(variables, *batch_args)`` runs the
        mesh-``annotated`` program when the first batch argument's
        leading dim divides the data axis, and the ``plain`` program for
        ragged tails (remainder predict/validation batches) or 0-d
        probes.  `make_eval_step` and the serving predictors share this
        instead of hand-rolling divergent copies."""
        width = self.data_axis_size

        def dispatch(variables, *args):
            leaf = jax.tree_util.tree_leaves(args[0])[0]
            shape = getattr(leaf, "shape", None)
            if shape and shape[0] % width == 0:
                return annotated(variables, *args)
            return plain(variables, *args)

        return dispatch

    def jit_places_batches(self) -> bool:
        """True when host batches can go straight into the annotated jit
        (single-process mesh, no per-key overrides) — the GSPMD
        declare-once fast path.  Multi-process meshes assemble global
        arrays from per-host shards (``place_batch``) instead."""
        return (self.batch_shardings() is not None
                and not mesh_lib.spans_processes(self.mesh))

    # -- elastic resize (declaration ⊆ mesh coverage) --------------------
    def declared_axes(self) -> frozenset:
        """Every mesh-axis name the declaration references: the batch
        overrides' PartitionSpecs plus the axes the state rules can
        resolve to (probed — rule spec builders close over their axis
        names; see ``tensor.rule_axes``)."""
        from analytics_zoo_tpu.parallel import tensor as tensor_lib

        axes = set()
        for spec in (self.batch_overrides or {}).values():
            axes |= _spec_axes(spec)
        if self.rules:
            axes |= set(tensor_lib.rule_axes(self.rules))
        return frozenset(axes)

    def missing_axes(self) -> tuple:
        """Declared axes ``self.mesh`` does not carry, sorted.  Rule axes
        in this set DEGRADE to replicated (sharding is an optimization);
        override axes in it would fail placement — ``place_batch`` /
        ``place_state`` surface that as ElasticPlacementError."""
        return tuple(sorted(self.declared_axes()
                            - set(self.mesh.axis_names)))

    def replace_mesh(self, new_mesh: Mesh) -> "SpecSet":
        """The elastic-resize boundary: the SAME declaration re-placed
        onto a different mesh (a checkpoint saved at width W restores at
        W′ by re-running ``place_state`` under the returned SpecSet —
        params are width-agnostic host values by construction).

        Raises :class:`ElasticPlacementError` when ``new_mesh`` drops an
        axis the declaration RESOLVED on the current mesh: silently
        degrading active tensor-parallel sharding mid-resize would
        change program geometry without a trace.  Callers who want the
        degradation build a fresh SpecSet via ``pipeline_specs``."""
        active = self.declared_axes() & set(self.mesh.axis_names)
        missing = tuple(sorted(active - set(new_mesh.axis_names)))
        if missing:
            raise ElasticPlacementError(
                f"replace_mesh: new mesh axes {tuple(new_mesh.axis_names)} "
                f"do not cover declared axes {missing} that the current "
                f"mesh {tuple(self.mesh.axis_names)} resolves — an elastic "
                f"re-placement must not silently drop active sharding")
        return dataclasses.replace(self, mesh=new_mesh)

    def _require_override_axes(self, site: str) -> None:
        """Boundary check: batch-override axes absent from the mesh would
        otherwise surface as an opaque NamedSharding failure deep inside
        jax at device_put time."""
        missing = tuple(sorted(
            {ax for spec in (self.batch_overrides or {}).values()
             for ax in _spec_axes(spec)} - set(self.mesh.axis_names)))
        if missing:
            raise ElasticPlacementError(
                f"{site}: mesh axes {tuple(self.mesh.axis_names)} do not "
                f"cover batch-override axes {missing} — the declaration "
                f"cannot be placed on this mesh")

    # -- placement (the one device_put site) ----------------------------
    def place_state(self, state: Any) -> Any:
        """Host state pytree → mesh placement per the declared specs:
        replicate (multi-host aware) without rules, rule-resolved
        ``NamedSharding`` placement with them."""
        from analytics_zoo_tpu.parallel import tensor as tensor_lib

        self._require_override_axes("place_state")
        if self.rules is None:
            return mesh_lib.replicate(state, self.mesh)
        return tensor_lib.shard_tree(state, self.mesh, self.rules)

    def place_batch(self, batch: Any) -> Any:
        """Host batch pytree → mesh placement (dim 0 over ``data``,
        overrides honored, multi-host local-shard assembly)."""
        self._require_override_axes("place_batch")
        return mesh_lib.shard_batch(batch, self.mesh,
                                    overrides=self.batch_overrides)

    def gather(self, tree: Any) -> Any:
        """Device pytree → host numpy copy (replicated leaves read their
        local replica; byte-identical to what was placed — the
        roundtrip ``tests/test_specs.py`` pins)."""
        return mesh_lib.host_local_state(tree)


# ---------------------------------------------------------------------------
# Pipeline registry — every entry point declares here, once
# ---------------------------------------------------------------------------

_PIPELINES: Dict[str, Callable[..., SpecSet]] = {}


def register_pipeline(name: str):
    """Register a ``builder(mesh, **opts) -> SpecSet`` under ``name``.
    ``tests/test_specs.py`` iterates the registry, so a new pipeline
    gets the structure-match + roundtrip guards for free."""
    def deco(fn: Callable[..., SpecSet]):
        _PIPELINES[name] = fn
        return fn
    return deco


def registered_pipelines() -> Sequence[str]:
    return tuple(sorted(_PIPELINES))


def pipeline_specs(name: str, mesh: Optional[Mesh] = None,
                   **opts: Any) -> SpecSet:
    """The declared :class:`SpecSet` for a registered pipeline on
    ``mesh`` (default: 1-D data mesh over every device)."""
    if name not in _PIPELINES:
        raise KeyError(f"no specs registered for pipeline {name!r} "
                       f"(registered: {', '.join(registered_pipelines())})")
    return _PIPELINES[name](mesh or mesh_lib.create_mesh(), **opts)


@register_pipeline("ssd")
def _ssd_specs(mesh: Mesh, tp: Optional[str] = None,
               resolution: int = 300) -> SpecSet:
    """SSD detection training/serving.  ``tp=None``: pure data parallel
    (params replicated).  ``tp="spatial"``: image HEIGHT over ``model``
    — the conv-trunk mode that measured 2.1× faster than channel
    sharding (TP_MICROBENCH.json).  ``tp="megatron"``: paired col/row
    weight sharding (``tensor.ssd_tp_rules``)."""
    from analytics_zoo_tpu.parallel import tensor as tensor_lib

    if tp is None:
        return SpecSet(mesh)
    if tp == "spatial":
        return SpecSet(mesh, batch_overrides={
            "input": tensor_lib.spatial_input_spec()})
    if tp == "megatron":
        return SpecSet(mesh,
                       rules=tensor_lib.ssd_tp_rules(resolution=resolution))
    raise ValueError(f"ssd tp mode {tp!r} (None | 'spatial' | 'megatron')")


@register_pipeline("frcnn")
def _frcnn_specs(mesh: Mesh) -> SpecSet:
    """Faster-RCNN joint training: data parallel (the proposal/ROI ops
    are batch-local; weights replicated)."""
    return SpecSet(mesh)


@register_pipeline("ds2")
def _ds2_specs(mesh: Mesh, param_rules: Optional[Sequence] = None
               ) -> SpecSet:
    """DeepSpeech2 CTC training: length-bucketed batches dim-0 over
    ``data`` (the (features, n_frames) input tuple is batch-major on
    both legs); optional tensor-parallel weight rules on a data×model
    mesh."""
    return SpecSet(mesh, rules=param_rules)


@register_pipeline("fraud")
def _fraud_specs(mesh: Mesh) -> SpecSet:
    """Fraud-detection MLP: pure data parallel."""
    return SpecSet(mesh)


@register_pipeline("rec")
def _rec_specs(mesh: Mesh, shard_tables: bool = True) -> SpecSet:
    """Recommendation (NeuralCF / Wide&Deep): data-parallel batches with
    every ``(vocab, dim)`` lookup table ROW-sharded over ``model`` when
    the mesh declares that axis (``tensor.embedding_row_rules`` — each
    device owns an id range; the lookup compiles to a shard-local gather
    plus the partitioner's collectives).  On a pure data mesh the rule
    degrades to replicated, so the same declaration serves both."""
    from analytics_zoo_tpu.parallel import tensor as tensor_lib

    rules = tensor_lib.embedding_row_rules() if shard_tables else None
    return SpecSet(mesh, rules=rules)


@register_pipeline("sentiment")
def _sentiment_specs(mesh: Mesh, shard_tables: bool = True) -> SpecSet:
    """Sentiment heads over a GloVe-scale vocab table: same embedding
    row-sharding declaration as ``rec`` (the table dominates the model's
    parameter count; the recurrent/conv head stays replicated)."""
    from analytics_zoo_tpu.parallel import tensor as tensor_lib

    rules = tensor_lib.embedding_row_rules() if shard_tables else None
    return SpecSet(mesh, rules=rules)
