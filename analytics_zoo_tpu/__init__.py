"""analytics_zoo_tpu — a TPU-native analytics/deep-learning framework.

A ground-up JAX/XLA/Pallas re-design of the capability surface of the early
Analytics Zoo (BigDL-on-Spark zoo of pipelines: SSD object detection,
DeepSpeech2 ASR, fraud detection, sentiment / recommendation apps, and the
transform/vision image-augmentation library).

Reference capability map: see SURVEY.md at the repo root.  Design notes:

- Compute path is jax.numpy / flax on XLA:TPU; hot detection ops (NMS,
  multibox matching) are vectorized with static shapes so they stay on the MXU
  instead of the reference's sequential JVM loops
  (reference: pipeline/ssd/.../common/nn/MultiBoxLoss.scala, Nms.scala).
- Distribution is jax.sharding.Mesh + pjit/shard_map with XLA collectives
  over ICI, replacing BigDL's Spark block-manager AllReduce
  (reference: §2.7 of SURVEY.md).
- The data layer is a host-side iterator-transformer pipeline with device
  prefetch, replacing Spark RDD chains and Hadoop SequenceFiles.
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("AZ_PLATFORM"):
    # Explicit backend override (e.g. AZ_PLATFORM=cpu to debug locally or
    # when the remote TPU relay is unreachable).  Must land before the
    # first backend touch; plugins that force their own jax_platforms at
    # registration (e.g. the axon relay) are overridden here too, which a
    # plain JAX_PLATFORMS env var is not able to do.
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["AZ_PLATFORM"])

from analytics_zoo_tpu.utils import engine  # noqa: F401
