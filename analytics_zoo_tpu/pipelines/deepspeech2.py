"""DeepSpeech2 inference pipeline: audio → transcript, batched on TPU.

Port of the reference's L6 ASR pipeline (``deepspeech2/example/
InferenceExample.scala:11``, ``InferenceEvaluate.scala:14``): read audio →
TimeSegmenter chunks tagged (audio_id, seq) → featurize → model forward →
greedy CTC decode → re-join per utterance ordered by seq → WER/CER.

The reference forwards one 1×1×13×T chunk per DataFrame row (batch size 1,
SURVEY.md §3.4 hot-loop note); here all segments across utterances are
padded to ``utt_length`` and forwarded as ONE batch per ``batch_size``
group — the MXU sees big matmuls, not row-at-a-time traffic.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from analytics_zoo_tpu.core.module import Model
from analytics_zoo_tpu.models import DeepSpeech2
from analytics_zoo_tpu.parallel import make_eval_step
from analytics_zoo_tpu.transform.audio import (
    ALPHABET,
    ASREvaluator,
    SAMPLE_RATE,
    TimeSegmenter,
    VocabDecoder,
    best_path_decode,
    featurize,
    read_audio,
)

logger = logging.getLogger("analytics_zoo_tpu")


@dataclasses.dataclass
class DS2Param:
    """Reference ``util/Param.scala:17-34``: segment seconds, partitions…"""

    segment_seconds: int = 30
    batch_size: int = 8
    n_mels: int = 13
    vocab: Optional[Sequence[str]] = None
    # featurize (window → rFFT → mel) on device as one jitted batch
    # program instead of per-segment host numpy (SURVEY.md §3.4 hot loop)
    device_featurize: bool = True
    # 'greedy' (reference BestPathDecoder) | 'beam' (prefix beam search —
    # sums alignment mass per prefix; net-new over the reference)
    decoder: str = "greedy"
    beam_width: int = 16

    @property
    def utt_length(self) -> int:
        # uttLength = segment·100 frames (reference InferenceExample.scala:58)
        return self.segment_seconds * 100


class DeepSpeech2Pipeline:
    """fit-less inference pipeline (the reference's Spark ML Pipeline of 6
    stages collapses into segment → featurize → forward → decode).

    ``sequence_mesh``: a Mesh with a ``sequence`` axis switches the forward
    to the time-sharded ``models.deepspeech2.sequence_parallel_forward`` —
    utterances longer than one chip's HBM stream through exactly, instead
    of relying on the lossy TimeSegmenter chunking alone.
    """

    def __init__(self, model: Model, param: DS2Param = DS2Param(),
                 sequence_mesh=None, clock=None):
        from analytics_zoo_tpu.utils.clock import as_now_fn

        self.model = model
        self.param = param
        # eval/throughput timing reads the ONE injected clock (utils.
        # clock) — az-analyze's one-clock rule pins it; tests may pass a
        # VirtualClock for deterministic throughput logs
        self._now = as_now_fn(clock)
        self.segmenter = TimeSegmenter(
            segment_size=SAMPLE_RATE * param.segment_seconds)
        self.utt_length = param.utt_length
        if sequence_mesh is not None:
            import jax

            from analytics_zoo_tpu.models.deepspeech2 import (
                sequence_parallel_forward)

            # chunks must be even per device (stride-2 conv front-end)
            mult = 2 * sequence_mesh.shape["sequence"]
            self.utt_length = ((self.utt_length + mult - 1) // mult) * mult
            batch_axis = ("data" if "data" in sequence_mesh.axis_names
                          else None)
            # data-axis sharding needs B divisible by the axis: pad ragged
            # final chunks up to batch_size (trimmed again after forward)
            self._pad_to_batch = batch_axis is not None
            # jit once: re-invocations hit the compile cache per batch shape
            self._eval_step = jax.jit(
                lambda variables, x: sequence_parallel_forward(
                    variables, x, sequence_mesh, batch_axis=batch_axis,
                    model=model.module))
        else:
            self._eval_step = make_eval_step(model.module)
            self._pad_to_batch = False
        self.vocab_decoder = (VocabDecoder(param.vocab)
                              if param.vocab else None)
        self._dev_featurizer = None      # built lazily per segment size
        self._fused_asr = None           # featurize→forward→argmax, one jit
        # the fused single-program path covers the standard forward; the
        # sequence-parallel forward keeps the split pipeline
        self._fused_ok = sequence_mesh is None

    def _make_featurizer(self):
        """The ONE construction site for the device featurizer — both the
        split path and the fused greedy program must featurize
        identically."""
        if self._dev_featurizer is None:
            from analytics_zoo_tpu.transform.audio import (
                make_featurizer_device)

            self._dev_featurizer = make_featurizer_device(
                self.segmenter.segment_size, utt_length=self.utt_length,
                n_mels=self.param.n_mels)
        return self._dev_featurizer

    def _pack_batch(self, chunk: List[dict]):
        """Zero-pad a chunk of segments to one fixed (batch_size,
        segment_samples) array + per-row valid sample counts — the
        shared packing contract of the split and fused paths."""
        bs = self.param.batch_size
        seg_samples = self.segmenter.segment_size
        batch = np.zeros((bs, seg_samples), np.float32)
        n_valid = np.zeros((bs,), np.int32)
        for i, s in enumerate(chunk):
            x = s["samples"]
            batch[i, :len(x)] = x
            n_valid[i] = len(x)
        return batch, n_valid

    def _featurize_device(self, segments: List[dict]) -> np.ndarray:
        """Featurize in fixed ``batch_size`` device batches (last one
        zero-padded) with host-parity frame masking — one static shape,
        so exactly one XLA compile and bounded device memory regardless
        of how many segments a call carries."""
        featurizer = self._make_featurizer()
        bs = self.param.batch_size
        out = np.zeros((len(segments), self.utt_length, self.param.n_mels),
                       np.float32)
        for start in range(0, len(segments), bs):
            chunk = segments[start:start + bs]
            batch, n_valid = self._pack_batch(chunk)
            out[start:start + len(chunk)] = np.asarray(
                featurizer(batch, n_valid))[:len(chunk)]
        return out

    def _fused_greedy(self):
        """ONE jitted program: device featurize → DS2 forward → per-frame
        argmax.  Features never round-trip to host (the split path reads
        them back only to re-upload), and the readback is (B, T) int ids
        — ~30× fewer bytes than (B, T, C) log-probs.  Serving on a
        remote accelerator is dispatch/transfer bound, so the greedy
        path must be a single call per batch (docs/PERFORMANCE.md)."""
        if self._fused_asr is None:
            import jax

            feat_fn = self._make_featurizer()
            eval_step = self._eval_step

            def run(variables, samples, n_valid):
                feats = feat_fn(samples, n_valid)
                log_probs = eval_step(variables, feats)
                return jnp.argmax(log_probs, axis=-1)

            self._fused_asr = jax.jit(run)
        return self._fused_asr

    def _decode(self, log_probs: np.ndarray) -> str:
        if self.param.decoder == "beam":
            from analytics_zoo_tpu.transform.audio import beam_search_decode
            return beam_search_decode(log_probs,
                                      beam_width=self.param.beam_width)
        return best_path_decode(log_probs)

    def _transcribe_fused(self, segments: List[dict]) -> List[str]:
        """Greedy + device-featurize fast path: one jit call per batch of
        raw samples, bounded dispatch-ahead window, int-ids readback."""
        from analytics_zoo_tpu.data import overlap_window
        from analytics_zoo_tpu.transform.audio.decoders import ids_to_text

        fused = self._fused_greedy()
        bs = self.param.batch_size
        texts: List[str] = []

        def dispatch(start):
            chunk = segments[start:start + bs]
            batch, n_valid = self._pack_batch(chunk)
            return fused(self.model.variables, batch, n_valid), len(chunk)

        def consume(token):
            ids, n_real = token
            ids = np.asarray(ids)
            texts.extend(ids_to_text(ids[j]) for j in range(n_real))

        overlap_window(range(0, len(segments), bs), dispatch, consume)
        return texts

    def transcribe_samples(self, utterances: Dict[str, np.ndarray]
                           ) -> Dict[str, str]:
        """{audio_id: samples} → {audio_id: transcript}."""
        segments: List[dict] = []
        for audio_id, samples in utterances.items():
            segments.extend(self.segmenter.segment(samples, audio_id))

        if segments and self._fused_ok and self.param.device_featurize \
                and self.param.decoder == "greedy":
            texts = self._transcribe_fused(segments)
        else:
            if not segments:
                feats = np.zeros((0, self.utt_length, self.param.n_mels),
                                 np.float32)
            elif self.param.device_featurize:
                feats = np.asarray(self._featurize_device(segments))
            else:
                feats = np.stack([
                    featurize(s["samples"], utt_length=self.utt_length,
                              n_mels=self.param.n_mels)
                    for s in segments
                ])

            texts = []
            for i in range(0, len(segments), self.param.batch_size):
                chunk = feats[i:i + self.param.batch_size]
                n_real = chunk.shape[0]
                if self._pad_to_batch and n_real < self.param.batch_size:
                    pad = np.zeros((self.param.batch_size - n_real,)
                                   + chunk.shape[1:], chunk.dtype)
                    chunk = np.concatenate([chunk, pad])
                log_probs = self._eval_step(self.model.variables,
                                            jnp.asarray(chunk))
                texts.extend(self._decode(np.asarray(log_probs[j]))
                             for j in range(n_real))

        # re-join by (audio_id, audio_seq) (reference InferenceEvaluate
        # groupBy(audio_id).sort(audio_seq) concat)
        joined: Dict[str, List[Tuple[int, str]]] = {}
        for seg, text in zip(segments, texts):
            joined.setdefault(seg["audio_id"], []).append(
                (seg["audio_seq"], text))
        out = {}
        for audio_id, parts in joined.items():
            text = " ".join(t for _, t in sorted(parts)).strip()
            if self.vocab_decoder is not None:
                text = self.vocab_decoder(text)
            out[audio_id] = text
        return out

    def transcribe_files(self, paths: Sequence[str]) -> Dict[str, str]:
        utts = {}
        for p in paths:
            samples, rate = read_audio(p)
            if rate != SAMPLE_RATE:
                raise ValueError(f"{p}: expected {SAMPLE_RATE} Hz, got {rate}")
            utts[p] = samples
        return self.transcribe_samples(utts)

    def evaluate(self, utterances: Dict[str, np.ndarray],
                 transcripts: Dict[str, str]) -> ASREvaluator:
        """WER/CER over labeled utterances (reference InferenceEvaluate
        per-utterance WER/CER print + total time log)."""
        t0 = self._now()
        hyps = self.transcribe_samples(utterances)
        ev = ASREvaluator()
        for audio_id, ref in transcripts.items():
            hyp = hyps.get(audio_id, "")
            ev.add(ref.upper(), hyp)
        dt = self._now() - t0
        logger.info("DS2 eval: %d utterances in %.2fs (%.2f utt/sec), "
                    "WER=%.4f CER=%.4f", len(transcripts), dt,
                    len(transcripts) / max(dt, 1e-9), ev.wer, ev.cer)
        return ev


def make_ds2_model(hidden: int = 1024, n_rnn_layers: int = 3,
                   n_mels: int = 13, utt_length: int = 300,
                   seed: int = 0, bidirectional: bool = True,
                   rnn_hoist: bool = True, rnn_block: int = 16,
                   rnn_engine: Optional[str] = None,
                   rnn_pallas_backward: str = "pallas",
                   rnn_pallas_grad: bool = True) -> Model:
    """``bidirectional=False`` builds the forward-only (streamable)
    variant consumed by :class:`StreamingDS2`.  ``rnn_hoist=False``
    selects the legacy per-step scan body (the bench A/B baseline);
    ``rnn_engine`` overrides the recurrence engine explicitly
    ("legacy" | "blocked" | "pallas" — "pallas" is the persistent-RNN
    kernel of ``ops.pallas_rnn``, which ``train_ds2`` consumes through
    the model; ``rnn_pallas_backward``/``rnn_pallas_grad`` are its
    grad-pass knobs — forward-only consumers pass
    ``rnn_pallas_grad=False`` so the VMEM budget prices only the
    forward).  The parameter tree is identical across engines, so
    checkpoints move freely between them."""
    model = Model(DeepSpeech2(hidden=hidden, n_rnn_layers=n_rnn_layers,
                              n_mels=n_mels, bidirectional=bidirectional,
                              rnn_hoist=rnn_hoist, rnn_block=rnn_block,
                              rnn_engine=rnn_engine,
                              rnn_pallas_backward=rnn_pallas_backward,
                              rnn_pallas_grad=rnn_pallas_grad))
    model.build(seed, jnp.zeros((1, utt_length, n_mels)))
    return model


def ds2_ctc_criterion(blank_id: int = 0):
    """CTC criterion closure for DS2 batches.  Length-bucketed batches
    carry per-row ``n_frames``; the valid OUTPUT frame count after the
    stride-2 conv is ``ceil(n/2)``, and frames past it are masked out of
    the loss (they carry no signal — the model zeroes them when fed
    ``n_frames``)."""
    from analytics_zoo_tpu.core.criterion import CTCCriterion

    ctc = CTCCriterion(blank_id=blank_id)

    def criterion(log_probs, batch):
        from analytics_zoo_tpu.models.deepspeech2 import ds2_valid_out_frames

        logit_mask = None
        if isinstance(batch, dict) and "n_frames" in batch:
            out_n = ds2_valid_out_frames(batch["n_frames"].astype(jnp.int32))
            T = log_probs.shape[1]
            logit_mask = (jnp.arange(T, dtype=jnp.int32)[None, :]
                          < out_n[:, None]).astype(jnp.float32)
        return ctc(log_probs, batch["labels"], logit_mask=logit_mask,
                   label_mask=batch.get("label_mask"))

    return criterion


def ds2_padding_metric(batch):
    """``make_train_step metric_fn``: valid/padded input-frame ratio of a
    length-bucketed batch (1.0 for unbucketed fixed-shape batches)."""
    if not (isinstance(batch, dict) and "n_frames" in batch):
        return {}
    x = batch["input"][0] if isinstance(batch["input"], tuple) \
        else batch["input"]
    total = x.shape[0] * x.shape[1]
    return {"padding_efficiency":
            jnp.sum(batch["n_frames"].astype(jnp.float32)) / total}


def train_ds2(model: Model, dataset, epochs: int = 10, lr: float = 3e-4,
              mesh=None, checkpoint_path: Optional[str] = None,
              param_rules=None, sequence_parallel: bool = False,
              specs=None):
    """CTC training for DS2 — capability the reference lacks (its DS2 is
    inference-only; SURVEY.md §2.3).  ``dataset`` yields batches
    ``{"input": (B,T,n_mels), "labels": (B,L) int32, "label_mask": (B,L)}``.
    Length-bucketed batches (``load_asr_train_set(bucket_edges=...)``)
    instead carry ``"input": ((B,T_bucket,n_mels), n_frames)`` — the model
    length-masks padding, the CTC loss masks invalid output frames, and
    step metrics gain ``padding_efficiency``.  The recurrence engine is
    the model's: build with ``make_ds2_model(rnn_engine="pallas")`` to
    train on the persistent-RNN kernel (h2h weights VMEM-resident —
    the docs/MFU_CEILING.md roofline lever; ``bench.py ds2_persistent``
    banks the A/B against the blocked scan).
    ``param_rules`` enables tensor-parallel weight sharding
    (``parallel.tensor.default_tp_rules``) on a data×model mesh.

    ``sequence_parallel=True`` (mesh must carry a "sequence" axis, e.g.
    ``create_mesh((2, 4), axis_names=("data", "sequence"))``) trains with
    the TIME axis sharded: the step's forward is the pipelined-scan +
    halo-exchange program of ``models.deepspeech2.sequence_parallel_forward``
    with global-batch BN statistics, so activation memory per device is
    O(T/n) — long-audio CTC training beyond single-chip HBM.  The CTC
    loss itself consumes the (tiny, n_alphabet-wide) log-probs gathered
    back over T.

    Sharding is declared ONCE through the spec registry
    (``specs=pipeline_specs("ds2", mesh=mesh, param_rules=...)``; built
    here from ``mesh``/``param_rules`` when not given) and consumed by
    the annotated train step — this entry point performs no device
    placement, and a wider ``data`` axis is the global-batch lever of
    docs/MFU_CEILING.md (per-chip batch × mesh width toward the B/128
    occupancy knee).
    """
    from analytics_zoo_tpu.parallel import (Adam, Optimizer, Trigger,
                                            pipeline_specs)

    if specs is None:
        specs = pipeline_specs("ds2", mesh=mesh, param_rules=param_rules)
    elif mesh is not None or param_rules is not None:
        raise ValueError("pass specs= OR (mesh=, param_rules=), not both")
    mesh = specs.mesh
    criterion = ds2_ctc_criterion(blank_id=0)

    forward_fn = None
    if sequence_parallel:
        from analytics_zoo_tpu.models.deepspeech2 import (
            make_sequence_parallel_forward_fn)
        if "sequence" not in mesh.axis_names:
            raise ValueError("sequence_parallel=True needs a mesh with a "
                             f"'sequence' axis, got {mesh.axis_names}")
        forward_fn = make_sequence_parallel_forward_fn(
            model.module, mesh,
            batch_axis="data" if "data" in mesh.axis_names else None)

    opt = (Optimizer(model, dataset, criterion, specs=specs,
                     forward_fn=forward_fn,
                     metric_fn=ds2_padding_metric)
           .set_optim_method(Adam(lr))
           .set_end_when(Trigger.max_epoch(epochs)))
    if checkpoint_path:
        opt.set_checkpoint(checkpoint_path, Trigger.every_epoch())
    return opt.optimize()


class StreamingDS2:
    """Stateful streaming ASR: feed successive sample chunks, get
    incremental transcript pieces — net-new over the reference, whose only
    long-audio mechanism processes chunks INDEPENDENTLY with zeroed
    context (``TimeSegmenter.scala:11``).

    Exactness contract: the emitted log-probs exactly equal the batch
    forward of the same (unidirectional) model over the whole utterance,
    because every boundary carries its true state:

    - featurization: a 240-sample window-overlap residue carries across
      chunks, so frames are identical to whole-utterance framing;
    - conv front-end (kernel 11, stride 2, SAME(5,5) in batch mode): the
      stream starts with 5 zero context frames (= the left SAME pad),
      carries the last 9 real mel frames between chunks, and ``flush()``
      appends the 5-zero right pad; the model runs the conv VALID on the
      extended chunk, so output indices line up exactly;
    - RNN layers: forward-only scan with hidden state carried across
      chunks (``DeepSpeech2(bidirectional=False)``);
    - decoding: greedy CTC with the collapse state (previous argmax id)
      carried, so repeats spanning a boundary collapse correctly.

    Compilation: chunks are processed in FIXED ``chunk_frames`` blocks
    (remainder buffered) so the jitted forward compiles exactly three
    shapes — first block, steady block, and the padded flush block (flush
    pads to the steady shape and truncates emissions to the true
    remaining count, which keeps the tail exact for any stream length).

    Latency: ``chunk_frames`` mel frames (10 ms each) of buffering plus
    the conv's inherent 5-input-frame lookahead.
    """

    _CTX = 9            # real mel frames carried between blocks
    _PAD = 5            # zero frames standing in for SAME padding at ends

    def __init__(self, model: Model, n_mels: int = 13,
                 chunk_frames: int = 100, keep_log_probs: bool = False):
        import jax

        if getattr(model.module, "bidirectional", True):
            raise ValueError("streaming needs DeepSpeech2(bidirectional="
                             "False) — the backward pass needs the future")
        if chunk_frames < 6 or chunk_frames % 2:
            raise ValueError("chunk_frames must be even and >= 6")
        self.model = model
        self.n_mels = n_mels
        self.chunk_frames = chunk_frames
        # retain emitted per-frame log-probs (exactness testing / lattice
        # consumers); unbounded for endless streams, so off by default
        self.keep_log_probs = keep_log_probs
        self._apply = jax.jit(lambda v, x, c: model.module.apply(
            v, x, carry=c, return_carry=True))
        self._hidden = model.module.hidden
        self._layers = model.module.n_rnn_layers
        from analytics_zoo_tpu.transform.audio.featurize import (
            WINDOW_SIZE, mel_filterbank_matrix)
        self._fb = mel_filterbank_matrix(n_mels, WINDOW_SIZE)
        self.reset()

    def reset(self) -> None:
        self._samples = np.zeros((0,), np.float32)
        self._frames = np.zeros((0, self.n_mels), np.float32)
        self._ctx: Optional[np.ndarray] = None     # None = stream start
        self._h = {"h": tuple(
            jnp.zeros((1, self._hidden)) for _ in range(self._layers))}
        self._prev_id = 0                          # CTC collapse carry
        self._pieces: List[str] = []
        self._log_probs: List[np.ndarray] = []
        self._total_frames = 0                     # real mel frames seen
        self._emitted = 0                          # output frames emitted
        self._finished = False

    # -- internals ---------------------------------------------------------
    def _featurize_new(self, samples: np.ndarray) -> np.ndarray:
        """Consume buffered samples into mel frames, keeping the
        window-overlap residue (window 400, stride 160 → 240 overlap)."""
        from analytics_zoo_tpu.transform.audio.featurize import (
            WINDOW_SIZE, WINDOW_STRIDE, dft_specgram, frame_signal,
            mel_features)

        self._samples = np.concatenate([self._samples, samples])
        n = max((len(self._samples) - WINDOW_SIZE) // WINDOW_STRIDE + 1, 0)
        if n == 0:
            return np.zeros((0, self.n_mels), np.float32)
        take = WINDOW_SIZE + WINDOW_STRIDE * (n - 1)
        frames = frame_signal(self._samples[:take])
        self._samples = self._samples[WINDOW_STRIDE * n:]
        return mel_features(dft_specgram(frames), n_mels=self.n_mels,
                            fb=self._fb)

    def _run(self, ext: np.ndarray, n_emit: Optional[int] = None) -> str:
        log_probs, self._h = self._apply(
            self.model.variables, jnp.asarray(ext[None]), self._h)
        lp = np.asarray(log_probs[0])
        if n_emit is not None:
            lp = lp[:n_emit]
        self._emitted += lp.shape[0]
        if self.keep_log_probs:
            self._log_probs.append(lp)
        return self._decode(lp)

    def _update_ctx(self, real_frames: np.ndarray) -> None:
        """ctx = last 9 REAL frames of the stream (zero-left-padded while
        fewer have been seen)."""
        prev = (self._ctx if self._ctx is not None
                else np.zeros((self._CTX, self.n_mels), np.float32))
        self._ctx = np.concatenate([prev, real_frames])[-self._CTX:]

    def _decode(self, log_probs: np.ndarray) -> str:
        out = []
        for t in np.argmax(log_probs, axis=-1):
            if t != self._prev_id and t != 0:
                out.append(ALPHABET[int(t)])
            self._prev_id = int(t)
        piece = "".join(out)
        self._pieces.append(piece)
        return piece

    # -- public API --------------------------------------------------------
    def accept(self, samples: np.ndarray) -> str:
        """Feed raw samples; returns the transcript piece decoded from any
        completed fixed-size frame blocks (possibly "")."""
        if self._finished:
            raise RuntimeError("stream finished — call reset() first")
        frames = self._featurize_new(np.asarray(samples, np.float32))
        if frames.shape[0]:
            self._frames = np.concatenate([self._frames, frames])
            self._total_frames += frames.shape[0]
        pieces = []
        C = self.chunk_frames
        while self._frames.shape[0] >= C:
            chunk, self._frames = self._frames[:C], self._frames[C:]
            if self._ctx is None:
                ext = np.concatenate(
                    [np.zeros((self._PAD, self.n_mels), np.float32), chunk])
            else:
                ext = np.concatenate([self._ctx, chunk])
            self._update_ctx(chunk)
            pieces.append(self._run(ext))
        return "".join(pieces)

    def flush(self) -> str:
        """End of stream: process buffered frames + the right SAME pad,
        padded up to the steady block shape (emissions truncated to the
        true remaining count, so the tail stays exact)."""
        if self._finished:
            return ""
        self._finished = True
        r = self._frames.shape[0]
        virgin = self._ctx is None
        ctx = (np.zeros((self._PAD, self.n_mels), np.float32) if virgin
               else self._ctx)
        # ONE flush shape regardless of remainder size or virginity:
        # r <= C-1 (accept drains full blocks) and ctx is 5 or 9 frames,
        # so pad >= PAD always holds
        target = self.chunk_frames + self._CTX + self._PAD
        pad = target - ctx.shape[0] - r
        assert pad >= self._PAD, (pad, r)
        ext = np.concatenate([
            ctx, self._frames,
            np.zeros((pad, self.n_mels), np.float32)])
        self._frames = np.zeros((0, self.n_mels), np.float32)
        expected_total = (self._total_frames + 1) // 2
        n_emit = max(expected_total - self._emitted, 0)
        return self._run(ext, n_emit=n_emit) if n_emit else ""

    @property
    def transcript(self) -> str:
        return "".join(self._pieces)

    @property
    def log_probs(self) -> np.ndarray:
        """Concatenated emitted log-probs (requires keep_log_probs)."""
        if not self._log_probs:
            return np.zeros((0, 0), np.float32)
        return np.concatenate(self._log_probs, axis=0)


# ---------------------------------------------------------------------------
# Training input pipeline
# ---------------------------------------------------------------------------


def load_asr_train_set(samples: np.ndarray, labels: np.ndarray,
                       label_lengths: Optional[np.ndarray] = None,
                       batch_size: int = 8,
                       utt_length: Optional[int] = None,
                       n_mels: int = 13, shuffle: bool = True,
                       seed: int = 0, worker_processes: int = 0,
                       sample_lengths: Optional[np.ndarray] = None,
                       bucket_edges: Optional[Sequence[int]] = None,
                       param=None):
    """DataSet of featurized CTC train batches from raw waveforms.

    The host featurize (frame → rFFT → mel, ``transform.audio.
    featurize``) is the per-sample hot loop, so ``worker_processes > 0``
    fans it out through the multiprocess loader
    (``data.parallel.ParallelLoader`` — shared-memory rings,
    order-preserving, deterministically seeded).  Prefer
    ``make_featurizer_device`` fused into the train step when the chip
    has headroom; this host path is for hosts feeding featurize-bound
    accelerators, and is the DS2 wiring of docs/PERFORMANCE.md "Host
    input pipeline".

    ``samples``: (N, S) float32 waveforms; ``labels``: (N, L) int32
    (0-padded); ``label_lengths``: (N,) true lengths (defaults to
    counting nonzero labels).  Batches: ``{"input", "labels",
    "label_mask"}`` ready for ``CTCCriterion``.

    **Length-bucketed mode** (``bucket_edges``, frame counts): ragged
    waveforms (``sample_lengths`` giving true per-row sample counts)
    are featurized at their TRUE length and batched into the smallest
    fitting padded bucket (``data.bucket.BucketBatcher`` — compile once
    per bucket, deterministic for any worker count, replayable from the
    PR-2 ``(base_seed, epoch, index)`` coordinates).  Batches then carry
    ``"input": (features, n_frames)`` so the model length-masks padding,
    plus top-level ``n_frames`` for the CTC logit mask and the
    ``padding_efficiency`` step metric.  ``param``
    (:class:`~analytics_zoo_tpu.pipelines.ssd.PreProcessParam`) supplies
    ``batch_size`` / ``worker_processes`` / ``loader_seed`` /
    ``bucket_edges`` in one object for pipeline-level wiring.
    """
    from analytics_zoo_tpu.data import DataSet, FnTransformer

    if param is not None:
        batch_size = param.batch_size
        worker_processes = param.worker_processes
        seed = param.loader_seed
        if getattr(param, "bucket_edges", None):
            bucket_edges = param.bucket_edges

    samples = np.asarray(samples, np.float32)
    labels = np.asarray(labels, np.int32)
    if label_lengths is None:
        label_lengths = (labels != 0).sum(axis=1).astype(np.int32)
    if sample_lengths is None:
        sample_lengths = np.full((len(samples),), samples.shape[1], np.int64)
    sample_lengths = np.asarray(sample_lengths, np.int64)
    L = labels.shape[1]

    base = DataSet.from_arrays(samples=samples, labels=labels,
                               n_label=label_lengths,
                               n_sample=sample_lengths,
                               shuffle=shuffle, seed=seed)

    if bucket_edges is None:
        def feat(s):
            x = featurize(s["samples"], utt_length=utt_length,
                          n_mels=n_mels)
            mask = (np.arange(L) < s["n_label"]).astype(np.float32)
            return {"input": x.astype(np.float32), "labels": s["labels"],
                    "label_mask": mask}

        return (base.transform(FnTransformer(feat))
                .batch(batch_size, num_workers=worker_processes,
                       base_seed=seed))

    # fail fast on under-sized edges: BucketBatcher would silently
    # truncate input FRAMES while the labels stay full-length, which can
    # leave CTC with no feasible alignment (inf loss poisoning the batch)
    from analytics_zoo_tpu.transform.audio.featurize import (
        WINDOW_SIZE, WINDOW_STRIDE)
    max_frames = (int(sample_lengths.max()) - WINDOW_SIZE) \
        // WINDOW_STRIDE + 1
    if max_frames > max(bucket_edges):
        raise ValueError(
            f"bucket_edges[-1]={max(bucket_edges)} < the longest "
            f"utterance's {max_frames} frames — add a covering last "
            "edge (or pre-segment the audio); truncating frames but "
            "not labels can make the CTC loss infeasible")

    def feat_ragged(s):
        n_samp = int(s["n_sample"])
        x = featurize(s["samples"][:n_samp], utt_length=None,
                      n_mels=n_mels)
        mask = (np.arange(L) < s["n_label"]).astype(np.float32)
        return {"input": x.astype(np.float32),
                "n_frames": np.int32(x.shape[0]),
                "labels": s["labels"], "label_mask": mask}

    def pack(batch):
        # model contract: inputs as (features, n_frames) so the forward
        # receives the lengths positionally; n_frames stays top-level
        # for the CTC logit mask + padding_efficiency metric
        return {"input": (batch["input"], batch["n_frames"]),
                "n_frames": batch["n_frames"],
                "labels": batch["labels"],
                "label_mask": batch["label_mask"]}

    from analytics_zoo_tpu.data.bucket import BucketBatcher
    ds = (base.transform(FnTransformer(feat_ragged))
          .transform(BucketBatcher(batch_size, bucket_edges,
                                   length_key="n_frames",
                                   pad_key="input"))
          .transform(FnTransformer(pack)))
    if worker_processes > 0:
        return ds.parallel(worker_processes, base_seed=seed)
    return ds


def ds2_serving_tiers(model: Model, param: Optional[DS2Param] = None,
                      degraded_beam: Optional[int] = None,
                      specs=None) -> List:
    """Degradation-ladder rungs for the online serving runtime
    (``serving.ServingRuntime``): prefix-beam width is DS2's analog of
    the SSD ladder's NMS top-K — the decode-side work that can be cut
    under overload with a bounded, explicit quality loss.

    Requests carry ONE featurized utterance
    (``{"input": (n_frames, n_mels) float32}``, ``length=n_frames``);
    the serving batcher pads the time axis to a configured bucket edge
    (``bucket_edges`` should match the training ``BucketBatcher`` edges
    so serving reuses compiled geometries) and hands the forward
    ``{"input": (B, edge, n_mels), "n_frames": (B,)}``.  Each tier
    decodes only ``ds2_valid_out_frames(n)`` output frames per row —
    padding never reaches the decoder.

    Tiers (cheapest last): full prefix-beam (``param.beam_width``),
    reduced beam (``degraded_beam``, default ``max(4, width // 4)``),
    greedy best-path.  With ``param.decoder == "greedy"`` there is no
    decode quality to shed, so the ladder is the single greedy tier.

    ``specs`` (e.g. ``pipeline_specs("ds2", mesh=mesh)``): the shared
    forward is then mesh-annotated through the spec layer (variables
    replicated, batch over ``data``).
    """
    from analytics_zoo_tpu.models.deepspeech2 import ds2_valid_out_frames
    from analytics_zoo_tpu.serving.ladder import ServingTier
    from analytics_zoo_tpu.transform.audio import beam_search_decode

    param = param or DS2Param()
    eval_step = make_eval_step(model.module, specs=specs)

    def audit_program(edge: int = 64):
        """``az_analyze --program`` hook: every tier dispatches this ONE
        annotated forward (tiers differ only in host-side decode), so
        each rung exposes it with shape-only example args."""
        B = specs.data_axis_size if specs is not None else 1
        return (eval_step,
                (model.variables,
                 jax.ShapeDtypeStruct((B, edge, param.n_mels),
                                      jnp.float32)),
                ())

    def forward_with(decode: Callable[[np.ndarray], str]):
        def forward(batch: Dict) -> List[str]:
            feats = batch["input"]
            n_frames = batch.get("n_frames")
            log_probs = np.asarray(eval_step(model.variables,
                                             jnp.asarray(feats)))
            texts: List[str] = []
            for i in range(feats.shape[0]):
                n = (int(n_frames[i]) if n_frames is not None
                     else feats.shape[1])
                if n <= 0:          # batch-axis padding row
                    texts.append("")
                    continue
                texts.append(decode(log_probs[i, :ds2_valid_out_frames(n)]))
            return texts
        return forward

    if param.decoder == "greedy":
        return [ServingTier("greedy", forward_with(best_path_decode),
                            speed=1.0, quality_note="best-path decode",
                            device_program=audit_program)]
    width = param.beam_width
    low = degraded_beam if degraded_beam is not None else max(4, width // 4)
    return [
        ServingTier(f"beam{width}",
                    forward_with(lambda lp: beam_search_decode(
                        lp, beam_width=width)),
                    speed=1.0,
                    quality_note=f"prefix beam search, width {width}",
                    device_program=audit_program),
        ServingTier(f"beam{low}",
                    forward_with(lambda lp: beam_search_decode(
                        lp, beam_width=low)),
                    speed=0.85,
                    quality_note=f"reduced beam width {low} (bounded "
                                 "WER cost under overload)",
                    device_program=audit_program),
        ServingTier("greedy", forward_with(best_path_decode), speed=0.7,
                    quality_note="best-path decode (no beam) — the "
                                 "cheapest rung",
                    device_program=audit_program),
    ]


def ds2_streaming_tiers(model: Model, n_mels: int = 13,
                        chunk_frames: int = 100) -> List:
    """ONE replica's tier instances for the first-class streaming ASR
    session model (ISSUE 14): a stateful forward owning this replica's
    session store — ``{session id: StreamingDS2}`` — so session-affine
    scheduling is physically meaningful (the carry state LIVES on the
    pinned replica; a migrated session would decode from zeroed state,
    which is exactly why the pool never fails a session batch over).

    Batch contract (what a ``ModelConfig(streaming=True)`` plan
    assembles): ``{"input": (B, edge) float32 raw samples, "n_samples":
    (B,) true lengths, "session": (B,) int64 ids (−1 padding),
    "final": (B,) int8 flush flags}``.  Each row routes to its
    session's :class:`StreamingDS2` — featurization residue, conv
    context, RNN hidden state and CTC collapse state all carry across
    chunks, so the concatenated pieces exactly equal the whole-
    utterance forward (the ``StreamingDS2`` exactness contract, now
    riding the multiplexed runtime).  A ``final`` row appends the
    stream's :meth:`StreamingDS2.flush` tail and retires the session's
    state.

    Use with ``functools.partial`` as the per-replica factory::

        ModelConfig(name="ds2-stream", streaming=True,
                    tiers=ds2_streaming_tiers(model),
                    tier_factory=lambda rid: ds2_streaming_tiers(model),
                    pad_key="input", length_key="n_samples",
                    bucket_edges=[...sample-count edges...])
    """
    from analytics_zoo_tpu.serving.ladder import ServingTier

    store: Dict[int, StreamingDS2] = {}

    def forward(batch: Dict) -> List[str]:
        sessions = batch["session"]
        final = batch["final"]
        lens = batch.get("n_samples")
        texts: List[str] = []
        for i in range(len(sessions)):
            sid = int(sessions[i])
            if sid < 0:             # batch-axis padding row
                texts.append("")
                continue
            stream = store.get(sid)
            if stream is None:
                stream = StreamingDS2(model, n_mels=n_mels,
                                      chunk_frames=chunk_frames)
                store[sid] = stream
            n = (int(lens[i]) if lens is not None
                 else batch["input"].shape[1])
            piece = (stream.accept(np.asarray(batch["input"][i][:n],
                                              np.float32))
                     if n > 0 else "")
            if int(final[i]):
                piece += stream.flush()
                store.pop(sid, None)
            texts.append(piece)
        return texts

    def device_program():
        """``az_analyze --program`` hook: the steady-block jitted apply
        every chunk dispatches (carry in, carry out)."""
        hidden = model.module.hidden
        layers = model.module.n_rnn_layers
        S = jax.ShapeDtypeStruct
        carry = {"h": tuple(S((1, hidden), jnp.float32)
                            for _ in range(layers))}
        fn = jax.jit(lambda v, x, c: model.module.apply(
            v, x, carry=c, return_carry=True))
        ext = chunk_frames + StreamingDS2._CTX
        return (fn, (model.variables,
                     S((1, ext, n_mels), jnp.float32), carry), ())

    return [ServingTier(
        "stream", forward, speed=1.0,
        quality_note=f"stateful streaming session ({chunk_frames}-frame "
                     f"blocks, exact to the whole-utterance forward)",
        device_program=device_program,
        # the runtime evicts a killed session's carry here, so failed
        # sessions don't leak StreamingDS2 state on the replica (its
        # still-queued chunks are failed before dispatch, so the entry
        # is never recreated)
        evict_session=lambda sid: store.pop(sid, None))]
