"""Sentiment-analysis pipeline: the GloVe-table family joins the zoo.

Port of the reference's ``apps/sentimentAnalysis/sentiment.ipynb``:
token ids → embedding table (trainable, or frozen GloVe vectors) →
selectable GRU / LSTM / BiLSTM / CNN / CNN-LSTM head → binary sigmoid,
trained with BCE.  The embedding table (vocab 20k × 100 for the
notebook's GloVe geometry) dominates the parameter count, so the
pipeline rides the same sharded-embedding substrate as recommendation:
the model's lookup defaults to the dedup'd gather and
``pipeline_specs("sentiment")`` row-shards the table over the ``model``
mesh axis when one is declared.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp

from analytics_zoo_tpu.core.criterion import BCECriterion
from analytics_zoo_tpu.core.module import Model
from analytics_zoo_tpu.models import SentimentNet
from analytics_zoo_tpu.parallel import Adam, Optimizer, Trigger, pipeline_specs


def make_sentiment_model(vocab_size: int = 20000, embedding_dim: int = 100,
                         hidden: int = 128, head: str = "gru",
                         embeddings: Optional[np.ndarray] = None,
                         lookup: str = "dedup", seq_len: int = 128,
                         seed: int = 0) -> Model:
    """Built SentimentNet :class:`Model` (params initialized at
    ``seq_len`` — the heads are length-polymorphic, so serving may pick
    a different bucket)."""
    model = Model(SentimentNet(vocab_size=vocab_size,
                               embedding_dim=embedding_dim, hidden=hidden,
                               head=head, embeddings=embeddings,
                               lookup=lookup))
    model.build(seed, jnp.zeros((1, seq_len), jnp.int32))
    return model


def review_batches(tokens: np.ndarray, labels: np.ndarray, batch_size: int):
    """(N, T) token ids + binary labels → train batches."""
    n = (len(tokens) // batch_size) * batch_size
    return [{"input": np.asarray(tokens[i:i + batch_size], np.int32),
             "target": np.asarray(labels[i:i + batch_size], np.float32)}
            for i in range(0, n, batch_size)]


def train_sentiment(model: Model, batches, epochs: int = 5,
                    lr: float = 1e-3, mesh=None,
                    shard_tables: bool = True) -> Model:
    """Train a SentimentNet on review batches with the declared
    ``sentiment`` SpecSet (BCE head, per the notebook)."""
    specs = pipeline_specs("sentiment", mesh=mesh,
                           shard_tables=shard_tables)
    (Optimizer(model, batches, BCECriterion(), specs=specs)
     .set_optim_method(Adam(lr))
     .set_end_when(Trigger.max_epoch(epochs))
     .optimize())
    return model


def sentiment_serving_tiers(model: Model, specs=None, seq_len: int = 128):
    """fp/int8 degradation rungs for the fleet runtime.  Requests carry
    one token-id matrix (``{"input": (B, seq_len) int32}``); the GloVe
    table matches the ``embedding$`` quantization pattern, so the int8
    rung compresses the model's dominant array.  Both rungs expose their
    jitted program to the az-analyze audit (``sentiment/serve:*``)."""
    from analytics_zoo_tpu.parallel import make_eval_step
    from analytics_zoo_tpu.serving.ladder import ServingTier
    from analytics_zoo_tpu.utils.quantize import (make_quantized_forward,
                                                  quantize_params)

    eval_step = make_eval_step(model.module, specs=specs)
    qparams = quantize_params(model.variables)
    qfwd = make_quantized_forward(model.module)

    def fwd_fp(batch: Dict) -> np.ndarray:
        return np.asarray(eval_step(model.variables,
                                    jnp.asarray(batch["input"], jnp.int32)))

    def fwd_int8(batch: Dict) -> np.ndarray:
        return np.asarray(qfwd(qparams,
                               jnp.asarray(batch["input"], jnp.int32)))

    B = specs.data_axis_size if specs is not None else 1
    tokens = jax.ShapeDtypeStruct((B, seq_len), jnp.int32)

    def audit_fp():
        return (eval_step, (model.variables, tokens), ())

    def audit_int8():
        return (qfwd, (qparams, tokens), ())

    return [
        ServingTier("fp", fwd_fp, speed=1.0,
                    quality_note="fp32 table + head, dedup'd gather, "
                                 "annotated eval step",
                    device_program=audit_fp),
        ServingTier("int8", fwd_int8, speed=0.8,
                    quality_note="weight-only int8 embedding table "
                                 "(quantize_params)",
                    device_program=audit_int8),
    ]
