"""Detection visualization (reference ``common/dataset/roiimage/
Visualizer.scala:31,85``: java.awt drawing → here cv2): draw class+score
boxes on images and save."""

from __future__ import annotations

import os
from typing import Optional, Sequence

import cv2
import numpy as np

from analytics_zoo_tpu.pipelines.voc import VOC_CLASSES

_COLORS = [
    (255, 56, 56), (50, 205, 50), (65, 105, 225), (255, 165, 0),
    (186, 85, 211), (0, 206, 209), (255, 105, 180), (154, 205, 50),
]


def vis_detection(image: np.ndarray, detections: np.ndarray,
                  class_names: Sequence[str] = VOC_CLASSES,
                  conf_thresh: float = 0.3,
                  out_path: Optional[str] = None) -> np.ndarray:
    """Draw (K, 6) detections (cls, score, x1, y1, x2, y2 in pixels) on a
    BGR image; optionally save (reference ``visDetection``)."""
    canvas = np.ascontiguousarray(image.astype(np.uint8))
    for row in np.asarray(detections):
        cls, score = int(row[0]), float(row[1])
        if cls < 0 or score < conf_thresh:
            continue
        x1, y1, x2, y2 = [int(round(v)) for v in row[2:6]]
        color = _COLORS[cls % len(_COLORS)]
        cv2.rectangle(canvas, (x1, y1), (x2, y2), color, 2)
        name = (class_names[cls] if 0 <= cls < len(class_names)
                else str(cls))
        label = f"{name} {score:.2f}"
        (tw, th), _ = cv2.getTextSize(label, cv2.FONT_HERSHEY_SIMPLEX, 0.5, 1)
        cv2.rectangle(canvas, (x1, max(y1 - th - 6, 0)),
                      (x1 + tw + 2, max(y1, th + 6)), color, -1)
        cv2.putText(canvas, label, (x1 + 1, max(y1 - 4, th)),
                    cv2.FONT_HERSHEY_SIMPLEX, 0.5, (255, 255, 255), 1,
                    cv2.LINE_AA)
    if out_path:
        os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
        cv2.imwrite(out_path, canvas)
    return canvas


def result_to_string(detections: np.ndarray,
                     class_names: Sequence[str] = VOC_CLASSES,
                     conf_thresh: float = 0.0) -> str:
    """Text dump of detections (reference ``BboxUtil.resultToString``)."""
    lines = []
    for row in np.asarray(detections):
        cls, score = int(row[0]), float(row[1])
        if cls < 0 or score < conf_thresh:
            continue
        name = class_names[cls] if 0 <= cls < len(class_names) else str(cls)
        lines.append(f"{name} {score:.4f} "
                     + " ".join(f"{v:.1f}" for v in row[2:6]))
    return "\n".join(lines)
