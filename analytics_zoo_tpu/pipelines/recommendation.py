"""Recommendation pipeline: NCF + Wide&Deep on the sharded-embedding substrate.

Port of the reference's ``apps/recommendation`` notebooks
(``recommender-explicit-feedback.ipynb``: user/item LookupTables →
JoinTable → MLP → LogSoftMax over 5 rating classes) plus the family's
second architecture, Wide&Deep.  This is the web-scale family: the model
is dominated by ``(vocab, dim)`` lookup tables, the hot path is the
dedup'd gather of ``ops.embedding`` (the models default to
``lookup="dedup"``), and the declared specs (``pipeline_specs("rec")``)
row-shard every table over the ``model`` mesh axis when one exists.

Training follows the fraud pipeline's shape — ``Optimizer`` over
``{"input": (users, items), "target": rating_class}`` batches with
sharding declared once through the spec registry — and
:func:`rec_serving_tiers` hands the fleet runtime the same fp/int8
degradation rungs as every other multiplexed family.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from analytics_zoo_tpu.core.criterion import ClassNLLCriterion
from analytics_zoo_tpu.core.module import Model
from analytics_zoo_tpu.models import NeuralCF, WideAndDeep
from analytics_zoo_tpu.parallel import Adam, Optimizer, Trigger, pipeline_specs


def make_ncf_model(n_users: int = 1000, n_items: int = 1000,
                   embedding_dim: int = 20, mf_embedding_dim: int = 8,
                   hidden: Sequence[int] = (40, 20), n_classes: int = 5,
                   include_mf: bool = True, lookup: str = "dedup",
                   seed: int = 0) -> Model:
    """Built NeuralCF :class:`Model` (params initialized)."""
    model = Model(NeuralCF(n_users=n_users, n_items=n_items,
                           embedding_dim=embedding_dim,
                           mf_embedding_dim=mf_embedding_dim,
                           hidden=tuple(hidden), n_classes=n_classes,
                           include_mf=include_mf, lookup=lookup))
    probe = jnp.zeros((1,), jnp.int32)
    model.build(seed, probe, probe)
    return model


def make_wide_deep_model(n_users: int = 1000, n_items: int = 1000,
                         embedding_dim: int = 20,
                         hidden: Sequence[int] = (40, 20),
                         n_classes: int = 5, cross_buckets: int = 1000,
                         lookup: str = "dedup", seed: int = 0) -> Model:
    """Built Wide&Deep :class:`Model` (params initialized)."""
    model = Model(WideAndDeep(n_users=n_users, n_items=n_items,
                              embedding_dim=embedding_dim,
                              hidden=tuple(hidden), n_classes=n_classes,
                              cross_buckets=cross_buckets, lookup=lookup))
    probe = jnp.zeros((1,), jnp.int32)
    model.build(seed, probe, probe)
    return model


def rating_batches(users: np.ndarray, items: np.ndarray, ratings: np.ndarray,
                   batch_size: int):
    """(user, item, rating 1..n_classes) triples → train batches.
    Ratings arrive 1-based (the MovieLens convention the notebook uses);
    targets are 0-based class indices for ``ClassNLLCriterion``."""
    n = (len(users) // batch_size) * batch_size
    out = []
    for i in range(0, n, batch_size):
        sl = slice(i, i + batch_size)
        out.append({
            "input": (np.asarray(users[sl], np.int32),
                      np.asarray(items[sl], np.int32)),
            "target": np.asarray(ratings[sl], np.int32) - 1,
        })
    return out


def train_recommender(model: Model, batches, epochs: int = 5,
                      lr: float = 1e-3, mesh=None,
                      shard_tables: bool = True) -> Model:
    """Train an NCF/Wide&Deep :class:`Model` on rating batches.  The
    ``rec`` SpecSet is declared once: batches dim-0 over ``data``,
    tables row-sharded over ``model`` when the mesh has that axis."""
    specs = pipeline_specs("rec", mesh=mesh, shard_tables=shard_tables)
    (Optimizer(model, batches, ClassNLLCriterion(), specs=specs)
     .set_optim_method(Adam(lr))
     .set_end_when(Trigger.max_epoch(epochs))
     .optimize())
    return model


def predict_ratings(model: Model, users, items) -> np.ndarray:
    """Predicted 1-based rating class per (user, item) pair."""
    log_probs = np.asarray(model.forward(jnp.asarray(users, jnp.int32),
                                         jnp.asarray(items, jnp.int32)))
    return log_probs.argmax(axis=-1) + 1


def rec_serving_tiers(model: Model, specs=None):
    """Degradation-ladder rungs for the fleet runtime: recommendation
    joins the multiplexed fleet (the 5th family after ssd/frcnn/ds2/
    fraud) with a SPARSE-lookup workload.

    Requests carry id pairs (``{"input": ((B,) int32 users, (B,) int32
    items)}``).  Tier 0 serves full-precision tables through the
    (optionally mesh-annotated) eval step — the dedup'd gather is the
    device program; tier 1 serves weight-only int8: every table matches
    the ``embedding$`` quantization pattern, so the int8 rung compresses
    exactly the arrays that dominate the model.  Both rungs expose their
    jitted program to the az-analyze serving audit (``rec/serve:*``)."""
    from analytics_zoo_tpu.parallel import make_eval_step
    from analytics_zoo_tpu.serving.ladder import ServingTier
    from analytics_zoo_tpu.utils.quantize import (make_quantized_forward,
                                                  quantize_params)

    eval_step = make_eval_step(model.module, specs=specs)
    qparams = quantize_params(model.variables)
    qfwd = make_quantized_forward(model.module)

    def _pair(batch: Dict):
        users, items = batch["input"]
        return jnp.asarray(users, jnp.int32), jnp.asarray(items, jnp.int32)

    def fwd_fp(batch: Dict) -> np.ndarray:
        return np.asarray(eval_step(model.variables, _pair(batch)))

    def fwd_int8(batch: Dict) -> np.ndarray:
        return np.asarray(qfwd(qparams, *_pair(batch)))

    B = specs.data_axis_size if specs is not None else 1
    ids = jax.ShapeDtypeStruct((B,), jnp.int32)

    def audit_fp():
        return (eval_step, (model.variables, (ids, ids)), ())

    def audit_int8():
        return (qfwd, (qparams, ids, ids), ())

    return [
        ServingTier("fp", fwd_fp, speed=1.0,
                    quality_note="fp32 tables, dedup'd gather, annotated "
                                 "eval step",
                    device_program=audit_fp),
        ServingTier("int8", fwd_int8, speed=0.8,
                    quality_note="weight-only int8 lookup tables "
                                 "(quantize_params embedding$ pattern)",
                    device_program=audit_int8),
    ]
