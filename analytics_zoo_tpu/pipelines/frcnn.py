"""Faster-RCNN serving pipeline (reference ``ssd/example/Predict.scala``
with ``FrcnnCaffeLoader`` + ``common/Predictor.scala``): preprocess chain →
one jitted detector forward (trunk → RPN → proposal → ROI pool → heads →
per-class NMS in-graph) → detections rescaled to original image size.

TPU-first deviation from the reference: the reference's Faster-RCNN
preprocess is aspect-preserving ``AspectScale(600, max 1000)`` which
yields variable input shapes (fine on CPU, a recompile per shape under
XLA).  Serving here keeps the reference's aspect-preserving geometry but
inside ONE fixed square canvas (``AspectScaleCanvas``: scale the long
side to ``resolution``, pad bottom/right) so every batch reuses a single
compiled program; ``im_info`` scale factors restore original-size pixel
boxes, exactly like the SSD path (``BboxUtil.scaleBatchOutput:384``).
Pass ``aspect_preserving=False`` to use the distorting square resize
instead (slightly fewer dead pixels, measurably worse accuracy for
imported py-faster-rcnn weights which saw undistorted inputs).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.faster_rcnn import FasterRcnnDetector
from analytics_zoo_tpu.pipelines.ssd import (
    BGR_MEANS,
    PreProcessParam,
    run_serving_loop,
    serving_chain,
)
from analytics_zoo_tpu.transform.vision import AspectScaleCanvas

logger = logging.getLogger("analytics_zoo_tpu")

# py-faster-rcnn BGR channel means (its models were trained with these,
# not the SSD-Caffe 104/117/123)
FRCNN_BGR_MEANS = (102.9801, 115.9465, 122.7717)


class FrcnnPredictor:
    """``SSDPredictor`` counterpart for the Faster-RCNN family.

    ``detector`` is a built ``FasterRcnnDetector`` module; ``variables``
    its params (e.g. from ``utils.caffe.load_frcnn_vgg_caffe``).
    """

    def __init__(self, detector: FasterRcnnDetector, variables,
                 param: Optional[PreProcessParam] = None,
                 aspect_preserving: bool = True,
                 swap_default_means: bool = True,
                 quantize: bool = False):
        self.detector = detector
        self.variables = variables
        if param is None:
            param = PreProcessParam(resolution=512,
                                    pixel_means=FRCNN_BGR_MEANS)
        elif (swap_default_means
              and tuple(param.pixel_means) == tuple(BGR_MEANS)):
            # caller set batch/resolution but left the SSD-Caffe default
            # means — silently wrong for py-faster-rcnn weights; swap in
            # the Faster-RCNN means.  A caller who genuinely wants the
            # SSD means must pass swap_default_means=False (the values
            # alone can't distinguish "default" from "chosen").
            logger.info("FrcnnPredictor: replacing default SSD pixel "
                        "means with FRCNN_BGR_MEANS "
                        "(swap_default_means=False keeps them)")
            param = dataclasses.replace(param,
                                        pixel_means=FRCNN_BGR_MEANS)
        if param.wire_format != "bgr":
            raise ValueError(
                "FrcnnPredictor serves over the uint8 BGR wire only; "
                f"wire_format={param.wire_format!r} is not supported "
                "(the yuv420 wire is an SSDPredictor feature)")
        self.param = param
        self.aspect_preserving = aspect_preserving
        means = np.asarray(self.param.pixel_means, np.float32)

        def apply_fn(v, x, info):
            if x.dtype == jnp.uint8:
                # uint8 staging path: normalize on device (4× fewer
                # host→device bytes than float32 staging)
                x = x.astype(jnp.float32) - means
            return detector.apply(v, x, info)

        if quantize:
            # int8 serving, like SSDPredictor(quantize=...): True/"weight"
            # keeps weights int8 in HBM (~4× smaller) with dequant fused
            # into the consuming convs; "int8" runs real int8×int8→int32
            # convolutions with dynamic activation quantization
            from analytics_zoo_tpu.utils.quantize import (
                make_quantized_forward, quantize_params)

            self.variables = quantize_params(variables)
            self._fwd = make_quantized_forward(
                detector, apply_fn=apply_fn,
                compute="int8" if quantize == "int8" else "dequant")
        else:
            self._fwd = jax.jit(apply_fn)

    def _detect_device(self, batch: Dict):
        """Dispatch one batch (async); returns (device detections,
        scale_h, scale_w) — boxes still in resized-image pixels."""
        # detector im_info rows are (height, width, scale): height/width
        # are the CONTENT dims — with AspectScaleCanvas the image fills
        # only im_info[:2] of the canvas, and the in-graph clip
        # (``clip_boxes``) must clip to the valid region, not the canvas,
        # or pad-region boxes rescale to out-of-bounds original pixels;
        # min_size filtering in the proposal layer uses the scale factor
        scale_h = np.maximum(batch["im_info"][:, 2], 1e-8)
        scale_w = np.maximum(batch["im_info"][:, 3], 1e-8)
        info = np.stack([batch["im_info"][:, 0].astype(np.float32),
                         batch["im_info"][:, 1].astype(np.float32),
                         ((scale_h + scale_w) * 0.5).astype(np.float32)],
                        axis=1)
        return (self._fwd(self.variables, batch["input"], info),
                scale_h, scale_w)

    @staticmethod
    def _rescale(dev_dets, scale_h, scale_w) -> np.ndarray:
        """Read back + project to original pixels: x/scale_w, y/scale_h
        (host-side numpy — the array is tiny)."""
        dets = np.array(dev_dets)
        dets[..., 2] /= scale_w[:, None]
        dets[..., 4] /= scale_w[:, None]
        dets[..., 3] /= scale_h[:, None]
        dets[..., 5] /= scale_h[:, None]
        return dets

    def detect_batch(self, batch: Dict) -> np.ndarray:
        """(B, max_per_image, 6) detections in ORIGINAL image pixels."""
        return self._rescale(*self._detect_device(batch))

    def predict(self, records) -> List[np.ndarray]:
        """records: iterable of SSDByteRecord → per-image (K, 6) arrays
        ``(class, score, x1, y1, x2, y2)`` in original pixel coords."""
        resize = (AspectScaleCanvas(self.param.resolution)
                  if self.aspect_preserving else None)
        return run_serving_loop(
            serving_chain(self.param, uint8=True, resize=resize)(records),
            self._detect_device, lambda t: self._rescale(*t))


def frcnn_serving_tiers(detector: FasterRcnnDetector, variables,
                        param: Optional[PreProcessParam] = None,
                        specs=None, aspect_preserving: bool = True) -> List:
    """Degradation-ladder rungs for the online serving runtime
    (ISSUE 14 — Faster-RCNN joins the multiplexed fleet): two
    :class:`~analytics_zoo_tpu.serving.ladder.ServingTier` s over the
    SAME in-graph post-processing forward, cheapest last — tier 0 full
    precision, tier 1 weight-only int8 via the ``FrcnnPredictor(
    quantize=True)`` path (dequant fused into the consuming convs).

    Requests carry one preprocessed fixed-canvas image (``{"input":
    (res, res, 3) float32}``, pixel means already subtracted — the
    serving batcher's FIXED bucket, same convention as the SSD tiers);
    the forward synthesizes the unit-scale ``im_info`` for the full
    canvas, so detections come back in canvas pixels.  Each rung's
    ``device_program`` thunk exposes the jitted detector program to the
    az-analyze serving audit (``frcnn/serve:*`` targets).
    """
    from analytics_zoo_tpu.serving.ladder import ServingTier

    full = FrcnnPredictor(detector, variables, param=param,
                          aspect_preserving=aspect_preserving)
    int8 = FrcnnPredictor(detector, variables, param=full.param,
                          swap_default_means=False, quantize=True)
    res = full.param.resolution

    def fwd(pred: FrcnnPredictor):
        def forward(batch: Dict) -> np.ndarray:
            B = batch["input"].shape[0]
            # fixed serving canvas at unit scale: content fills the
            # square, boxes come back in canvas pixels
            im_info = np.tile(
                np.asarray([[res, res, 1.0, 1.0]], np.float32), (B, 1))
            return pred.detect_batch({"input": batch["input"],
                                      "im_info": im_info})
        return forward

    def audit(pred: FrcnnPredictor):
        def device_program():
            B = specs.data_axis_size if specs is not None else 1
            S = jax.ShapeDtypeStruct
            return (pred._fwd,
                    (pred.variables, S((B, res, res, 3), jnp.float32),
                     S((B, 3), jnp.float32)), ())
        return device_program

    return [
        ServingTier("fp", fwd(full), speed=1.0,
                    quality_note="full precision, in-graph NMS",
                    device_program=audit(full)),
        ServingTier("int8", fwd(int8), speed=0.77,
                    quality_note="weight-only int8 (dequant fused into "
                                 "the consuming convs)",
                    device_program=audit(int8)),
    ]


def frcnn_train_batches(dataset, resolution: int):
    """Adapt SSD-style labeled batches (normalized gt) to the Faster-RCNN
    train step's input contract: ``input`` becomes the forward tuple
    ``(pixels, im_info, gt_px, gt_mask)`` — the gt boxes double as
    ``extra_rois`` (py-faster-rcnn's guaranteed-foreground sampling
    trick) — and ``target.bboxes`` is scaled to pixels for the target
    assignment."""

    class _DS:
        def __len__(self):
            return len(dataset)

        def __iter__(self):
            for b in dataset:
                B = b["input"].shape[0]
                gt_px = np.asarray(b["target"]["bboxes"],
                                   np.float32) * resolution
                im_info = np.tile(
                    np.asarray([[resolution, resolution, 1.0]], np.float32),
                    (B, 1))
                yield {
                    "input": (np.asarray(b["input"], np.float32), im_info,
                              gt_px, np.asarray(b["target"]["mask"],
                                                np.float32)),
                    "im_info": im_info,
                    "target": {
                        "bboxes": gt_px,
                        "labels": np.asarray(b["target"]["labels"],
                                             np.int32),
                        "mask": np.asarray(b["target"]["mask"],
                                           np.float32),
                    },
                }

    return _DS()


def train_frcnn(model, dataset, resolution: int, epochs: int = 10,
                lr: float = 1e-3, mesh=None, loss_param=None,
                grad_clip_norm: Optional[float] = 10.0,
                lr_schedule=None, epoch_hook=None):
    """End-to-end Faster-RCNN training — capability the REFERENCE DOES
    NOT HAVE (its proposal layer throws on backward,
    ``common/nn/Proposal.scala``; Faster-RCNN there is import-and-serve
    only).  Approximate joint training: RPN objectness/box losses +
    head class/box losses (``ops.frcnn_train``), gt boxes injected as
    extra ROIs, deterministic hard-negative sampling.

    ``model``: a ``core.Model`` wrapping ``FasterRcnnVgg``; ``dataset``
    yields SSD-style labeled batches with NORMALIZED gt (e.g.
    ``pipelines.ssd.load_train_set`` — pass ``PreProcessParam(
    worker_processes=N)`` there to fan the decode/augment host work out
    to the multiprocess loader; the adapter preserves its ordering and
    early-close semantics) — adapted via :func:`frcnn_train_batches`.
    """
    from analytics_zoo_tpu.ops.frcnn_train import (FrcnnLossParam,
                                                   frcnn_training_loss)
    from analytics_zoo_tpu.parallel import (Optimizer, SGD, Trigger,
                                            pipeline_specs)

    loss_param = loss_param or FrcnnLossParam()
    module = model.module

    def forward_fn(variables, inputs, train=False, rngs=None):
        x, im_info, gt_px, gt_mask = inputs
        out = module.apply(variables, x, im_info, train=train,
                           extra_rois=gt_px, extra_rois_mask=gt_mask,
                           train_outputs=True, rngs=rngs)
        return out, None

    def criterion(outputs, batch):
        return frcnn_training_loss(outputs, batch, loss_param)

    # sharding declared once through the spec registry (data parallel;
    # the annotated step owns all placement — no device_put here)
    opt = (Optimizer(model, frcnn_train_batches(dataset, resolution),
                     criterion, specs=pipeline_specs("frcnn", mesh=mesh),
                     forward_fn=forward_fn, grad_clip_norm=grad_clip_norm)
           .set_optim_method(SGD(lr, momentum=0.9, schedule=lr_schedule))
           .set_end_when(Trigger.max_epoch(epochs)))
    if epoch_hook is not None:
        opt.set_epoch_hook(epoch_hook)
    opt.optimize()
    return model
