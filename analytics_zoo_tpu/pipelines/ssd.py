"""SSD pipeline: train / test / predict over the TPU runtime.

Port of the reference's L6 pipeline (``pipeline/ssd``): the canonical data
chains (``IOUtils.loadTrainSet/loadValSet``, ``ssd/Utils.scala:56,72``),
``SSDPredictor`` (``ssd/SSDPredictor.scala:30``), ``Validator``
(``ssd/Validator.scala:34`` with its throughput log) and the ``Train``
entry point's optimizer assembly (``ssd/example/Train.scala:140-252``:
optional Adam warm-up to a target mAP, then SGD + MultiStep/Plateau,
per-epoch validation/checkpoint/summaries).
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from analytics_zoo_tpu.core.module import Model
from analytics_zoo_tpu.data import (
    DataSet,
    ParallelTransformer,
    RandomTransformer,
    SSDByteRecord,
    Transformer,
    overlap_window,
    pad_ragged,
)
from analytics_zoo_tpu.models import SSDVgg, build_priors, ssd300_config, ssd512_config
from analytics_zoo_tpu.ops import (
    DetectionOutputParam,
    MultiBoxLoss,
    MultiBoxLossParam,
    detection_output,
    scale_detections,
)
from analytics_zoo_tpu.parallel import (
    SGD,
    Adam,
    Optimizer,
    Plateau,
    TrainSummary,
    Trigger,
    ValidationSummary,
    make_eval_step,
    multistep,
)
from analytics_zoo_tpu.pipelines.evaluation import (
    CocoMeanAveragePrecision, DetectionResult, MeanAveragePrecision,
    MultiIoUResult)
from analytics_zoo_tpu.transform.vision import (
    BytesToMat,
    ColorJitter,
    Expand,
    HFlip,
    ImageFeature,
    MatToFloats,
    RandomSampler,
    Resize,
    RoiExpand,
    RoiHFlip,
    RoiLabel,
    RoiNormalize,
)

logger = logging.getLogger("analytics_zoo_tpu")

# Caffe-VGG channel means, BGR (reference PreProcessParam meansRGB defaults)
BGR_MEANS = (104.0, 117.0, 123.0)


@dataclasses.dataclass
class PreProcessParam:
    """Reference ``PreProcessParam`` (``ssd/model/SSDGraph.scala:30``)."""

    batch_size: int = 32
    resolution: int = 300
    pixel_means: Sequence[float] = BGR_MEANS
    n_partition: int = 1
    max_gt: int = 100
    # host augmentation worker threads (SURVEY.md §7.3 hard part 4);
    # 1 = serial (deterministic order), >1 = ParallelTransformer pool
    num_workers: int = 1
    # host augmentation worker PROCESSES (data.parallel.ParallelLoader):
    # 0 = in-process; >0 fans decode+augment out to that many forked
    # workers with shared-memory rings — order-preserving and, unlike
    # the thread pool, deterministically seeded (byte-identical stream
    # for any worker count, seeded from loader_seed).  When set, the
    # thread-pool num_workers is ignored (the process pool replaces it).
    worker_processes: int = 0
    loader_seed: int = 0
    # record-level windowed shuffle (data.ShuffleBuffer) applied to the
    # decoded record stream; 0 disables (file-order shuffle still on).
    # Replaces the global shuffle Spark RDD repartitioning provided.
    shuffle_buffer: int = 0
    shuffle_seed: int = 0
    # device-augmentation staging canvas (None = DeviceAugParam default
    # 512).  Images larger than this are pre-downscaled on host; a tight
    # canvas cuts host→device transfer bytes (the staging tensor is the
    # whole uint8 canvas) at the cost of resolution for oversized images.
    canvas_size: Optional[int] = None
    # staged-pixel wire format for the device-aug path ("bgr" | "yuv420");
    # see DeviceAugParam.wire_format — "yuv420" halves host→device bytes
    wire_format: str = "bgr"
    # pack the device-aug staged batch into one (B, item_bytes) uint8
    # transfer (DeviceAugParam.pack): wins when per-transfer latency,
    # not bandwidth, bounds the input link
    pack_staging: bool = False
    # length-bucketed batching edges (data.bucket.BucketBatcher) for
    # variable-length pipelines — consumed by the DS2 ASR loader
    # (pipelines.deepspeech2.load_asr_train_set(param=...)); the fixed-
    # resolution SSD/FRCNN image chains have no length axis and ignore it
    bucket_edges: Optional[Sequence[int]] = None

    def __post_init__(self):
        # fail fast on the serving path too — a typo'd wire_format would
        # otherwise silently fall through to the 3 B/px bgr wire (the
        # train path already validates via DeviceAugParam.__post_init__)
        if self.wire_format not in ("bgr", "yuv420"):
            raise ValueError(f"unknown wire_format {self.wire_format!r}; "
                             "expected 'bgr' or 'yuv420'")


class RecordToFeature(Transformer):
    """SSDByteRecord → ImageFeature (reference ``RecordToFeature.scala:28``)."""

    def transform(self, record: SSDByteRecord) -> ImageFeature:
        f = ImageFeature(record.data, path=record.path)
        gt = record.gt if record.gt is not None else np.zeros((0, 6), np.float32)
        f["label"] = RoiLabel.from_gt_matrix(gt)
        return f


class RoiImageToBatch(Transformer):
    """Batch ImageFeatures into padded device-ready dicts — the
    ``SSDMiniBatch`` equivalent (reference ``RoiImageToBatch.scala:41``,
    ``Types.scala:41``): CHW float pack becomes NHWC stack; the ragged
    7-col label matrix becomes (B, max_gt, ·) + mask (SURVEY.md §7.3)."""

    def __init__(self, batch_size: int, max_gt: int = 100,
                 keep_label: bool = True, drop_remainder: bool = True):
        self.batch_size = batch_size
        self.max_gt = max_gt
        self.keep_label = keep_label
        self.drop_remainder = drop_remainder

    def _usable(self, f: ImageFeature) -> bool:
        # invalid features stay in the batch ONLY once MatToFloats has
        # zero-filled them — callers' outputs stay index-aligned
        return f.is_valid or f.get("floats") is not None

    def apply_iter(self, it):
        buf: List[ImageFeature] = []
        for f in it:
            if not self._usable(f):
                continue
            buf.append(f)
            if len(buf) == self.batch_size:
                yield self.collate(buf)
                buf = []
        if buf and not self.drop_remainder:
            yield self.collate(buf)

    def collate(self, feats: Sequence[ImageFeature]) -> Dict:
        imgs = np.stack([f["floats"] for f in feats]).astype(np.float32)
        im_info = np.stack([f.get_im_info() for f in feats])
        batch = {"input": imgs, "im_info": im_info}
        if self.keep_label:
            boxes, labels, difficult = [], [], []
            for f in feats:
                lab = f.label if isinstance(f.label, RoiLabel) else RoiLabel(
                    np.zeros(0), np.zeros((0, 4)))
                boxes.append(lab.bboxes)
                labels.append(lab.labels.reshape(-1, 1))
                difficult.append(lab.difficult.reshape(-1, 1))
            b, mask = pad_ragged(boxes, self.max_gt)
            l, _ = pad_ragged(labels, self.max_gt)
            d, _ = pad_ragged(difficult, self.max_gt)
            batch["target"] = {
                "bboxes": b, "labels": l[..., 0].astype(np.int32),
                "difficult": d[..., 0], "mask": mask,
            }
        return batch


def train_transformer(param: PreProcessParam) -> Transformer:
    """The canonical SSD augmentation chain (reference
    ``IOUtils.loadTrainSet:56``): RecordToFeature -> BytesToMat ->
    RoiNormalize -> ColorJitter -> Random(Expand->RoiExpand) ->
    RandomSampler -> Resize(random interp) -> Random(HFlip->RoiHFlip) ->
    MatToFloats(mean subtract)."""
    return (
        RecordToFeature()
        >> BytesToMat()
        >> RoiNormalize()
        >> ColorJitter()
        >> RandomTransformer(Expand(means=param.pixel_means) >> RoiExpand(), 0.5)
        >> RandomSampler()
        >> Resize(param.resolution, param.resolution, interp=-1)
        >> RandomTransformer(HFlip() >> RoiHFlip(), 0.5)
        >> MatToFloats(mean=param.pixel_means,
                       valid_height=param.resolution,
                       valid_width=param.resolution)
    )


def val_transformer(param: PreProcessParam,
                    flip: bool = False) -> Transformer:
    """Validation chain without augmentation (reference ``loadValSet:72``).

    ``flip=True`` inserts a random horizontal flip before the float
    extraction — the resize-only TRAIN chain
    (``load_train_set(augment=False)``) shares this one implementation
    so train/val preprocessing can never skew."""
    chain = (
        RecordToFeature()
        >> BytesToMat()
        >> RoiNormalize()
        >> Resize(param.resolution, param.resolution)
    )
    if flip:
        # before MatToFloats: the float tensor is extracted there, so a
        # later mat flip would desync pixels from the flipped labels
        chain = chain >> RandomTransformer(HFlip() >> RoiHFlip(), 0.5)
    return chain >> MatToFloats(mean=param.pixel_means,
                                valid_height=param.resolution,
                                valid_width=param.resolution)


def _maybe_parallel(t: Transformer, workers: int) -> Transformer:
    return ParallelTransformer(t, workers) if workers > 1 else t


def _maybe_loader(ds: DataSet, param: PreProcessParam):
    """Wrap the assembled dataset in the multiprocess loader when the
    param asks for worker processes (docs/PERFORMANCE.md "Host input
    pipeline"); otherwise return the DataSet unchanged."""
    if param.worker_processes > 0:
        return ds.parallel(param.worker_processes,
                           base_seed=param.loader_seed)
    return ds


def load_train_set_device(pattern: str, param: PreProcessParam,
                          aug: Optional["DeviceAugParam"] = None):
    """Device-augmentation train path (``transform/vision/device.py``):
    host does decode + geometry/label math; all pixel work runs on-chip.
    Returns (DataSet of staging batches, jitted augment fn).

    Supported usage: pass the augment fn as ``device_transform=`` to the
    ``Optimizer`` / ``make_train_step`` so it FUSES into the compiled
    train step (one dispatch per iteration).  Applying it manually per
    batch also works (e.g. for inspection) but costs an extra dispatch —
    don't do both."""
    from analytics_zoo_tpu.transform.vision import (DeviceAugBatch,
                                                    DeviceAugParam,
                                                    DeviceAugPrepare,
                                                    make_device_augment)

    if aug is None:
        extra = ({"canvas_size": param.canvas_size}
                 if param.canvas_size else {})
        aug = DeviceAugParam(resolution=param.resolution,
                             pixel_means=tuple(param.pixel_means),
                             wire_format=param.wire_format,
                             pack=param.pack_staging, **extra)
    chain = (RecordToFeature() >> BytesToMat(to_float=False) >> RoiNormalize()
             >> DeviceAugPrepare(aug))
    ds = DataSet.from_record_files(pattern, SSDByteRecord.decode,
                                   shuffle_files=True)
    if param.shuffle_buffer:
        ds = ds.shuffle(param.shuffle_buffer, seed=param.shuffle_seed)
    ds = (ds.transform(_maybe_parallel(chain, param.num_workers))
          .transform(DeviceAugBatch(param.batch_size, param.max_gt,
                                    pack=aug.pack)))
    return _maybe_loader(ds, param), make_device_augment(aug)


def _warn_host_chain_ignores_wire(param: PreProcessParam, fn: str) -> None:
    # The host-aug chains always ship plain bgr float batches; silently
    # dropping a requested yuv420/packed wire would make callers believe
    # they benched the thin wire (bench.py's hostaug phase did exactly
    # that).  Mirror the FrcnnPredictor guard: loud, not fatal.
    if param.wire_format != "bgr" or param.pack_staging:
        import warnings
        warnings.warn(
            f"{fn}: wire_format={param.wire_format!r} / "
            f"pack_staging={param.pack_staging} are device-aug options; the "
            "host-aug chain ignores them (use load_train_set_device)",
            stacklevel=3)


def load_train_set(pattern: str, param: PreProcessParam,
                   augment: bool = True) -> DataSet:
    """``augment=False`` keeps the TRAINING conveniences (file shuffling,
    shuffle buffer, random flip, drop_remainder batching — one compiled
    shape) but swaps the heavy geometric chain (Expand zoom-out + crop
    samplers) for a plain resize: detectors whose feature stride is
    coarse relative to the image (e.g. Faster-RCNN at small
    resolutions) lose their objects below the feature grid under
    zoom-out augmentation."""
    _warn_host_chain_ignores_wire(param, "load_train_set")
    ds = DataSet.from_record_files(pattern, SSDByteRecord.decode,
                                   shuffle_files=True)
    if param.shuffle_buffer:
        ds = ds.shuffle(param.shuffle_buffer, seed=param.shuffle_seed)
    chain = (train_transformer(param) if augment
             else val_transformer(param, flip=True))
    if param.worker_processes > 0:
        # strip decode bytes + working mat (im_info materialized first)
        # so the shared-memory ring ships only what the batcher reads
        from analytics_zoo_tpu.transform.vision import SealForWire
        chain = chain >> SealForWire()
    return _maybe_loader(
        ds.transform(_maybe_parallel(chain, param.num_workers))
        .transform(RoiImageToBatch(param.batch_size, param.max_gt)), param)


def load_val_set(pattern: str, param: PreProcessParam) -> DataSet:
    # no wire guard here: device-aug training legitimately shares one
    # PreProcessParam between load_train_set_device and this val loader
    # (examples/train_ssd.py), and validation has no device-aug variant
    # to redirect to
    chain = val_transformer(param)
    if param.worker_processes > 0:
        # same wire shrink as load_train_set: RoiImageToBatch reads only
        # floats/im_info/labels, so the decode bytes + working mat are
        # dead weight through the shared-memory ring (and raw JPEG bytes
        # pickle IN-BAND — they would blow the slot budget)
        from analytics_zoo_tpu.transform.vision import SealForWire
        chain = chain >> SealForWire()
    return _maybe_loader(
        DataSet.from_record_files(pattern, SSDByteRecord.decode)
        .transform(_maybe_parallel(chain, param.num_workers))
        .transform(RoiImageToBatch(param.batch_size, param.max_gt,
                                   drop_remainder=False)), param)


class SSDPredictor:
    """Distributed inference (reference ``SSDPredictor.scala:30``): jitted
    forward + in-graph DetectionOutput, detections rescaled to original
    image size via im_info (``BboxUtil.scaleBatchOutput``)."""

    def __init__(self, model: Model, param: PreProcessParam,
                 post: Optional[DetectionOutputParam] = None,
                 n_classes: int = 21, compute_dtype=None,
                 quantize=False, specs=None):
        """``quantize``: ``False`` (fp serving), ``True``/``"weight"``
        (int8 weights in HBM, fp math — bandwidth compression), or
        ``"int8"`` (real int8×int8→int32 convolutions on the MXU with
        dynamic per-tensor activation quantization).

        ``specs`` (:class:`~analytics_zoo_tpu.parallel.specs.SpecSet`):
        serve over a sharded mesh — the jitted detect program is
        annotated with the declared shardings (variables replicated,
        batch dim-0 over ``data``), so widening the mesh widens serving
        with no predictor change.  The predictor itself never calls
        ``device_put``; placement lives in the spec layer only."""
        self.model = model
        self.param = param
        self.specs = specs
        self.post = post or DetectionOutputParam(n_classes=n_classes)
        priors, variances = build_priors(
            ssd300_config() if param.resolution == 300 else ssd512_config())
        # host numpy on purpose: closing a COMMITTED device array into the
        # jitted _detect degrades the remote-TPU transfer path process-wide
        self._priors = np.asarray(priors)
        self._variances = np.asarray(variances)
        # quantized mode snapshots int8 weights and drops the Model
        # reference so the caller CAN release the fp32 tree (otherwise the
        # 4x HBM saving never materializes); fp32 mode reads
        # model.variables at call time so later load_weights take effect
        self._variables = None
        if quantize:
            from analytics_zoo_tpu.parallel.train import resolve_compute_dtype
            from analytics_zoo_tpu.utils.quantize import (
                make_quantized_forward, quantize_params)
            self._variables = quantize_params(model.variables)
            self._eval_step = make_quantized_forward(
                model.module, resolve_compute_dtype(compute_dtype),
                compute="int8" if quantize == "int8" else "dequant")
            self.model = None
        else:
            self._eval_step = make_eval_step(model.module,
                                             compute_dtype=compute_dtype)

    def set_top_k(self, k: int) -> "SSDPredictor":
        """Return a predictor serving ``keep_topk=k`` (reference
        ``setTopK``, which mutates the DetectionOutput layer in place).

        Copy-on-write on purpose: the RECEIVER is unchanged.  Serving
        tiers close over a shared predictor and read ``self.post`` at
        dispatch time, so the old in-place mutation silently changed
        every tier's output geometry and forced a recompile of each
        tier's serving program (``post`` is a static jit argument).
        The returned copy shares weights and the cached jitted
        programs — ``post`` is an argument, so no recompile of the
        receiver's geometry ever happens."""
        import copy

        new = copy.copy(self)
        new.post = dataclasses.replace(self.post, keep_topk=k)
        return new

    def _serving_jit(self, fn, static_argnums, n_batch_args: int):
        """jit a serving program through the spec layer: with a declared
        SpecSet the program carries in_shardings (variables replicated,
        the ``n_batch_args`` leading batch-major array args dim-0 over
        ``data``); batches whose dim 0 doesn't divide the data axis
        (ragged predict tails) fall back to the un-annotated program.
        No SpecSet → the legacy single-program jit."""
        plain = jax.jit(fn, static_argnums=static_argnums)
        if self.specs is None:
            return plain
        annotated = jax.jit(
            fn, static_argnums=static_argnums,
            in_shardings=(self.specs.replicated,)
            + (self.specs.data_sharding,) * n_batch_args)
        return self.specs.ragged_dispatch(annotated, plain)

    @functools.cached_property
    def _detect(self):
        """ONE jitted program for forward + softmax + DetectionOutput +
        rescale.  A remote accelerator pays a fixed round-trip per
        dispatch, so serving must be a single call per batch, not a chain
        of eager ops (the in-graph-DetectionOutput philosophy the
        reference applies by making post-processing a model layer,
        ``SSDGraph.scala``)."""
        means = np.asarray(self.param.pixel_means, np.float32)
        tail = self._forward_tail

        def detect(variables, inputs, h, w, post):
            if inputs.dtype == jnp.uint8:
                # uint8 staging path: normalize ON DEVICE (host sends 4×
                # fewer bytes; MatToFloats semantics, in-graph)
                inputs = inputs.astype(jnp.float32) - means
            return tail(variables, inputs, h, w, post)

        return self._serving_jit(detect, static_argnums=(4,),
                                 n_batch_args=3)

    @property
    def _forward_tail(self):
        """Shared post-input serving pipeline (forward + softmax +
        DetectionOutput + rescale) closed over by every staging variant —
        one place to change, no way for the wire paths to diverge."""
        eval_step = self._eval_step
        priors, variances = self._priors, self._variances

        def tail(variables, inputs, h, w, post):
            loc, conf = eval_step(variables, inputs)
            probs = jax.nn.softmax(conf, axis=-1)
            dets = detection_output(loc, probs, priors, variances, post)
            return scale_detections(dets, h, w)

        return tail

    @functools.cached_property
    def _detect_yuv(self):
        """yuv420-staged variant: the host ships Y + 2×2-subsampled
        chroma (1.5 B/px — half the uint8 staging bytes); BGR
        reconstruction, normalize, forward and DetectionOutput all run
        in the ONE jitted program."""
        from analytics_zoo_tpu.transform.vision.device import (
            yuv420_to_bgr_device)

        means = np.asarray(self.param.pixel_means, np.float32)
        tail = self._forward_tail

        def detect(variables, y, uv, h, w, post):
            return tail(variables, yuv420_to_bgr_device(y, uv) - means,
                        h, w, post)

        return self._serving_jit(detect, static_argnums=(5,),
                                 n_batch_args=4)

    def detect_normalized(self, inputs) -> jnp.ndarray:
        """Forward + softmax + DetectionOutput → (B, K, 6) normalized-box
        detections (shared by predict and Validator so serving and eval
        can't diverge)."""
        variables = (self._variables if self._variables is not None
                     else self.model.variables)
        ones = jnp.ones((inputs.shape[0],), jnp.float32)
        return self._detect(variables, jnp.asarray(inputs), ones, ones,
                            self.post)

    def _detect_device(self, batch: Dict) -> jnp.ndarray:
        """Dispatch one batch; returns the (B, K, 6) device array WITHOUT
        forcing a host sync (jax dispatch is async — callers can overlap
        the next batch's host prep with this one's device execution)."""
        variables = (self._variables if self._variables is not None
                     else self.model.variables)
        # rescale normalized boxes to ORIGINAL pixel sizes: im_info rows are
        # (h, w, scale_h, scale_w); original = current / scale
        h = batch["im_info"][:, 0] / np.maximum(batch["im_info"][:, 2], 1e-8)
        w = batch["im_info"][:, 1] / np.maximum(batch["im_info"][:, 3], 1e-8)
        if "input_uv" in batch:
            return self._detect_yuv(variables, jnp.asarray(batch["input"]),
                                    jnp.asarray(batch["input_uv"]),
                                    jnp.asarray(h), jnp.asarray(w), self.post)
        return self._detect(variables, jnp.asarray(batch["input"]),
                            jnp.asarray(h), jnp.asarray(w), self.post)

    def detect_batch(self, batch: Dict) -> np.ndarray:
        return np.asarray(self._detect_device(batch))

    def predict(self, records) -> List[np.ndarray]:
        """records: iterable of SSDByteRecord → per-image (K, 6) arrays.

        Uses the uint8 staging chain: pixels stay uint8 from decode to
        device, normalize runs in-graph (4× fewer host→device bytes)."""
        return run_serving_loop(
            serving_chain(self.param, uint8=True)(records),
            self._detect_device, np.asarray)


class Uint8ToBatch(RoiImageToBatch):
    """Serving-path batcher: stacks RESIZED uint8 mats + im_info.

    Staging uint8 instead of mean-subtracted float32 sends 4× fewer
    host→device bytes — decisive on a remote accelerator whose transfer
    path is latency/bandwidth constrained; the cast + mean-subtract runs
    inside the jitted serving program (``SSDPredictor._detect``).

    Invalid (decode-failed) records become zero images so predict()
    outputs stay index-aligned with the input records — the same
    contract ``MatToFloats`` gives the float chain (reference
    ``Convertor.scala:74-84``)."""

    def __init__(self, batch_size: int, resolution: int,
                 drop_remainder: bool = False, wire_format: str = "bgr"):
        super().__init__(batch_size, keep_label=False,
                         drop_remainder=drop_remainder)
        self.resolution = resolution
        if wire_format == "yuv420" and resolution % 2:
            raise ValueError("yuv420 serving needs an even resolution, "
                             f"got {resolution}")
        self.wire_format = wire_format

    def _usable(self, f: ImageFeature) -> bool:
        return True                     # invalid → zero image in collate

    def apply_iter(self, it):
        # A final partial batch would be a NEW shape — one extra XLA
        # compile of the whole fused serving program per distinct
        # remainder size (minutes on a cold cache).  Pad it to
        # ``batch_size`` with zero images (the existing invalid-record
        # convention) and record the true count; ``run_serving_loop``
        # slices the outputs back.
        for batch in super().apply_iter(it):
            n = batch["input"].shape[0]
            if n < self.batch_size:
                pad = self.batch_size - n

                def _pad(arr, fill=0):
                    return np.concatenate(
                        [arr, np.full((pad,) + arr.shape[1:], fill,
                                      arr.dtype)])

                padded = {"input": _pad(batch["input"]),
                          "im_info": np.concatenate(
                              [batch["im_info"],
                               np.tile(np.array([[self.resolution,
                                                  self.resolution,
                                                  1.0, 1.0]], np.float32),
                                       (pad, 1))]),
                          "n_valid": n}
                if "input_uv" in batch:     # neutral chroma → black pixels
                    padded["input_uv"] = _pad(batch["input_uv"], 128)
                batch = padded
            yield batch

    def collate(self, feats: Sequence[ImageFeature]) -> Dict:
        res = self.resolution
        default_info = np.array([res, res, 1.0, 1.0], np.float32)
        infos = [f.get_im_info() if (f.is_valid and f.mat is not None)
                 else default_info for f in feats]
        if self.wire_format == "yuv420":
            # planes were staged per-feature by Yuv420Staging INSIDE the
            # (possibly parallel) chain; invalid records get black frames
            zero_y = np.zeros((res, res), np.uint8)
            zero_uv = np.full((res // 2, res // 2, 2), 128, np.uint8)
            ys = [f.get("yuv_y", zero_y) if f.is_valid else zero_y
                  for f in feats]
            uvs = [f.get("yuv_uv", zero_uv) if f.is_valid else zero_uv
                   for f in feats]
            return {"input": np.stack(ys), "input_uv": np.stack(uvs),
                    "im_info": np.stack(infos)}
        zero = np.zeros((res, res, 3), np.uint8)
        mats = [f.mat if (f.is_valid and f.mat is not None) else zero
                for f in feats]
        return {"input": np.stack(mats), "im_info": np.stack(infos)}


def serving_chain(param: PreProcessParam, uint8: bool = False,
                  resize: Optional[Transformer] = None):
    """The shared serving preprocess chain (reference ``SSDPredictor.
    scala:55-60``): val transformer + unlabeled batching.

    ``uint8=True`` keeps pixels uint8 end-to-end on the host (decode →
    resize → stack) and defers normalize to the device program.
    ``resize`` overrides the square ``Resize`` (e.g. Faster-RCNN's
    aspect-preserving ``AspectScaleCanvas``) — it must still emit mats of
    exactly ``param.resolution``² so every batch shares one shape."""
    if uint8:
        chain = (RecordToFeature() >> BytesToMat(to_float=False)
                 >> (resize if resize is not None
                     else Resize(param.resolution, param.resolution)))
        if param.wire_format == "yuv420":
            from analytics_zoo_tpu.transform.vision.device import (
                Yuv420Staging)

            chain = chain >> Yuv420Staging()
        return (_maybe_parallel(chain, param.num_workers)
                >> Uint8ToBatch(param.batch_size, param.resolution,
                                wire_format=param.wire_format))
    return (_maybe_parallel(val_transformer(param), param.num_workers)
            >> RoiImageToBatch(param.batch_size, keep_label=False,
                               drop_remainder=False))


def run_serving_loop(batches, dispatch, readback,
                     max_inflight: int = 4) -> List[np.ndarray]:
    """``overlap_window`` specialized to collecting per-image arrays.

    Honors the padded-final-batch convention (``Uint8ToBatch``): a batch
    carrying ``n_valid`` yields only its first ``n_valid`` rows."""
    out: List[np.ndarray] = []

    def dispatch_sliced(batch):
        n = batch.pop("n_valid", None) if isinstance(batch, dict) else None
        return dispatch(batch), n

    def consume(token):
        tok, n = token
        arr = readback(tok)
        out.extend(arr[i] for i in range(arr.shape[0] if n is None else n))

    overlap_window(batches, dispatch_sliced, consume, max_inflight)
    return out


class Validator:
    """Distributed eval with throughput logging (reference
    ``Validator.scala:34,56-86``: forward + evaluator per batch, monoid
    reduce, records/sec accumulator log)."""

    def __init__(self, model: Model, param: PreProcessParam,
                 evaluator: Optional[MeanAveragePrecision] = None,
                 post: Optional[DetectionOutputParam] = None,
                 quantize=False, clock=None):
        """``quantize`` forwards to :class:`SSDPredictor` — evaluate the
        int8 serving modes with the same Validator the fp path uses.
        ``clock``: injected time source for the throughput log (utils.
        clock convention — the one-clock rule bans raw time.time)."""
        from analytics_zoo_tpu.utils.clock import as_now_fn

        self.predictor = SSDPredictor(model, param, post=post,
                                      quantize=quantize)
        self.evaluator = evaluator or MeanAveragePrecision()
        self._now = as_now_fn(clock)

    def test(self, dataset) -> DetectionResult:
        total: Optional[DetectionResult] = None
        n_records = 0
        t0 = self._now()

        def dispatch(batch):
            nonlocal n_records
            n_records += batch["input"].shape[0]
            return self.predictor.detect_normalized(batch["input"]), batch

        def consume(token):
            nonlocal total
            dets, batch = token
            r = self.evaluator(np.asarray(dets), batch)
            total = r if total is None else total + r

        # dispatch-ahead window: the next batches' forwards overlap this
        # one's readback + host-side eval
        overlap_window(dataset, dispatch, consume)
        dt = self._now() - t0
        logger.info("[Prediction] %d in %.2f seconds. Throughput is %.2f "
                    "records/sec", n_records, dt, n_records / max(dt, 1e-9))
        return total


class SSDMeanAveragePrecision:
    """ValidationMethod adapter for the Optimizer's validation loop: the
    raw SSDVgg output is (loc, conf) logits, so decode + NMS runs here
    before delegating to MeanAveragePrecision (the reference's
    MeanAveragePrecision similarly decodes inside the ValidationMethod,
    ``DetectionResult.scala`` → ``BboxUtil.decodeBatchOutput``)."""

    def __init__(self, n_classes: int = 21, resolution: int = 300,
                 post: Optional[DetectionOutputParam] = None,
                 use_07_metric: bool = True, metric: str = "voc"):
        if metric == "coco":
            self.inner = CocoMeanAveragePrecision(n_classes=n_classes)
        elif metric == "voc":
            self.inner = MeanAveragePrecision(n_classes=n_classes,
                                              use_07_metric=use_07_metric)
        else:
            raise ValueError(f"metric must be 'voc' or 'coco', got {metric!r}")
        self.post = post or DetectionOutputParam(n_classes=n_classes)
        priors, variances = build_priors(
            ssd300_config() if resolution == 300 else ssd512_config())
        # host numpy (see SSDPredictor: device-array constants poison the
        # remote-TPU transfer path)
        self._priors = np.asarray(priors)
        self._variances = np.asarray(variances)
        self.name = self.inner.name

    def __call__(self, output, batch) -> "DetectionResult | MultiIoUResult":
        loc, conf = output
        probs = jax.nn.softmax(conf, axis=-1)
        dets = detection_output(loc, probs, self._priors, self._variances,
                                self.post)
        return self.inner(np.asarray(dets), batch)


@dataclasses.dataclass
class TrainParams:
    """Reference ``TrainParams`` (``ssd/example/Train.scala:39``)."""

    batch_size: int = 32
    resolution: int = 300
    n_classes: int = 21
    learning_rate: float = 0.0035
    momentum: float = 0.9
    weight_decay: float = 0.0005
    max_epoch: int = 250
    schedule: str = "plateau"           # 'plateau' | 'multistep'
    lr_steps: Sequence[int] = ()
    warm_up_map: Optional[float] = None  # Adam warm-up target mAP
    warm_up_lr: float = 1e-4
    checkpoint_path: Optional[str] = None
    overwrite_checkpoint: bool = True
    log_dir: Optional[str] = None
    job_name: str = "ssd300"
    max_gt: int = 100
    # MXU-native mixed precision (fp32 masters, bf16 compute); None = fp32
    compute_dtype: Optional[str] = "bf16"
    # background shard+transfer depth (Optimizer prefetch); 0 = sync
    prefetch: int = 2


def train_ssd(train_set, val_set, params: TrainParams,
              model: Optional[Model] = None, mesh=None,
              device_transform: Optional[Callable] = None,
              tp: Optional[str] = None) -> Model:
    """The Train entry point's optimize() assembly (reference
    ``Train.scala:150-252``).

    ``device_transform``: the jitted augment returned by
    ``load_train_set_device`` — fuses the on-device augmentation into
    every compiled train step (pass the matching staged ``train_set``).

    Sharding is declared ONCE through the spec registry
    (``pipeline_specs("ssd", ...)``) and consumed by the Optimizer's
    annotated jit — this entry point performs no device placement.
    ``tp``: ``None`` (data parallel) | ``"spatial"`` (image height over
    the ``model`` axis) | ``"megatron"`` (paired col/row weight
    sharding); parallelism modes compose by changing the MESH SHAPE
    (e.g. ``create_mesh((2, 4), axis_names=("data", "model"))``), not
    this function."""
    from analytics_zoo_tpu.parallel import pipeline_specs

    specs = pipeline_specs("ssd", mesh=mesh, tp=tp,
                           resolution=params.resolution)
    cfg = (ssd300_config() if params.resolution == 300 else ssd512_config())
    priors, variances = build_priors(cfg)
    criterion = MultiBoxLoss(priors, variances,
                             MultiBoxLossParam(n_classes=params.n_classes))
    if model is None:
        model = Model(SSDVgg(num_classes=params.n_classes,
                             resolution=params.resolution))
        model.build(0, jnp.zeros((1, params.resolution, params.resolution, 3)))

    evaluator = SSDMeanAveragePrecision(n_classes=params.n_classes,
                                        resolution=params.resolution)

    def make_optimizer(optim_method, end_when):
        opt = (Optimizer(model, train_set, criterion, specs=specs,
                         skip_loss_above=50.0,
                         compute_dtype=params.compute_dtype,
                         prefetch=params.prefetch,
                         device_transform=device_transform)
               .set_optim_method(optim_method)
               .set_end_when(end_when))
        if val_set is not None:
            opt.set_validation(Trigger.every_epoch(), val_set, [evaluator])
        if params.checkpoint_path:
            opt.set_checkpoint(params.checkpoint_path, Trigger.every_epoch(),
                               overwrite=params.overwrite_checkpoint)
        if params.log_dir:
            opt.set_train_summary(TrainSummary(params.log_dir, params.job_name))
            opt.set_validation_summary(
                ValidationSummary(params.log_dir, params.job_name))
        return opt

    # optional Adam warm-up until a target mAP (reference Train.scala:178-187)
    if params.warm_up_map is not None and val_set is not None:
        logger.info("warm-up with Adam until mAP >= %.3f", params.warm_up_map)
        make_optimizer(
            Adam(params.warm_up_lr),
            Trigger.or_(Trigger.max_score(params.warm_up_map),
                        Trigger.max_epoch(params.max_epoch)),
        ).optimize()

    if params.schedule == "multistep" and params.lr_steps:
        optim = SGD(params.learning_rate, momentum=params.momentum,
                    weight_decay=params.weight_decay,
                    schedule=multistep(params.learning_rate, params.lr_steps,
                                       0.1))
    else:
        optim = SGD(params.learning_rate, momentum=params.momentum,
                    weight_decay=params.weight_decay,
                    plateau=Plateau(monitor="score", factor=0.5, patience=10,
                                    mode="max", min_lr=1e-5))
    make_optimizer(optim, Trigger.max_epoch(params.max_epoch)).optimize()
    return model


def ssd_serving_tiers(model: Model, param: PreProcessParam,
                      post: Optional[DetectionOutputParam] = None,
                      n_classes: int = 21, compute_dtype=None,
                      degraded_topk: int = 50, specs=None) -> List:
    """Degradation-ladder rungs for the online serving runtime
    (``serving.ServingRuntime``): three :class:`~analytics_zoo_tpu.
    serving.ladder.ServingTier` s over the SAME ``SSDPredictor`` serving
    program, cheapest last.

    - tier 0 ``fp``: full-precision weights, full NMS ``keep_topk``;
    - tier 1 ``int8``: weight-only int8 via ``quantize_params`` (the
      banked readings: ~4× less HBM traffic, 1.3× conv speedup,
      mAP delta +0.0001 — INT8_MAP_PARITY.json);
    - tier 2 ``int8_topk``: int8 plus ``keep_topk=degraded_topk`` — a
      bounded, explicit post-processing cut (reference ``setTopK``).

    All three rungs dispatch whatever DetectionOutput backend ``post``
    selects — with the default ``backend="auto"`` that is the FUSED
    single-kernel post-processing program on a TPU backend
    (``ops/pallas_detout.py``; pass ``post=DetectionOutputParam(
    backend="fused")`` to force it elsewhere, interpret-mode off-TPU),
    so the int8 rung's conv win is no longer buried under four staged
    post-processing dispatches (docs/PERFORMANCE.md "DetectionOutput").
    The ``device_program`` thunks below expose exactly those fused
    programs to the az-analyze serving audit.

    Requests carry preprocessed fixed-resolution images
    (``{"input": (H, W, 3) float32}``, no variable axis — the serving
    batcher's FIXED bucket); every tier's forward is jit-compiled once
    per (tier, batch) geometry, which the runtime pins by always padding
    the batch axis to ``max_batch``.  ``speed`` values are relative
    service-time hints for the batcher's flush heuristic, from the
    banked int8 conv reading — the EWMA refines them online.

    ``specs`` (:class:`~analytics_zoo_tpu.parallel.specs.SpecSet`, e.g.
    ``pipeline_specs("ssd", mesh=mesh)``): every tier's detect program
    is then mesh-annotated (variables replicated, batch over ``data``)
    — serving scales out by widening the mesh, with the spec layer as
    the only placement site.
    """
    import copy

    from analytics_zoo_tpu.serving.ladder import ServingTier

    full = SSDPredictor(model, param, post=post, n_classes=n_classes,
                        compute_dtype=compute_dtype, specs=specs)
    int8 = SSDPredictor(model, param, post=post, n_classes=n_classes,
                        compute_dtype=compute_dtype, quantize=True,
                        specs=specs)
    # tier 2 shares tier 1's quantized variables (no second quantize
    # pass); only the DetectionOutput param differs — `post` is a static
    # jit argument, so the shared program specializes per tier
    low = copy.copy(int8)
    low.post = dataclasses.replace(int8.post, keep_topk=degraded_topk)

    def fwd(pred: SSDPredictor) -> Callable[[Dict], np.ndarray]:
        def forward(batch: Dict) -> np.ndarray:
            return np.asarray(pred.detect_normalized(batch["input"]))
        return forward

    def audit(pred: SSDPredictor) -> Callable[[], tuple]:
        """``az_analyze --program`` hook: the tier's actual jitted
        detect program + shape-only example args (ShapeDtypeStructs —
        the audit traces, it never dispatches)."""
        def device_program():
            B = (pred.specs.data_axis_size if pred.specs is not None
                 else 1)
            res = pred.param.resolution
            variables = (pred._variables if pred._variables is not None
                         else pred.model.variables)
            S = jax.ShapeDtypeStruct
            ones = S((B,), jnp.float32)
            return (pred._detect,
                    (variables, S((B, res, res, 3), jnp.float32),
                     ones, ones, pred.post),
                    (4,))
        return device_program

    return [
        ServingTier("fp", fwd(full), speed=1.0,
                    quality_note="full precision, full NMS top-K",
                    device_program=audit(full)),
        ServingTier("int8", fwd(int8), speed=0.77,
                    quality_note="int8 weights, fp math (mAP delta "
                                 "+0.0001, INT8_MAP_PARITY.json)",
                    device_program=audit(int8)),
        ServingTier(f"int8_topk{degraded_topk}", fwd(low), speed=0.7,
                    quality_note=f"int8 + keep_topk={degraded_topk} "
                                 "(fewer kept detections per image)",
                    device_program=audit(low)),
    ]
