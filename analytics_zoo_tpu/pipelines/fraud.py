"""Fraud-detection pipeline: the minimum end-to-end slice.

Port of the reference's ``pipeline/fraudDetection`` +
``BigDLKaggleFraud.scala:13-78``: CSV frame → VectorAssembler +
StandardScaler + label remap → time-quantile 70/30 split → MLP
(``Linear(29,10)→Linear(10,2)→LogSoftMax``) as a frame Estimator stage
(the ``DLClassifier`` equivalent) → optional ``Bagging`` of N models →
threshold sweep with AUPRC / precision / recall.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from analytics_zoo_tpu.core.criterion import ClassNLLCriterion
from analytics_zoo_tpu.core.module import Model
from analytics_zoo_tpu.models import FraudMLP
from analytics_zoo_tpu.parallel import Adam, Optimizer, Trigger, pipeline_specs
from analytics_zoo_tpu.pipelines.frame import (
    Frame,
    FramePipeline,
    FuncTransformer,
    Stage,
    StandardScaler,
    StratifiedSampler,
    VectorAssembler,
    time_ordered_split,
)


class MLPClassifier(Stage):
    """Frame estimator wrapping the TPU train loop (the reference's
    ``DLClassifier`` adapter over BigDL)."""

    def __init__(self, in_features: int = 29, hidden: int = 10,
                 n_classes: int = 2, epochs: int = 10, batch_size: int = 64,
                 lr: float = 5e-3, features_col: str = "features",
                 label_col: str = "label",
                 prediction_col: str = "prediction", mesh=None, seed: int = 0):
        self.in_features = in_features
        self.hidden = hidden
        self.n_classes = n_classes
        self.epochs = epochs
        self.batch_size = batch_size
        self.lr = lr
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col
        self.mesh = mesh
        self.seed = seed
        self.model: Optional[Model] = None

    def _batches(self, x: np.ndarray, y: np.ndarray):
        n = (len(x) // self.batch_size) * self.batch_size
        out = []
        for i in range(0, n, self.batch_size):
            out.append({"input": x[i:i + self.batch_size],
                        "target": y[i:i + self.batch_size]})
        return out

    def fit(self, frame: Frame) -> "MLPClassifier":
        x = np.asarray(frame[self.features_col], np.float32)
        y = np.asarray(frame[self.label_col], np.int32)
        # sharding declared once through the spec registry; the
        # annotated train step owns all placement
        specs = pipeline_specs("fraud", mesh=self.mesh)
        model = Model(FraudMLP(in_features=self.in_features,
                               hidden=self.hidden, n_classes=self.n_classes))
        model.build(self.seed, jnp.zeros((1, x.shape[1])))
        batches = self._batches(x, y)
        (Optimizer(model, batches, ClassNLLCriterion(), specs=specs)
         .set_optim_method(Adam(self.lr))
         .set_end_when(Trigger.max_epoch(self.epochs))
         .optimize())
        self.model = model
        return self

    def transform(self, frame: Frame) -> Frame:
        if self.model is None:
            raise RuntimeError("MLPClassifier not fitted")
        x = jnp.asarray(np.asarray(frame[self.features_col], np.float32))
        log_probs = np.asarray(self.model.forward(x))
        out = dict(frame)
        out[self.prediction_col] = log_probs.argmax(axis=1)
        out["log_probs"] = log_probs
        return out


def fraud_serving_tiers(model: Model, specs=None):
    """Degradation-ladder rungs for the online serving runtime
    (ISSUE 14 — fraud joins the multiplexed fleet): two
    :class:`~analytics_zoo_tpu.serving.ladder.ServingTier` s over the
    trained ``FraudMLP``, cheapest last.

    Requests carry one assembled+scaled feature row (``{"input":
    (in_features,) float32}`` — the frame pipeline's ``features``
    column; fixed shape, the serving batcher's FIXED bucket).  Tier 0
    serves full-precision weights through the (optionally mesh-
    annotated) eval step; tier 1 serves weight-only int8 via the same
    ``quantize_params`` mechanism as the SSD ladder.  Both rungs
    expose their jitted program to the az-analyze serving audit
    (``fraud/serve:*`` targets).
    """
    from analytics_zoo_tpu.parallel import make_eval_step
    from analytics_zoo_tpu.serving.ladder import ServingTier
    from analytics_zoo_tpu.utils.quantize import (make_quantized_forward,
                                                  quantize_params)

    in_features = model.module.in_features
    eval_step = make_eval_step(model.module, specs=specs)
    qparams = quantize_params(model.variables)
    qfwd = make_quantized_forward(model.module)

    def fwd_fp(batch: Dict) -> np.ndarray:
        return np.asarray(eval_step(model.variables,
                                    jnp.asarray(batch["input"])))

    def fwd_int8(batch: Dict) -> np.ndarray:
        return np.asarray(qfwd(qparams, jnp.asarray(batch["input"])))

    B = specs.data_axis_size if specs is not None else 1

    def audit_fp():
        return (eval_step,
                (model.variables,
                 jax.ShapeDtypeStruct((B, in_features), jnp.float32)), ())

    def audit_int8():
        return (qfwd,
                (qparams,
                 jax.ShapeDtypeStruct((B, in_features), jnp.float32)), ())

    return [
        ServingTier("fp", fwd_fp, speed=1.0,
                    quality_note="fp32 weights, annotated eval step",
                    device_program=audit_fp),
        ServingTier("int8", fwd_int8, speed=0.8,
                    quality_note="weight-only int8 (quantize_params)",
                    device_program=audit_int8),
    ]


def auprc(labels: np.ndarray, scores: np.ndarray) -> float:
    """Area under the precision-recall curve (the reference evaluates with
    ``BinaryClassificationEvaluator`` AUPRC, ``BigDLKaggleFraud.scala:60``)."""
    order = np.argsort(-scores)
    labels = np.asarray(labels)[order]
    tp = np.cumsum(labels == 1)
    fp = np.cumsum(labels != 1)
    npos = max(int((labels == 1).sum()), 1)
    precision = tp / np.maximum(tp + fp, 1)
    recall = tp / npos
    # step-wise integration over recall increments
    d_recall = np.diff(np.concatenate([[0.0], recall]))
    return float(np.sum(precision * d_recall))


def precision_recall(labels: np.ndarray, preds: np.ndarray,
                     positive: int = 1):
    tp = int(((preds == positive) & (labels == positive)).sum())
    fp = int(((preds == positive) & (labels != positive)).sum())
    fn = int(((preds != positive) & (labels == positive)).sum())
    precision = tp / max(tp + fp, 1)
    recall = tp / max(tp + fn, 1)
    return precision, recall


@dataclasses.dataclass
class FraudResult:
    auprc: float
    best_threshold: int
    precision: float
    recall: float


def run_fraud_pipeline(frame: Frame, feature_cols: Sequence[str],
                       label_col: str = "label", time_col: str = "time",
                       n_models: int = 20,
                       thresholds: Optional[Sequence[int]] = None,
                       epochs: int = 10, mesh=None) -> FraudResult:
    """End-to-end reference flow (``BigDLKaggleFraud.scala``): preprocess →
    time split → Bagging(MLP) over stratified samples → threshold sweep
    (reference sweeps 20..40 with 20 models; default here scales the sweep
    to ``n_models`` so small ensembles stay meaningful)."""
    from analytics_zoo_tpu.pipelines.frame import Bagging

    if thresholds is None:
        thresholds = range(max(n_models // 2, 1), n_models + 1)
    else:
        thresholds = [t for t in thresholds if 1 <= t <= n_models]
        if not thresholds:
            raise ValueError(
                f"no requested vote threshold lies in [1, {n_models}] — "
                f"thresholds must not exceed n_models")

    pre = FramePipeline([
        VectorAssembler(feature_cols),
        StandardScaler(),
    ])
    frame = pre.fit_transform(frame)
    train, test = time_ordered_split(frame, time_col)

    n_feat = np.asarray(frame["features"]).shape[1]
    bag = Bagging(
        base_fn=lambda: MLPClassifier(in_features=n_feat, epochs=epochs,
                                      mesh=mesh),
        n_models=n_models,
        sampler=StratifiedSampler({0: 1.0, 1: 10.0}, label_col=label_col),
        threshold=min(thresholds),
    )
    bag.fit(train)
    scored = bag.transform(test)
    votes = scored["votes"]
    labels = np.asarray(test[label_col])
    pr_auc = auprc(labels, votes.astype(np.float32) / n_models)
    best = (0, 0.0, 0.0)
    for t in thresholds:
        preds = (votes >= t).astype(np.int64)
        p, r = precision_recall(labels, preds)
        if p + r > best[1] + best[2]:
            best = (t, p, r)
    return FraudResult(auprc=pr_auc, best_threshold=best[0],
                       precision=best[1], recall=best[2])
