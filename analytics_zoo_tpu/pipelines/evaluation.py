"""Detection evaluation: Pascal VOC mAP machinery.

Port of the reference's ``common/EvalUtil.scala`` (per-batch TP/FP marking
with difficult handling ``evaluateBatch:100``, ``computeAP:195``, VOC07
11-point vs area-under-PR ``vocAp:37``), ``common/DetectionResult.scala``
(the ``+``-mergeable ValidationMethod plugged into the optimizer's
validation loop) and ``common/PascalVocEvaluator.scala`` (per-class AP
printout, 07 vs 10+ metric by year).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def voc_ap(recall: np.ndarray, precision: np.ndarray,
           use_07_metric: bool = False) -> float:
    """AP from a PR curve (reference ``EvalUtil.vocAp:37``): 11-point
    interpolation (VOC07) or area under the monotonized curve (VOC10+)."""
    if use_07_metric:
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            mask = recall >= t
            p = float(precision[mask].max()) if mask.any() else 0.0
            ap += p / 11.0
        return ap
    mrec = np.concatenate([[0.0], recall, [1.0]])
    mpre = np.concatenate([[0.0], precision, [0.0]])
    for i in range(len(mpre) - 2, -1, -1):
        mpre[i] = max(mpre[i], mpre[i + 1])
    idx = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))


def mark_tp_fp(det_boxes: np.ndarray, det_scores: np.ndarray,
               gt_boxes: np.ndarray, gt_difficult: np.ndarray,
               iou_threshold: float = 0.5,
               normalized: bool = False) -> np.ndarray:
    """Greedy-match one image's detections (sorted by score desc) against
    gt (reference ``EvalUtil.evaluateBatch:100`` inner loop).

    Returns (N, 3) rows (score, tp, fp); detections matching a *difficult*
    gt count as neither.
    """
    order = np.argsort(-det_scores)
    taken = np.zeros(len(gt_boxes), bool)
    out = np.zeros((len(det_boxes), 3), np.float32)
    off = 0.0 if normalized else 1.0
    if len(gt_boxes):
        # vectorized IoU matrix (numpy twin of ops.bbox.iou_matrix)
        d, g = np.asarray(det_boxes, np.float64), np.asarray(gt_boxes, np.float64)
        ix1 = np.maximum(d[:, None, 0], g[None, :, 0])
        iy1 = np.maximum(d[:, None, 1], g[None, :, 1])
        ix2 = np.minimum(d[:, None, 2], g[None, :, 2])
        iy2 = np.minimum(d[:, None, 3], g[None, :, 3])
        inter = (np.maximum(ix2 - ix1 + off, 0)
                 * np.maximum(iy2 - iy1 + off, 0))
        area_d = (d[:, 2] - d[:, 0] + off) * (d[:, 3] - d[:, 1] + off)
        area_g = (g[:, 2] - g[:, 0] + off) * (g[:, 3] - g[:, 1] + off)
        iou_all = inter / np.maximum(area_d[:, None] + area_g[None, :] - inter,
                                     1e-12)
    for row, i in enumerate(order):
        out[row, 0] = det_scores[i]
        if len(gt_boxes):
            best_j = int(np.argmax(iou_all[i]))
            best_iou = float(iou_all[i, best_j])
        else:
            best_iou, best_j = 0.0, -1
        if best_iou >= iou_threshold and best_j >= 0:
            if gt_difficult[best_j] > 0:
                continue                       # difficult: ignore entirely
            if not taken[best_j]:
                out[row, 1] = 1.0              # tp
                taken[best_j] = True
            else:
                out[row, 2] = 1.0              # duplicate -> fp
        else:
            out[row, 2] = 1.0                  # no match -> fp
    return out


class DetectionResult:
    """Mergeable per-class accumulation of (score, tp, fp) + positive count
    (reference ``DetectionResult.scala:25,57`` monoid)."""

    name = "MeanAveragePrecision"

    def __init__(self, n_classes: int, use_07_metric: bool = True,
                 class_names: Optional[Sequence[str]] = None):
        self.n_classes = n_classes
        self.use_07_metric = use_07_metric
        self.class_names = class_names
        self.marks: Dict[int, List[np.ndarray]] = {c: [] for c in range(n_classes)}
        self.npos = np.zeros(n_classes, np.int64)

    def __add__(self, other: "DetectionResult") -> "DetectionResult":
        out = DetectionResult(self.n_classes, self.use_07_metric,
                              self.class_names)
        for c in range(self.n_classes):
            out.marks[c] = self.marks[c] + other.marks[c]
        out.npos = self.npos + other.npos
        return out

    def ap_per_class(self) -> np.ndarray:
        aps = np.zeros(self.n_classes, np.float32)
        for c in range(self.n_classes):
            if self.npos[c] == 0:
                aps[c] = np.nan
                continue
            if not self.marks[c]:
                aps[c] = 0.0
                continue
            rows = np.concatenate(self.marks[c], axis=0)
            order = np.argsort(-rows[:, 0])
            tp = np.cumsum(rows[order, 1])
            fp = np.cumsum(rows[order, 2])
            recall = tp / self.npos[c]
            precision = tp / np.maximum(tp + fp, 1e-12)
            aps[c] = voc_ap(recall, precision, self.use_07_metric)
        return aps

    def result(self) -> float:
        aps = self.ap_per_class()
        valid = ~np.isnan(aps)
        return float(aps[valid].mean()) if valid.any() else 0.0

    def __repr__(self):
        return f"{self.name}: {self.result():.4f}"


class MeanAveragePrecision:
    """ValidationMethod over ``(detections, target)`` batches — plugs into
    ``parallel.validate`` the way the reference plugs its
    MeanAveragePrecision into the Optimizer's validation loop.

    ``output``: (B, K, 6) DetectionOutput rows (cls, score, x1,y1,x2,y2).
    ``batch["target"]``: padded gt dict (bboxes (B,G,4), labels (B,G),
    difficult (B,G) optional, mask (B,G)).
    """

    def __init__(self, n_classes: int = 21, use_07_metric: bool = True,
                 iou_threshold: float = 0.5, normalized: bool = True,
                 class_names: Optional[Sequence[str]] = None):
        self.n_classes = n_classes
        self.use_07_metric = use_07_metric
        self.iou = iou_threshold
        self.normalized = normalized
        self.class_names = class_names
        self.name = "MeanAveragePrecision"

    def __call__(self, output, batch) -> DetectionResult:
        dets = np.asarray(output)
        target = batch["target"]
        gt_boxes = np.asarray(target["bboxes"])
        gt_labels = np.asarray(target["labels"])
        gt_mask = np.asarray(target["mask"])
        gt_diff = np.asarray(target.get("difficult", np.zeros_like(gt_mask)))
        res = DetectionResult(self.n_classes, self.use_07_metric,
                              self.class_names)
        B = dets.shape[0]
        for b in range(B):
            valid_gt = gt_mask[b] > 0
            for c in range(1, self.n_classes):
                cls_gt = valid_gt & (gt_labels[b] == c)
                res.npos[c] += int((cls_gt & (gt_diff[b] == 0)).sum())
                sel = (dets[b, :, 0] == c) & (dets[b, :, 1] > 0)
                if not sel.any():
                    continue
                marks = mark_tp_fp(
                    dets[b, sel, 2:6], dets[b, sel, 1],
                    gt_boxes[b][cls_gt], gt_diff[b][cls_gt],
                    self.iou, self.normalized)
                res.marks[c].append(marks)
        return res


def _iou_matrix(det_boxes: np.ndarray, gt_boxes: np.ndarray,
                normalized: bool) -> np.ndarray:
    d = np.asarray(det_boxes, np.float64)
    g = np.asarray(gt_boxes, np.float64)
    off = 0.0 if normalized else 1.0
    ix1 = np.maximum(d[:, None, 0], g[None, :, 0])
    iy1 = np.maximum(d[:, None, 1], g[None, :, 1])
    ix2 = np.minimum(d[:, None, 2], g[None, :, 2])
    iy2 = np.minimum(d[:, None, 3], g[None, :, 3])
    inter = (np.maximum(ix2 - ix1 + off, 0) * np.maximum(iy2 - iy1 + off, 0))
    area_d = (d[:, 2] - d[:, 0] + off) * (d[:, 3] - d[:, 1] + off)
    area_g = (g[:, 2] - g[:, 0] + off) * (g[:, 3] - g[:, 1] + off)
    return inter / np.maximum(area_d[:, None] + area_g[None, :] - inter,
                              1e-12)


def mark_tp_fp_multi(det_boxes: np.ndarray, det_scores: np.ndarray,
                     gt_boxes: np.ndarray, gt_difficult: np.ndarray,
                     thresholds: Sequence[float],
                     normalized: bool = True) -> List[np.ndarray]:
    """COCO-convention matching at several IoU thresholds sharing ONE IoU
    matrix + score sort: each detection (score desc) matches the
    HIGHEST-IoU still-unmatched non-difficult gt with IoU ≥ t (pycocotools
    semantics — NOT the VOC argmax-only rule of :func:`mark_tp_fp`, which
    marks a duplicate FP even when another gt would match).  Difficult
    (COCO "ignore") gts absorb otherwise-unmatched detections.

    Returns one (N, 3) (score, tp, fp) array per threshold.
    """
    order = np.argsort(-np.asarray(det_scores))
    n_det, n_gt = len(det_boxes), len(gt_boxes)
    iou = (_iou_matrix(det_boxes, gt_boxes, normalized) if n_gt
           else np.zeros((n_det, 0)))
    diff = np.asarray(gt_difficult) > 0
    outs = []
    for t in thresholds:
        out = np.zeros((n_det, 3), np.float32)
        taken = np.zeros(n_gt, bool)
        for row, i in enumerate(order):
            out[row, 0] = det_scores[i]
            cand = ~taken & ~diff & (iou[i] >= t) if n_gt else np.zeros(0, bool)
            if cand.any():
                j = int(np.argmax(np.where(cand, iou[i], -1.0)))
                taken[j] = True
                out[row, 1] = 1.0                      # tp
            elif n_gt and (diff & (iou[i] >= t)).any():
                continue                               # ignore region
            else:
                out[row, 2] = 1.0                      # fp
        outs.append(out)
    return outs


class MultiIoUResult:
    """Monoid over per-IoU-threshold DetectionResults (COCO-style)."""

    def __init__(self, results: List[DetectionResult],
                 name: str = "mAP@[.5:.95]"):
        self.results = results
        self.name = name

    def __add__(self, other: "MultiIoUResult") -> "MultiIoUResult":
        return MultiIoUResult([a + b for a, b in
                               zip(self.results, other.results)], self.name)

    def result(self) -> float:
        vals = [r.result() for r in self.results]
        return float(np.mean(vals)) if vals else 0.0

    def per_threshold(self) -> List[float]:
        return [r.result() for r in self.results]

    def __repr__(self):
        return f"{self.name}: {self.result():.4f}"


class CocoMeanAveragePrecision:
    """COCO-convention mAP averaged over IoU thresholds 0.50:0.05:0.95
    with area-under-PR AP and pycocotools matching (best still-unmatched
    gt, difficult = ignore region) — net-new over the reference, whose
    COCO support stops at dataset ingestion + VOC-style eval
    (``common/Coco.scala``, ``EvalUtil``).  Same batch interface as
    :class:`MeanAveragePrecision`, so it plugs into ``parallel.validate``
    / ``set_validation`` unchanged.  The per-image IoU matrix and score
    sort are computed ONCE and shared across all thresholds.
    """

    def __init__(self, n_classes: int = 81, normalized: bool = True,
                 class_names: Optional[Sequence[str]] = None,
                 thresholds: Optional[Sequence[float]] = None):
        self.thresholds = (list(thresholds) if thresholds is not None
                           else [0.5 + 0.05 * i for i in range(10)])
        self.n_classes = n_classes
        self.normalized = normalized
        self.class_names = class_names
        self.name = "mAP@[.5:.95]"

    def __call__(self, output, batch) -> MultiIoUResult:
        dets = np.asarray(output)
        target = batch["target"]
        gt_boxes = np.asarray(target["bboxes"])
        gt_labels = np.asarray(target["labels"])
        gt_mask = np.asarray(target["mask"])
        gt_diff = np.asarray(target.get("difficult", np.zeros_like(gt_mask)))
        results = [DetectionResult(self.n_classes, use_07_metric=False,
                                   class_names=self.class_names)
                   for _ in self.thresholds]
        for b in range(dets.shape[0]):
            valid_gt = gt_mask[b] > 0
            for c in range(1, self.n_classes):
                cls_gt = valid_gt & (gt_labels[b] == c)
                npos = int((cls_gt & (gt_diff[b] == 0)).sum())
                for r in results:
                    r.npos[c] += npos
                sel = (dets[b, :, 0] == c) & (dets[b, :, 1] > 0)
                if not sel.any():
                    continue
                marks = mark_tp_fp_multi(
                    dets[b, sel, 2:6], dets[b, sel, 1],
                    gt_boxes[b][cls_gt], gt_diff[b][cls_gt],
                    self.thresholds, self.normalized)
                for r, m in zip(results, marks):
                    r.marks[c].append(m)
        return MultiIoUResult(results, self.name)


class PascalVocEvaluator:
    """Standalone evaluator with per-class AP printout (reference
    ``PascalVocEvaluator.scala:33``; metric picked by year: 2007 → 11-point)."""

    def __init__(self, image_set: str = "voc_2007_test",
                 class_names: Optional[Sequence[str]] = None):
        self.use_07_metric = "2007" in image_set
        self.class_names = class_names

    def evaluate(self, result: DetectionResult) -> float:
        # the year decides the metric, overriding whatever the accumulating
        # method defaulted to (reference picks 07 vs 10+ metric by year)
        result.use_07_metric = self.use_07_metric
        aps = result.ap_per_class()
        names = self.class_names or [str(i) for i in range(len(aps))]
        for name, ap in zip(names[1:], aps[1:]):
            if not np.isnan(ap):
                print(f"AP for {name} = {ap:.4f}")
        valid = ~np.isnan(aps)
        m = float(aps[valid].mean()) if valid.any() else 0.0
        print(f"Mean AP = {m:.4f}")
        return m
