"""Column-pipeline abstraction: the Spark-ML-Pipeline stand-in.

The reference composes DeepSpeech2 and fraud detection as Spark ML
``Pipeline``s of column transformers over DataFrames (SURVEY.md §2.3/§2.4,
§7.3 hard part #8).  Here a **Frame** is a plain dict of named columns
(numpy arrays or Python lists, equal length) and stages follow the
fit/transform contract:

- ``Stage.fit(frame) -> Stage`` learns state (scalers, vocab, models);
- ``Stage.transform(frame) -> frame`` adds/replaces columns;
- ``FramePipeline([...])`` chains them (``new Pipeline().setStages``).

Includes ports of the Spark-ML extensions the reference adds:
``FuncTransformer`` (``feature/FuncTransformer.scala:46``),
``StratifiedSampler`` (``feature/StratifiedSampler.scala:42``), ``Bagging``
(``ensemble/Bagging.scala:79``), plus StandardScaler/VectorAssembler
equivalents used by the fraud pipeline (``BigDLKaggleFraud.scala:37-49``).
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

Frame = Dict[str, Any]


def frame_length(frame: Frame) -> int:
    return len(next(iter(frame.values())))


def frame_select(frame: Frame, idx: np.ndarray) -> Frame:
    out = {}
    for k, v in frame.items():
        arr = np.asarray(v)
        out[k] = arr[idx]
    return out


class Stage:
    def fit(self, frame: Frame) -> "Stage":
        return self

    def transform(self, frame: Frame) -> Frame:
        return frame

    def fit_transform(self, frame: Frame) -> Frame:
        return self.fit(frame).transform(frame)


class FramePipeline(Stage):
    """``Pipeline().setStages([...])`` equivalent: fit stages in order, each
    consuming the previous stage's transformed output."""

    def __init__(self, stages: Sequence[Stage]):
        self.stages = list(stages)

    def fit(self, frame: Frame) -> "FramePipeline":
        self.fit_transform(frame)
        return self

    def fit_transform(self, frame: Frame) -> Frame:
        """Fit stages in order and return the final transformed frame —
        avoids the second full pass a fit().transform() pair would cost."""
        cur = frame
        for s in self.stages:
            s.fit(cur)
            cur = s.transform(cur)
        return cur

    def transform(self, frame: Frame) -> Frame:
        cur = frame
        for s in self.stages:
            cur = s.transform(cur)
        return cur


class FuncTransformer(Stage):
    """Apply an arbitrary function to one column (reference
    ``FuncTransformer``: persistable udf transformer, used for the fraud
    label remap 0↔2)."""

    def __init__(self, fn: Callable, input_col: str,
                 output_col: Optional[str] = None):
        self.fn = fn
        self.input_col = input_col
        self.output_col = output_col or input_col

    def transform(self, frame: Frame) -> Frame:
        out = dict(frame)
        col = np.asarray(frame[self.input_col])
        out[self.output_col] = np.asarray([self.fn(v) for v in col])
        return out


class VectorAssembler(Stage):
    """Concatenate feature columns into one (N, D) matrix column."""

    def __init__(self, input_cols: Sequence[str], output_col: str = "features"):
        self.input_cols = list(input_cols)
        self.output_col = output_col

    def transform(self, frame: Frame) -> Frame:
        cols = []
        for c in self.input_cols:
            arr = np.asarray(frame[c], np.float32)
            cols.append(arr[:, None] if arr.ndim == 1 else arr)
        out = dict(frame)
        out[self.output_col] = np.concatenate(cols, axis=1)
        return out


class StandardScaler(Stage):
    """Fit mean/std on a matrix column, transform to z-scores."""

    def __init__(self, input_col: str = "features",
                 output_col: Optional[str] = None):
        self.input_col = input_col
        self.output_col = output_col or input_col
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None

    def fit(self, frame: Frame) -> "StandardScaler":
        x = np.asarray(frame[self.input_col], np.float32)
        self.mean = x.mean(axis=0)
        self.std = np.maximum(x.std(axis=0), 1e-8)
        return self

    def transform(self, frame: Frame) -> Frame:
        if self.mean is None:
            raise RuntimeError("StandardScaler not fitted")
        out = dict(frame)
        x = np.asarray(frame[self.input_col], np.float32)
        out[self.output_col] = (x - self.mean) / self.std
        return out


class StratifiedSampler(Stage):
    """Per-label-fraction resampling (reference ``StratifiedSampler``:
    e.g. ``{2: 0.05, 1: 10, 0: 1}`` — under-sample label 2 to 5%,
    over-sample label 1 ×10)."""

    def __init__(self, fractions: Dict[Any, float], label_col: str = "label",
                 seed: int = 0):
        self.fractions = fractions
        self.label_col = label_col
        self.seed = seed

    def transform(self, frame: Frame) -> Frame:
        rng = np.random.RandomState(self.seed)
        labels = np.asarray(frame[self.label_col])
        keep_idx: List[np.ndarray] = []
        for value, frac in self.fractions.items():
            idx = np.where(labels == value)[0]
            if frac <= 1.0:
                n = int(round(len(idx) * frac))
                keep_idx.append(rng.choice(idx, size=n, replace=False))
            else:
                whole = int(frac)
                rem = frac - whole
                parts = [idx] * whole
                if rem > 0:
                    parts.append(rng.choice(idx, size=int(len(idx) * rem),
                                            replace=False))
                keep_idx.append(np.concatenate(parts))
        idx = np.concatenate(keep_idx)
        rng.shuffle(idx)
        return frame_select(frame, idx)


class Bagging(Stage):
    """Bootstrap-aggregated ensemble (reference ``Bagging.scala:79``):
    N resampled fits of a base estimator; classification votes with an
    integer threshold (≥ t positive sub-votes → positive), regression
    averages.

    ``base_fn() -> Stage`` (or ``base_fn(i) -> Stage``, receiving the
    sub-model index for seeding) must return a fresh estimator whose
    ``transform`` adds ``prediction_col``.
    """

    def __init__(self, base_fn: Callable[[], Stage], n_models: int = 20,
                 sampler: Optional[Stage] = None,
                 prediction_col: str = "prediction",
                 is_classification: bool = True, threshold: int = 10,
                 seed: int = 0):
        self.base_fn = base_fn
        self.n_models = n_models
        self.sampler = sampler
        self.prediction_col = prediction_col
        self.is_classification = is_classification
        self.threshold = threshold
        self.seed = seed
        self.models: List[Stage] = []

    def fit(self, frame: Frame) -> "Bagging":
        n = frame_length(frame)
        self.models = []
        for i in range(self.n_models):
            rng = np.random.RandomState(self.seed + i)
            if self.sampler is not None:
                sampler = copy.deepcopy(self.sampler)
                if hasattr(sampler, "seed"):
                    sampler.seed = self.seed + i
                sub = sampler.transform(frame)
            else:
                idx = rng.randint(0, n, size=n)   # bootstrap
                sub = frame_select(frame, idx)
            import inspect
            # vary model init per sub-model — identical seeds would collapse
            # the ensemble into near-copies and degenerate the vote; prefer
            # passing the index into base_fn, fall back to a seed attribute
            try:
                takes_index = len(inspect.signature(self.base_fn).parameters) >= 1
            except (TypeError, ValueError):
                takes_index = False
            m = self.base_fn(i) if takes_index else self.base_fn()
            if not takes_index and hasattr(m, "seed"):
                m.seed = self.seed + i
            m.fit(sub)
            self.models.append(m)
        return self

    def transform(self, frame: Frame) -> Frame:
        if not self.models:
            raise RuntimeError("Bagging not fitted")
        preds = np.stack([
            np.asarray(m.transform(frame)[self.prediction_col])
            for m in self.models
        ], axis=0)                                 # (M, N)
        out = dict(frame)
        if self.is_classification:
            votes = (preds > 0).sum(axis=0)
            out[self.prediction_col] = (votes >= self.threshold).astype(np.int64)
            out["votes"] = votes
        else:
            out[self.prediction_col] = preds.mean(axis=0)
        return out


def time_ordered_split(frame: Frame, time_col: str,
                       train_fraction: float = 0.7):
    """Quantile split on a time column (reference fraud pipeline's 70/30
    time-based split, ``BigDLKaggleFraud.scala``)."""
    t = np.asarray(frame[time_col], np.float64)
    cut = np.quantile(t, train_fraction)
    train_idx = np.where(t <= cut)[0]
    test_idx = np.where(t > cut)[0]
    return frame_select(frame, train_idx), frame_select(frame, test_idx)
