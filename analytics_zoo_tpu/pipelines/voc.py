"""Dataset registries: Pascal VOC + COCO → SSD records.

Port of the reference's ``common/dataset/{Imdb,PascalVoc,Coco}.scala``:
``Imdb.getImdb`` name registry (``Imdb.scala:34``), VOC XML annotation
parsing into RoiLabels with the 20-class list (``PascalVoc.scala:76-87``),
and COCO via pre-generated ImageSets + JSON annotations with the 80-class
id remap (``Coco.scala:32,47``).  Output feeds ``data.records`` (the
SequenceFile replacement) via ``to_ssd_records``.
"""

from __future__ import annotations

import json
import os
import xml.etree.ElementTree as ET
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.data.records import SSDByteRecord, write_ssd_records
from analytics_zoo_tpu.transform.vision.roi import RoiLabel

VOC_CLASSES = (
    "__background__",
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
)


def parse_voc_annotation(xml_path: str,
                         use_difficult: bool = True) -> RoiLabel:
    """One VOC XML file → RoiLabel (reference ``PascalVoc.loadAnnotation:87``;
    pixel corner boxes, 1-based class ids into VOC_CLASSES)."""
    root = ET.parse(xml_path).getroot()
    labels, boxes, difficult = [], [], []
    for obj in root.findall("object"):
        name = obj.find("name").text.strip().lower()
        if name not in VOC_CLASSES:
            continue
        diff = int(obj.findtext("difficult", "0"))
        if not use_difficult and diff:
            continue
        bb = obj.find("bndbox")
        boxes.append([float(bb.findtext("xmin")), float(bb.findtext("ymin")),
                      float(bb.findtext("xmax")), float(bb.findtext("ymax"))])
        labels.append(VOC_CLASSES.index(name))
        difficult.append(diff)
    if not boxes:
        return RoiLabel(np.zeros(0), np.zeros((0, 4)), np.zeros(0))
    return RoiLabel(np.asarray(labels), np.asarray(boxes),
                    np.asarray(difficult))


class PascalVoc:
    """VOCdevkit reader (reference ``PascalVoc.scala``): image set files
    under ``ImageSets/Main/<set>.txt``, annotations under ``Annotations``,
    images under ``JPEGImages``."""

    def __init__(self, devkit_root: str, year: str = "2007",
                 image_set: str = "trainval"):
        self.root = os.path.join(devkit_root, f"VOC{year}")
        self.image_set = image_set
        self.year = year

    @property
    def name(self) -> str:
        return f"voc_{self.year}_{self.image_set}"

    def image_ids(self) -> List[str]:
        path = os.path.join(self.root, "ImageSets", "Main",
                            f"{self.image_set}.txt")
        with open(path) as f:
            return [line.strip().split()[0] for line in f if line.strip()]

    def load(self) -> Iterator[SSDByteRecord]:
        for img_id in self.image_ids():
            img_path = os.path.join(self.root, "JPEGImages", f"{img_id}.jpg")
            ann_path = os.path.join(self.root, "Annotations", f"{img_id}.xml")
            with open(img_path, "rb") as f:
                data = f.read()
            label = parse_voc_annotation(ann_path)
            yield SSDByteRecord(data=data, path=img_path,
                                gt=label.to_gt_matrix())


class Coco:
    """COCO reader from instances json (reference ``Coco.scala``): remaps
    the sparse COCO category ids onto contiguous 1..80 ids."""

    def __init__(self, image_dir: str, annotation_json: str):
        self.image_dir = image_dir
        self.annotation_json = annotation_json

    def load(self) -> Iterator[SSDByteRecord]:
        with open(self.annotation_json) as f:
            coco = json.load(f)
        cat_ids = sorted(c["id"] for c in coco["categories"])
        remap = {cid: i + 1 for i, cid in enumerate(cat_ids)}  # 1..80
        by_image: Dict[int, List[dict]] = {}
        for ann in coco["annotations"]:
            if ann.get("iscrowd", 0):
                continue
            by_image.setdefault(ann["image_id"], []).append(ann)
        images = {im["id"]: im for im in coco["images"]}
        for img_id, anns in by_image.items():
            im = images[img_id]
            path = os.path.join(self.image_dir, im["file_name"])
            if not os.path.exists(path):
                continue
            rows = []
            for a in anns:
                x, y, w, h = a["bbox"]
                rows.append([remap[a["category_id"]], 0.0,
                             x, y, x + w, y + h])
            with open(path, "rb") as f:
                data = f.read()
            yield SSDByteRecord(
                data=data, path=path,
                gt=np.asarray(rows, np.float32).reshape(-1, 6))


def get_imdb(name: str, root: str):
    """Dataset registry by name (reference ``Imdb.getImdb:34``), e.g.
    ``voc_2007_trainval`` / ``voc_2012_test``."""
    parts = name.split("_")
    if parts[0] == "voc":
        return PascalVoc(root, year=parts[1], image_set="_".join(parts[2:]))
    if parts[0] == "coco":
        # standard COCO layout: <root>/<set>/ images,
        # <root>/annotations/instances_<set>.json
        subset = "_".join(parts[1:])
        return Coco(os.path.join(root, subset),
                    os.path.join(root, "annotations",
                                 f"instances_{subset}.json"))
    raise ValueError(f"unknown imdb {name!r}")


def to_ssd_records(dataset, prefix: str, num_shards: int = 8) -> List[str]:
    """Materialize a dataset as sharded record files — the
    ``RoiImageSeqGenerator`` equivalent (reference
    ``common/dataset/RoiImageSeqGenerator.scala:25``)."""
    return write_ssd_records(list(dataset.load()), prefix, num_shards)
