"""Task pipelines (L6): SSD detection, DeepSpeech2 ASR, fraud detection,
plus the column-pipeline abstraction and evaluation machinery."""

from analytics_zoo_tpu.pipelines.frame import (
    Bagging,
    Frame,
    FramePipeline,
    FuncTransformer,
    Stage,
    StandardScaler,
    StratifiedSampler,
    VectorAssembler,
    time_ordered_split,
)
from analytics_zoo_tpu.pipelines.evaluation import (
    CocoMeanAveragePrecision,
    DetectionResult,
    MeanAveragePrecision,
    MultiIoUResult,
    PascalVocEvaluator,
    mark_tp_fp,
    voc_ap,
)
from analytics_zoo_tpu.pipelines.voc import (
    VOC_CLASSES,
    Coco,
    PascalVoc,
    get_imdb,
    parse_voc_annotation,
    to_ssd_records,
)
from analytics_zoo_tpu.pipelines.ssd import (
    PreProcessParam,
    RecordToFeature,
    RoiImageToBatch,
    SSDMeanAveragePrecision,
    SSDPredictor,
    TrainParams,
    Validator,
    load_train_set,
    load_train_set_device,
    load_val_set,
    train_ssd,
    train_transformer,
    val_transformer,
)
from analytics_zoo_tpu.pipelines.frcnn import (
    FRCNN_BGR_MEANS,
    FrcnnPredictor,
    frcnn_serving_tiers,
)
from analytics_zoo_tpu.pipelines.fraud import (
    FraudResult,
    MLPClassifier,
    auprc,
    fraud_serving_tiers,
    precision_recall,
    run_fraud_pipeline,
)
from analytics_zoo_tpu.pipelines.recommendation import (
    make_ncf_model,
    make_wide_deep_model,
    predict_ratings,
    rating_batches,
    rec_serving_tiers,
    train_recommender,
)
from analytics_zoo_tpu.pipelines.sentiment import (
    make_sentiment_model,
    review_batches,
    sentiment_serving_tiers,
    train_sentiment,
)
from analytics_zoo_tpu.pipelines.visualizer import result_to_string, vis_detection
from analytics_zoo_tpu.pipelines.deepspeech2 import (
    DS2Param,
    DeepSpeech2Pipeline,
    ds2_serving_tiers,
    ds2_streaming_tiers,
    make_ds2_model,
)

__all__ = [k for k in dir() if not k.startswith("_")]
