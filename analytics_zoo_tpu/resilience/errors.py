"""Failure taxonomy for the resilience layer.

Every error class here is dependency-free on purpose: the data layer
(``data/prefetch.py``, ``data/records.py``), the checkpoint layer
(``parallel/checkpoint.py``) and the supervisor (``parallel/elastic.py``)
all import from this module, so it must sit at the bottom of the import
graph.

The split that matters operationally is *retryable* vs *fatal*:

- retryable — the program was correct but the world failed under it
  (device lost, host preempted, a step hung, a worker thread died).  The
  :func:`~analytics_zoo_tpu.parallel.elastic.run_resilient` supervisor
  rebuilds and resumes from the newest intact checkpoint.
- fatal — a programming or configuration error (``TypeError``,
  ``ValueError``, shape mismatches).  Restarting cannot fix these; they
  propagate on the first attempt so the bug surfaces immediately.

``retryable_errors()`` assembles the canonical retryable tuple, pulling
in the jaxlib runtime error type when available (transient XLA/device
errors — the TPU-native analogue of a lost Spark executor).
"""

from __future__ import annotations

from typing import Tuple, Type


class Preempted(RuntimeError):
    """The host received SIGTERM/SIGINT mid-training; a graceful final
    checkpoint was taken at the step boundary before raising.  Retryable:
    a supervisor (or the next scheduled incarnation of this job) resumes
    from that checkpoint."""


class StallError(RuntimeError):
    """A train step or data fetch made no progress past the
    :class:`~analytics_zoo_tpu.resilience.watchdog.StallWatchdog`
    deadline.  Raised *instead of hanging forever* — a hung device call
    or dead input pipeline otherwise blocks the host loop silently."""


class PrefetchWorkerDied(RuntimeError):
    """An input-pipeline worker died and could not be replaced.

    Raised by (a) the prefetch thread (``data.prefetch``) when the
    worker thread dies without enqueueing its stop sentinel — the
    consumer would previously block on ``q.get()`` forever — and (b)
    the multiprocess loader (``data.parallel.ParallelLoader``) when a
    worker PROCESS dies and the bounded respawn budget
    (``max_respawns`` per epoch; deterministic seeding lets a respawn
    recompute exactly the groups still owed) is exhausted.  Retryable:
    a fresh attempt rebuilds the whole input pipeline."""


class CheckpointCorrupt(RuntimeError):
    """A snapshot failed manifest verification (missing manifest, missing
    file, size or checksum mismatch) and no older intact snapshot could
    be restored in its place."""


class ShardReadError(IOError):
    """A data-shard read kept failing after the bounded retry/backoff
    budget was exhausted.  Persistent (not transient) by definition —
    NOT retryable via restart; use ``skip_errors=True`` in the record
    reader to skip-and-count the shard instead."""


class InjectedFault(RuntimeError):
    """Default exception for chaos/fault injection — stands in for a
    lost device or killed task, so it counts as retryable."""


class TrainingDiverged(RuntimeError):
    """Numerical recovery is exhausted: the anomaly ladder (skip the
    step → roll back to the last-known-good snapshot → re-seek past the
    bad region) was climbed to its top and the run STILL produces
    non-finite losses/grads/params — or no last-known-good snapshot
    exists to roll back to.  Fatal by design: a blind restart would
    resume from the same checkpoint into the same divergence, so the
    supervisor must NOT retry; a human (armed with the forensics bundle
    ``anomaly_<step>.json`` and ``tools/replay_batch.py``) decides what
    changes.  Also raised by the legacy
    :class:`~analytics_zoo_tpu.parallel.elastic.DivergenceDetector`
    after a non-finite loss streak."""


class ServerOverloaded(RuntimeError):
    """The serving admission queue is full — the request was SHED at
    submit time, before consuming any device time (``serving.request.
    AdmissionQueue``).  Retryable WITH BACKOFF: the queue being bounded
    is the load-shedding contract, so an immediate blind retry from
    every rejected client would just re-create the overload; clients
    should back off (exponentially) or hedge to another serving cell."""


class RequestTimeout(RuntimeError):
    """A serving request's deadline passed while it was still queued, so
    it was shed before device dispatch (a late answer costs the same
    device time as a useful one).  Retryable: the client may resubmit
    with a fresh deadline — by then the burst that starved this request
    has usually drained (or the degradation ladder has stepped down)."""


class ReplicaWedged(RuntimeError):
    """A serving replica's forward wedged past its StallWatchdog
    deadline or crashed mid-batch.  Dual semantics by design:

    - for the REPLICA this is fatal — the runtime fences it (no further
      dispatches) and restarts it in the background;
    - for the REQUESTS of the in-flight batch it is retryable — the
      runtime re-dispatches that batch to a healthy replica exactly
      once, and only if THAT dispatch also fails do the requests fail
      with this error (at which point the client may retry elsewhere).

    Classified retryable in the taxonomy because the error object only
    ever escapes to request/supervisor scope — replica fencing is
    handled internally by ``serving.replica.ReplicaPool``."""


class DeviceQuarantine(RuntimeError):
    """The device-health sentinel (``resilience/health.py``) confirmed a
    specific device as unhealthy — a parity-audit minority vote, a
    shadow-recompute mismatch with a tiebreak, or a persistent straggler
    past the hysteresis ladder — and quarantined it.  ``device`` names
    the flat mesh index (or replica id) being evicted.  Retryable: the
    culprit is ATTRIBUTED, so the supervisor rebuilds on the surviving
    devices (``health.evict_device`` + ``SpecSet.replace_mesh`` + LKG
    tier + ``elastic_resume_coordinates``) and the smaller-width restart
    does not re-create the fault."""

    def __init__(self, message: str, device=None):
        super().__init__(message)
        self.device = device


class SdcDetected(RuntimeError):
    """Silent data corruption was PROVEN (replica fingerprints diverged,
    or a shadow recompute disagreed with the primary) but could not be
    attributed to a single device — a two-way split, multiple divergers,
    or no tiebreak vote.  Fatal by design: with no named culprit there
    is nothing to evict, and a blind restart lands on the same silicon
    with corrupted trust in every copy of the params; an operator must
    triage the hardware (the sentinel's event log carries the
    per-replica fingerprints)."""


class ElasticPlacementError(ValueError):
    """An elastic re-placement asked for a mesh that cannot carry the
    declared sharding: the new mesh's axis names do not cover every axis
    the :class:`~analytics_zoo_tpu.parallel.specs.SpecSet` declaration
    references (rules, batch overrides, or the data axis).  Raised at
    the substrate boundary — ``SpecSet.replace_mesh`` / ``place_state``
    / ``place_batch`` — with the missing axes listed, instead of the
    opaque NamedSharding failure jax raises deep inside ``device_put``.
    Fatal: a declaration/mesh mismatch is a configuration error; a
    restart onto the same mesh re-creates it."""


#: Explicit classification registries.  EVERY exception class defined in
#: this module must appear in exactly one of the two tuples below — the
#: taxonomy completeness test (tests/test_anomaly.py) enforces it, so a
#: future error class cannot silently fall through ``run_resilient``'s
#: retry filter with unconsidered semantics.
_RETRYABLE_CLASSES: Tuple[Type[BaseException], ...] = (
    Preempted,
    StallError,
    PrefetchWorkerDied,
    InjectedFault,
    ServerOverloaded,
    RequestTimeout,
    ReplicaWedged,
    DeviceQuarantine,
)

#: Fatal: restarting cannot fix these (no intact snapshot left; a shard
#: that stays unreadable; a run whose numerics keep diverging).
FATAL_ERRORS: Tuple[Type[BaseException], ...] = (
    CheckpointCorrupt,
    ShardReadError,
    TrainingDiverged,
    ElasticPlacementError,
    SdcDetected,
)


def retryable_errors() -> Tuple[Type[BaseException], ...]:
    """The canonical tuple of transient, restart-recoverable failures."""
    errs = _RETRYABLE_CLASSES
    try:  # transient device/runtime errors (lost TPU, relay drop, OOM)
        import jaxlib.xla_extension as _xe

        errs = errs + (_xe.XlaRuntimeError,)
    except Exception:  # pragma: no cover - jaxlib always present in-image
        pass
    return errs


def is_retryable(exc: BaseException) -> bool:
    """Classify one failure instance against the taxonomy.  Fatal classes
    win over retryable bases (``TrainingDiverged`` is a ``RuntimeError``
    subclass, but divergence must never be restart-masked)."""
    if isinstance(exc, FATAL_ERRORS):
        return False
    return isinstance(exc, retryable_errors())
