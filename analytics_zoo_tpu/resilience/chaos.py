"""Chaos fault-injection matrix — grow-up of ``parallel.elastic.FaultInjector``.

Where :class:`~analytics_zoo_tpu.parallel.elastic.FaultInjector` raises a
single exception once, :class:`ChaosMonkey` drives a whole *schedule* of
heterogeneous faults against a running training job, each at a chosen
global batch index:

===================  ======================================================
kind                 effect
===================  ======================================================
``crash``            raise :class:`InjectedFault` (generic lost task)
``xla_transient``    raise ``jaxlib...XlaRuntimeError`` (device/runtime
                     error — what a real TPU relay drop surfaces as)
``sigterm``          deliver SIGTERM to this process (graceful-preemption
                     path: checkpoint at the boundary, ``Preempted``)
``mid_save_kill``    arm a one-shot hook that crashes the NEXT checkpoint
                     save after the snapshot is written but BEFORE the
                     atomic publish rename (crash mid-save)
``corrupt_latest``   truncate a manifest-listed file of the newest intact
                     snapshot on disk (restore must fall back)
``stall``            sleep past the StallWatchdog deadline (hung step)
``nan_grads``        poison the batch input with a NaN — loss/grads go
                     non-finite (the anomaly sentinel must skip)
``inf_loss``         blow the batch target up so the loss overflows to
                     inf (spike/overflow path of the health word)
``corrupt_batch``    deterministically scramble the input payload's raw
                     bytes (a corrupt record surviving decode)
``bit_flip``         arm a persistent single-bit corruption of ONE named
                     replica's view of the params/output (silent data
                     corruption — the device-health parity audit must
                     name the minority device)
``slow_device``      persistent per-device slowdown (service-time
                     multiplier) — unlike the one-shot ``slow_forward``
                     it never wedges, so only the straggler EWMA
                     detector catches it
===================  ======================================================

The last three are *numerical* faults: instead of raising, they MUTATE
the yielded batch (deterministically — the scramble RNG is seeded from
the global batch index, so ``tools/replay_batch.py`` can re-apply the
exact corruption during forensics replay).  ``FaultSpec(batches=N)``
stretches a numerical fault over N consecutive batches — one batch
exercises the sentinel's skip, ``rollback_after`` consecutive force a
rollback, and a persistent window drives the ladder to
``TrainingDiverged``.

The schedule is plain data (:class:`FaultSpec` list), so drills can build
it from a seeded RNG and stay deterministic.  The monkey's batch counter
is *global across epochs and restart attempts* — wrap the dataset once,
reuse the wrapper in every rebuilt Optimizer, and each fault fires
exactly once per schedule entry.

Used by ``tools/chaos_drill.py`` (committed artifact RESILIENCE_r01.json)
and the tier-1 chaos-matrix tests in ``tests/test_elastic.py``.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal as _signal
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.resilience.errors import InjectedFault

logger = logging.getLogger("analytics_zoo_tpu")

#: kinds that MUTATE the yielded batch instead of raising/killing
NUMERICAL_KINDS = ("nan_grads", "inf_loss", "corrupt_batch")

#: kinds the SERVING runtime consumes (``serving.runtime`` /
#: ``tools/serve_drill.py``) via :meth:`ChaosMonkey.serving_active` —
#: they never fire from a wrapped training dataset:
#:
#: ``slow_forward``   injected latency on ONE replica's forward
#:                    (``detail={"replica": r, "delay_s": d}``) — drives
#:                    the StallWatchdog-wedged → fence → failover path
#: ``replica_crash``  the targeted replica's forward raises mid-batch
#:                    (``detail={"replica": r}``)
#: ``burst_load``     arrival-rate spike: the drill's workload generator
#:                    multiplies its arrival rate by
#:                    ``detail={"rate_x": k}`` inside the window
SERVING_KINDS = ("slow_forward", "replica_crash", "burst_load")

#: kinds modeling UNHEALTHY SILICON (``resilience.health``):
#:
#: ``bit_flip``     fires from the dataset wrapper like a raising kind,
#:                  but instead of raising it ARMS ``health.arm_bit_flip``
#:                  (``detail={"replica": r, "element": e, "bit": b}``) —
#:                  a persistent stuck bit in that device's read path,
#:                  visible only to the parity audit / shadow recompute
#: ``slow_device``  consumed by the serving runtime via
#:                  :meth:`ChaosMonkey.serving_active` (dispatch index,
#:                  like ``slow_forward``) — ``detail={"replica": r,
#:                  "slow_x": k}`` multiplies the replica's service time
#:                  over the window WITHOUT tripping wedge detection
DEVICE_KINDS = ("bit_flip", "slow_device")

KINDS = ("crash", "xla_transient", "sigterm", "mid_save_kill",
         "corrupt_latest", "stall") + NUMERICAL_KINDS + SERVING_KINDS \
    + DEVICE_KINDS

#: accepted ``FaultSpec.detail`` keys per kind — kinds absent here take
#: no detail at all.  ``__post_init__`` REJECTS unknown keys: a typo'd
#: knob (``dealy_s``) used to be silently ignored, turning a drill's
#: fault into a no-op that still "passed".
_DETAIL_KEYS: Dict[str, frozenset] = {
    "slow_forward": frozenset({"replica", "delay_s"}),
    "replica_crash": frozenset({"replica"}),
    "burst_load": frozenset({"rate_x"}),
    "bit_flip": frozenset({"replica", "element", "bit"}),
    "slow_device": frozenset({"replica", "slow_x"}),
}


def _poison_leaf(batch: Dict[str, Any], key: str) -> np.ndarray:
    """Copy-on-write float leaf under ``batch[key]`` (first element of a
    tuple/list input).  The caller's batch is never mutated in place —
    the same host arrays may be re-yielded on a later epoch."""
    val = batch[key]
    if isinstance(val, (tuple, list)):
        arr = np.array(np.asarray(val[0]), copy=True)
        rest = list(val)[1:]
        batch[key] = type(val)([arr] + rest) if isinstance(val, list) \
            else (arr,) + tuple(rest)
    else:
        arr = np.array(np.asarray(val), copy=True)
        batch[key] = arr
    if not np.issubdtype(arr.dtype, np.floating):
        raise TypeError(f"numerical chaos needs a float leaf at "
                        f"batch[{key!r}], got {arr.dtype}")
    return arr


def mutate_batch(kind: str, batch: Dict[str, Any], seed: int) -> Dict[str, Any]:
    """Apply one numerical fault to a batch, deterministically.

    ``seed`` is the batch's GLOBAL stream index by convention: replaying
    the same (kind, seed) on the same clean batch reproduces the
    corrupted payload byte for byte (the forensics replay contract).
    Returns a shallow copy; poisoned leaves are fresh arrays."""
    if kind not in NUMERICAL_KINDS:
        raise ValueError(f"not a numerical fault kind: {kind!r}")
    if not isinstance(batch, dict):
        raise TypeError("numerical chaos kinds need dict batches")
    out = dict(batch)
    if kind == "nan_grads":
        arr = _poison_leaf(out, "input")
        arr.reshape(-1)[0] = np.nan
    elif kind == "inf_loss":
        key = "target" if "target" in out else "input"
        arr = _poison_leaf(out, key)
        # large-but-representable: the squared error overflows f32 → inf
        arr.reshape(-1)[0] = np.asarray(1e30, arr.dtype)
    else:  # corrupt_batch: scramble the payload's raw bytes
        arr = _poison_leaf(out, "input")
        rng = np.random.Generator(np.random.PCG64(seed & 0xFFFFFFFFFFFFFFFF))
        flat = arr.view(np.uint8).reshape(-1)
        flat[:] = flat[rng.permutation(flat.size)]
    return out


def transient_xla_error(msg: str = "injected transient device error"):
    """An exception of the real jaxlib runtime-error type when available
    (so the retry filter is exercised against the genuine class)."""
    try:
        import jaxlib.xla_extension as xe

        return xe.XlaRuntimeError(msg)
    except Exception:  # pragma: no cover - jaxlib always present in-image
        return InjectedFault(msg)


def corrupt_snapshot(checkpoint_path: str) -> Tuple[str, str]:
    """Truncate the largest manifest-listed file of the newest intact
    snapshot under ``checkpoint_path`` to half its size.  Returns
    ``(snapshot_dir, relative_file)``.  Raises ``FileNotFoundError``
    when no intact snapshot exists to corrupt."""
    from analytics_zoo_tpu.parallel import checkpoint as ckpt

    found = ckpt.newest_intact(checkpoint_path)
    if found is None:
        raise FileNotFoundError(
            f"no intact snapshot under {checkpoint_path} to corrupt")
    snap_dir, man = found
    files = man.get("files", {})
    if not files:
        raise FileNotFoundError(f"{snap_dir}: manifest lists no files")
    rel = max(files, key=lambda r: files[r]["size"])
    full = os.path.join(snap_dir, rel)
    size = os.path.getsize(full)
    with open(full, "r+b") as f:
        f.truncate(max(size // 2, 1))
    logger.warning("chaos: truncated %s (%d -> %d bytes)", full, size,
                   os.path.getsize(full))
    return snap_dir, rel


@dataclasses.dataclass
class FaultSpec:
    """One scheduled fault: ``kind`` fires just before the wrapped
    dataset yields global batch index ``at_batch`` (counted across epochs
    AND restart attempts).  Numerical kinds may stretch over ``batches``
    consecutive batches (``[at_batch, at_batch + batches)``) — the knob
    that distinguishes a one-off bad record (skip), a bad burst
    (rollback) and persistent divergence (``TrainingDiverged``)."""

    kind: str
    at_batch: int
    batches: int = 1
    #: kind-specific knobs (serving kinds: target replica, delay, rate
    #: multiplier).  Plain data so drill schedules stay seedable.
    detail: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"one of {KINDS}")
        if self.batches < 1:
            raise ValueError("batches must be >= 1")
        windowed = NUMERICAL_KINDS + SERVING_KINDS + ("slow_device",)
        if self.batches > 1 and self.kind not in windowed:
            raise ValueError(f"batches>1 only applies to windowed kinds "
                             f"{windowed}, not {self.kind!r}")
        accepted = _DETAIL_KEYS.get(self.kind, frozenset())
        unknown = set(self.detail) - accepted
        if unknown:
            raise ValueError(
                f"unknown detail key(s) {sorted(unknown)} for kind "
                f"{self.kind!r}; accepted: "
                f"{sorted(accepted) if accepted else '(none)'}")


class ChaosMonkey:
    """Executes a :class:`FaultSpec` schedule against a training job.

    ``checkpoint_path`` is required for the ``mid_save_kill`` and
    ``corrupt_latest`` kinds.  ``stall_s`` sizes the injected hang (must
    exceed the job's StallWatchdog deadline to trigger it).  Every fired
    fault is appended to :attr:`events` (plain dicts, no wall-clock — so
    drill artifacts stay deterministic).
    """

    def __init__(self, faults: Sequence[FaultSpec],
                 checkpoint_path: Optional[str] = None,
                 stall_s: float = 1.0):
        self.faults = sorted(faults, key=lambda f: f.at_batch)
        self.checkpoint_path = checkpoint_path
        self.stall_s = stall_s
        self.events: List[Dict[str, Any]] = []
        self.consumed = 0          # global batch counter
        self._fired = [False] * len(self.faults)
        self._armed_hook = None    # mid_save_kill hook awaiting a save
        self._armed_flip = False   # bit_flip armed on the health module

    def arm(self, fault: FaultSpec) -> None:
        """Schedule an additional fault mid-run — how a drill targets a
        fault at a condition only known at runtime (e.g. "crash a
        replica while THIS rollout is draining"): observe the state,
        then arm a spec at a near-future index.  Deterministic as long
        as the observed state and the chosen index are."""
        self.faults.append(fault)
        self._fired.append(False)

    # -- dataset hook ------------------------------------------------------
    def dataset(self, ds) -> "ChaosDataset":
        """Wrap ``ds`` so faults fire at their scheduled batch indices.
        The wrapper is re-iterable (one fresh pass over ``ds`` per epoch)
        while the fault schedule and counter stay with the monkey."""
        return ChaosDataset(self, ds)

    def _due(self) -> List[int]:
        # slow_device is serving-consumed (dispatch index) like the
        # SERVING_KINDS; bit_flip DOES fire from the dataset wrapper
        # (it arms the health hook instead of raising)
        return [i for i, f in enumerate(self.faults)
                if not self._fired[i] and f.at_batch <= self.consumed
                and f.kind not in NUMERICAL_KINDS
                and f.kind not in SERVING_KINDS
                and f.kind != "slow_device"]

    def on_batch(self, batch=None):
        """Fire every due fault (called by the wrapper before each yield)
        and apply any numerical fault whose window covers this batch to
        ``batch``.  Raising kinds record first, then raise.  Returns the
        (possibly mutated) batch."""
        for i in self._due():
            self._fired[i] = True
            f = self.faults[i]
            logger.warning("chaos: firing %s at batch %d", f.kind,
                           self.consumed)
            getattr(self, f"_fire_{f.kind}")(f, i)
        for i, f in enumerate(self.faults):
            if f.kind not in NUMERICAL_KINDS or self._fired[i]:
                continue
            if not (f.at_batch <= self.consumed < f.at_batch + f.batches):
                continue
            logger.warning("chaos: %s poisoning batch %d (window %d..%d)",
                           f.kind, self.consumed, f.at_batch,
                           f.at_batch + f.batches - 1)
            # seed = global batch index: forensics replay re-applies the
            # identical corruption to the re-materialized clean batch
            batch = mutate_batch(f.kind, batch, seed=self.consumed)
            self._record(f, scheduled_at=f.at_batch, seed=self.consumed)
            if self.consumed >= f.at_batch + f.batches - 1:
                self._fired[i] = True
        return batch

    def _record(self, f: FaultSpec, **detail) -> None:
        self.events.append({"kind": f.kind, "at_batch": self.consumed,
                            **detail})

    # -- fault kinds -------------------------------------------------------
    def _fire_crash(self, f: FaultSpec, i: int) -> None:
        self._record(f)
        raise InjectedFault(f"injected crash at batch {self.consumed}")

    def _fire_xla_transient(self, f: FaultSpec, i: int) -> None:
        self._record(f)
        raise transient_xla_error(
            f"injected transient device error at batch {self.consumed}")

    def _fire_sigterm(self, f: FaultSpec, i: int) -> None:
        self._record(f)
        os.kill(os.getpid(), _signal.SIGTERM)

    def _fire_stall(self, f: FaultSpec, i: int) -> None:
        self._record(f, stall_s=self.stall_s)
        time.sleep(self.stall_s)

    def _fire_mid_save_kill(self, f: FaultSpec, i: int) -> None:
        from analytics_zoo_tpu.parallel import checkpoint as ckpt

        if self.checkpoint_path is None:
            raise ValueError("mid_save_kill needs ChaosMonkey("
                             "checkpoint_path=...) — an unscoped hook "
                             "could detonate in an unrelated job's save")
        armed_at = self.consumed
        scope = os.path.abspath(self.checkpoint_path)

        def hook(phase: str, path: str) -> None:
            if phase != "pre_publish":
                return
            # scoped to this monkey's checkpoint tree: an armed hook
            # must never detonate inside an unrelated job's save
            if not os.path.abspath(path).startswith(scope + os.sep):
                return
            ckpt.set_fault_hook(None)  # one-shot
            self._armed_hook = None
            self.events.append({"kind": "mid_save_kill",
                                "armed_at_batch": armed_at,
                                "fired_in_save": os.path.basename(path)})
            raise InjectedFault(
                f"injected crash mid-save of {path} (before publish)")

        self._armed_hook = hook
        ckpt.set_fault_hook(hook)

    def _fire_bit_flip(self, f: FaultSpec, i: int) -> None:
        from analytics_zoo_tpu.resilience import health

        replica = int(f.detail.get("replica", 0))
        element = int(f.detail.get("element", 0))
        bit = int(f.detail.get("bit", 0))
        health.arm_bit_flip(replica, element=element, bit=bit)
        self._armed_flip = True
        self._record(f, replica=replica, element=element, bit=bit)

    def _fire_corrupt_latest(self, f: FaultSpec, i: int) -> None:
        if self.checkpoint_path is None:
            raise ValueError("corrupt_latest needs ChaosMonkey("
                             "checkpoint_path=...)")
        try:
            snap, rel = corrupt_snapshot(self.checkpoint_path)
            self._record(f, snapshot=os.path.basename(snap), file=rel)
        except FileNotFoundError:
            # nothing on disk yet — re-arm one batch later
            self._fired[i] = False
            self.faults[i] = FaultSpec(f.kind, f.at_batch + 1)

    # -- serving hooks -----------------------------------------------------
    def serving_active(self, kind: str, index: int,
                       consume: bool = True) -> Optional[FaultSpec]:
        """Window query for the SERVING fault kinds: return the spec of
        ``kind`` whose ``[at_batch, at_batch + batches)`` window covers
        ``index``, else ``None``.  Serving drills drive their OWN
        counter (dispatch index for ``slow_forward``/``replica_crash``,
        request index for ``burst_load``) — independent of the training
        batch counter the dataset wrapper advances.

        ``consume=True`` marks the spec fired once ``index`` reaches the
        window's last slot (so a one-shot ``replica_crash`` fires on
        exactly one dispatch) and records an event; ``consume=False`` is
        a pure peek (the workload generator probes ``burst_load`` before
        time reaches the window)."""
        if kind not in SERVING_KINDS + ("slow_device",):
            raise ValueError(
                f"not a serving-consumed fault kind: {kind!r}; one of "
                f"{SERVING_KINDS + ('slow_device',)}")
        for i, f in enumerate(self.faults):
            if f.kind != kind or self._fired[i]:
                continue
            if not (f.at_batch <= index < f.at_batch + f.batches):
                continue
            if consume:
                self.events.append({"kind": kind, "at_index": int(index),
                                    **f.detail})
                if index >= f.at_batch + f.batches - 1:
                    self._fired[i] = True
            return f
        return None

    def disarm(self) -> None:
        """Clear any still-armed process-global hooks — a
        ``mid_save_kill`` hook on the checkpoint module and/or a
        ``bit_flip`` on the health module.  Call when the drill/test
        ends (whether or not the hook ever fired) so no armed fault
        leaks into a later job in the same process."""
        from analytics_zoo_tpu.parallel import checkpoint as ckpt

        if self._armed_hook is not None:
            prev = ckpt.set_fault_hook(None)
            if prev is not None and prev is not self._armed_hook:
                ckpt.set_fault_hook(prev)   # not ours — put it back
            self._armed_hook = None
        if self._armed_flip:
            from analytics_zoo_tpu.resilience import health

            health.clear_bit_flip()
            self._armed_flip = False

    def __enter__(self) -> "ChaosMonkey":
        return self

    def __exit__(self, *exc) -> None:
        self.disarm()

    # -- reporting ---------------------------------------------------------
    def fired_kinds(self) -> List[str]:
        return sorted({e["kind"] for e in self.events})

    def all_fired(self) -> bool:
        return all(self._fired)


class ChaosDataset:
    """Re-iterable dataset wrapper bound to a :class:`ChaosMonkey`.
    Unknown attributes delegate to the wrapped dataset, so loader
    metadata (``base_seed``, ``last_epoch``, ``num_workers`` — the
    anomaly-forensics RNG coordinates) stays visible through the wrap."""

    def __init__(self, monkey: ChaosMonkey, ds):
        self.monkey = monkey
        self.ds = ds

    def __iter__(self):
        for batch in self.ds:
            batch = self.monkey.on_batch(batch)
            self.monkey.consumed += 1
            yield batch

    def __len__(self):
        return len(self.ds)

    def __getattr__(self, name):
        return getattr(self.__dict__["ds"], name)
