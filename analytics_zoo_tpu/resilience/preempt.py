"""Graceful preemption: SIGTERM/SIGINT → checkpoint → retryable error.

TPU VMs (and any managed fleet) preempt with a SIGTERM and a short grace
window.  Without a handler the process dies mid-step and loses all
progress since the last trigger-driven checkpoint — for every-epoch
checkpointing that can be an entire epoch.  :class:`PreemptionHandler`
converts the signal into a *request* flag; the training loop checks it
at each step boundary, takes a forced checkpoint, and raises
:class:`~analytics_zoo_tpu.resilience.errors.Preempted` (retryable, so
an in-process supervisor — or the next scheduled incarnation of the job
— resumes exactly where the signal landed).

A second signal while the first is still being honoured escalates:
handlers are restored and ``KeyboardInterrupt`` is raised immediately
(the operator insisting on a hard stop beats a graceful checkpoint).
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Dict, Sequence

from analytics_zoo_tpu.resilience.errors import Preempted  # noqa: F401 (re-export)

logger = logging.getLogger("analytics_zoo_tpu")


class PreemptionHandler:
    """Installable SIGTERM/SIGINT trap with a step-boundary request flag.

    Usage (what ``Optimizer.optimize`` does internally)::

        ph = PreemptionHandler()
        ph.install()
        try:
            for batch in data:
                step(batch)
                if ph.requested:
                    checkpoint_now()
                    raise Preempted("preempted; checkpointed")
        finally:
            ph.uninstall()

    Signal handlers can only be installed from the main thread; from any
    other thread ``install()`` degrades to a no-op with a warning (the
    flag can still be set programmatically via :meth:`request` — the
    chaos drill uses that in threaded contexts).

    Only SIGTERM is trapped by default: ``Preempted`` is *retryable*, so
    trapping SIGINT would turn a single Ctrl-C under ``run_resilient``
    into a silent restart instead of a stop.  Pass
    ``signals=(SIGTERM, SIGINT)`` explicitly for unattended jobs where
    SIGINT should also mean "checkpoint and hand off".
    """

    def __init__(self, signals: Sequence[int] = (signal.SIGTERM,)):
        self.signals = tuple(signals)
        self._requested = False
        self._prev: Dict[int, object] = {}
        self._installed = False
        # a StallWatchdog wired here (Optimizer does this) lets the
        # handler distinguish the watchdog's simulated SIGINT from a
        # real preemption: a stalled loop may never reach the step
        # boundary where `requested` is honoured, so it must hard-raise
        self.stall_watchdog = None

    # -- flag --------------------------------------------------------------
    @property
    def requested(self) -> bool:
        return self._requested

    def request(self) -> None:
        """Programmatic preemption request (no signal delivery needed)."""
        self._requested = True

    def clear(self) -> None:
        self._requested = False

    # -- install/uninstall -------------------------------------------------
    def install(self) -> "PreemptionHandler":
        self._requested = False
        if threading.current_thread() is not threading.main_thread():
            logger.warning("PreemptionHandler: not on the main thread; "
                           "signal trap NOT installed (programmatic "
                           "request() still works)")
            return self
        for s in self.signals:
            self._prev[s] = signal.signal(s, self._handle)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, TypeError):  # pragma: no cover
                pass
        self._prev.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- handler -----------------------------------------------------------
    def _handle(self, signum, frame) -> None:
        wd = self.stall_watchdog
        if wd is not None and getattr(wd, "stalled", False):
            logger.error("interrupt during a detected stall: hard stop "
                         "(the loop cannot reach a graceful boundary)")
            self.uninstall()
            raise KeyboardInterrupt("stall interrupt")
        if self._requested:
            logger.warning("second signal %s: hard stop", signum)
            self.uninstall()
            raise KeyboardInterrupt(f"second signal {signum}")
        self._requested = True
        logger.warning(
            "received signal %s: graceful checkpoint requested at the "
            "next step boundary", signum)
