"""Host-side stall detection.

A hung device step (relay drop, deadlocked collective) or a dead input
pipeline does not raise — it blocks the host loop forever, which is the
worst failure mode for a supervised job: no error, no restart, no
progress.  :class:`StallWatchdog` turns "no progress past a deadline"
into an exception the :func:`~analytics_zoo_tpu.parallel.elastic.
run_resilient` supervisor can retry.

Mechanism: the watched loop calls :meth:`StallWatchdog.beat` on every
unit of progress (one optimizer step, one batch fetched); a daemon
monitor thread checks the heartbeat age every ``poll_s`` and, past
``timeout_s``, marks the watchdog stalled and interrupts the main thread
(``_thread.interrupt_main`` — a simulated KeyboardInterrupt that fires
even while the main thread is blocked in Python-level waits).  The
training loop translates that interrupt into :class:`StallError` when
``stalled`` is set, so a real Ctrl-C is never misclassified.

The deadline must cover the slowest *legitimate* step, including the
first-step XLA compile — size ``timeout_s`` generously (minutes for real
models; the tests use sub-second steps).
"""

from __future__ import annotations

import _thread
import logging
import threading
from typing import Callable, Optional

from analytics_zoo_tpu.resilience.errors import StallError
from analytics_zoo_tpu.utils.clock import as_now_fn

logger = logging.getLogger("analytics_zoo_tpu")


class StallWatchdog:
    """Heartbeat-based stall detector.

    Usage::

        wd = StallWatchdog(timeout_s=300)
        wd.start()
        try:
            for batch in data:
                step(batch)
                wd.beat()
        except KeyboardInterrupt:
            if wd.stalled:
                raise StallError("train step stalled") from None
            raise
        finally:
            wd.stop()

    ``on_stall`` (optional) replaces the default main-thread interrupt —
    e.g. a callback that dumps stacks or pages an operator.  Pull-style
    consumers can instead call :meth:`check` periodically.
    """

    def __init__(self, timeout_s: float, poll_s: Optional[float] = None,
                 name: str = "train",
                 on_stall: Optional[Callable[["StallWatchdog"], None]] = None,
                 clock=None):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.poll_s = max(0.01, poll_s if poll_s is not None
                          else min(timeout_s / 4.0, 1.0))
        self.name = name
        self.on_stall = on_stall
        # injectable time source — a utils.clock.Clock object or a bare
        # now() callable (both normalized): the serving runtime
        # supervises replica forwards in PULL mode (beat → check) on a
        # virtual clock so the wedged-replica path is deterministic in
        # tests and the drill; the threaded monitor path keeps real
        # time by default
        self._clock = as_now_fn(clock)
        self._last = self._clock()
        self._stalled = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "StallWatchdog":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._stalled = False
        self._last = self._clock()
        self._thread = threading.Thread(
            target=self._monitor, name=f"stall-watchdog-{self.name}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_s * 4)
            self._thread = None

    def __enter__(self) -> "StallWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- heartbeat ---------------------------------------------------------
    def beat(self) -> None:
        """Record one unit of progress (resets the deadline)."""
        self._last = self._clock()

    def reset(self) -> None:
        """Clear a latched stall verdict and restart the deadline —
        for supervised units that RECOVER in place (a serving replica
        coming back from its background restart).  The push-mode
        monitor thread latches via ``start()`` instead."""
        self._stalled = False
        self._last = self._clock()

    @property
    def stalled(self) -> bool:
        return self._stalled

    @property
    def age_s(self) -> float:
        """Seconds since the last heartbeat."""
        return self._clock() - self._last

    def check(self) -> None:
        """Pull-style: raise :class:`StallError` if the deadline passed
        (for loops that can poll instead of being interrupted)."""
        if self._stalled or self.age_s > self.timeout_s:
            self._stalled = True
            raise StallError(
                f"{self.name}: no progress for {self.age_s:.1f}s "
                f"(deadline {self.timeout_s:.1f}s)")

    # -- monitor -----------------------------------------------------------
    def _monitor(self) -> None:
        while not self._stop.wait(self.poll_s):
            age = self._clock() - self._last
            if age > self.timeout_s:
                self._stalled = True
                logger.error(
                    "StallWatchdog[%s]: no progress for %.1fs "
                    "(deadline %.1fs) — interrupting", self.name, age,
                    self.timeout_s)
                if self.on_stall is not None:
                    self.on_stall(self)
                else:
                    # interrupt_main simulates SIGINT.  With a
                    # PreemptionHandler installed, ITS handler receives
                    # the interrupt — it checks `stalled` on the
                    # watchdog wired to it and raises KeyboardInterrupt
                    # immediately instead of treating it as preemption.
                    _thread.interrupt_main()
                return
