"""Resilience layer: failure taxonomy, stall detection, graceful
preemption, and chaos fault injection.

The reference delegated its whole failure story to Spark task retry and
lineage (``ssd/example/Train.scala:153``); a TPU-native system owns it
itself.  The pieces (see docs/RESILIENCE.md):

- :mod:`errors` — retryable vs fatal taxonomy (:func:`retryable_errors`,
  :data:`FATAL_ERRORS`, :func:`is_retryable`)
- :mod:`watchdog` — :class:`StallWatchdog` (hung step → StallError)
- :mod:`preempt` — :class:`PreemptionHandler` (SIGTERM → checkpoint →
  Preempted)
- :mod:`anomaly` — the numerical-anomaly sentinel: in-graph health word,
  skip → rollback-to-last-known-good → ``TrainingDiverged`` ladder,
  deterministic bad-batch forensics (``tools/replay_batch.py``)
- :mod:`chaos` — :class:`ChaosMonkey` fault matrix + ``tools/chaos_drill``
- :mod:`health` — the device-health sentinel: cross-replica parity
  audit, shadow recompute spot-check, straggler EWMA ladder, and the
  quarantine/eviction actuators (``tools/sdc_drill``)
- atomic/verified snapshots live in :mod:`analytics_zoo_tpu.parallel.
  checkpoint`; the restart supervisor in :mod:`analytics_zoo_tpu.
  parallel.elastic`.
"""

from analytics_zoo_tpu.resilience.errors import (
    FATAL_ERRORS,
    CheckpointCorrupt,
    DeviceQuarantine,
    ElasticPlacementError,
    InjectedFault,
    Preempted,
    PrefetchWorkerDied,
    SdcDetected,
    ShardReadError,
    StallError,
    TrainingDiverged,
    is_retryable,
    retryable_errors,
)
from analytics_zoo_tpu.resilience.watchdog import StallWatchdog
from analytics_zoo_tpu.resilience.preempt import PreemptionHandler
from analytics_zoo_tpu.resilience.anomaly import (
    AnomalyPolicy,
    AnomalySentinel,
    batch_fingerprint,
    decode_health,
    health_sections,
)
from analytics_zoo_tpu.resilience.chaos import (
    ChaosMonkey,
    FaultSpec,
    corrupt_snapshot,
    transient_xla_error,
)
from analytics_zoo_tpu.resilience.health import (
    AuditVerdict,
    HealthPolicy,
    HealthSentinel,
    evict_device,
    make_audit_fn,
    tree_fingerprint,
)

__all__ = [k for k in dir() if not k.startswith("_")]
