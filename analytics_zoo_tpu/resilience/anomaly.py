"""Training anomaly sentinel: in-graph numerical health + the host ladder.

The reference's numerical failure story is one scalar guard — MultiBoxLoss
skips backward when the loss exceeds 50 (``MultiBoxLoss.scala:546``,
ported as ``make_train_step(skip_loss_above=...)``) — and a checkpoint
skip once the logged loss is *already* NaN.  By then the params and
optimizer slots may have been poisoned for hundreds of steps.  This
module supplies the production ladder instead (mixed-precision practice
à la Micikevicius et al.; large-run logbooks treat non-finite steps as
routine, not fatal):

1. **Health word** — ``make_train_step(health_check=True)`` folds ONE
   fused ``isfinite``-and-threshold reduction over the loss, the grads,
   and the *updated* params into a single int32 scalar per step (cheap
   on TPU: a handful of ANDs over values already in registers, one extra
   all-reduce word).  Per-tree-section bits name WHICH top-level
   parameter subtree went non-finite — see :func:`decode_health`.
2. **Skip** — ``skip_unhealthy=True`` discards the whole update in-graph
   (params, optimizer slots AND batch stats keep their pre-step values)
   whenever the word is non-zero, subsuming the scalar
   ``skip_loss_above`` guard (which becomes the word's spike bit).
3. **Rollback** — :class:`AnomalySentinel` (driven by the Optimizer
   loop) counts consecutive bad steps; at ``rollback_after`` it restores
   the **last-known-good** checkpoint tier (promoted only after
   ``promote_after`` consecutive clean steps — ``parallel.checkpoint``
   ``tier="lkg"``) and re-seeks the deterministic loader past the bad
   region.
4. **Diverged** — after ``max_rollbacks`` rollbacks the run raises
   :class:`~analytics_zoo_tpu.resilience.errors.TrainingDiverged`
   (fatal, NOT retried: a blind restart would resume into the same
   divergence).

On the first bad step of an episode a **forensics bundle**
(``anomaly_<step>.json``) records the batch coordinates under the PR-2
determinism contract — ``(base_seed, epoch, batch index)`` — plus the
decoded health word, a content hash of the offending batch, and the
recent loss history; ``tools/replay_batch.py`` re-materializes that
exact batch and re-runs one step in float32 to classify data-vs-
optimization causes.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import json
import logging
import os
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger("analytics_zoo_tpu")

# ---------------------------------------------------------------------------
# Health word layout (int32 scalar; 0 == healthy)
# ---------------------------------------------------------------------------

#: bit 0: loss non-finite; bit 1: loss spike (> threshold); bit 2: any
#: grad non-finite; bit 3: any updated param non-finite; bits 4+2i /
#: 5+2i: grads / params of tree section i non-finite.  Sections are the
#: sorted top-level keys of the params tree; sections beyond
#: ``MAX_SECTIONS`` fold into the last pair so the word stays one int32.
BIT_LOSS_NONFINITE = 0
BIT_LOSS_SPIKE = 1
BIT_GRADS_NONFINITE = 2
BIT_PARAMS_NONFINITE = 3
_SECTION_BIT0 = 4
MAX_SECTIONS = 13          # 4 + 2*13 = 30 bits used, sign bit untouched


def health_sections(params: Any) -> List[str]:
    """Stable section names for a params tree: its sorted top-level keys
    (one section for a non-mapping tree).  Traced and decoded with the
    SAME list, so the per-section bits are meaningful on the host."""
    if isinstance(params, Mapping) and len(params):
        return sorted(str(k) for k in params.keys())
    return ["params"]


def _section_bit(i: int, kind: str) -> int:
    i = min(i, MAX_SECTIONS - 1)
    return _SECTION_BIT0 + 2 * i + (0 if kind == "grads" else 1)


def tree_health_word(loss, grads, new_params, sections: Sequence[str],
                     spike_loss_above: Optional[float] = None):
    """Traced: fold loss/grads/params finiteness into one int32 scalar.

    Runs INSIDE the jitted train step — every reduction fuses with the
    update computation, and on a mesh the scalar replicates with the
    loss (one extra word on the existing all-reduce).
    """
    import jax
    import jax.numpy as jnp

    def tree_bad(tree) -> Any:
        """True when any inexact leaf holds a non-finite value."""
        bad = jnp.zeros((), jnp.bool_)
        for leaf in jax.tree_util.tree_leaves(tree):
            if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
                bad = bad | ~jnp.all(jnp.isfinite(leaf))
        return bad

    def as_map(tree) -> Mapping:
        return tree if isinstance(tree, Mapping) else {"params": tree}

    gmap, pmap = as_map(grads), as_map(new_params)
    word = jnp.zeros((), jnp.int32)

    def set_bit(word, flag, bit):
        return word | (flag.astype(jnp.int32) << bit)

    word = set_bit(word, ~jnp.isfinite(loss), BIT_LOSS_NONFINITE)
    if spike_loss_above is not None:
        # isfinite-AND-threshold in one fold: a spike only counts when
        # the loss is finite (non-finite already has its own bit)
        spike = jnp.isfinite(loss) & (loss > spike_loss_above)
        word = set_bit(word, spike, BIT_LOSS_SPIKE)
    any_g = jnp.zeros((), jnp.bool_)
    any_p = jnp.zeros((), jnp.bool_)
    for i, name in enumerate(sections):
        g_bad = tree_bad(gmap.get(name))
        p_bad = tree_bad(pmap.get(name))
        word = set_bit(word, g_bad, _section_bit(i, "grads"))
        word = set_bit(word, p_bad, _section_bit(i, "params"))
        any_g, any_p = any_g | g_bad, any_p | p_bad
    word = set_bit(word, any_g, BIT_GRADS_NONFINITE)
    word = set_bit(word, any_p, BIT_PARAMS_NONFINITE)
    return word


def decode_health(word: int, sections: Sequence[str]) -> Dict[str, Any]:
    """Host-side report for a health word: names the failing subtrees."""
    word = int(word)
    out: Dict[str, Any] = {
        "healthy": word == 0,
        "loss_nonfinite": bool(word >> BIT_LOSS_NONFINITE & 1),
        "loss_spike": bool(word >> BIT_LOSS_SPIKE & 1),
        "grads_nonfinite": bool(word >> BIT_GRADS_NONFINITE & 1),
        "params_nonfinite": bool(word >> BIT_PARAMS_NONFINITE & 1),
        "bad_sections": {},
    }
    for i, name in enumerate(sections):
        g = bool(word >> _section_bit(i, "grads") & 1)
        p = bool(word >> _section_bit(i, "params") & 1)
        if g or p:
            out["bad_sections"][name] = {"grads": g, "params": p}
    return out


def batch_fingerprint(batch: Any) -> str:
    """Content hash of a (possibly device-resident) batch pytree —
    key-ordered, dtype/shape-tagged blake2s over the raw bytes.  The
    forensics bundle records it; ``tools/replay_batch.py`` asserts the
    re-materialized batch matches byte for byte."""
    import jax

    h = hashlib.blake2s()
    leaves = jax.tree_util.tree_flatten_with_path(batch)[0]
    for path, leaf in leaves:
        arr = np.asarray(leaf)
        h.update(jax.tree_util.keystr(path).encode())
        h.update(str(arr.dtype).encode())
        h.update(repr(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# Policy + sentinel (host side)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AnomalyPolicy:
    """Knobs for the skip → rollback → diverge ladder.

    ``skip`` discards unhealthy updates in-graph.  ``rollback_after``
    consecutive bad steps restore the last-known-good tier;
    ``reseek_batches`` (default: ``rollback_after``) deterministic
    batches are then skipped so the stream clears the bad region before
    stepping resumes.  The LKG tier is promoted after ``promote_after``
    consecutive clean steps (and at most every ``promote_after`` steps).
    ``max_rollbacks`` exceeded raises ``TrainingDiverged`` (fatal).
    ``spike_loss_above`` arms the health word's loss-spike bit.
    """

    skip: bool = True
    rollback_after: int = 3
    promote_after: int = 20
    max_rollbacks: int = 2
    reseek_batches: Optional[int] = None
    spike_loss_above: Optional[float] = None
    promote_initial: bool = True
    loss_history: int = 64
    forensics_dir: Optional[str] = None

    def __post_init__(self):
        if self.rollback_after < 1:
            raise ValueError("rollback_after must be >= 1")
        if self.promote_after < 1:
            raise ValueError("promote_after must be >= 1")
        if self.max_rollbacks < 0:
            raise ValueError("max_rollbacks must be >= 0")

    @property
    def reseek(self) -> int:
        return (self.rollback_after if self.reseek_batches is None
                else self.reseek_batches)


class AnomalySentinel:
    """Host-side state machine over per-step health words.

    The Optimizer feeds it one word per step; it answers with the action
    to take (``ok`` / ``skipped`` / ``rollback`` / ``diverged``) and
    keeps the deterministic event log + loss history the forensics
    bundle and the chaos drill read.
    """

    def __init__(self, policy: AnomalyPolicy, sections: Sequence[str]):
        self.policy = policy
        self.sections = list(sections)
        self.consecutive_bad = 0
        self.clean_streak = 0
        self.bad_steps = 0
        self.skipped = 0
        self.spike_skips = 0
        self.rollbacks = 0
        self.promotions = 0
        self._since_promote: Optional[int] = None
        self.events: List[Dict[str, Any]] = []
        self.loss_history: collections.deque = collections.deque(
            maxlen=policy.loss_history)
        self.forensics_paths: List[str] = []

    # -- per-step ----------------------------------------------------------
    def record_loss(self, loss: float) -> None:
        self.loss_history.append(float(loss))

    def observe(self, word: int) -> Tuple[str, bool]:
        """Feed one health word; returns ``(action, first_detection)``.
        ``first_detection`` is True exactly on the clean→bad transition
        of an episode (the forensics-bundle moment).

        A word carrying ONLY the loss-spike bit keeps the reference
        guard's semantics — skip the update, nothing more: finite
        spikes are routine early training (the reason MultiBoxLoss
        merely skips), so they never count toward the rollback ladder
        and never trigger forensics.  They do reset the clean streak,
        so the LKG tier is not promoted mid-spike-burst."""
        if self._since_promote is not None:
            self._since_promote += 1
        if word == 0:
            self.consecutive_bad = 0
            self.clean_streak += 1
            return "ok", False
        self.clean_streak = 0
        self.bad_steps += 1
        if self.policy.skip:
            self.skipped += 1
        if word == (1 << BIT_LOSS_SPIKE):
            self.spike_skips += 1
            return "skipped", False
        first = self.consecutive_bad == 0
        self.consecutive_bad += 1
        if self.consecutive_bad >= self.policy.rollback_after:
            if self.rollbacks >= self.policy.max_rollbacks:
                return "diverged", first
            return "rollback", first
        return "skipped", first

    # -- ladder bookkeeping ------------------------------------------------
    def should_promote(self) -> bool:
        """Promote the LKG tier when the word has been clean for
        ``promote_after`` consecutive steps, throttled so a long clean
        run re-promotes at most every ``promote_after`` steps."""
        if self.clean_streak < self.policy.promote_after:
            return False
        return (self._since_promote is None
                or self._since_promote >= self.policy.promote_after)

    def note_promoted(self, step: int, snapshot: str) -> None:
        self.promotions += 1
        self._since_promote = 0
        self.events.append({"kind": "lkg_promoted", "step": int(step),
                            "snapshot": snapshot})

    def note_rollback(self, **detail: Any) -> None:
        self.rollbacks += 1
        self.consecutive_bad = 0
        self.clean_streak = 0
        self._since_promote = None   # re-promote only after a fresh streak
        self.events.append({"kind": "rollback",
                            "rollback_index": self.rollbacks, **detail})

    def note_skip(self, word: int, step: int) -> None:
        self.events.append({"kind": "skip", "step": int(step),
                            "health_word": int(word),
                            "consecutive": self.consecutive_bad})

    # -- forensics ---------------------------------------------------------
    def write_forensics(self, directory: str,
                        payload: Dict[str, Any]) -> str:
        """Dump ``anomaly_<step>.json`` (payload must carry ``step``).
        Returns the path; also recorded in :attr:`forensics_paths`."""
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, f"anomaly_{payload['step']}.json")
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        self.forensics_paths.append(path)
        self.events.append({"kind": "forensics",
                            "path": os.path.basename(path),
                            "step": payload["step"],
                            "health_word": payload.get("health_word")})
        logger.warning("anomaly sentinel: forensics bundle written to %s "
                       "(health word %s)", path, payload.get("health_word"))
        return path

    def stats(self) -> Dict[str, Any]:
        return {"bad_steps": self.bad_steps, "skipped": self.skipped,
                "spike_skips": self.spike_skips,
                "rollbacks": self.rollbacks, "promotions": self.promotions,
                "forensics_bundles": len(self.forensics_paths)}
