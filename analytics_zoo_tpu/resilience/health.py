"""Device-health sentinel: silent-data-corruption detection, straggler
quarantine, and elastic eviction (ISSUE 20).

The PR-3 anomaly ladder catches *non-finite math* and the serving pool
catches *crashed or wedged replicas* — but both trust the silicon: a
chip that computes wrong answers (silent data corruption, SDC) or runs
persistently slow degrades the fleet undetected.  This module treats
unpredictable devices the way Clockwork treats unpredictable components
— as failed — and gives training and serving the detectors plus the
decision machinery to *evict* them:

- **Cross-replica parity audit** (:func:`make_audit_fn`): data-parallel
  replicas must hold bit-identical params post-all-reduce, so every
  ``audit_every`` steps an in-graph per-replica param-tree fingerprint
  (a folded uint32 reduction inside ``shard_map``, no host sync on the
  hot path) is compared at the decision boundary; a divergence names
  the minority device (:meth:`HealthSentinel.observe_audit`).
- **Shadow recompute spot-check**: a sampled microbatch's forward is
  re-executed on a second device and the output fingerprints compared
  (:meth:`HealthSentinel.observe_shadow`) — catching SDC that the
  gradient all-reduce would otherwise average into the fleet.
- **Straggler detector** (:meth:`HealthSentinel.observe_step_time`):
  per-device step-time EWMAs vs the fleet median with hysteresis (the
  PR-5 ladder idiom — ``flag_after`` consecutive over-threshold
  windows flag, ``clear_after`` clean ones clear), so persistent
  outliers are flagged and one-shot noise never is.
- **Quarantine + eviction**: a confirmed suspect raises
  :class:`~analytics_zoo_tpu.resilience.errors.DeviceQuarantine`
  (retryable — the supervisor rebuilds on the surviving devices via
  :func:`evict_device` + ``SpecSet.replace_mesh`` + the LKG tier +
  ``elastic_resume_coordinates``); an *ambiguous* divergence (no
  strict minority) raises
  :class:`~analytics_zoo_tpu.resilience.errors.SdcDetected` (fatal —
  restarting onto the same unattributed silicon re-creates it).
  Serving retires a flagged device's slice through
  ``ReplicaPool.quarantine`` (drain-then-retire, ``device_budget``
  decremented).

Every knob defaults **off** (``HealthPolicy(audit_every=0,
shadow_every=0)`` and no sentinel armed anywhere by default), so legacy
runs and every banked drill replay byte-identically.

Chaos composition: the ``bit_flip`` fault kind
(:mod:`analytics_zoo_tpu.resilience.chaos`) arms a module-global flip
spec here (:func:`arm_bit_flip`, the ``set_fault_hook`` precedent) that
the audit/shadow programs consume as *traced* scalars — a deterministic
single-element single-bit corruption of the named replica's view of the
params/output, modeling a stuck bit in that device's read path.  Banked
drill: ``tools/sdc_drill.py`` → ``SDC_r01.json``.
"""

from __future__ import annotations

import dataclasses
import logging
import statistics
from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger("analytics_zoo_tpu")


# ---------------------------------------------------------------------------
# Chaos hook: deterministic bit-flip injection (the SDC fault model)
# ---------------------------------------------------------------------------

#: armed flip spec ``(replica, element, bit)`` or None — module-global on
#: purpose (the ``checkpoint.set_fault_hook`` precedent): the chaos
#: schedule fires from the dataset wrapper while the audit runs deep in
#: the train loop, and neither holds a reference to the other.
_FLIP: Optional[Tuple[int, int, int]] = None


def arm_bit_flip(replica: int, element: int = 0,
                 bit: int = 0) -> Optional[Tuple[int, int, int]]:
    """Arm a persistent single-bit corruption of device ``replica``'s
    view of the audited tree (flat ``element`` of the first leaf, bit
    ``bit``).  Persistent — a stuck bit, not a transient — until
    :func:`clear_bit_flip` (``ChaosMonkey.disarm`` calls it).  Returns
    the previously armed spec."""
    global _FLIP
    prev = _FLIP
    _FLIP = (int(replica), int(element), int(bit))
    logger.warning("health: bit_flip armed on replica %d (element %d, "
                   "bit %d)", *_FLIP)
    return prev


def clear_bit_flip() -> None:
    global _FLIP
    _FLIP = None


def active_bit_flip() -> Optional[Tuple[int, int, int]]:
    """The armed flip spec, or None.  The trainer passes it into the
    audit program as traced scalars (no retrace per arm/clear)."""
    return _FLIP


# ---------------------------------------------------------------------------
# In-graph fingerprints (traced; no host sync)
# ---------------------------------------------------------------------------


def _as_u32(x):
    """Flat uint32 view of one leaf: 4-byte dtypes are bitcast (exact —
    two values differing in ONE bit fold to different words), others are
    value-cast through a 32-bit carrier."""
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(x)
    if x.dtype.itemsize == 4:
        return jax.lax.bitcast_convert_type(x, jnp.uint32).reshape(-1)
    if jnp.issubdtype(x.dtype, jnp.floating):
        return jax.lax.bitcast_convert_type(
            x.astype(jnp.float32), jnp.uint32).reshape(-1)
    return x.astype(jnp.uint32).reshape(-1)


def tree_fingerprint(tree, flip=None):
    """Traced uint32 fold over every leaf of ``tree`` — an FNV-style
    position-weighted reduction (uint32 arithmetic wraps mod 2^32 in
    XLA, so the fold is exact and deterministic; leaf order is jax's
    canonical tree order).  Any single-element change anywhere in the
    tree changes the word with overwhelming probability, and a one-BIT
    change ALWAYS changes the folded leaf's term (bitcast + per-position
    odd weight: flipping bit ``b`` of a word perturbs the fold by
    ``±2^b·w mod 2^32``, which is non-zero for every ``b < 32`` exactly
    because ``w`` is odd).

    ``flip`` (optional) = ``(element, bit, on)`` traced scalars: when
    ``on`` is true, flat ``element`` of the FIRST leaf has ``bit``
    XOR-flipped *in this device's view* before folding — the chaos
    ``bit_flip`` injection point."""
    import jax
    import jax.numpy as jnp

    word = jnp.uint32(2166136261)           # FNV-1a offset basis
    for k, leaf in enumerate(jax.tree_util.tree_leaves(tree)):
        u = _as_u32(leaf)
        if flip is not None and k == 0:
            element, bit, on = flip
            idx = jnp.clip(jnp.uint32(element), 0, u.size - 1)
            flipped = u.at[idx].set(
                u[idx] ^ (jnp.uint32(1) << jnp.uint32(bit)))
            u = jnp.where(on, flipped, u)
        # per-position Knuth-hash weights FORCED odd (|1): an even
        # weight is blind to high bits (2^b·w ≡ 0 mod 2^32 once
        # w ≡ 0 mod 2^(32-b)) — the old idx·K + (2k+1) scheme was even
        # at every odd idx and so missed sign-bit flips there.  The
        # leaf index mixes into the fold as its own odd term instead,
        # keeping leaf reorders visible.
        w = ((jnp.arange(u.size, dtype=jnp.uint32)
              * jnp.uint32(2654435761)) | jnp.uint32(1))
        word = (word * jnp.uint32(16777619) + jnp.uint32(2 * k + 1)
                + jnp.sum(u * w, dtype=jnp.uint32))
    return word


def make_audit_fn(mesh):
    """Build the jitted cross-replica parity audit for a pure
    data-parallel mesh: ``audit_fn(params, target, element, bit) →
    uint32[W]`` — each device folds ITS OWN local copy of the
    (logically replicated) params inside ``shard_map``, so the output
    vector holds one fingerprint per replica and the comparison happens
    at the host decision boundary, not in the hot path.

    ``target`` (traced int32, -1 = none) is the chaos ``bit_flip``
    replica: that device's view has ``(element, bit)`` flipped before
    folding — on healthy silicon this is the only way replicas can
    diverge, which is exactly what the fault drill banks."""
    import jax
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover - older jax spelling
        from jax.experimental.shard_map import shard_map

    from analytics_zoo_tpu.parallel import mesh as mesh_lib

    if len(mesh.axis_names) != 1:
        raise ValueError(
            f"parity audit needs a pure data-parallel mesh (params "
            f"replicated over one axis); got axes {mesh.axis_names} — "
            f"hybrid meshes shard params, so per-replica bit-identity "
            f"does not hold")
    axis = mesh_lib.data_axis(mesh)

    def per_device(params, target, element, bit):
        me = jax.lax.axis_index(axis)
        on = (target >= 0) & (me == target)
        word = tree_fingerprint(params, flip=(element, bit, on))
        return word[None]                   # (1,) per device → (W,)

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(P(), P(), P(), P()),
                   out_specs=P(axis), check_rep=False)
    return jax.jit(fn)


def make_shadow_fn(module, forward_fn=None):
    """Build the jitted shadow-recompute program: ``shadow(variables,
    batch, element, bit, on) → uint32`` — a deterministic (train=False)
    forward of the microbatch folded to one fingerprint word.  The
    caller executes it under ``jax.default_device(d)`` for each device
    being cross-checked; ``on`` keys in the armed ``bit_flip`` when the
    executing device is the chaos target (corrupting that device's view
    of the OUTPUT — SDC in the compute path, which a gradient
    all-reduce would have averaged into the fleet)."""
    import jax

    from analytics_zoo_tpu.parallel.train import _forward

    def shadow(variables, batch, element, bit, on):
        if forward_fn is not None:
            output, _ = forward_fn(variables, batch["input"],
                                   train=False, rngs=None)
        else:
            output, _ = _forward(module, variables, batch["input"],
                                 train=False)
        return tree_fingerprint({"output": output},
                                flip=(element, bit, on))

    return jax.jit(shadow)


def evict_device(mesh, device_index: int, new_width: Optional[int] = None):
    """The eviction actuator's mesh half: a fresh pure-data mesh over
    the surviving devices of ``mesh`` with flat index ``device_index``
    removed (``new_width`` optionally narrows further, e.g. so the
    width keeps dividing the global batch).  Compose with
    ``SpecSet.replace_mesh`` + the LKG tier + ``restore_elastic`` +
    ``elastic_resume_coordinates`` for checkpoint-free recovery at the
    smaller width (the PR-19 elastic path)."""
    from analytics_zoo_tpu.parallel import mesh as mesh_lib

    devices = [d for i, d in enumerate(mesh.devices.flat)
               if i != int(device_index)]
    if not devices:
        raise ValueError("cannot evict the only device in the mesh")
    if new_width is not None:
        if not 1 <= new_width <= len(devices):
            raise ValueError(f"new_width {new_width} not in "
                             f"[1, {len(devices)}]")
        devices = devices[:new_width]
    return mesh_lib.create_mesh(devices=devices,
                                axis_names=mesh.axis_names)


# ---------------------------------------------------------------------------
# Policy + sentinel (host-side decision machinery)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class HealthPolicy:
    """Knobs for the device-health sentinel.  Both detector cadences
    default to 0 = **off**, so an un-armed job (and every legacy banked
    drill) runs byte-identically."""

    #: parity-audit cadence in steps (0 = off)
    audit_every: int = 0
    #: shadow-recompute cadence in steps (0 = off)
    shadow_every: int = 0
    #: device index the shadow forward is re-executed on
    shadow_device: int = 1
    #: a device is an outlier when its EWMA > factor × fleet median
    straggler_factor: float = 1.75
    #: EWMA smoothing for per-device step times
    straggler_alpha: float = 0.25
    #: hysteresis: consecutive outlier observations before flagging —
    #: one-shot noise (a GC pause, one slow batch) never flags
    flag_after: int = 3
    #: consecutive clean observations before an outlier streak resets
    clear_after: int = 2
    #: per-device observations ignored before the EWMA is trusted
    #: (compile / warm-up noise)
    warmup_obs: int = 2
    #: raise ``DeviceQuarantine`` on a confirmed suspect (False =
    #: detect-and-log only)
    evict: bool = True
    #: quarantine budget — evictions beyond it degrade to log-only
    #: (each eviction shrinks the fleet; past the budget an operator
    #: should be looking at the hardware, not the supervisor)
    max_evictions: int = 1

    def __post_init__(self):
        if self.audit_every < 0 or self.shadow_every < 0:
            raise ValueError("audit_every/shadow_every must be >= 0 "
                             "(0 = off)")
        if self.shadow_device < 1:
            raise ValueError("shadow_device must be >= 1 (device 0 is "
                             "the primary)")
        if self.straggler_factor <= 1.0:
            raise ValueError("straggler_factor must be > 1 (an EWMA at "
                             "the median is not an outlier)")
        if not 0.0 < self.straggler_alpha <= 1.0:
            raise ValueError("straggler_alpha must be in (0, 1]")
        if self.flag_after < 1 or self.clear_after < 1:
            raise ValueError("flag_after/clear_after must be >= 1")
        if self.warmup_obs < 0:
            raise ValueError("warmup_obs must be >= 0")
        if self.max_evictions < 0:
            raise ValueError("max_evictions must be >= 0")


@dataclasses.dataclass
class AuditVerdict:
    """One parity-audit comparison: ``ok`` when all replicas agree;
    otherwise ``suspect`` names the single minority device (strict
    majority agrees) or stays None with ``ambiguous=True`` (a 2-way
    split / multiple divergers — eviction cannot be attributed)."""

    ok: bool
    suspect: Optional[int] = None
    ambiguous: bool = False
    fingerprints: Tuple[int, ...] = ()


class HealthSentinel:
    """Host-side state machine for the three detectors.  Pure decision
    logic: callers hand it HOST values (fingerprint vectors fetched at
    the decision boundary, per-device step seconds) and act on the
    returned verdicts — raising/evicting stays with the trainer or the
    serving runtime, so the sentinel is trivially unit-testable."""

    def __init__(self, policy: Optional[HealthPolicy] = None,
                 registry=None):
        self.policy = policy or HealthPolicy()
        self.registry = registry
        self.events: List[Dict[str, Any]] = []
        self._ewma: Dict[int, float] = {}
        self._obs: Dict[int, int] = {}
        self._streak: Dict[int, int] = {}
        self._clean: Dict[int, int] = {}
        self._flagged: set = set()
        self.audits = 0
        self.divergences = 0
        self.shadow_checks = 0
        self.shadow_mismatches = 0
        self.straggler_flags = 0
        self.quarantines = 0

    def _count(self, name: str) -> None:
        if self.registry is not None:
            # az-allow: registered-metric-names — sentinel-internal helper; every caller passes a literal from the health/* family declared in obs/names.py
            self.registry.counter(name).inc()

    # -- parity audit ------------------------------------------------------
    def observe_audit(self, step: int,
                      fingerprints: Sequence[int]) -> AuditVerdict:
        """Compare one audit's per-replica fingerprint vector (host
        ints).  All-equal → ok.  A single device against a strict
        majority → that device is the suspect.  Anything else (2-way
        tie, multiple divergers) → ambiguous: corruption is proven but
        unattributable, the ``SdcDetected`` path."""
        fps = tuple(int(v) for v in fingerprints)
        self.audits += 1
        self._count("health/audits")
        if len(set(fps)) <= 1:
            return AuditVerdict(ok=True, fingerprints=fps)
        self.divergences += 1
        self._count("health/audit_divergences")
        maj_val, maj_n = Counter(fps).most_common(1)[0]
        minority = [i for i, v in enumerate(fps) if v != maj_val]
        suspect = (minority[0] if len(minority) == 1
                   and 2 * maj_n > len(fps) else None)
        self.events.append({"kind": "audit_divergence", "step": int(step),
                            "suspect": suspect,
                            "minority": [int(i) for i in minority],
                            "fingerprints": [int(v) for v in fps]})
        logger.error("health: parity audit diverged at step %d — "
                     "suspect=%s fingerprints=%s", step, suspect,
                     list(fps))
        return AuditVerdict(ok=False, suspect=suspect,
                            ambiguous=suspect is None, fingerprints=fps)

    # -- shadow recompute --------------------------------------------------
    def observe_shadow(self, step: int, primary_fp: int, shadow_fp: int,
                       device: int,
                       tiebreak_fp: Optional[int] = None) -> AuditVerdict:
        """Compare a shadow recompute against the primary.  A mismatch
        with a third vote (``tiebreak_fp``) names the odd one out; a
        bare two-way mismatch is ambiguous (proven SDC, unknown
        culprit)."""
        p, s = int(primary_fp), int(shadow_fp)
        self.shadow_checks += 1
        self._count("health/shadow_checks")
        if p == s:
            return AuditVerdict(ok=True, fingerprints=(p, s))
        self.shadow_mismatches += 1
        self._count("health/shadow_mismatches")
        suspect = None
        if tiebreak_fp is not None:
            t = int(tiebreak_fp)
            if p == t:
                suspect = int(device)       # shadow is the odd one out
            elif s == t:
                suspect = 0                 # primary is the odd one out
        self.events.append({"kind": "shadow_mismatch", "step": int(step),
                            "device": int(device), "suspect": suspect,
                            "primary_fp": p, "shadow_fp": s,
                            "tiebreak_fp": (int(tiebreak_fp)
                                            if tiebreak_fp is not None
                                            else None)})
        logger.error("health: shadow recompute mismatch at step %d "
                     "(device %d vs primary) — suspect=%s", step, device,
                     suspect)
        return AuditVerdict(ok=False, suspect=suspect,
                            ambiguous=suspect is None,
                            fingerprints=(p, s))

    # -- straggler detector ------------------------------------------------
    def observe_step_time(self, device: int,
                          seconds: float) -> Optional[int]:
        """Feed one per-device step/service time.  Returns the device id
        when its EWMA has now been over ``straggler_factor`` × the fleet
        median for ``flag_after`` consecutive observations (the
        hysteresis ladder), else None.  A flagged device stays flagged
        (no re-return) until ``clear_after`` clean observations."""
        p = self.policy
        device = int(device)
        n = self._obs.get(device, 0) + 1
        self._obs[device] = n
        prev = self._ewma.get(device)
        self._ewma[device] = (float(seconds) if prev is None else
                              (1.0 - p.straggler_alpha) * prev
                              + p.straggler_alpha * float(seconds))
        if n <= p.warmup_obs:
            return None
        peers = [e for d, e in self._ewma.items()
                 if d != device and self._obs.get(d, 0) > p.warmup_obs]
        if not peers:
            return None
        median = statistics.median(peers)
        if self._ewma[device] > p.straggler_factor * median:
            self._clean[device] = 0
            streak = self._streak.get(device, 0) + 1
            self._streak[device] = streak
            if streak >= p.flag_after and device not in self._flagged:
                self._flagged.add(device)
                self.straggler_flags += 1
                self._count("health/straggler_flags")
                self.events.append({
                    "kind": "straggler_flagged", "device": device,
                    "ewma_s": round(self._ewma[device], 6),
                    "fleet_median_s": round(median, 6),
                    "streak": streak})
                logger.warning("health: device %d flagged as straggler "
                               "(ewma %.4fs vs median %.4fs, streak %d)",
                               device, self._ewma[device], median, streak)
                return device
        else:
            clean = self._clean.get(device, 0) + 1
            self._clean[device] = clean
            if clean >= p.clear_after:
                self._streak[device] = 0
                if device in self._flagged:
                    self._flagged.discard(device)
                    self.events.append({"kind": "straggler_cleared",
                                        "device": device})
        return None

    # -- bookkeeping -------------------------------------------------------
    def note_quarantine(self, device: int, reason: str) -> None:
        """Record an actuated eviction (the caller raises/retires) and
        drop the device's straggler state — a retired device's inflated
        EWMA must not keep counting as a peer in the fleet median, where
        it would skew every later outlier decision."""
        device = int(device)
        self.quarantines += 1
        self._count("health/quarantines")
        for m in (self._ewma, self._obs, self._streak, self._clean):
            m.pop(device, None)
        self.events.append({"kind": "quarantine", "device": device,
                            "reason": reason})

    @property
    def eviction_budget_left(self) -> bool:
        return self.quarantines < self.policy.max_evictions

    def flagged(self) -> List[int]:
        return sorted(self._flagged)

    def stats(self) -> Dict[str, int]:
        return {"audits": self.audits,
                "audit_divergences": self.divergences,
                "shadow_checks": self.shadow_checks,
                "shadow_mismatches": self.shadow_mismatches,
                "straggler_flags": self.straggler_flags,
                "quarantines": self.quarantines}
