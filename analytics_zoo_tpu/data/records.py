"""Sharded binary record files — the Hadoop-SequenceFile replacement.

The reference stores detection datasets as SequenceFiles of
``SSDByteRecord`` blobs with layout ``[dataLen][classLen][jpeg bytes]
[classes+difficult floats][bbox floats]`` written by ``RoiByteImageToSeq``
(reference ``common/dataset/roiimage/*.scala``, SURVEY.md §2.2
"Serialization format").  Here the container is a simple length-prefixed
record file (``.azr``) designed for per-host sharding: shard k of N is the
natural unit a TPU-VM host reads (`grain`/tf.data can also consume it via
the generator API).

File layout:  magic ``AZR1`` | then per record: u32 length | payload.
``SSDByteRecord`` payload:  u32 path_len | path utf-8 | u32 img_len |
jpeg/png bytes | u32 n_gt | n_gt × 6 float32 (label, difficult, x1,y1,x2,y2).
"""

from __future__ import annotations

import dataclasses
import glob as globlib
import logging
import os
import struct
import time
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.resilience.errors import ShardReadError

logger = logging.getLogger("analytics_zoo_tpu")

MAGIC = b"AZR1"


@dataclasses.dataclass
class ReadStats:
    """Skip-and-count bookkeeping for resilient shard reads (the
    reference's corrupt-image tolerance, surfaced as numbers instead of
    silence)."""

    records: int = 0           # records successfully yielded
    retries: int = 0           # transient I/O errors retried
    skipped_records: int = 0   # undecodable records dropped
    skipped_shards: int = 0    # whole shards dropped (retry exhaustion /
    #                            truncation with skip_errors=True)

    def publish(self, registry, prefix: str = "data/read") -> None:
        """Mirror the counters into a central ``obs.MetricRegistry`` as
        ``<prefix>/records`` etc.  Gauges (set, not inc) — the dataclass
        is the source of truth and ``publish`` may be called repeatedly
        (e.g. once per epoch) without double counting."""
        for field in dataclasses.fields(self):
            # az-allow: registered-metric-names — prefix-parameterized mirror; the canonical data/read/* family is declared in obs/names.py
            registry.gauge(f"{prefix}/{field.name}").set(
                getattr(self, field.name))


# ---------------------------------------------------------------------------
# Raw container
# ---------------------------------------------------------------------------


class RecordWriter:
    def __init__(self, path: str):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "wb")
        self._f.write(MAGIC)
        self.count = 0

    def write(self, payload: bytes) -> None:
        self._f.write(struct.pack("<I", len(payload)))
        self._f.write(payload)
        self.count += 1

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_records(path: str, retries: int = 0, backoff_s: float = 0.05,
                 stats: Optional[ReadStats] = None,
                 opener: Callable = open) -> Iterator[bytes]:
    """Iterate raw payloads of one shard.

    ``retries`` bounds recovery from *transient* I/O errors (flaky NFS/
    object-store FUSE mounts): the shard is reopened, seeked back to the
    last good record boundary, and reading continues, with exponential
    backoff (``backoff_s``, doubling per retry).  When the budget is
    exhausted, :class:`ShardReadError` is raised with the last cause.
    ``stats`` (a :class:`ReadStats`) counts yielded records and retries.
    ``opener`` is the file-open callable (fault-injection seam for tests
    and the chaos drill)."""
    state = {"budget": retries, "delay": backoff_s}

    def _transient(e: OSError, what: str) -> None:
        """Consume one retry (sleep + count) or raise ShardReadError."""
        if state["budget"] <= 0:
            raise ShardReadError(
                f"{path}: {what} failed after {retries} retries: {e}") from e
        state["budget"] -= 1
        if stats is not None:
            stats.retries += 1
        logger.warning("shard %s: transient error on %s (%s); retrying in "
                       "%.2fs (%d retries left)", path, what, e,
                       state["delay"], state["budget"])
        time.sleep(state["delay"])
        state["delay"] *= 2

    def _open_at(pos: int):
        f = opener(path, "rb")
        try:
            if f.read(4) != MAGIC:
                raise ValueError(f"{path}: not an AZR1 record file")
            if pos > 4:
                f.seek(pos)
            return f
        except Exception:
            f.close()
            raise

    offset = 4   # next unread record boundary
    f = None
    try:
        while True:
            if f is None:
                try:
                    f = _open_at(offset)
                except OSError as e:
                    _transient(e, "open")
                    continue
            try:
                head = f.read(4)
                if len(head) < 4:
                    return
                (n,) = struct.unpack("<I", head)
                payload = f.read(n)
            except OSError as e:
                f.close()
                f = None   # reopen + reseek at the last record boundary
                _transient(e, f"read at offset {offset}")
                continue
            if len(payload) < n:
                raise ValueError(f"{path}: truncated record")
            offset += 4 + n
            if stats is not None:
                stats.records += 1
            yield payload
    finally:
        if f is not None:
            f.close()


def shard_paths(pattern: str, shard_index: Optional[int] = None,
                num_shards: Optional[int] = None) -> List[str]:
    """Deterministic per-host file sharding: host k takes files k, k+N, …
    (replaces Spark's RDD partition placement for input files)."""
    paths = sorted(globlib.glob(pattern)) if any(c in pattern for c in "*?[") \
        else sorted(
            os.path.join(pattern, p) for p in os.listdir(pattern)
        ) if os.path.isdir(pattern) else [pattern]
    if shard_index is None:
        import jax
        shard_index, num_shards = jax.process_index(), jax.process_count()
    elif num_shards is None:
        raise ValueError("num_shards required when shard_index is given")
    return paths[shard_index::max(num_shards, 1)]


# ---------------------------------------------------------------------------
# SSDByteRecord
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SSDByteRecord:
    """JPEG bytes + ground-truth matrix (reference ``SSDByteRecord``,
    ``common/dataset/roiimage/Types.scala:31``).  ``gt`` rows are
    (label, difficult, x1, y1, x2, y2) in pixel coords."""

    data: bytes
    path: str = ""
    gt: Optional[np.ndarray] = None  # (N, 6) float32

    def encode(self) -> bytes:
        path_b = self.path.encode("utf-8")
        gt = (np.zeros((0, 6), np.float32) if self.gt is None
              else np.asarray(self.gt, np.float32).reshape(-1, 6))
        return b"".join([
            struct.pack("<I", len(path_b)), path_b,
            struct.pack("<I", len(self.data)), self.data,
            struct.pack("<I", gt.shape[0]), gt.tobytes(),
        ])

    @staticmethod
    def decode(payload: bytes) -> "SSDByteRecord":
        off = 0
        (plen,) = struct.unpack_from("<I", payload, off); off += 4
        path = payload[off:off + plen].decode("utf-8"); off += plen
        (dlen,) = struct.unpack_from("<I", payload, off); off += 4
        data = payload[off:off + dlen]; off += dlen
        (n_gt,) = struct.unpack_from("<I", payload, off); off += 4
        gt = np.frombuffer(payload, np.float32, n_gt * 6, off).reshape(n_gt, 6).copy()
        return SSDByteRecord(data=data, path=path, gt=gt)


def write_ssd_records(records: Sequence[SSDByteRecord], prefix: str,
                      num_shards: int = 1) -> List[str]:
    """Shard records round-robin into ``<prefix>-00000-of-0000N.azr``
    (the ``RoiImageSeqGenerator`` equivalent, reference
    ``common/dataset/RoiImageSeqGenerator.scala:25``)."""
    paths = [f"{prefix}-{i:05d}-of-{num_shards:05d}.azr" for i in range(num_shards)]
    writers = [RecordWriter(p) for p in paths]
    for i, rec in enumerate(records):
        writers[i % num_shards].write(rec.encode())
    for w in writers:
        w.close()
    return paths


def read_ssd_records(paths: Sequence[str], skip_errors: bool = False,
                     retries: int = 0, backoff_s: float = 0.05,
                     stats: Optional[ReadStats] = None,
                     opener: Callable = open) -> Iterator[SSDByteRecord]:
    """Decode SSD records across shards, optionally fault-tolerantly.

    ``retries``/``backoff_s`` bound transient I/O recovery per shard (see
    :func:`read_records`).  With ``skip_errors=True`` the reader follows
    the reference's corrupt-data policy — skip and count, never abort:
    an undecodable record is dropped (``stats.skipped_records``); a
    truncated shard tail or a shard whose retry budget is exhausted drops
    the REST of that shard (``stats.skipped_shards``) and reading
    continues with the next shard.  Without it, errors propagate."""
    stats = stats if stats is not None else ReadStats()
    for p in paths:
        try:
            for payload in read_records(p, retries=retries,
                                        backoff_s=backoff_s, stats=stats,
                                        opener=opener):
                try:
                    yield SSDByteRecord.decode(payload)
                except (struct.error, ValueError, UnicodeDecodeError) as e:
                    if not skip_errors:
                        raise
                    stats.skipped_records += 1
                    logger.warning("%s: skipping undecodable record (%s)",
                                   p, e)
        except (ShardReadError, ValueError) as e:
            if not skip_errors:
                raise
            stats.skipped_shards += 1
            logger.warning("%s: skipping rest of shard (%s)", p, e)
