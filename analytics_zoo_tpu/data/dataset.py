"""DataSet: epoch-iterable sources + transform chains + batching.

Replaces BigDL's ``DataSet.rdd(...) -> transformer chain`` (reference
``ssd/Utils.scala:34-85``) with host-side Python iterators: a ``DataSet``
wraps a re-invocable source, transformers attach with ``.transform`` (or
``>>``), and ``iter(ds)`` yields one epoch.  Per-host input sharding
replaces Spark partition placement; batches come out as numpy dicts ready
for ``parallel.shard_batch`` / ``device_prefetch``.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.data import records as records_lib
from analytics_zoo_tpu.data.transformer import Transformer


class DataSet:
    #: False when the SOURCE's record order is not reproducible across
    #: iterations (e.g. the threaded native reader) — the multiprocess
    #: loader requires replayable order and refuses such sources
    _order_deterministic: bool = True

    def __init__(self, source_fn: Callable[[], Iterator[Any]],
                 size: Optional[int] = None):
        self._source_fn = source_fn
        self._size = size
        self._stages: List[Transformer] = []

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_list(items: Sequence[Any], shuffle: bool = False,
                  seed: int = 0) -> "DataSet":
        items = list(items)
        state = {"epoch": 0}

        def source():
            out = items
            if shuffle:
                out = list(items)
                random.Random(seed + state["epoch"]).shuffle(out)
                state["epoch"] += 1
            return iter(out)

        return DataSet(source, size=len(items))

    @staticmethod
    def from_record_files(pattern: str, decode_fn: Optional[Callable] = None,
                          shard_by_host: bool = True,
                          shuffle_files: bool = False, seed: int = 0,
                          native_threads: int = 0) -> "DataSet":
        """Sharded record-file source (the ``DataSet.rdd(sc.sequenceFile)``
        equivalent, reference ``ssd/Utils.scala:37``).

        ``native_threads > 0`` reads through the C++ threaded reader
        (``data.native``) when built — higher IO throughput, but record
        order across shards is then nondeterministic.
        """
        if shard_by_host:
            paths = records_lib.shard_paths(pattern)
        else:
            paths = records_lib.shard_paths(pattern, 0, 1)
        state = {"epoch": 0}

        def source():
            order = list(paths)
            if shuffle_files:
                random.Random(seed + state["epoch"]).shuffle(order)
                state["epoch"] += 1
            if native_threads > 0:
                from analytics_zoo_tpu.data import native
                if native.available():
                    with native.NativeRecordReader(
                            order, n_threads=native_threads) as reader:
                        for payload in reader:
                            yield decode_fn(payload) if decode_fn else payload
                    return
            for p in order:
                for payload in records_lib.read_records(p):
                    yield decode_fn(payload) if decode_fn else payload

        ds = DataSet(source)
        if native_threads > 0:
            ds._order_deterministic = False
        return ds

    @staticmethod
    def from_arrays(shuffle: bool = False, seed: int = 0, **arrays) -> "DataSet":
        """Columnar in-memory source: yields per-sample dicts."""
        n = len(next(iter(arrays.values())))
        idx_state = {"epoch": 0}

        def source():
            idx = np.arange(n)
            if shuffle:
                np.random.RandomState(seed + idx_state["epoch"]).shuffle(idx)
                idx_state["epoch"] += 1
            for i in idx:
                yield {k: v[i] for k, v in arrays.items()}

        return DataSet(source, size=n)

    # -- combinators -------------------------------------------------------
    def transform(self, t: Transformer) -> "DataSet":
        out = DataSet(self._source_fn, self._size)
        out._stages = self._stages + [t]
        out._order_deterministic = self._order_deterministic
        return out

    __rshift__ = transform

    def batch(self, batch_size: int, collate_fn: Optional[Callable] = None,
              drop_remainder: bool = True, num_workers: int = 0,
              base_seed: int = 0):
        """Batch the stream.  ``num_workers > 0`` returns the batched
        dataset wrapped in a :class:`~analytics_zoo_tpu.data.parallel.
        ParallelLoader` — per-sample transforms fan out to that many
        worker processes (shared-memory rings, order-preserving,
        deterministically seeded); this is a terminal combinator, so
        attach further transforms before ``batch``."""
        out = self.transform(Batcher(batch_size, collate_fn, drop_remainder))
        if num_workers > 0:
            return out.parallel(num_workers, base_seed=base_seed)
        return out

    def bucket_batch(self, batch_size: int, bucket_edges: Sequence[int],
                     length_key: str = "n_frames", pad_key: str = "input",
                     drop_remainder: bool = True,
                     num_workers: int = 0, base_seed: int = 0):
        """Length-bucketed batching (:class:`~analytics_zoo_tpu.data.
        bucket.BucketBatcher`): samples land in the smallest fitting
        padded-length bucket and a batch is emitted each time a bucket
        fills — a small pinned set of shapes instead of one max-padded
        shape.  Terminal like :meth:`batch`; ``num_workers > 0`` wraps
        the result in a deterministic multiprocess ``ParallelLoader``
        (the batcher itself always runs serially in the parent)."""
        from analytics_zoo_tpu.data.bucket import BucketBatcher
        out = self.transform(BucketBatcher(
            batch_size, bucket_edges, length_key=length_key,
            pad_key=pad_key, drop_remainder=drop_remainder))
        if num_workers > 0:
            return out.parallel(num_workers, base_seed=base_seed)
        return out

    def parallel(self, num_workers: int, base_seed: int = 0, **kw):
        """Wrap in a multiprocess :class:`~analytics_zoo_tpu.data.
        parallel.ParallelLoader` (``num_workers=0`` = the deterministic
        in-process serial reference path)."""
        from analytics_zoo_tpu.data.parallel import ParallelLoader
        return ParallelLoader(self, num_workers, base_seed=base_seed, **kw)

    def shuffle(self, buffer_size: int = 1024, seed: Optional[int] = None
                ) -> "DataSet":
        """Record-level windowed shuffle (``transformer.ShuffleBuffer``)."""
        from analytics_zoo_tpu.data.transformer import ShuffleBuffer
        rng = random.Random(seed) if seed is not None else None
        return self.transform(ShuffleBuffer(buffer_size, rng=rng))

    # -- iteration ---------------------------------------------------------
    def __iter__(self) -> Iterator[Any]:
        it = self._source_fn()
        for stage in self._stages:
            it = stage.apply_iter(iter(it))
        return it

    def __len__(self) -> int:
        if self._size is None:
            raise TypeError("DataSet size unknown (streaming source)")
        return self._size


# ---------------------------------------------------------------------------
# Batching
# ---------------------------------------------------------------------------


def default_collate(samples: List[Any]) -> Any:
    """Stack a list of samples: dicts stack per key, arrays stack on dim 0."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate(list(col)) for col in zip(*samples))
    if np.isscalar(first) or isinstance(first, np.ndarray):
        return np.stack([np.asarray(s) for s in samples], axis=0)
    return samples


class Batcher(Transformer):
    def __init__(self, batch_size: int, collate_fn: Optional[Callable] = None,
                 drop_remainder: bool = True):
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.drop_remainder = drop_remainder

    def apply_iter(self, it: Iterator[Any]) -> Iterator[Any]:
        buf: List[Any] = []
        for sample in it:
            buf.append(sample)
            if len(buf) == self.batch_size:
                yield self.collate_fn(buf)
                buf = []
        if buf and not self.drop_remainder:
            yield self.collate_fn(buf)


def pad_ragged(rows: List[np.ndarray], max_len: int,
               pad_value: float = 0.0):
    """Pad a list of (n_i, D) arrays to (B, max_len, D) + (B, max_len) mask —
    the static-shape encoding of the reference's ragged 7-col ground-truth
    matrix (``RoiImageToBatch.scala:86+``; SURVEY.md §7.3 "Ragged detection
    labels")."""
    D = rows[0].shape[1] if rows and rows[0].ndim == 2 else 1
    B = len(rows)
    out = np.full((B, max_len, D), pad_value, np.float32)
    mask = np.zeros((B, max_len), np.float32)
    for i, r in enumerate(rows):
        r = np.asarray(r, np.float32).reshape(-1, D)
        n = min(r.shape[0], max_len)
        out[i, :n] = r[:n]
        mask[i, :n] = 1.0
    return out, mask
