"""Device prefetch: overlap host batch prep with device compute.

The reference hides data-prep latency by caching transformed RDD partitions
on executors (SURVEY.md §3.1 HOT LOOP #1); the TPU equivalent is a small
host-side pipeline that device_puts the next batch(es) while the current
step runs, double-buffering into HBM.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator, Optional

from analytics_zoo_tpu.parallel import mesh as mesh_lib
from analytics_zoo_tpu.resilience.errors import PrefetchWorkerDied


def _drain(q: "queue.Queue", stop: object, err: list, worker,
           poll_s: float = 0.2) -> Iterator[Any]:
    """Consumer side of the prefetch queue.

    A bare ``q.get()`` would block FOREVER if the worker thread died
    without enqueueing the stop sentinel (killed interpreter thread,
    c-extension abort) — the silent-hang failure mode.  Poll with a
    timeout instead and, when the queue is empty AND the worker is dead,
    raise a descriptive error: the worker's recorded exception if it left
    one, else :class:`PrefetchWorkerDied`."""
    while True:
        try:
            item = q.get(timeout=poll_s)
        except queue.Empty:
            if worker.is_alive():
                continue
            # worker is gone, so nothing more can be enqueued — but it
            # may have delivered its tail (and the sentinel) between our
            # timeout and the liveness check: drain before declaring death
            try:
                item = q.get_nowait()
            except queue.Empty:
                if err:
                    raise err[0]
                raise PrefetchWorkerDied(
                    "prefetch worker thread died without delivering its "
                    "stop sentinel (no exception recorded) — input "
                    "pipeline is gone; restart the attempt")
        if item is stop:
            if err:
                raise err[0]
            return
        yield item


def device_prefetch(batches: Iterable[Any], mesh, size: int = 2,
                    close_source: bool = False) -> Iterator[Any]:
    """Yield device-resident, data-sharded batches, staying ``size`` ahead.

    Early consumer exit (e.g. the train loop breaking on ``end_when``) is
    handled: closing the generator signals the worker to stop, so no thread
    is left blocked holding device buffers.

    ``close_source=True`` additionally closes ``batches`` itself when the
    stream ends or is cancelled — FROM THE WORKER THREAD, which is the
    only thread ever executing the source generator (a consumer-side
    ``close()`` on a generator suspended inside another thread's
    ``next()`` raises).  Use it when the source owns real resources —
    e.g. a multiprocess ``ParallelLoader`` epoch whose worker processes
    must not outlive the stream.  Leave it False when the caller reuses
    the source across several prefetch streams (``bench_overlap``).
    """
    if size < 1:
        # a non-positive maxsize would make the Queue UNBOUNDED and the
        # worker would transfer the whole epoch into HBM ahead of compute
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    q: "queue.Queue" = queue.Queue(maxsize=size)
    stop = object()
    cancelled = threading.Event()
    err: list = []

    def worker():
        try:
            for b in batches:
                item = mesh_lib.shard_batch(b, mesh)
                while not cancelled.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if cancelled.is_set():
                    return
        except BaseException as e:  # propagate to consumer
            err.append(e)
        finally:
            if close_source and hasattr(batches, "close"):
                try:
                    batches.close()
                except Exception:  # noqa: BLE001 - cleanup best-effort
                    pass
            # Block until the stop sentinel fits — NEVER pop queued real
            # batches to make room (a slow consumer keeps the queue full
            # at end-of-stream, and popping would silently drop batches).
            # A cancelled consumer is gone and needs no sentinel.
            while not cancelled.is_set():
                try:
                    q.put(stop, timeout=0.1)
                    break
                except queue.Full:
                    continue

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        yield from _drain(q, stop, err, t)
    finally:
        cancelled.set()
        if close_source:
            # Wait for the worker to actually finish: its cleanup
            # (closing a multiprocess loader epoch = reaping worker
            # processes + advancing the source state) must COMPLETE
            # before control returns to the consumer — an immediately
            # restarted epoch would otherwise fork new workers from the
            # not-yet-advanced source state (replaying the old shuffle
            # order) while two pools briefly coexist.  Bounded: the
            # worker observes ``cancelled`` within one batch
            # production.  Without close_source there is nothing to
            # reap, and blocking here would stall the very paths (e.g.
            # StallWatchdog recovery around a hung source) that close
            # early.  The timeout bounds stall-recovery latency when
            # the source itself is the thing that hung — but a timeout
            # means the completion invariant did NOT hold, so say so.
            t.join(timeout=5.0)
            if t.is_alive():
                import logging

                logging.getLogger("analytics_zoo_tpu").warning(
                    "prefetch worker still closing its source after the "
                    "5s grace — an immediately restarted epoch may fork "
                    "workers from a stale source state")


class PrefetchDataSet:
    """Wrap a DataSet so every epoch iterates device-resident batches.

    ``size`` is the staging depth: 2 = double buffering (batch ``t+1``
    transfers while the step runs on ``t``), 3 = triple.  ``num_workers
    > 0`` additionally fans the host decode/augment work out to that
    many processes (``data.parallel.ParallelLoader``) before the
    overlapped H2D stage — the full host-input pipeline in one wrapper.
    Early consumer exit closes the host iterator too, so worker
    processes never outlive the epoch."""

    def __init__(self, dataset, mesh, size: int = 2, num_workers: int = 0,
                 base_seed: int = 0, **loader_kw):
        if num_workers > 0:
            from analytics_zoo_tpu.data.parallel import ParallelLoader
            dataset = ParallelLoader(dataset, num_workers,
                                     base_seed=base_seed, **loader_kw)
        self.dataset = dataset
        self.mesh = mesh
        self.size = size

    def __iter__(self):
        # close_source: the epoch iterator (possibly a multiprocess
        # loader owning worker processes) is closed by the prefetch
        # worker thread itself — the only thread executing it
        return device_prefetch(iter(self.dataset), self.mesh, self.size,
                               close_source=True)

    def __len__(self):
        return len(self.dataset)


def overlap_window(items, dispatch, consume, max_inflight: int = 4) -> None:
    """Bounded-window overlap of host prep / device execution / readback.

    ``dispatch(item)`` must be async (a jit call returning a token);
    ``consume(token)`` forces the result to host and processes it.  Up to
    ``max_inflight`` items are in flight, so the remote device's fixed
    per-call latency overlaps with the next items' host prep WITHOUT
    letting the whole dataset's input buffers accumulate in HBM.  Used by
    the serving predictors, the Validator, and the ASR pipeline."""
    from collections import deque

    pending: "deque" = deque()
    for item in items:
        pending.append(dispatch(item))
        if len(pending) >= max_inflight:
            consume(pending.popleft())
    while pending:
        consume(pending.popleft())
