"""Device prefetch: overlap host batch prep with device compute.

The reference hides data-prep latency by caching transformed RDD partitions
on executors (SURVEY.md §3.1 HOT LOOP #1); the TPU equivalent is a small
host-side pipeline that device_puts the next batch(es) while the current
step runs, double-buffering into HBM.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator, Optional

from analytics_zoo_tpu.parallel import mesh as mesh_lib
from analytics_zoo_tpu.resilience.errors import PrefetchWorkerDied


def _drain(q: "queue.Queue", stop: object, err: list, worker,
           poll_s: float = 0.2) -> Iterator[Any]:
    """Consumer side of the prefetch queue.

    A bare ``q.get()`` would block FOREVER if the worker thread died
    without enqueueing the stop sentinel (killed interpreter thread,
    c-extension abort) — the silent-hang failure mode.  Poll with a
    timeout instead and, when the queue is empty AND the worker is dead,
    raise a descriptive error: the worker's recorded exception if it left
    one, else :class:`PrefetchWorkerDied`."""
    while True:
        try:
            item = q.get(timeout=poll_s)
        except queue.Empty:
            if worker.is_alive():
                continue
            # worker is gone, so nothing more can be enqueued — but it
            # may have delivered its tail (and the sentinel) between our
            # timeout and the liveness check: drain before declaring death
            try:
                item = q.get_nowait()
            except queue.Empty:
                if err:
                    raise err[0]
                raise PrefetchWorkerDied(
                    "prefetch worker thread died without delivering its "
                    "stop sentinel (no exception recorded) — input "
                    "pipeline is gone; restart the attempt")
        if item is stop:
            if err:
                raise err[0]
            return
        yield item


def device_prefetch(batches: Iterable[Any], mesh, size: int = 2) -> Iterator[Any]:
    """Yield device-resident, data-sharded batches, staying ``size`` ahead.

    Early consumer exit (e.g. the train loop breaking on ``end_when``) is
    handled: closing the generator signals the worker to stop, so no thread
    is left blocked holding device buffers.
    """
    if size < 1:
        # a non-positive maxsize would make the Queue UNBOUNDED and the
        # worker would transfer the whole epoch into HBM ahead of compute
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    q: "queue.Queue" = queue.Queue(maxsize=size)
    stop = object()
    cancelled = threading.Event()
    err: list = []

    def worker():
        try:
            for b in batches:
                item = mesh_lib.shard_batch(b, mesh)
                while not cancelled.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if cancelled.is_set():
                    return
        except BaseException as e:  # propagate to consumer
            err.append(e)
        finally:
            # Block until the stop sentinel fits — NEVER pop queued real
            # batches to make room (a slow consumer keeps the queue full
            # at end-of-stream, and popping would silently drop batches).
            # A cancelled consumer is gone and needs no sentinel.
            while not cancelled.is_set():
                try:
                    q.put(stop, timeout=0.1)
                    break
                except queue.Full:
                    continue

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        yield from _drain(q, stop, err, t)
    finally:
        cancelled.set()


class PrefetchDataSet:
    """Wrap a DataSet so every epoch iterates device-resident batches."""

    def __init__(self, dataset, mesh, size: int = 2):
        self.dataset = dataset
        self.mesh = mesh
        self.size = size

    def __iter__(self):
        return device_prefetch(iter(self.dataset), self.mesh, self.size)

    def __len__(self):
        return len(self.dataset)


def overlap_window(items, dispatch, consume, max_inflight: int = 4) -> None:
    """Bounded-window overlap of host prep / device execution / readback.

    ``dispatch(item)`` must be async (a jit call returning a token);
    ``consume(token)`` forces the result to host and processes it.  Up to
    ``max_inflight`` items are in flight, so the remote device's fixed
    per-call latency overlaps with the next items' host prep WITHOUT
    letting the whole dataset's input buffers accumulate in HBM.  Used by
    the serving predictors, the Validator, and the ASR pipeline."""
    from collections import deque

    pending: "deque" = deque()
    for item in items:
        pending.append(dispatch(item))
        if len(pending) >= max_inflight:
            consume(pending.popleft())
    while pending:
        consume(pending.popleft())
