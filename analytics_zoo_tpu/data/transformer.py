"""Iterator transformers — the reference's core data-pipeline abstraction.

BigDL's ``Transformer[A,B]`` is an ``Iterator[A] => Iterator[B]`` composed
with ``->`` (reference ``transform/vision/.../image/Types.scala:167-217``,
``ssd/Utils.scala:59-69``).  Here the same combinator algebra is plain
Python: subclasses override ``transform`` (1→1), ``apply_iter`` (full
stream), compose with ``>>`` (the ``->`` of the reference), and are cheaply
``clone()``-able so parallel workers get independent RNG/scratch state
(reference ``cloneTransformer``, ``common/Predictor.scala:82-86``).
"""

from __future__ import annotations

import copy
import os
import random
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence


class Transformer:
    """Base: override ``transform(sample)`` or ``apply_iter(iterator)``."""

    def transform(self, sample: Any) -> Any:
        return sample

    def apply_iter(self, it: Iterator[Any]) -> Iterator[Any]:
        for sample in it:
            out = self.transform(sample)
            if out is not None:
                yield out

    def __call__(self, data: Iterable[Any]) -> Iterator[Any]:
        return self.apply_iter(iter(data))

    def __rshift__(self, other: "Transformer") -> "ChainedTransformer":
        """``a >> b``: feed a's output stream into b (BigDL ``->``)."""
        return ChainedTransformer(self, other)

    def clone(self) -> "Transformer":
        """Deep copy with INDEPENDENT randomness — the reference's
        ``cloneTransformer`` contract (``common/Predictor.scala:82-86``:
        per-worker clones must not replay each other's augmentation
        decisions).  deepcopy duplicates Mersenne state exactly, so any
        held RNG is reseeded from the OS entropy pool."""
        c = copy.deepcopy(self)
        _reseed_rngs(c)
        return c


def walk_rngs(obj: Any, visit: Callable[[Any], None],
              _seen: Optional[set] = None) -> None:
    """Recursively find every RNG reachable from ``obj`` (attribute /
    dict / sequence walk, stable traversal order) and call ``visit`` on
    it.  Recognizes ``random.Random``, ``np.random.RandomState`` and
    ``np.random.Generator``.  The ONE discovery walk shared by
    ``clone()``'s entropy reseed below and the deterministic per-sample
    seeding in ``data.parallel`` — two traversals would drift."""
    import numpy as _np

    if _seen is None:
        _seen = set()
    if id(obj) in _seen:
        return
    _seen.add(id(obj))
    if isinstance(obj, (random.Random, _np.random.RandomState,
                        _np.random.Generator)):
        visit(obj)
        return
    if isinstance(obj, dict):
        for v in obj.values():
            walk_rngs(v, visit, _seen)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            walk_rngs(v, visit, _seen)
    elif hasattr(obj, "__dict__"):
        walk_rngs(vars(obj), visit, _seen)


def _reseed_rngs(obj: Any) -> None:
    import numpy as _np

    def visit(rng):
        if isinstance(rng, random.Random):
            rng.seed(int.from_bytes(os.urandom(8), "little"))
        elif isinstance(rng, _np.random.RandomState):
            rng.seed(int.from_bytes(os.urandom(4), "little"))
        else:   # np.random.Generator — same bit-generator type (a
            # Philox state assigned to a PCG64 raises)
            rng.bit_generator.state = type(rng.bit_generator)(
                int.from_bytes(os.urandom(8), "little")).state

    walk_rngs(obj, visit)


class ChainedTransformer(Transformer):
    def __init__(self, *stages: Transformer):
        flat = []
        for s in stages:
            if isinstance(s, ChainedTransformer):
                flat.extend(s.stages)
            else:
                flat.append(s)
        self.stages: Sequence[Transformer] = flat

    def apply_iter(self, it: Iterator[Any]) -> Iterator[Any]:
        for stage in self.stages:
            it = stage.apply_iter(it)
        return it

    def transform(self, sample: Any) -> Any:
        """Per-sample composition, so a chain can be wrapped by
        RandomTransformer (e.g. ``Random(Expand >> RoiExpand, 0.5)`` in the
        SSD train pipeline).  Only valid when every stage is 1→1."""
        for stage in self.stages:
            sample = stage.transform(sample)
        return sample


class Pipeline(ChainedTransformer):
    """List-style composition (the Python API's ``Pipeline([...])``,
    reference ``transform/vision/src/main/python/image.py:26``)."""

    def __init__(self, stages: Sequence[Transformer]):
        super().__init__(*stages)


class FnTransformer(Transformer):
    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn

    def transform(self, sample):
        return self.fn(sample)


class ParallelTransformer(Transformer):
    """Run a 1→1 transformer over a thread pool — the host-augmentation
    throughput answer to SURVEY.md §7.3 ("ColorJitter/RandomSampler per
    image on CPU can starve a v5e host").

    The reference parallelises the same work by cloning the transformer
    once per Spark executor core (``common/Predictor.scala:82-86``,
    ``RoiImageSeqGenerator.scala`` multi-threaded writer); here each pool
    thread lazily ``clone()``s the inner transformer so RNG and scratch
    buffers stay thread-private.  OpenCV/NumPy release the GIL, so threads
    give real parallelism without pickling images across processes.
    Output order is preserved (a bounded sliding window of futures, so
    memory stays O(workers + lookahead)).
    """

    def __init__(self, inner: Transformer, workers: int = 8,
                 max_pending: Optional[int] = None):
        self.inner = inner
        self.workers = max(1, workers)
        self.max_pending = max_pending or 2 * self.workers

    def apply_iter(self, it: Iterator[Any]) -> Iterator[Any]:
        if self.workers == 1:
            yield from self.inner.apply_iter(it)
            return
        local = threading.local()

        def run(sample):
            t = getattr(local, "t", None)
            if t is None:
                t = local.t = self.inner.clone()
            return t.transform(sample)

        with ThreadPoolExecutor(self.workers) as ex:
            pending: deque = deque()
            for sample in it:
                pending.append(ex.submit(run, sample))
                if len(pending) >= self.max_pending:
                    out = pending.popleft().result()
                    if out is not None:
                        yield out
            while pending:
                out = pending.popleft().result()
                if out is not None:
                    yield out


class RandomTransformer(Transformer):
    """Apply the wrapped transformer with probability ``prob`` (reference
    ``RandomTransformer``, ``image/Types.scala:232`` — e.g.
    ``Random(Expand -> RoiExpand, 0.5)`` in the SSD train chain)."""

    def __init__(self, inner: Transformer, prob: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.inner = inner
        self.prob = prob
        self.rng = rng or random.Random()

    def transform(self, sample):
        if self.rng.random() < self.prob:
            return self.inner.transform(sample)
        return sample


class ShuffleBuffer(Transformer):
    """Streaming record-level shuffle with a bounded ``buffer_size``
    window (the tf.data ``shuffle()`` pattern): fill the buffer, then for
    every incoming sample emit a uniformly-drawn buffered one and replace
    it.  Replaces the global shuffle the reference got for free from
    Spark RDD repartitioning — a full in-memory shuffle is impossible for
    multi-GB record sets on a TPU host, a windowed one is O(buffer).

    Approximation quality scales with ``buffer_size``; combine with
    ``shuffle_files=True`` on the record source so the window isn't
    limited to one shard's ordering."""

    def __init__(self, buffer_size: int = 1024,
                 rng: Optional[random.Random] = None):
        if buffer_size < 1:
            raise ValueError("buffer_size must be >= 1")
        self.buffer_size = buffer_size
        self.rng = rng or random.Random()

    def transform(self, sample: Any) -> Any:
        raise TypeError(
            "ShuffleBuffer is a stream (many-to-many) transformer; it "
            "cannot run per-sample inside ParallelTransformer or a "
            "per-sample chain — attach it with DataSet.shuffle()/"
            ".transform() directly")

    def apply_iter(self, it: Iterator[Any]) -> Iterator[Any]:
        buf: list = []
        for sample in it:
            if len(buf) < self.buffer_size:
                buf.append(sample)
                continue
            j = self.rng.randrange(self.buffer_size)
            buf[j], sample = sample, buf[j]
            yield sample
        self.rng.shuffle(buf)
        yield from buf
