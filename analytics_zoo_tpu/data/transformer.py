"""Iterator transformers — the reference's core data-pipeline abstraction.

BigDL's ``Transformer[A,B]`` is an ``Iterator[A] => Iterator[B]`` composed
with ``->`` (reference ``transform/vision/.../image/Types.scala:167-217``,
``ssd/Utils.scala:59-69``).  Here the same combinator algebra is plain
Python: subclasses override ``transform`` (1→1), ``apply_iter`` (full
stream), compose with ``>>`` (the ``->`` of the reference), and are cheaply
``clone()``-able so parallel workers get independent RNG/scratch state
(reference ``cloneTransformer``, ``common/Predictor.scala:82-86``).
"""

from __future__ import annotations

import copy
import random
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence


class Transformer:
    """Base: override ``transform(sample)`` or ``apply_iter(iterator)``."""

    def transform(self, sample: Any) -> Any:
        return sample

    def apply_iter(self, it: Iterator[Any]) -> Iterator[Any]:
        for sample in it:
            out = self.transform(sample)
            if out is not None:
                yield out

    def __call__(self, data: Iterable[Any]) -> Iterator[Any]:
        return self.apply_iter(iter(data))

    def __rshift__(self, other: "Transformer") -> "ChainedTransformer":
        """``a >> b``: feed a's output stream into b (BigDL ``->``)."""
        return ChainedTransformer(self, other)

    def clone(self) -> "Transformer":
        return copy.deepcopy(self)


class ChainedTransformer(Transformer):
    def __init__(self, *stages: Transformer):
        flat = []
        for s in stages:
            if isinstance(s, ChainedTransformer):
                flat.extend(s.stages)
            else:
                flat.append(s)
        self.stages: Sequence[Transformer] = flat

    def apply_iter(self, it: Iterator[Any]) -> Iterator[Any]:
        for stage in self.stages:
            it = stage.apply_iter(it)
        return it

    def transform(self, sample: Any) -> Any:
        """Per-sample composition, so a chain can be wrapped by
        RandomTransformer (e.g. ``Random(Expand >> RoiExpand, 0.5)`` in the
        SSD train pipeline).  Only valid when every stage is 1→1."""
        for stage in self.stages:
            sample = stage.transform(sample)
        return sample


class Pipeline(ChainedTransformer):
    """List-style composition (the Python API's ``Pipeline([...])``,
    reference ``transform/vision/src/main/python/image.py:26``)."""

    def __init__(self, stages: Sequence[Transformer]):
        super().__init__(*stages)


class FnTransformer(Transformer):
    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn

    def transform(self, sample):
        return self.fn(sample)


class RandomTransformer(Transformer):
    """Apply the wrapped transformer with probability ``prob`` (reference
    ``RandomTransformer``, ``image/Types.scala:232`` — e.g.
    ``Random(Expand -> RoiExpand, 0.5)`` in the SSD train chain)."""

    def __init__(self, inner: Transformer, prob: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.inner = inner
        self.prob = prob
        self.rng = rng or random.Random()

    def transform(self, sample):
        if self.rng.random() < self.prob:
            return self.inner.transform(sample)
        return sample
