"""ctypes bindings for the native data path (``native/azrecord.cpp``).

The reference rides native code for its data hot loops (OpenCV JNI decode,
SequenceFile IO — SURVEY.md §2.6); this module is the equivalent binding
layer: a multithreaded C++ record reader and libjpeg BGR decode.  Every
entry point degrades gracefully to the pure-Python implementations in
``data.records`` / cv2 when the shared library isn't built, so the
framework works everywhere and goes fast where the native lib exists.

Build once per machine: ``make -C native`` or :func:`build`.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Iterator, List, Optional, Sequence

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libazrecord.so")

_lib: Optional[ctypes.CDLL] = None
_lib_missing = False   # negative probe cache: don't stat() per image


def build(quiet: bool = True) -> str:
    """Compile the native library (g++ + libjpeg, no external deps)."""
    global _lib_missing
    subprocess.run(["make", "-C", _NATIVE_DIR],
                   check=True, capture_output=quiet)
    _lib_missing = False
    return _LIB_PATH


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_missing
    if _lib is not None:
        return _lib
    if _lib_missing:
        return None
    if not os.path.exists(_LIB_PATH):
        _lib_missing = True
        return None
    lib = ctypes.CDLL(_LIB_PATH)
    lib.az_reader_open.restype = ctypes.c_void_p
    lib.az_reader_open.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
        ctypes.c_int]
    lib.az_reader_next.restype = ctypes.c_long
    lib.az_reader_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
    lib.az_buffer_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.az_reader_close.argtypes = [ctypes.c_void_p]
    lib.az_decode_jpeg.restype = ctypes.c_int
    lib.az_decode_jpeg.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_long,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int)]
    lib.az_count_records.restype = ctypes.c_long
    lib.az_count_records.argtypes = [ctypes.c_char_p]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


class NativeRecordReader:
    """Threaded reader over sharded .azr files; yields payload bytes.

    Intra-file record order is preserved per thread; cross-file order is
    nondeterministic with ``n_threads > 1`` (fine for training; use one
    thread for deterministic evaluation order).
    """

    def __init__(self, paths: Sequence[str], n_threads: int = 4,
                 queue_capacity: int = 128):
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "native library not built — run make -C native or use the "
                "pure-Python data.records reader")
        self._lib = lib
        arr = (ctypes.c_char_p * len(paths))(
            *[p.encode("utf-8") for p in paths])
        self._handle = lib.az_reader_open(arr, len(paths), n_threads,
                                          queue_capacity)
        if not self._handle:
            raise ValueError("az_reader_open failed (no paths?)")

    def __iter__(self) -> Iterator[bytes]:
        out = ctypes.POINTER(ctypes.c_uint8)()
        while True:
            n = self._lib.az_reader_next(self._handle, ctypes.byref(out))
            if n < 0:
                return
            try:
                yield ctypes.string_at(out, n)
            finally:
                self._lib.az_buffer_free(out)

    def close(self) -> None:
        if self._handle:
            self._lib.az_reader_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _exif_orientation(data: bytes) -> int:
    """EXIF Orientation tag (1..8; 1 = upright) from raw JPEG bytes."""
    try:
        if data[:2] != b"\xff\xd8":
            return 1
        i = 2
        while i + 4 <= len(data):
            if data[i] != 0xFF:
                return 1
            marker = data[i + 1]
            if marker == 0xD9 or marker == 0xDA:
                return 1
            size = int.from_bytes(data[i + 2:i + 4], "big")
            if marker == 0xE1 and data[i + 4:i + 10] == b"Exif\x00\x00":
                tiff = i + 10
                bo = "little" if data[tiff:tiff + 2] == b"II" else "big"
                ifd = tiff + int.from_bytes(data[tiff + 4:tiff + 8], bo)
                n = int.from_bytes(data[ifd:ifd + 2], bo)
                for k in range(n):
                    e = ifd + 2 + k * 12
                    if int.from_bytes(data[e:e + 2], bo) == 0x0112:
                        v = int.from_bytes(data[e + 8:e + 10], bo)
                        return v if 1 <= v <= 8 else 1
                return 1
            i += 2 + size
    except Exception:
        pass
    return 1


def _apply_orientation(arr: np.ndarray, o: int) -> np.ndarray:
    if o == 2:
        return arr[:, ::-1]
    if o == 3:
        return arr[::-1, ::-1]
    if o == 4:
        return arr[::-1]
    if o == 5:
        return np.transpose(arr, (1, 0, 2))
    if o == 6:
        return np.rot90(arr, 3)
    if o == 7:
        return np.transpose(arr, (1, 0, 2))[::-1, ::-1]
    if o == 8:
        return np.rot90(arr, 1)
    return arr


def decode_jpeg(data: bytes) -> Optional[np.ndarray]:
    """JPEG bytes → (H, W, 3) BGR uint8 via libjpeg; None on decode failure
    or when the native lib is unavailable (callers fall back to cv2).

    EXIF orientation is applied, matching cv2.imdecode's behavior so the
    native and fallback paths produce identically-oriented mats.
    """
    lib = _load()
    if lib is None:
        return None
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
    out = ctypes.POINTER(ctypes.c_uint8)()
    w = ctypes.c_int()
    h = ctypes.c_int()
    c = ctypes.c_int()
    rc = lib.az_decode_jpeg(buf, len(data), ctypes.byref(out),
                            ctypes.byref(w), ctypes.byref(h), ctypes.byref(c))
    if rc != 0:
        return None
    try:
        arr = np.ctypeslib.as_array(out, shape=(h.value, w.value, c.value))
        orientation = _exif_orientation(data)
        if orientation != 1:
            return np.ascontiguousarray(_apply_orientation(arr, orientation))
        return arr.copy()
    finally:
        lib.az_buffer_free(out)


def count_records(path: str) -> int:
    lib = _load()
    if lib is None:
        from analytics_zoo_tpu.data.records import read_records
        return sum(1 for _ in read_records(path))
    return int(lib.az_count_records(path.encode("utf-8")))
