"""Data layer: iterator transformers, datasets, record IO, batching, prefetch.

TPU-native replacement for the reference's BigDL ``Transformer``/``DataSet``
/ Hadoop-SequenceFile stack (SURVEY.md §2.2 "Dataset / IO", §2.7 "Data
pipeline").
"""

from analytics_zoo_tpu.data.transformer import (
    ChainedTransformer,
    FnTransformer,
    ParallelTransformer,
    Pipeline,
    RandomTransformer,
    ShuffleBuffer,
    Transformer,
)
from analytics_zoo_tpu.data.dataset import (
    Batcher,
    DataSet,
    default_collate,
    pad_ragged,
)
from analytics_zoo_tpu.data.bucket import (
    BucketBatcher,
    padding_efficiency,
)
from analytics_zoo_tpu.data.records import (
    RecordWriter,
    SSDByteRecord,
    read_records,
    read_ssd_records,
    shard_paths,
    write_ssd_records,
)
from analytics_zoo_tpu.data.prefetch import (PrefetchDataSet,
                                             device_prefetch,
                                             overlap_window)
from analytics_zoo_tpu.data.parallel import (ParallelLoader,
                                             elastic_resume_coordinates,
                                             make_input_pipeline,
                                             sample_rng,
                                             seed_rngs,
                                             stable_seed)
from analytics_zoo_tpu.data.synthetic import (
    SHAPE_CLASSES,
    generate_shapes_records,
    render_shapes_image,
)

__all__ = [k for k in dir() if not k.startswith("_")]
