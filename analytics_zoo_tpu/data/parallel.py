"""Multiprocess host input pipeline: decode + augmentation fan-out.

Every committed train sweep is host-bound (``bench.py``
host_bound_fraction 0.81-0.88): the device step waits on ONE Python
thread doing decode + augment + collate.  The reference got its input
throughput from Spark's coarse-grained executor parallelism (SURVEY §0);
the JAX-native equivalent here is a process pool feeding the device
asynchronously — the same host/accelerator split tf.data and Grain use.

Design (one producer ring per worker, order-preserving):

- The wrapped :class:`~analytics_zoo_tpu.data.dataset.DataSet` is split
  into *leading stream stages* (cheap, e.g. ``ShuffleBuffer``), the
  *per-sample chain* (the expensive decode/augment stages), and
  *trailing stream stages* (batchers).  Every worker iterates the raw
  source + leading stages identically (cheap byte reads), but applies
  the per-sample chain only to its own sample *groups* (group ``g``
  belongs to worker ``g % num_workers``), so the heavy work — JPEG
  decode, ColorJitter, RandomSampler — is done exactly once across the
  pool.  The parent merges groups back in order and applies the
  trailing stages, so batch boundaries, remainder handling and sample
  drops are byte-identical to the serial path.
- Groups travel through a per-worker **shared-memory ring**: ndarray
  payloads are extracted out-of-band (pickle protocol 5
  ``buffer_callback``) and memcpy'd through the ring slots — zero
  pickle on the hot path for array bytes; only the tiny structural
  metadata is pickled.  The ring is the ONLY channel (headers included,
  no pipes): a slot is published by releasing the ``items`` semaphore
  strictly AFTER the slot is fully written, so a worker killed mid-write
  can never leave a truncated message for the consumer to block on —
  the unreleased slot simply never becomes visible (a ``mp.Queue`` here
  measurably hangs the parent when SIGKILL lands mid pipe-write).
  Groups larger than a slot degrade gracefully to a spill file
  (counted).
- **Determinism**: each worker's base PRNG is seeded from ``(base_seed,
  epoch, shard)`` and every sample's augmentation RNG is then folded in
  from the sample's *global* stream index, so the batch stream is
  byte-identical for ANY worker count — including ``num_workers=0``
  (the in-process serial reference path), pinned by
  ``tests/test_parallel_loader.py``.
- **Worker death** flows into the PR-1 resilience taxonomy: a crashed
  worker is respawned (deterministic seeding lets it recompute from its
  next owed group) at most ``max_respawns`` times per epoch, after
  which :class:`~analytics_zoo_tpu.resilience.errors.PrefetchWorkerDied`
  (retryable) escalates to the supervisor.

Overlapped H2D: compose with :func:`~analytics_zoo_tpu.data.prefetch.
device_prefetch` (``make_input_pipeline`` below, or
``PrefetchDataSet(..., num_workers=N)``) so the sharded host→device
transfer of batch ``t+1`` — one packed uint8 transfer on the
``DeviceAugBatch(pack=True)`` path — overlaps the device step on ``t``.
"""

from __future__ import annotations

import hashlib
import logging
import multiprocessing as mp
import os
import pickle
import random
import shutil
import struct
import tempfile
import warnings
from typing import Any, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.data.transformer import (ChainedTransformer,
                                                ParallelTransformer,
                                                Transformer,
                                                walk_rngs)
from analytics_zoo_tpu.resilience.errors import PrefetchWorkerDied

logger = logging.getLogger("analytics_zoo_tpu")

_DEFAULT_SLOT_BYTES = 32 << 20
_POLL_S = 0.2


# ---------------------------------------------------------------------------
# Deterministic seeding
# ---------------------------------------------------------------------------


_SEEDABLE = (int, float, bool, str, bytes, type(None))


def stable_seed(*keys) -> int:
    """Stable 63-bit seed from scalar keys (process/run independent —
    Python's ``hash`` is salted, so it cannot be used here).  Keys are
    restricted to value-repr'd scalars (and tuples/lists of them): an
    arbitrary object's default repr embeds its ADDRESS, which would
    silently break the stability promise."""
    def check(k):
        if isinstance(k, (tuple, list)):
            for v in k:
                check(v)
        elif not isinstance(k, _SEEDABLE):
            raise TypeError(
                f"stable_seed keys must be int/float/bool/str/bytes/"
                f"None (or tuples of them), got {type(k).__name__} — "
                "an object repr would make the seed address-dependent")

    check(keys)
    h = hashlib.blake2s(repr(keys).encode())
    return struct.unpack("<q", h.digest()[:8])[0] & 0x7FFFFFFFFFFFFFFF


def seed_rngs(obj: Any, seed: int) -> None:
    """Deterministically seed every ``random.Random`` /
    ``np.random.RandomState`` / ``np.random.Generator`` reachable from
    ``obj`` (the shared ``transformer.walk_rngs`` discovery walk, so
    this and ``clone()``'s entropy reseed can never drift)."""
    count = [0]

    def visit(rng):
        s = stable_seed(seed, count[0])
        count[0] += 1
        if isinstance(rng, random.Random):
            rng.seed(s)
        elif isinstance(rng, np.random.RandomState):
            rng.seed(s & 0xFFFFFFFF)
        else:   # np.random.Generator — rebuild with the Generator's OWN
            # bit-generator type (a Philox state assigned to a PCG64
            # raises ValueError)
            rng.bit_generator.state = type(rng.bit_generator)(s).state

    walk_rngs(obj, visit)


def _rng_signature(rng: Any) -> str:
    """Value-based fingerprint of an RNG's CURRENT state (stable across
    processes — no addresses).  Folding a leading stage's construction-
    time signature into its per-epoch seeding key preserves the user's
    own seed choice (e.g. ``DataSet.shuffle(seed=...)``): two loaders
    built with different shuffle seeds keep producing different
    streams, while the reseed still pins determinism per epoch."""
    if isinstance(rng, random.Random):
        return repr(rng.getstate())
    if isinstance(rng, np.random.RandomState):
        kind, keys, pos, has_g, g = rng.get_state()
        return f"{kind}:{keys.tobytes().hex()}:{pos}:{has_g}:{g}"
    return repr(rng.bit_generator.state)        # np.random.Generator


def stream_stage_keys(leading: Sequence[Transformer]) -> List[str]:
    """One seeding key per leading stream stage, capturing the stage
    index and its RNGs' construction-time state signatures."""
    keys = []
    for i, stage in enumerate(leading):
        sigs: List[str] = []
        walk_rngs(stage, lambda r: sigs.append(_rng_signature(r)))
        keys.append(f"{i}:{':'.join(sigs)}")
    return keys


#: Process-local numpy Generator for per-sample transform randomness —
#: the sanctioned replacement for drawing from numpy's process-GLOBAL
#: RNG (which ``seed_sample`` historically ``np.random.seed``-ed per
#: sample; az-analyze's seeded-rng-only rule now bans both the global
#: seed and global draws: global state any import can perturb is
#: exactly what the byte-identical-for-any-worker-count contract cannot
#: be built on).  ``seed_sample`` rewinds THIS Generator from
#: ``(base_seed, epoch, sample_index)`` in whichever process runs the
#: chain, so a transform drawing from ``sample_rng()`` sees the same
#: stream in a forked worker, a respawned worker, and the serial
#: reference.
_SAMPLE_RNG = np.random.Generator(np.random.PCG64(0))


def sample_rng() -> np.random.Generator:
    """The per-sample-seeded local Generator for transform chains."""
    return _SAMPLE_RNG


def seed_sample(chain: Optional[Sequence[Transformer]], base_seed: int,
                epoch: int, index: int) -> None:
    """Pin ALL randomness for one sample's trip through the chain.

    The vision transforms draw from the module-level ``random`` (and the
    samplers derive their numpy Generators from it), numpy consumers
    draw from the loader's local :func:`sample_rng`, and chain-held RNG
    instances are reseeded by ``seed_rngs`` — all from ``(base_seed,
    epoch, sample_index)``, so the augmentation decisions are a pure
    function of the sample's stream position, independent of which
    worker (or thread, or respawn attempt) runs it.  The numpy GLOBAL
    RNG is deliberately left alone."""
    s = stable_seed("sample", base_seed, epoch, index)
    random.seed(s)
    _SAMPLE_RNG.bit_generator.state = np.random.PCG64(s).state
    if chain:
        seed_rngs(chain, stable_seed("chain", base_seed, epoch, index))


# ---------------------------------------------------------------------------
# Stage classification
# ---------------------------------------------------------------------------


def _is_per_sample(stage: Transformer) -> bool:
    """True when ``stage`` is a 1->1 transformer (safe to run per sample
    inside a worker): it overrides ``transform`` and keeps the base
    streaming ``apply_iter`` (chains of such stages count too)."""
    if isinstance(stage, ParallelTransformer):
        return _is_per_sample(stage.inner)
    if isinstance(stage, ChainedTransformer):
        return all(_is_per_sample(s) for s in stage.stages)
    cls = type(stage)
    return (cls.transform is not Transformer.transform
            and cls.apply_iter is Transformer.apply_iter)


def _flatten_per_sample(stage: Transformer) -> List[Transformer]:
    """Unwrap a per-sample stage into its atomic 1->1 transformers:
    ``ParallelTransformer`` wrappers dissolve (the process pool replaces
    the thread pool) and chains flatten — at EVERY nesting level, so a
    wrapper nested inside a chain can never survive into the worker
    chain where its base-class identity ``transform`` would silently
    skip the wrapped work."""
    if isinstance(stage, ParallelTransformer):
        return _flatten_per_sample(stage.inner)
    if isinstance(stage, ChainedTransformer):
        out: List[Transformer] = []
        for s in stage.stages:
            out.extend(_flatten_per_sample(s))
        return out
    return [stage]


def split_stages(stages: Sequence[Transformer]
                 ) -> Tuple[List[Transformer], List[Transformer],
                            List[Transformer]]:
    """(leading stream stages, per-sample chain stages, trailing stages).

    ``ParallelTransformer`` wrappers are unwrapped — the process pool
    replaces the thread pool.  Everything from the first per-sample
    stage up to the next stream stage becomes the worker chain; the
    remainder (batchers etc.) runs in the parent."""
    leading: List[Transformer] = []
    chain: List[Transformer] = []
    trailing: List[Transformer] = []
    for stage in stages:
        if isinstance(stage, ParallelTransformer):
            stage = stage.inner
        if trailing:
            trailing.append(stage)
        elif _is_per_sample(stage):
            chain.extend(_flatten_per_sample(stage))
        elif chain:
            trailing.append(stage)
        else:
            leading.append(stage)
    return leading, chain, trailing


def _apply_chain(chain: Sequence[Transformer], sample: Any) -> Any:
    """Per-sample chain application with the streaming drop semantics:
    a ``None`` from any stage drops the sample (base ``apply_iter``)."""
    for stage in chain:
        sample = stage.transform(sample)
        if sample is None:
            return None
    return sample


# ---------------------------------------------------------------------------
# Shared-memory ring (headers + payload; crash-atomic, no pipes)
# ---------------------------------------------------------------------------

_KIND_GRP = 0
_KIND_END = 1
_KIND_ERR = 2
_KIND_SPILL = 3
# u32 kind | u64 idx | u64 meta_len | u32 nbufs  (then nbufs u64 lens,
# meta bytes, payload bytes — all inside one slot)
_HDR = struct.Struct("<IQQI")


class _Ring:
    """Single-producer single-consumer shared-memory ring.

    ``slots`` fixed-size slots used strictly round-robin; ``free``
    counts writable slots (producer acquires before writing), ``items``
    counts published slots (released only after a slot is COMPLETELY
    written — the crash-atomicity invariant: a producer killed at any
    instant leaves either a fully-published slot or an invisible one,
    never a truncated message).  The consumer copies out, then releases
    ``free``.  No pipes anywhere, so a SIGKILLed producer cannot wedge
    the consumer in a blocking read."""

    def __init__(self, ctx, slots: int, slot_bytes: int, spill_dir: str):
        from multiprocessing import shared_memory

        self.slots = slots
        self.slot_bytes = slot_bytes
        self.spill_dir = spill_dir
        self.shm = shared_memory.SharedMemory(create=True,
                                              size=slots * slot_bytes)
        self.free = ctx.Semaphore(slots)
        self.items = ctx.Semaphore(0)
        self.seq = 0            # producer- and consumer-side slot cursor

    def close(self) -> None:
        try:
            self.shm.close()
            self.shm.unlink()
        except Exception:
            pass

    # -- producer side (worker process) -----------------------------------
    def _write_slot(self, kind: int, idx: int, meta: bytes,
                    lens: Sequence[int], payload: Sequence) -> None:
        base = (self.seq % self.slots) * self.slot_bytes
        buf = self.shm.buf
        _HDR.pack_into(buf, base, kind, idx, len(meta), len(lens))
        off = base + _HDR.size
        for n in lens:
            struct.pack_into("<Q", buf, off, n)
            off += 8
        buf[off:off + len(meta)] = meta
        off += len(meta)
        for m in payload:
            buf[off:off + len(m)] = m
            off += len(m)
        self.seq += 1

    def put(self, kind: int, idx: int, meta: bytes, lens: Sequence[int],
            payload: Sequence, stop_event) -> bool:
        """Publish one message; False when cancelled via ``stop_event``."""
        need = _HDR.size + 8 * len(lens) + len(meta) + sum(lens)
        if need > self.slot_bytes:
            raise ValueError(
                f"message needs {need} bytes > slot_bytes={self.slot_bytes}"
                " (spill should have caught this)")
        while not self.free.acquire(timeout=_POLL_S):
            if stop_event.is_set():
                return False
        self._write_slot(kind, idx, meta, lens, payload)
        self.items.release()          # publish — ONLY after a full write
        return True

    def put_group(self, group_idx: int, samples: List[Any],
                  stop_event) -> Tuple[bool, bool]:
        """Ship one group of transformed samples.  Returns (ok,
        spilled): ndarray payloads go out-of-band through the slot;
        oversize groups degrade to a spill file referenced from the
        slot (written and fsync'd BEFORE the slot publishes, so the
        crash-atomicity invariant holds for them too)."""
        raw: List[memoryview] = []

        def grab(b) -> bool:
            # a falsy return serializes OUT-of-band (we captured the
            # buffer); True keeps a non-contiguous buffer in-band
            try:
                raw.append(b.raw())
                return False
            except BufferError:
                return True

        meta = pickle.dumps(samples, protocol=5, buffer_callback=grab)
        lens = [len(m) for m in raw]
        need = _HDR.size + 8 * len(lens) + len(meta) + sum(lens)
        if need <= self.slot_bytes:
            return (self.put(_KIND_GRP, group_idx, meta, lens, raw,
                             stop_event), False)
        # spill file carries meta AND payload: a group whose IN-BAND
        # pickle alone exceeds the slot (e.g. raw JPEG bytes objects)
        # must degrade the same way as one with big ndarray buffers
        path = os.path.join(self.spill_dir,
                            f"spill-{os.getpid()}-{group_idx}.bin")
        with open(path, "wb") as f:
            f.write(meta)
            for m in raw:
                f.write(m)
            f.flush()
            os.fsync(f.fileno())
        blob = pickle.dumps((len(meta), lens, path))
        return (self.put(_KIND_SPILL, group_idx, blob, (), (),
                         stop_event), True)

    # -- consumer side (parent) --------------------------------------------
    def get(self, timeout: float):
        """One published message or None on timeout: (kind, idx, obj)
        where obj is the unpickled group for GRP/SPILL, the pickled
        payload bytes for ERR, and None for END."""
        if not self.items.acquire(timeout=timeout):
            return None
        base = (self.seq % self.slots) * self.slot_bytes
        buf = self.shm.buf
        kind, idx, meta_len, nbufs = _HDR.unpack_from(buf, base)
        off = base + _HDR.size
        lens = []
        for _ in range(nbufs):
            lens.append(struct.unpack_from("<Q", buf, off)[0])
            off += 8
        meta = bytes(buf[off:off + meta_len])
        off += meta_len
        if kind == _KIND_GRP:
            bufs = []
            for n in lens:
                bufs.append(bytearray(buf[off:off + n]))    # copy out
                off += n
            self.seq += 1
            self.free.release()
            return kind, idx, pickle.loads(meta, buffers=bufs)
        self.seq += 1
        self.free.release()
        if kind == _KIND_SPILL:
            meta_len, s_lens, path = pickle.loads(meta)
            with open(path, "rb") as f:
                # bytearray: reconstructed arrays must be WRITABLE like
                # the ring path's (immutable bytes would make in-place
                # mutation fail only on groups that happened to spill)
                data = bytearray(f.read())
            os.unlink(path)
            view = memoryview(data)
            bufs, off2 = [], meta_len
            for n in s_lens:
                bufs.append(view[off2:off2 + n])
                off2 += n
            return _KIND_SPILL, idx, pickle.loads(view[:meta_len],
                                                  buffers=bufs)
        if kind == _KIND_ERR:
            return kind, idx, meta
        return kind, idx, None


# ---------------------------------------------------------------------------
# Worker process
# ---------------------------------------------------------------------------


def _advance_source_epochs(source_fn, n: int) -> None:
    """Fast-forward a DataSet source's per-epoch closure state by ``n``
    epochs.  Every DataSet constructor advances its epoch counter inside
    the generator body, so creating the generator and pulling ONE item
    is enough to step the state without reading the whole epoch."""
    for _ in range(n):
        it = source_fn()
        next(iter(it), None)


def _worker_main(worker_id: int, num_workers: int, epoch: int,
                 start_group: int, ring: _Ring, stop_event,
                 source_fn, leading: List[Transformer],
                 stream_keys: List[str],
                 chain: List[Transformer], group_size: int,
                 base_seed: int) -> None:
    """Producer body (runs in a forked child; must never touch jax).

    Iterates the full raw stream (cheap), transforms only the groups
    owned by this shard, and ships them through the ring.  All
    randomness is pinned: worker-level RNGs from ``(base_seed, epoch,
    shard)``, per-sample RNGs folded in from the global stream index."""
    try:
        # per-worker base PRNG: worker-local decisions (none on the hot
        # path today, but the contract is part of the API)
        random.seed(stable_seed("worker", base_seed, epoch, worker_id))
        for stage, key in zip(leading, stream_keys):
            seed_rngs(stage, stable_seed("stream", base_seed, epoch, key))
        it: Iterator[Any] = iter(source_fn())
        for stage in leading:
            it = stage.apply_iter(it)

        group: List[Any] = []
        g = 0
        idx = 0
        mine = (g % num_workers == worker_id) and g >= start_group

        warned = [False]

        def flush() -> bool:
            if mine:
                ok, spilled = ring.put_group(g, group, stop_event)
                if spilled and not warned[0]:
                    warned[0] = True
                    logger.warning(
                        "input worker %d: group %d exceeded slot_bytes; "
                        "spilling to disk (size the ring slots to the "
                        "batch — further spills not logged)", worker_id, g)
                return ok
            return True

        for sample in it:
            if stop_event.is_set():
                return
            if mine:
                seed_sample(chain, base_seed, epoch, idx)
                out = _apply_chain(chain, sample)
                if out is not None:
                    group.append(out)
            idx += 1
            if idx % group_size == 0:
                if not flush():
                    return
                group = []
                g += 1
                mine = ((g % num_workers == worker_id)
                        and g >= start_group)
        if idx % group_size:
            if not flush():
                return
            g += 1
        ring.put(_KIND_END, g, b"", (), (), stop_event)
    except BaseException as e:  # noqa: BLE001 - shipped to the parent
        import traceback

        tb = traceback.format_exc()
        try:
            payload = pickle.dumps((e, tb))
        except Exception:
            payload = pickle.dumps((None, tb))
        try:
            ring.put(_KIND_ERR, 0, payload, (), (), stop_event)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# The loader
# ---------------------------------------------------------------------------


class ParallelLoader:
    """Order-preserving multiprocess loader over a ``DataSet``.

    ``num_workers=0`` runs the SAME deterministically-seeded pipeline
    in-process (the serial reference the parallel stream is pinned
    byte-identical to); ``num_workers>0`` fans the per-sample chain out
    to forked worker processes with shared-memory rings.

    One live iterator at a time: each ``iter()`` call starts a new
    epoch (advancing the shuffle state exactly like serial epochs do)
    and owns the worker pool until exhausted or ``.close()``d.

    Note on shared RNGs: the vision/augment transforms draw from the
    process-global ``random`` (pre-existing design) and numpy consumers
    from the loader-local :func:`sample_rng` Generator, so pinning them
    means ``seed_sample`` reseeds both per sample in whichever process
    runs the chain (numpy's process-GLOBAL RNG is never touched —
    seeded-rng-only rule).  With ``num_workers>0`` that is a forked
    worker; with ``num_workers=0`` it is THIS process (the prefetch
    thread, when composed with ``device_prefetch``) — code that draws
    from those RNGs concurrently with a serial-mode epoch will see
    sample-pinned values, exactly as it already would next to a
    ``ParallelTransformer`` thread pool.
    """

    def __init__(self, dataset, num_workers: int = 0, *,
                 base_seed: int = 0, group_size: Optional[int] = None,
                 slots: int = 4, slot_bytes: int = _DEFAULT_SLOT_BYTES,
                 max_respawns: int = 2, start_epoch: int = 0):
        if num_workers < 0:
            raise ValueError(f"num_workers must be >= 0, got {num_workers}")
        if num_workers > 0 and not getattr(dataset, "_order_deterministic",
                                           True):
            # every worker replays the raw stream independently; a
            # nondeterministically-ordered source (native_threads>0
            # record reader) would give each worker a DIFFERENT order
            # and the group partition would silently duplicate/drop
            # samples — refuse instead of corrupting the stream
            raise ValueError(
                "ParallelLoader(num_workers>0) requires a source with "
                "reproducible iteration order; this dataset's source is "
                "marked nondeterministic (e.g. from_record_files with "
                "native_threads>0) — use native_threads=0 or "
                "num_workers=0")
        self.dataset = dataset
        self.num_workers = num_workers
        self.base_seed = base_seed
        self.slots = max(2, slots)
        self.slot_bytes = slot_bytes
        self.max_respawns = max_respawns
        self._epoch = start_epoch
        if start_epoch:
            # resume contract (mid-epoch checkpoint restart): the caller
            # hands a FRESHLY-constructed dataset plus the checkpointed
            # epoch, and the loader owns BOTH halves of the coordinate —
            # the per-epoch seeding keys (stable_seed folds the epoch
            # index) AND the source's own per-epoch closure state
            # (e.g. from_arrays' reshuffle counter), which replay_batches
            # always had to advance by hand.  Without this, a resumed
            # process replays epoch 0's sample ORDER under epoch N's
            # seeds — a silently different stream.
            _advance_source_epochs(self.dataset._source_fn, start_epoch)
        self.leading, self.chain, self.trailing = split_stages(
            dataset._stages)
        # construction-time RNG signatures: the per-epoch reseed of
        # leading stream stages folds in the user's own seed choice
        self._stream_keys = stream_stage_keys(self.leading)
        if group_size is None:
            group_size = next((s.batch_size for s in self.trailing
                               if hasattr(s, "batch_size")), 32)
        self.group_size = max(1, int(group_size))
        # observability (tests + chaos drills read these)
        self.respawns = 0
        self.spills = 0
        #: epoch index of the most recently STARTED epoch (None before
        #: the first) — the anomaly sentinel records it as the replay
        #: coordinate of a bad batch (with base_seed + batch index, the
        #: determinism contract pins the batch; see replay_batches)
        self.last_epoch: Optional[int] = None
        self._procs: List[mp.Process] = []
        if num_workers > 0 and not hasattr(os, "fork"):  # pragma: no cover
            warnings.warn("platform lacks fork(); ParallelLoader falls "
                          "back to the serial path")
            self.num_workers = 0

    # -- public surface ----------------------------------------------------
    def __len__(self) -> int:
        return len(self.dataset)

    def worker_pids(self) -> List[int]:
        """Live worker PIDs of the current epoch (chaos drills)."""
        return [p.pid for p in self._procs if p.is_alive()]

    def __iter__(self) -> Iterator[Any]:
        if any(p.is_alive() for p in self._procs):
            # enforce the one-live-iterator contract: a second pool
            # would fork from the previous epoch's UN-advanced source
            # state (silent stream corruption) and clobber the first
            # pool's cleanup tracking
            raise RuntimeError(
                "previous epoch's worker pool is still live — exhaust "
                "or close() the prior iterator before starting a new "
                "epoch (ParallelLoader supports one live iterator)")
        epoch = self._epoch
        self._epoch += 1
        self.last_epoch = epoch
        if self.num_workers == 0:
            return self._serial_epoch(epoch)
        return self._apply_trailing(self._merged_samples(epoch))

    # -- serial reference path --------------------------------------------
    def _serial_epoch(self, epoch: int) -> Iterator[Any]:
        for stage, key in zip(self.leading, self._stream_keys):
            seed_rngs(stage, stable_seed("stream", self.base_seed, epoch,
                                         key))
        it: Iterator[Any] = iter(self.dataset._source_fn())
        for stage in self.leading:
            it = stage.apply_iter(it)

        def samples():
            for idx, sample in enumerate(it):
                seed_sample(self.chain, self.base_seed, epoch, idx)
                out = _apply_chain(self.chain, sample)
                if out is not None:
                    yield out

        return self._apply_trailing(samples())

    def _apply_trailing(self, it: Iterator[Any]) -> Iterator[Any]:
        for stage in self.trailing:
            it = stage.apply_iter(it)
        return it

    # -- parallel path ----------------------------------------------------
    def _spawn(self, ctx, worker_id: int, epoch: int, start_group: int,
               stop_event, spill_dir: str) -> Tuple[_Ring, mp.Process]:
        ring = _Ring(ctx, self.slots, self.slot_bytes, spill_dir)
        proc = ctx.Process(
            target=_worker_main,
            args=(worker_id, self.num_workers, epoch, start_group, ring,
                  stop_event, self.dataset._source_fn, self.leading,
                  self._stream_keys, self.chain, self.group_size,
                  self.base_seed),
            daemon=True)
        with warnings.catch_warnings():
            # CPython warns that fork + multithreaded jax may deadlock;
            # workers never touch jax (data/transform code only), which
            # is the specific hazard the warning is about
            warnings.filterwarnings(
                "ignore", message=".*fork.*", category=RuntimeWarning)
            proc.start()
        return ring, proc

    def _merged_samples(self, epoch: int) -> Iterator[Any]:
        ctx = mp.get_context("fork")
        stop_event = ctx.Event()
        W = self.num_workers
        spill_dir = tempfile.mkdtemp(prefix="azt-loader-")
        # forked children inherit the parent's source state verbatim, so
        # the parent must NOT consume the source itself this epoch; it
        # advances its copy once in the finally below, which keeps
        # serial epochs and parallel epochs interchangeable.
        rings: List[_Ring] = []
        procs: List[mp.Process] = []
        respawns_left = self.max_respawns
        for w in range(W):
            ring, proc = self._spawn(ctx, w, epoch, 0, stop_event,
                                     spill_dir)
            rings.append(ring)
            procs.append(proc)
        self._procs = procs
        try:
            g = 0
            total_groups: Optional[int] = None
            while total_groups is None or g < total_groups:
                w = g % W
                kind, payload = self._next_message(
                    ctx, w, g, epoch, rings, procs, stop_event, spill_dir,
                    respawns_left)
                if kind == "respawned":
                    respawns_left -= 1
                    continue
                if kind == "end":
                    total_groups = payload
                    continue   # re-check the loop condition (g == total)
                for sample in payload:
                    yield sample
                g += 1
        finally:
            # pool cleanup FIRST (a failing source advance must never
            # leave workers spinning on live rings)...
            stop_event.set()
            for proc in procs:
                proc.join(timeout=1.0)
                if proc.is_alive():
                    proc.terminate()
                    proc.join(timeout=5.0)
            for ring in rings:
                ring.close()
            shutil.rmtree(spill_dir, ignore_errors=True)
            self._procs = []
            # ...then advance the parent's copy of the source state by
            # one epoch, so serial and parallel epochs stay
            # interchangeable.  Workers (and respawns) always fork from
            # the UN-advanced state — respawns happen only inside the
            # loop, never after this point.
            _advance_source_epochs(self.dataset._source_fn, 1)

    def _next_message(self, ctx, w: int, g: int, epoch: int,
                      rings: List[_Ring], procs: List[mp.Process],
                      stop_event, spill_dir: str, respawns_left: int):
        """Wait for worker ``w``'s next ring message, handling death.

        Returns ("grp", samples) / ("end", total) / ("respawned", None).
        A dead worker with an empty ring is respawned from the group it
        still owes — deterministic seeding makes the respawn recompute
        the identical stream — until the respawn budget is exhausted,
        then PrefetchWorkerDied (retryable) escalates."""
        while True:
            msg = rings[w].get(timeout=_POLL_S)
            if msg is None:
                if procs[w].is_alive():
                    continue
                # dead — drain the publish-vs-death race window before
                # declaring the ring empty
                msg = rings[w].get(timeout=0.0)
                if msg is None:
                    if respawns_left <= 0:
                        raise PrefetchWorkerDied(
                            f"input worker {w} (pid {procs[w].pid}) died "
                            f"at group {g} with the respawn budget "
                            f"exhausted (max_respawns="
                            f"{self.max_respawns}) — input pipeline is "
                            "gone; restart the attempt")
                    logger.warning(
                        "input worker %d died (exitcode %s); respawning "
                        "from group %d (%d respawns left)", w,
                        procs[w].exitcode, g, respawns_left - 1)
                    rings[w].close()
                    ring, proc = self._spawn(ctx, w, epoch, g, stop_event,
                                             spill_dir)
                    rings[w] = ring
                    procs[w] = proc
                    self._procs = procs
                    self.respawns += 1
                    return "respawned", None
            kind, idx, obj = msg
            if kind == _KIND_ERR:
                try:
                    exc, tb = pickle.loads(obj)
                except Exception:
                    exc, tb = None, "<worker exception unpicklable — " \
                        "traceback lost in transit>"
                if exc is not None:
                    # chain the worker-side traceback (the parent-side
                    # raise alone would point only at this frame)
                    raise exc from RuntimeError(
                        f"input worker {w} traceback:\n{tb}")
                # unknown exception type: re-raise as a BARE RuntimeError
                # (NOT retryable PrefetchWorkerDied — a deterministic
                # programming error must propagate, never be retried;
                # docs/RESILIENCE.md fatal-propagation contract)
                raise RuntimeError(
                    f"input worker {w} raised an unpicklable exception:"
                    f"\n{tb}")
            if kind == _KIND_SPILL:
                self.spills += 1
                kind = _KIND_GRP
            if kind == _KIND_END:
                if idx > g:  # pragma: no cover - protocol bug
                    raise PrefetchWorkerDied(
                        f"worker {w} ended at group {idx} while group "
                        f"{g} was still owed")
                return "end", idx
            if idx != g:  # pragma: no cover - protocol bug
                raise PrefetchWorkerDied(
                    f"worker {w} sent group {idx}, expected {g}")
            return "grp", obj


# ---------------------------------------------------------------------------
# Deterministic replay (anomaly forensics re-seek hook)
# ---------------------------------------------------------------------------


def replay_batches(dataset, epoch: int, batch_indices: Sequence[int],
                   base_seed: int = 0, batch_transform=None):
    """Re-materialize exact batches of ``epoch`` under the determinism
    contract — the forensics hook behind ``tools/replay_batch.py``.

    ``dataset`` must be FRESHLY CONSTRUCTED (its source at epoch-0
    state): a :class:`ParallelLoader` (its own ``base_seed``/grouping
    win) or a bare ``DataSet`` (wrapped on the serial path with
    ``base_seed``).  The source is fast-forwarded ``epoch`` epochs, the
    per-epoch/per-sample RNGs are re-pinned exactly as the live run
    pinned them — for ANY worker count, including the failed run's —
    and the requested 0-based batch indices of that epoch are returned
    as ``{index: batch}``.  ``batch_transform(batch, index)``
    post-processes each batch (drills re-apply a recorded injected
    corruption here so the replayed bytes match the recorded hash).
    """
    if isinstance(dataset, ParallelLoader):
        loader = ParallelLoader(dataset.dataset, 0,
                                base_seed=dataset.base_seed,
                                group_size=dataset.group_size)
    else:
        loader = ParallelLoader(dataset, 0, base_seed=base_seed)
    want = sorted({int(i) for i in batch_indices})
    if not want:
        return {}
    _advance_source_epochs(loader.dataset._source_fn, epoch)
    out = {}
    for i, batch in enumerate(loader._serial_epoch(epoch)):
        if i in want:
            out[i] = (batch_transform(batch, i) if batch_transform
                      else batch)
        if i >= want[-1]:
            break
    missing = [i for i in want if i not in out]
    if missing:
        raise ValueError(
            f"epoch {epoch} ended before batch index(es) {missing} — "
            "wrong epoch coordinate, or the dataset was not freshly "
            "constructed (its source state already advanced)")
    return out


def elastic_resume_coordinates(epoch: int, samples_into_epoch: int,
                               global_batch: int):
    """Translate a checkpoint's GLOBAL stream coordinate into loader
    re-seek terms under a (possibly different) batch geometry.

    The deterministic stream is defined over the merged global SAMPLE
    sequence — per-sample seeds fold the global index (``seed_sample``),
    batching is a trailing stage — so the stream itself is independent
    of world size and worker count.  What changes across an elastic
    resize is only how many samples each BATCH carries: a run that
    checkpointed ``samples_into_epoch`` samples into ``epoch`` resumes
    on any geometry by constructing the loader with
    ``start_epoch=epoch`` and skipping ``samples_into_epoch //
    global_batch`` whole batches of the new stream.

    Returns ``(start_epoch, skip_batches)``.  Raises ``ValueError``
    when the saved offset does not land on a batch boundary of the new
    stream — resuming there would re-train (or silently drop) a partial
    batch, so the geometries are incompatible (pick a global batch that
    divides the offset, or resume at the old geometry).
    """
    if epoch < 0 or samples_into_epoch < 0 or global_batch < 1:
        raise ValueError(
            f"elastic_resume_coordinates: invalid coordinate (epoch="
            f"{epoch}, samples={samples_into_epoch}, batch={global_batch})")
    if samples_into_epoch % global_batch:
        raise ValueError(
            f"elastic resume: sample offset {samples_into_epoch} is not "
            f"a multiple of the new global batch {global_batch} — the "
            f"checkpoint boundary does not land on a batch boundary of "
            f"the resumed stream")
    return int(epoch), samples_into_epoch // global_batch


# ---------------------------------------------------------------------------
# Device-overlap composition
# ---------------------------------------------------------------------------


def make_input_pipeline(dataset, mesh, num_workers: int = 0,
                        prefetch: int = 2, base_seed: int = 0,
                        loader: Optional[ParallelLoader] = None,
                        **loader_kw):
    """One-stop host→device input pipeline: multiprocess decode/augment
    (``ParallelLoader``) composed with ``device_prefetch`` so the packed
    H2D transfer of batch ``t+1`` overlaps the device step on ``t``.

    Returns an iterable; each ``iter()`` is one epoch of device-resident
    sharded batches, staying ``prefetch`` batches ahead."""
    from analytics_zoo_tpu.data.prefetch import PrefetchDataSet

    if loader is None:
        loader = ParallelLoader(dataset, num_workers, base_seed=base_seed,
                                **loader_kw)
    return PrefetchDataSet(loader, mesh, size=prefetch)
