"""Rendered-shapes detection dataset — real JPEGs, known ground truth.

The environment has no network egress, so VOC/COCO can't be downloaded;
this generator stands in as the *real-data path* for benchmarks and
end-to-end accuracy runs: images are rendered with OpenCV, JPEG-encoded,
and written as ``.azr`` shards, so every host-side stage the reference
identifies as HOT LOOP #1 (SURVEY.md §3.1: decode, augmentation chain,
batching) runs exactly as it would on VOC.  Ground truth is exact by
construction, so a trained detector's mAP is a true end-to-end
correctness measurement of the whole train→eval stack (priors, matching,
loss, decode, NMS, mAP), in the spirit of the reference's golden-value
test style (SURVEY.md §4).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from analytics_zoo_tpu.data.records import SSDByteRecord, write_ssd_records

SHAPE_CLASSES = ("__background__", "rectangle", "ellipse", "triangle")


def _jpeg_encode(img: np.ndarray, quality: int = 92) -> bytes:
    import cv2

    ok, buf = cv2.imencode(".jpg", img, [cv2.IMWRITE_JPEG_QUALITY, quality])
    if not ok:
        raise RuntimeError("cv2.imencode failed")
    return bytes(buf.tobytes())


def render_shapes_image(rng: np.random.RandomState, resolution: int = 300,
                        max_shapes: int = 3,
                        n_classes: int = len(SHAPE_CLASSES) - 1,
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """One image: textured background + 1..max_shapes colored shapes.

    Returns (BGR uint8 image, gt matrix (N,6) of
    (label, difficult, x1, y1, x2, y2) in pixel coords — the
    ``SSDByteRecord`` layout).
    """
    import cv2

    res = resolution
    # low-frequency textured background (so JPEG statistics are realistic)
    base = rng.randint(0, 120, (res // 10, res // 10, 3), np.uint8)
    img = cv2.resize(base, (res, res), interpolation=cv2.INTER_CUBIC)
    img = cv2.GaussianBlur(img, (5, 5), 0)

    n = rng.randint(1, max_shapes + 1)
    gt: List[List[float]] = []
    for _ in range(n):
        cls = rng.randint(1, n_classes + 1)
        size = rng.randint(res // 6, res // 2)
        x1 = rng.randint(0, res - size)
        y1 = rng.randint(0, res - size)
        w = size
        h = rng.randint(int(size * 0.6), size + 1)
        y1 = min(y1, res - h)
        x2, y2 = x1 + w, y1 + h
        # bright, saturated color — contrasts the dark background
        color = tuple(int(c) for c in rng.randint(140, 256, 3))
        if cls == 1:                      # rectangle
            cv2.rectangle(img, (x1, y1), (x2, y2), color, -1)
        elif cls == 2:                    # ellipse
            cv2.ellipse(img, ((x1 + x2) // 2, (y1 + y2) // 2),
                        (w // 2, h // 2), 0, 0, 360, color, -1)
        else:                             # triangle
            pts = np.array([[(x1 + x2) // 2, y1], [x1, y2 - 1], [x2 - 1, y2 - 1]],
                           np.int32)
            cv2.fillPoly(img, [pts], color)
        gt.append([float(cls), 0.0, float(x1), float(y1),
                   float(x2 - 1), float(y2 - 1)])
    return img, np.asarray(gt, np.float32)


def generate_shapes_records(prefix: str, n_images: int = 800,
                            resolution: int = 300, num_shards: int = 4,
                            seed: int = 0, max_shapes: int = 3,
                            jpeg_quality: int = 92) -> List[str]:
    """Render → JPEG-encode → write ``.azr`` shards.  Returns shard paths."""
    rng = np.random.RandomState(seed)
    records = []
    for i in range(n_images):
        img, gt = render_shapes_image(rng, resolution, max_shapes)
        records.append(SSDByteRecord(data=_jpeg_encode(img, jpeg_quality),
                                     path=f"shapes/{i:06d}.jpg", gt=gt))
    return write_ssd_records(records, prefix, num_shards)
