"""Length-bucketed batching: pinned padded shapes, bounded waste.

The DS2 CTC train path pads every utterance to one global ``utt_length``
— on a realistic length distribution most of the padded tensor is zeros
(the RNN stack then *scans* those zeros).  :class:`BucketBatcher` groups
samples into a small FIXED set of padded-length buckets instead:

- **Compile-once shapes**: every emitted batch's time axis is one of
  ``bucket_edges``, so the jit cache warms once per bucket and stays
  warm (the same pinned-shape discipline as the SSD canvas staging).
- **Determinism**: bucket assignment is a pure function of the sample's
  own length, and batches are emitted the moment a bucket fills while
  iterating the (already deterministic) sample stream — so the batch
  stream is byte-identical for any ``ParallelLoader`` worker count, and
  ``data.parallel.replay_batches`` re-materializes a recorded batch from
  its ``(base_seed, epoch, index)`` coordinates unchanged.  The batcher
  is a stream (trailing) stage: it always runs in the parent process.
- **Waste accounting**: each batch carries per-row ``n_frames``; the
  train step reports ``padding_efficiency`` (valid / padded frames) in
  its metrics, and ``bench.py bench_ds2_train`` banks it per line.

Samples are dicts with ``pad_key`` holding a ``(n, D)`` array and
``length_key`` its true length ``n``.  A sample longer than the last
edge is truncated to it (counted in ``truncated``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.data.transformer import Transformer


class BucketBatcher(Transformer):
    """Batch a sample stream into fixed padded-length buckets.

    ``bucket_edges``: ascending padded lengths; a sample lands in the
    smallest bucket that fits it.  ``drop_remainder=False`` flushes
    partial buckets at end of stream in ascending-edge order (shapes
    stay pinned — only dim 0 shrinks).
    """

    def __init__(self, batch_size: int, bucket_edges: Sequence[int],
                 length_key: str = "n_frames", pad_key: str = "input",
                 drop_remainder: bool = True,
                 collate_fn: Optional[Callable] = None):
        edges = sorted(int(e) for e in bucket_edges)
        if not edges or any(e <= 0 for e in edges):
            raise ValueError(f"bucket_edges must be positive, got "
                             f"{bucket_edges!r}")
        if len(set(edges)) != len(edges):
            raise ValueError(f"duplicate bucket edges in {bucket_edges!r}")
        self.batch_size = int(batch_size)
        self.bucket_edges = edges
        self.length_key = length_key
        self.pad_key = pad_key
        self.drop_remainder = drop_remainder
        from analytics_zoo_tpu.data.dataset import default_collate
        self.collate_fn = collate_fn or default_collate
        #: samples truncated to the last edge (observability; reset per
        #: epoch by apply_iter)
        self.truncated = 0

    def _edge_for(self, n: int) -> int:
        return edge_for(n, self.bucket_edges)

    def _make_batch(self, edge: int, samples: List[Dict[str, Any]]):
        rows = []
        lengths = []
        for s in samples:
            arr = np.asarray(s[self.pad_key])
            n = min(int(s[self.length_key]), edge, arr.shape[0])
            padded = np.zeros((edge,) + arr.shape[1:], arr.dtype)
            padded[:n] = arr[:n]
            out = dict(s)
            out[self.pad_key] = padded
            out[self.length_key] = np.int32(n)
            rows.append(out)
            lengths.append(n)
        batch = self.collate_fn(rows)
        if isinstance(batch, dict):
            batch[self.length_key] = np.asarray(lengths, np.int32)
        return batch

    def apply_iter(self, it: Iterator[Any]) -> Iterator[Any]:
        self.truncated = 0
        buckets: Dict[int, List[Any]] = {e: [] for e in self.bucket_edges}
        for sample in it:
            n = int(sample[self.length_key])
            edge = self._edge_for(n)
            if n > edge:
                self.truncated += 1
            buckets[edge].append(sample)
            if len(buckets[edge]) == self.batch_size:
                yield self._make_batch(edge, buckets[edge])
                buckets[edge] = []
        if not self.drop_remainder:
            for edge in self.bucket_edges:
                if buckets[edge]:
                    yield self._make_batch(edge, buckets[edge])


def edge_for(n: int, edges: Sequence[int]) -> int:
    """Smallest bucket edge that fits length ``n`` (the last edge when
    none does — the caller truncates).  THE bucket-assignment rule:
    shared by the train-side :class:`BucketBatcher` and the serving
    batcher (``serving.batcher.DeadlineBatcher``), so online batches
    land on exactly the padded geometries training already compiled."""
    for e in edges:
        if n <= e:
            return e
    return edges[-1]


def padding_efficiency(n_frames, padded_len: int) -> float:
    """valid frames / padded frames for rows padded to ``padded_len`` —
    the host-side waste metric (``bench.py ds2_ragged`` banks it for the
    pad-to-max discipline).  The in-graph step metric re-derives the
    same ratio in jnp (``pipelines.deepspeech2.ds2_padding_metric``)."""
    n = np.asarray(n_frames)
    return float(n.sum()) / float(max(n.shape[0] * padded_len, 1))
