"""Graceful degradation ladder: trade answer quality for throughput
under sustained overload, then climb back.

Load shedding keeps the queue honest but every shed is a lost answer.
Before shedding hard, a serving cell can buy capacity by serving a
CHEAPER variant of the same model — the degradation tiers:

- tier 0: full quality (bf16/fp32 weights, full NMS top-K / beam);
- tier 1: int8 weights via the existing ``utils.quantize.
  quantize_params`` path (~4× less HBM traffic, measured 1.3× conv
  speedup, mAP delta +0.0001 — ``INT8_MAP_PARITY.json``);
- tier 2+: int8 plus reduced post-processing work (NMS ``keep_topk``,
  beam width) — bounded, explicit quality cuts.

Transitions use the SAME hysteresis discipline as the PR-3 anomaly
ladder's promote-after-M-clean-steps: ``down_after`` consecutive
overloaded decision windows step one tier down; ``up_after`` consecutive
clean windows step one tier up.  Asymmetric on purpose (``up_after`` >
``down_after`` by default): stepping down is cheap and urgent, stepping
up into still-marginal load re-creates the overload and makes the tier
oscillate — exactly the flapping the clean-window count suppresses.

The ladder is pure host state driven by ``observe_window``; what a tier
*means* (which forward fn, which top-K) is the runtime's business
(``ServingTier`` descriptors, built e.g. by
``pipelines.ssd.ssd_serving_tiers``).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("analytics_zoo_tpu")


@dataclasses.dataclass
class ServingTier:
    """Descriptor for one rung: a human-readable name, the per-replica
    forward callable factory's product (bound by the runtime), and the
    relative speed the batcher's service-time model may consult
    (1.0 = tier-0 time; int8 < 1).

    ``device_program`` (optional): a zero-arg thunk returning ``(fn,
    example_args, static_argnums)`` for the tier's underlying jitted
    device program — what ``az_analyze --program`` traces, so the
    static audit covers exactly the program this tier dispatches (the
    ``forward`` callable itself is a host closure with decode loops and
    cannot be traced).

    ``evict_session`` (streaming session tiers, ISSUE 14): drop one
    session's carry state from this tier instance's store — the
    runtime calls it on the pinned replica when a session dies without
    its final chunk ever being served (killed, shed, replica loss), so
    failed sessions don't leak their state on the replica."""

    name: str
    forward: Callable[[Dict[str, Any]], Any]
    speed: float = 1.0
    quality_note: str = ""
    device_program: Optional[Callable[[], tuple]] = None
    evict_session: Optional[Callable[[int], None]] = None


@dataclasses.dataclass
class LadderPolicy:
    """``down_after`` consecutive overloaded windows → one tier down
    (toward cheaper); ``up_after`` consecutive clean windows → one tier
    up.  A window is overloaded when the runtime observed any shed in it
    or its end-of-window queue depth exceeded ``depth_high`` batches'
    worth of work."""

    down_after: int = 2
    up_after: int = 4
    depth_high: int = 2     # in units of max_batch

    def __post_init__(self):
        if self.down_after < 1 or self.up_after < 1:
            raise ValueError("down_after/up_after must be >= 1")


class DegradationLadder:
    """Hysteresis state machine over overload observations.

    ``tier`` is the current rung index (0 = full quality, rising =
    cheaper).  ``events`` logs every transition with its window index —
    the drill pins engage/disengage against the configured hysteresis.
    """

    def __init__(self, n_tiers: int, policy: Optional[LadderPolicy] = None):
        if n_tiers < 1:
            raise ValueError("need at least one tier")
        self.n_tiers = int(n_tiers)
        self.policy = policy or LadderPolicy()
        self.tier = 0
        self.overloaded_streak = 0
        self.clean_streak = 0
        self.windows = 0
        self.events: List[Dict[str, Any]] = []

    def observe_window(self, overloaded: bool,
                       detail: Optional[Dict[str, Any]] = None) -> str:
        """Feed one decision window; returns ``"down"``, ``"up"`` or
        ``"hold"``.  Streaks reset on every transition so each further
        step needs a FULL fresh streak (step-at-a-time, like the anomaly
        ladder's rollback budget)."""
        self.windows += 1
        action = "hold"
        if overloaded:
            self.clean_streak = 0
            self.overloaded_streak += 1
            if (self.overloaded_streak >= self.policy.down_after
                    and self.tier < self.n_tiers - 1):
                self.tier += 1
                self.overloaded_streak = 0
                action = "down"
        else:
            self.overloaded_streak = 0
            self.clean_streak += 1
            if (self.clean_streak >= self.policy.up_after
                    and self.tier > 0):
                self.tier -= 1
                self.clean_streak = 0
                action = "up"
        if action != "hold":
            ev = {"kind": f"tier_{action}", "window": self.windows,
                  "tier": self.tier, **(detail or {})}
            self.events.append(ev)
            logger.warning("serving ladder: tier %s to %d (window %d)",
                           action, self.tier, self.windows)
        return action

    def observe_decision(self, decision,
                         detail: Optional[Dict[str, Any]] = None) -> str:
        """Feed one :class:`~analytics_zoo_tpu.obs.slo.SloDecision`
        instead of a raw overloaded flag — the SLO-driven decision
        input (PR 11): a window is overloaded when an SLO is *burning*
        on both burn-rate windows, not merely when a shed happened.
        The transition event records which SLOs drove it, so a banked
        drill can show the step-down was SLO-attributed."""
        d = {"slo_burning": list(decision.burning),
             "scale_hint": decision.scale_hint, **(detail or {})}
        return self.observe_window(decision.overloaded, detail=d)

    def snapshot(self) -> Dict[str, Any]:
        return {"tier": self.tier, "windows": self.windows,
                "overloaded_streak": self.overloaded_streak,
                "clean_streak": self.clean_streak,
                "transitions": list(self.events)}
