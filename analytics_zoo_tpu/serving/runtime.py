"""The online serving runtime: request-level API over the predictors.

Glues the pieces into one synchronous, clock-driven scheduler:

- :class:`~analytics_zoo_tpu.serving.request.AdmissionQueue` — bounded,
  EDF, shed-before-dispatch;
- :class:`~analytics_zoo_tpu.serving.batcher.DeadlineBatcher` — flush on
  full-or-urgent over pre-compiled geometries only;
- :class:`~analytics_zoo_tpu.serving.replica.ReplicaPool` — StallWatchdog
  supervision, fence, exactly-once failover, background restart;
- :class:`~analytics_zoo_tpu.serving.ladder.DegradationLadder` — tier
  step-down under sustained overload, hysteresis step-up;
- :class:`~analytics_zoo_tpu.serving.metrics.ServingMetrics` — the
  snapshot dict the drill banks.

Single-threaded on purpose: every scheduling decision happens inside
:meth:`ServingRuntime.pump`, reading time ONLY through the injected
clock.  Against a real accelerator the same loop runs on a
:class:`~analytics_zoo_tpu.serving.clock.MonotonicClock` with jax's
async dispatch providing the device overlap (the
``SSDPredictor._detect_device`` contract); under a
:class:`~analytics_zoo_tpu.serving.clock.VirtualClock` plus a
``service_time`` model the whole overload/failover story replays
deterministically — that is what ``tests/test_serving.py`` and
``tools/serve_drill.py`` pin.

Usage::

    tiers = ssd_serving_tiers(model, param)       # pipelines.ssd hook
    rt = ServingRuntime(tiers, n_replicas=2, max_batch=8,
                        queue_capacity=64, default_deadline_s=0.2)
    req = rt.submit({"input": img})               # may raise ServerOverloaded
    rt.pump()                                     # run due scheduling work
    ...
    rt.drain()                                    # flush everything queued
    print(rt.metrics.snapshot())
"""

from __future__ import annotations

import itertools
import logging
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.resilience.errors import ReplicaWedged
from analytics_zoo_tpu.serving.batcher import (AssembledBatch,
                                               DeadlineBatcher)
from analytics_zoo_tpu.serving.clock import Clock, MonotonicClock
from analytics_zoo_tpu.serving.ladder import (DegradationLadder,
                                              LadderPolicy, ServingTier)
from analytics_zoo_tpu.serving.metrics import ServingMetrics
from analytics_zoo_tpu.serving.replica import Replica, ReplicaPool
from analytics_zoo_tpu.serving.request import AdmissionQueue, Request

#: span trace-id for one request's life (submit → terminal) — the
#: obs.span_conservation check keys on this prefix
REQ_TRACE = "req-{rid}"

logger = logging.getLogger("analytics_zoo_tpu")


class ServingRuntime:
    """Deadline-aware serving over N supervised replicas.

    ``tiers``: degradation rungs, cheapest last (see
    ``pipelines.ssd.ssd_serving_tiers`` / ``pipelines.deepspeech2.
    ds2_serving_tiers``).  ``service_time(edge, n, tier)``: estimated
    service seconds — REQUIRED with a virtual clock (it also advances
    it); with the default monotonic clock it may be ``None`` (the
    batcher then learns an EWMA from observed forwards).

    ``chaos``: an armed :class:`~analytics_zoo_tpu.resilience.chaos.
    ChaosMonkey` whose serving-kind windows (``slow_forward``,
    ``replica_crash``) are applied per dispatch index.

    ``slo``: an :class:`~analytics_zoo_tpu.obs.slo.SloEvaluator` —
    when armed, every decision window feeds the metric registry's
    snapshot through the multi-window burn-rate evaluation and the
    degradation ladder steps on ``SloDecision.overloaded`` (SLO burn)
    instead of the raw shed/queue-depth flag; each decision is noted
    into the flight recorder (``slo_decision`` events) when ``obs`` is
    armed, and ``snapshot()`` carries the SLO report.  The same
    evaluator's ``scale_hint`` is the autoscaler input (ROADMAP
    item 1).

    ``specs``: the pipeline's declared
    :class:`~analytics_zoo_tpu.parallel.specs.SpecSet` — pass the SAME
    object the tiers were built with (``ssd_serving_tiers(specs=...)``
    / ``ds2_serving_tiers(specs=...)``), so train and serve share ONE
    sharding declaration.  The runtime itself never places arrays (the
    tiers' annotated forwards do); it records the mesh topology in
    ``snapshot()`` so a banked drill names the serving geometry.
    """

    def __init__(self, tiers: Sequence[ServingTier], n_replicas: int = 2,
                 clock: Optional[Clock] = None,
                 queue_capacity: int = 64, max_batch: int = 8,
                 bucket_edges: Optional[Sequence[int]] = None,
                 pad_key: str = "input",
                 length_key: Optional[str] = "n_frames",
                 default_deadline_s: float = 1.0,
                 wedge_timeout_s: float = 10.0,
                 restart_s: float = 5.0,
                 service_time: Optional[
                     Callable[[Any, int, int], float]] = None,
                 slack_margin_s: float = 0.0,
                 ladder_policy: Optional[LadderPolicy] = None,
                 decision_every: int = 8,
                 shed_expired: bool = True,
                 chaos=None, obs=None, specs=None, slo=None):
        if not tiers:
            raise ValueError("need at least one ServingTier")
        self.tiers = list(tiers)
        self.specs = specs
        self.clock = clock or MonotonicClock()
        self.default_deadline_s = float(default_deadline_s)
        self.max_batch = int(max_batch)
        self.decision_every = int(decision_every)
        self.chaos = chaos
        # SLO engine (obs.slo.SloEvaluator): when armed, each decision
        # window feeds a registry snapshot through the multi-window
        # burn-rate evaluation and the ladder steps on SLO burn instead
        # of the raw shed/depth flag (see _decide_window)
        self.slo = slo
        # telemetry spine (obs.Observability): request-lifecycle spans
        # into the flight recorder, metrics into the shared registry; a
        # replica fence dumps the black box when a dump_path is armed
        self.obs = obs
        if obs is not None:
            obs.adopt_clock(self.clock)
        self.metrics = ServingMetrics(
            registry=obs.registry if obs is not None else None)
        self.requests: List[Request] = []      # every request ever submitted
        self._rid = itertools.count()
        self._spans: Dict[int, Dict[str, Any]] = {}   # rid -> open spans
        self._window_shed = 0
        self._dispatch_idx = 0                 # chaos serving-fault index
        self._since_decision = 0

        self.queue = AdmissionQueue(queue_capacity, self.clock,
                                    on_shed=self._on_shed,
                                    shed_expired=shed_expired)
        self.batcher = DeadlineBatcher(
            self.queue, max_batch, bucket_edges=bucket_edges,
            pad_key=pad_key, length_key=length_key,
            service_time=service_time, slack_margin_s=slack_margin_s)
        self._service_time = service_time
        virtual = service_time is not None

        def service_hook(edge, n, tier, rid):
            return service_time(edge, n, tier)

        forward_fns = [t.forward for t in self.tiers]
        self.pool = ReplicaPool(
            [Replica(r, forward_fns, self.clock, wedge_timeout_s,
                     service_hook=service_hook if virtual else None)
             for r in range(n_replicas)],
            self.clock, restart_s=restart_s,
            observer=self._on_pool_event if obs is not None else None)
        self.ladder = DegradationLadder(len(self.tiers), ladder_policy)

    # -- telemetry -----------------------------------------------------------
    def _on_pool_event(self, ev: Dict[str, Any]) -> None:
        """Every pool event (fence / failover / restart) lands in the
        flight recorder; a FENCE is a terminal condition — it trips the
        black-box dump when one is armed."""
        self.obs.recorder.record(ev)
        if ev["kind"] == "replica_fenced" and self.obs.dump_path:
            self.obs.dump("replica_fenced")

    def _end_request_spans(self, req: Request, status: str,
                           **attrs: Any) -> None:
        if self.obs is None:
            return
        spans = self._spans.pop(req.rid, None)
        if spans is None:
            return
        d = spans.get("dispatch")
        if d is not None:
            d.end(status=status, **attrs)
        spans["root"].end(status=status)

    # -- shed observer -------------------------------------------------------
    def _on_shed(self, req: Request, cause: str) -> None:
        self.metrics.on_shed(cause)
        self._window_shed += 1
        if self.obs is not None:
            spans = self._spans.pop(req.rid, None)
            if spans is not None:
                q = spans.get("queue")
                if q is not None:
                    q.end(status=cause)
                spans["root"].end(status=req.state, cause=cause)

    # -- client API ----------------------------------------------------------
    def submit(self, payload: Any, deadline_s: Optional[float] = None,
               length: Optional[int] = None) -> Request:
        """Admit one request; raises
        :class:`~analytics_zoo_tpu.resilience.errors.ServerOverloaded`
        on a full queue (the request is still accounted, state
        ``shed``).  ``length``: variable-axis length for bucket
        assignment."""
        now = self.clock.now()
        req = Request(rid=next(self._rid), payload=payload, arrival_t=now,
                      deadline_t=now + (deadline_s if deadline_s is not None
                                        else self.default_deadline_s),
                      length=length)
        self.requests.append(req)
        self.metrics.on_submit()
        if self.obs is not None:
            # root span of this request's trace: opened here, closed at
            # whatever terminal state the request reaches
            root = self.obs.tracer.start(
                "request", REQ_TRACE.format(rid=req.rid), rid=req.rid,
                deadline_s=round(req.deadline_t - now, 6))
            self._spans[req.rid] = {"root": root}
        self.queue.submit(req)   # may raise; _on_shed closes the spans
        if self.obs is not None and req.rid in self._spans:
            spans = self._spans[req.rid]
            spans["queue"] = self.obs.tracer.start(
                "queue", spans["root"].trace_id, parent=spans["root"])
        return req

    # -- scheduler -----------------------------------------------------------
    def pump(self, force: bool = False) -> int:
        """Run all currently due scheduling work: shed expired requests,
        assemble and dispatch every flush-ready batch.  Returns the
        number of batches dispatched.  Call after submits and after
        advancing the clock."""
        dispatched = 0
        while True:
            batch = self.batcher.next_batch(self.ladder.tier, force=force)
            if batch is None:
                # no batch is flush-ready; expiry may still have shed —
                # that counts toward the current decision window
                break
            self._dispatch(batch)
            dispatched += 1
        return dispatched

    def drain(self, max_batches: int = 10_000) -> None:
        """Force-flush everything still queued (shutdown / end of drill):
        every pending request reaches a terminal state."""
        for _ in range(max_batches):
            if self.pump(force=True) == 0 and len(self.queue) == 0:
                return
        raise RuntimeError("drain did not converge")

    # -- internals -----------------------------------------------------------
    def _fault_for(self, replica: Replica) -> Optional[Callable]:
        """Compose the chaos hooks targeting ``replica`` at the current
        dispatch index (None when nothing is due)."""
        if self.chaos is None:
            return None
        idx = self._dispatch_idx
        hooks: List[Callable] = []
        spec = self.chaos.serving_active("slow_forward", idx, consume=False)
        if spec is not None and spec.detail.get(
                "replica", replica.rid) == replica.rid:
            self.chaos.serving_active("slow_forward", idx)  # record+consume
            delay = float(spec.detail.get("delay_s", 2.0))
            hooks.append(lambda r: self.clock.sleep(delay))
        spec = self.chaos.serving_active("replica_crash", idx, consume=False)
        if spec is not None and spec.detail.get(
                "replica", replica.rid) == replica.rid:
            self.chaos.serving_active("replica_crash", idx)

            def crash(r):
                from analytics_zoo_tpu.resilience.errors import InjectedFault

                raise InjectedFault(
                    f"chaos: replica {r.rid} killed mid-batch")

            hooks.append(crash)
        if not hooks:
            return None

        def fault(r):
            for h in hooks:
                h(r)

        return fault

    def _dispatch(self, batch: AssembledBatch) -> None:
        self._dispatch_idx += 1
        self.metrics.on_batch(batch.n_valid, self.max_batch,
                              self.queue.depth)
        t0 = self.clock.now()
        batch_span = None
        if self.obs is not None:
            # the batch gets its own trace (it belongs to N requests at
            # once); each member request's queue span closes here and a
            # per-request dispatch child opens under its root
            batch_span = self.obs.tracer.start(
                "batch", f"batch-{self._dispatch_idx}",
                requests=[r.rid for r in batch.requests],
                edge=str(batch.edge), n_valid=batch.n_valid,
                tier=batch.tier)
            for req in batch.requests:
                spans = self._spans.get(req.rid)
                if spans is None:
                    continue
                q = spans.pop("queue", None)
                if q is not None:
                    q.end(status="assembled", edge=str(batch.edge))
                spans["dispatch"] = self.obs.tracer.start(
                    "dispatch", spans["root"].trace_id,
                    parent=spans["root"], tier=batch.tier,
                    batch=self._dispatch_idx)
        try:
            out = self.pool.dispatch(batch, fault_for=self._fault_for)
        except ReplicaWedged as err:
            now = self.clock.now()
            for req in batch.requests:
                req.finish("failed", now, error=err)
                self.metrics.on_fail()
                self._end_request_spans(req, "failed",
                                        attempts=req.attempts)
            if batch_span is not None:
                batch_span.end(status="failed",
                               redispatched=batch.redispatched)
            self._after_dispatch(batch, t0, failed=True)
            return
        now = self.clock.now()
        rows = np.asarray(out)
        for i, req in enumerate(batch.requests):
            req.tier = batch.tier
            req.finish("done", now, result=rows[i])
            missed = now > req.deadline_t
            self.metrics.on_complete(now - req.arrival_t, batch.tier,
                                     missed=missed)
            self._end_request_spans(req, "done", attempts=req.attempts,
                                    missed=missed)
        if batch_span is not None:
            batch_span.end(status="done", redispatched=batch.redispatched)
        self._after_dispatch(batch, t0, failed=False)

    def _after_dispatch(self, batch: AssembledBatch, t0: float,
                        failed: bool) -> None:
        dt = self.clock.now() - t0
        if not failed:
            self.batcher.observe_service_s(batch.edge, dt, tier=batch.tier)
        if batch.redispatched:
            self.metrics.redispatches += 1
        self._since_decision += 1
        if self._since_decision >= self.decision_every:
            self._decide_window()

    def _decide_window(self) -> None:
        detail = {"shed_in_window": self._window_shed,
                  "queue_depth": self.queue.depth}
        if self.slo is not None:
            # SLO-driven path: window verdicts come from multi-window
            # burn rates over registry snapshots, not the raw flag —
            # the decision itself lands in the black box (Clockwork:
            # the action log explains the action)
            now = self.clock.now()
            self.slo.observe_registry(self.metrics.registry, now)
            decision = self.slo.decide(now)
            if self.obs is not None:
                self.obs.recorder.note(
                    "slo_decision", t=round(now, 6),
                    overloaded=decision.overloaded,
                    burning=list(decision.burning),
                    new_trips=list(decision.new_trips),
                    recovered=list(decision.recovered),
                    scale_hint=decision.scale_hint)
            self.ladder.observe_decision(decision, detail=detail)
        else:
            depth_high = self.ladder.policy.depth_high * self.max_batch
            overloaded = (self._window_shed > 0
                          or self.queue.depth > depth_high)
            self.ladder.observe_window(overloaded, detail=detail)
        self._window_shed = 0
        self._since_decision = 0

    # -- observability -------------------------------------------------------
    def accounting(self) -> Dict[str, Any]:
        """Request-conservation check: every submitted request is in
        exactly one terminal state once the runtime is drained —
        ``unaccounted == 0`` is the drill's hard invariant."""
        by_state: Dict[str, int] = {}
        for r in self.requests:
            by_state[r.state] = by_state.get(r.state, 0) + 1
        terminal = sum(v for k, v in by_state.items()
                       if k in ("done", "shed", "timeout", "failed"))
        return {"submitted": len(self.requests), "by_state": by_state,
                "terminal": terminal,
                "unaccounted": len(self.requests) - terminal}

    def snapshot(self) -> Dict[str, Any]:
        mesh_info = None
        if self.specs is not None:
            mesh_info = {
                "axes": dict(self.specs.mesh.shape),
                "data_axis_size": self.specs.data_axis_size,
            }
        out = {
            "mesh": mesh_info,
            "metrics": self.metrics.snapshot(),
            "queue": self.queue.snapshot(),
            "replicas": self.pool.snapshot(),
            "ladder": self.ladder.snapshot(),
            "tiers": [{"name": t.name, "speed": t.speed,
                       "quality_note": t.quality_note}
                      for t in self.tiers],
            "accounting": self.accounting(),
        }
        if self.slo is not None:
            # keyed in only when armed, so pre-PR-11 snapshots (and the
            # banked RESILIENCE_r03/OBS_r01 replays) are byte-unchanged
            r = self.slo.report()
            out["slo"] = {k: r[k] for k in
                          ("slos", "windows", "decisions", "trips",
                           "peak_burns")}
        return out
