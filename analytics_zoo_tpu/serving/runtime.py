"""The online serving runtime: request-level API over the predictors.

Glues the pieces into one synchronous, clock-driven scheduler:

- :class:`~analytics_zoo_tpu.serving.request.AdmissionQueue` — bounded,
  EDF, shed-before-dispatch;
- :class:`~analytics_zoo_tpu.serving.batcher.DeadlineBatcher` — flush on
  full-or-urgent over pre-compiled geometries only;
- :class:`~analytics_zoo_tpu.serving.replica.ReplicaPool` — StallWatchdog
  supervision, fence, exactly-once failover, background restart;
- :class:`~analytics_zoo_tpu.serving.ladder.DegradationLadder` — tier
  step-down under sustained overload, hysteresis step-up;
- :class:`~analytics_zoo_tpu.serving.metrics.ServingMetrics` — the
  snapshot dict the drill banks.

Single-threaded on purpose: every scheduling decision happens inside
:meth:`ServingRuntime.pump`, reading time ONLY through the injected
clock.  Against a real accelerator the same loop runs on a
:class:`~analytics_zoo_tpu.serving.clock.MonotonicClock` with jax's
async dispatch providing the device overlap (the
``SSDPredictor._detect_device`` contract); under a
:class:`~analytics_zoo_tpu.serving.clock.VirtualClock` plus a
``service_time`` model the whole overload/failover story replays
deterministically — that is what ``tests/test_serving.py`` and
``tools/serve_drill.py`` pin.

**Fleet mode** (ISSUE 14 — the Clipper model-multiplexing frontend +
Clockwork predictability discipline): pass ``models=[ModelConfig(...),
...]`` instead of ``tiers`` and ONE runtime schedules several model
families on the SHARED replica pool — per-model batching geometry
(models never share a batch), per-model degradation ladders, per-model
SLOs whose burn rates weight the EDF dispatch order (a burning model's
slack counts for more), and per-model service-time EWMAs (a new model
never inherits another's estimate).  Streaming session models
(``ModelConfig(streaming=True)``) get session-affine scheduling:
:meth:`open_session` pins a session to one replica (where its carry
state lives), every :meth:`submit_chunk` carries an incremental
per-chunk deadline, and chunk order is preserved because chunk
deadlines are monotone under EDF.  A closed-loop
:class:`~analytics_zoo_tpu.serving.autoscale.Autoscaler` (``autoscaler=``)
turns the PR-11 ``SloDecision.scale_hint`` into actual
:meth:`~analytics_zoo_tpu.serving.replica.ReplicaPool.resize` calls —
growth pre-warms compiled geometries before the replica joins dispatch,
shrink drains-then-retires.

Usage::

    tiers = ssd_serving_tiers(model, param)       # pipelines.ssd hook
    rt = ServingRuntime(tiers, n_replicas=2, max_batch=8,
                        queue_capacity=64, default_deadline_s=0.2)
    req = rt.submit({"input": img})               # may raise ServerOverloaded
    rt.pump()                                     # run due scheduling work
    ...
    rt.drain()                                    # flush everything queued
    print(rt.metrics.snapshot())
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import os
from typing import (Any, Callable, Dict, List, Optional, Sequence, Set,
                    Tuple)

import numpy as np

from analytics_zoo_tpu.resilience.errors import (ReplicaWedged,
                                                 ServerOverloaded)
from analytics_zoo_tpu.serving.batcher import (AssembledBatch,
                                               DeadlineBatcher, FIXED,
                                               ModelPlan)
from analytics_zoo_tpu.serving.clock import Clock, MonotonicClock
from analytics_zoo_tpu.serving.ladder import (DegradationLadder,
                                              LadderPolicy, ServingTier)
from analytics_zoo_tpu.serving.metrics import ServingMetrics
from analytics_zoo_tpu.serving.autoscale import OCCUPANCY_KNEE, Reshape
from analytics_zoo_tpu.serving.replica import (Replica, ReplicaPool,
                                               ReplicaSlice)
from analytics_zoo_tpu.serving.request import (DEFAULT_MODEL,
                                               AdmissionQueue, Request)

#: span trace-id for one request's life (submit → terminal) — the
#: obs.span_conservation check keys on this prefix
REQ_TRACE = "req-{rid}"

logger = logging.getLogger("analytics_zoo_tpu")


@dataclasses.dataclass
class ModelConfig:
    """One multiplexed model family on the shared pool (ISSUE 14).

    ``tiers``: the degradation rungs (cheapest last — the same
    descriptors the single-model runtime takes).  ``tier_factory``
    (optional): ``replica_rid -> [ServingTier]`` building PER-REPLICA
    tier instances — how streaming models give every replica its own
    session-state store, so session affinity is physically meaningful;
    ``tiers`` stays the template (names/speeds/audit hooks).

    ``bucket_edges``/``pad_key``/``length_key``/``max_batch``: the
    model's batching plan (see :class:`~analytics_zoo_tpu.serving.
    batcher.ModelPlan`).  ``default_deadline_s``: per-model deadline
    when ``submit`` doesn't pass one (``None`` = the runtime default).
    ``slos``: this model's objectives (:mod:`analytics_zoo_tpu.obs.slo`
    — e.g. ``model_slos(name)``); their burn rates drive the model's
    ladder and its weighted-EDF dispatch weight.  ``streaming`` marks a
    session-type model (``open_session``/``submit_chunk``) with
    ``chunk_deadline_s`` as the per-chunk incremental deadline.

    ``weights_to_tiers``: ``(placed_variables, replica_rid) ->
    [ServingTier]`` — how :meth:`ServingRuntime.hot_swap` turns a
    checkpoint's (SpecSet-placed) variables into this model's tier
    stack.  ``rid == -1`` builds the canary mirror (not bound to any
    replica).  Without it the model cannot live-swap.
    """

    name: str
    tiers: Sequence[ServingTier]
    tier_factory: Optional[Callable[[int], Sequence[ServingTier]]] = None
    weights_to_tiers: Optional[Callable[[Any, int],
                                        Sequence[ServingTier]]] = None
    bucket_edges: Optional[Sequence[int]] = None
    pad_key: str = "input"
    length_key: Optional[str] = "n_frames"
    max_batch: Optional[int] = None
    default_deadline_s: Optional[float] = None
    slos: Sequence[Any] = ()
    streaming: bool = False
    chunk_deadline_s: float = 0.5
    ladder_policy: Optional[LadderPolicy] = None

    def __post_init__(self):
        if not self.tiers:
            raise ValueError(f"model {self.name!r} needs at least one tier")
        if self.streaming and self.tier_factory is None:
            raise ValueError(
                f"streaming model {self.name!r} needs a tier_factory — "
                f"session carry state must live per replica for session "
                f"affinity to mean anything")
        if self.streaming and self.bucket_edges \
                and len(self.bucket_edges) > 1:
            # chunk order relies on EDF within ONE (model, affinity,
            # edge) group: with several edges a session's later chunk
            # could land in a bucket that flushes first and decode out
            # of order.  Session chunks are fixed-size blocks anyway
            # (StreamingDS2 compiles exactly three shapes).
            raise ValueError(
                f"streaming model {self.name!r} may declare at most one "
                f"bucket edge — multiple edges would let a later chunk's "
                f"bucket flush before an earlier chunk's, breaking "
                f"in-order decode")

    def plan(self) -> ModelPlan:
        return ModelPlan(bucket_edges=self.bucket_edges,
                         pad_key=self.pad_key, length_key=self.length_key,
                         max_batch=self.max_batch,
                         streaming=self.streaming)


class ServingRuntime:
    """Deadline-aware serving over N supervised replicas.

    ``tiers``: degradation rungs, cheapest last (see
    ``pipelines.ssd.ssd_serving_tiers`` / ``pipelines.deepspeech2.
    ds2_serving_tiers``) — the single-model path.  ``models``: a list of
    :class:`ModelConfig` instead, for the multiplexed fleet path
    (``tiers`` must then be ``None``).  ``service_time(edge, n, tier)``
    (single-model) / ``service_time(model, edge, n, tier)``
    (multiplexed): estimated service seconds — REQUIRED with a virtual
    clock (it also advances it); with the default monotonic clock it
    may be ``None`` (the batcher then learns a per-(model, edge, tier)
    EWMA from observed forwards).

    ``chaos``: an armed :class:`~analytics_zoo_tpu.resilience.chaos.
    ChaosMonkey` whose serving-kind windows (``slow_forward``,
    ``replica_crash``) are applied per dispatch index.

    ``slo``: an :class:`~analytics_zoo_tpu.obs.slo.SloEvaluator` —
    when armed, every decision window feeds the metric registry's
    snapshot through the multi-window burn-rate evaluation and the
    degradation ladder steps on ``SloDecision.overloaded`` (SLO burn)
    instead of the raw shed/queue-depth flag; each decision is noted
    into the flight recorder (``slo_decision`` events) when ``obs`` is
    armed, and ``snapshot()`` carries the SLO report.  In fleet mode
    the runtime BUILDS the evaluator from the models' declared SLOs
    when none is passed (``slo_params`` forwards evaluator kwargs like
    ``time_scale``), maps each burning SLO back to its model for the
    per-model ladders, and refreshes the weighted-EDF weights from the
    fast-window burns every decision window.

    ``autoscaler``: an armed :class:`~analytics_zoo_tpu.serving.
    autoscale.Autoscaler` — the decision window's ``scale_hint`` feeds
    its policy loop and a due actuation calls ``pool.resize`` (growth
    pre-warmed per ``compile_s``/the models' geometry plan, shrink
    drain-then-retire, session-pinned replicas protected).

    ``fence_budget_s``: bounds wedge detection (see
    :mod:`analytics_zoo_tpu.serving.replica`) — ``None`` keeps the
    PR-5 return-then-check behavior.  ``compile_s``: per-geometry
    compile cost for the pre-warm / cold-compile modeling (0 disables).
    ``retain_requests=False`` drops per-request objects once terminal
    (accounting stays exact via incremental counters) — the
    million-request drill's memory bound.

    ``specs``: the pipeline's declared
    :class:`~analytics_zoo_tpu.parallel.specs.SpecSet` — pass the SAME
    object the tiers were built with (``ssd_serving_tiers(specs=...)``
    / ``ds2_serving_tiers(specs=...)``), so train and serve share ONE
    sharding declaration.  The runtime itself never places arrays (the
    tiers' annotated forwards do); it records the mesh topology in
    ``snapshot()`` so a banked drill names the serving geometry.
    """

    def __init__(self, tiers: Optional[Sequence[ServingTier]] = None,
                 n_replicas: int = 2,
                 clock: Optional[Clock] = None,
                 queue_capacity: int = 64, max_batch: int = 8,
                 bucket_edges: Optional[Sequence[int]] = None,
                 pad_key: str = "input",
                 length_key: Optional[str] = "n_frames",
                 default_deadline_s: float = 1.0,
                 wedge_timeout_s: float = 10.0,
                 restart_s: float = 5.0,
                 service_time: Optional[Callable[..., float]] = None,
                 slack_margin_s: float = 0.0,
                 ladder_policy: Optional[LadderPolicy] = None,
                 decision_every: int = 8,
                 shed_expired: bool = True,
                 chaos=None, obs=None, specs=None, slo=None,
                 models: Optional[Sequence[ModelConfig]] = None,
                 autoscaler=None,
                 fence_budget_s: Optional[float] = None,
                 compile_s: float = 0.0,
                 slo_params: Optional[Dict[str, Any]] = None,
                 weight_cap: float = 4.0,
                 retain_requests: bool = True,
                 parallel_replicas: bool = False,
                 slice_width: int = 1,
                 device_budget: Optional[int] = None,
                 health=None):
        if models is not None:
            if tiers is not None:
                raise ValueError("pass tiers= OR models=, not both")
            if not models:
                raise ValueError("models= must name at least one model")
            self.models: Dict[str, ModelConfig] = {}
            for cfg in models:
                if cfg.name in self.models:
                    raise ValueError(f"duplicate model name {cfg.name!r}")
                self.models[cfg.name] = cfg
            self._multi = True
            self.tiers = None
        else:
            if not tiers:
                raise ValueError("need at least one ServingTier")
            self.tiers = list(tiers)
            self.models = {DEFAULT_MODEL: ModelConfig(
                name=DEFAULT_MODEL, tiers=self.tiers,
                bucket_edges=bucket_edges, pad_key=pad_key,
                length_key=length_key)}
            self._multi = False
        self.specs = specs
        self.clock = clock or MonotonicClock()
        self.default_deadline_s = float(default_deadline_s)
        self.max_batch = int(max_batch)
        self.decision_every = int(decision_every)
        self.wedge_timeout_s = float(wedge_timeout_s)
        self.chaos = chaos
        # device-health sentinel (resilience.health.HealthSentinel):
        # parallel-mode completions feed per-replica service times into
        # its straggler EWMA ladder; a flagged replica is quarantined
        # through the pool's drain-then-retire path with device_budget
        # decremented.  None (default) = zero behavior change.
        self.health = health
        self.weight_cap = float(weight_cap)
        self.retain_requests = bool(retain_requests)
        # parallel-service mode (the fleet capacity model): dispatch
        # assigns a batch to a FREE replica whose completion lands at
        # start + cold_tax + service on ITS busy horizon — replicas
        # serve concurrently and pool size IS capacity.  The legacy
        # serial mode (every dispatch sleeps the shared clock) stays
        # the default: the PR-5/PR-11 drills replay byte-identically,
        # and chaos wedge/crash injection lives there.
        self.parallel = bool(parallel_replicas)
        if self.parallel and service_time is None:
            raise ValueError("parallel_replicas needs a service_time "
                             "model (it is a virtual-time mode)")
        # telemetry spine (obs.Observability): request-lifecycle spans
        # into the flight recorder, metrics into the shared registry; a
        # replica fence dumps the black box when a dump_path is armed
        self.obs = obs
        if obs is not None:
            obs.adopt_clock(self.clock)
        self.metrics = ServingMetrics(
            registry=obs.registry if obs is not None else None)
        # SLO engine (obs.slo.SloEvaluator): when armed, each decision
        # window feeds a registry snapshot through the multi-window
        # burn-rate evaluation and the ladder steps on SLO burn instead
        # of the raw shed/depth flag (see _decide_window).  Fleet mode
        # builds it from the models' declared SLOs when none is passed.
        self._slo_model: Dict[str, str] = {}
        for cfg in self.models.values():
            for s in cfg.slos:
                self._slo_model[s.name] = cfg.name
        if slo is None and self._slo_model:
            from analytics_zoo_tpu.obs.slo import SloEvaluator

            all_slos = [s for cfg in self.models.values()
                        for s in cfg.slos]
            slo = SloEvaluator(slos=all_slos,
                               registry=self.metrics.registry,
                               **(slo_params or {}))
        self.slo = slo
        self._slo_params = dict(slo_params or {})
        # live-weight hot-swap control (ISSUE 18): one rollout at a
        # time — canary stage, then the pool's one-replica-at-a-time
        # machine; _swap_ctl is None between rollouts, _swap_log keeps
        # the banked history, _lkg the pending serve-LKG hysteresis
        self._swap_ctl: Optional[Dict[str, Any]] = None
        self._swap_counter = 0
        self._swap_log: List[Dict[str, Any]] = []
        self._swap_stats = {"completed": 0, "rollbacks": 0, "trips": 0,
                            "lkg_promotions": 0}
        self._lkg: Optional[Dict[str, Any]] = None
        self.autoscaler = autoscaler
        if autoscaler is not None and autoscaler.registry is None:
            autoscaler.registry = self.metrics.registry
        # replicas-as-mesh-slices (ISSUE 19): every pool entry occupies
        # ``slice_width`` devices; ``device_budget`` is the pool's hard
        # device ceiling.  ``_model_width`` tracks each model's CURRENT
        # slice width (a reshape moves one model wider); the service
        # model divides by the occupancy-limited width speedup, so
        # width only pays off past the ≈B/128 knee (docs/MFU_CEILING.md)
        if slice_width < 1:
            raise ValueError(f"slice_width must be >= 1, got {slice_width}")
        self.slice_width = int(slice_width)
        self._model_width: Dict[str, int] = {
            name: self.slice_width for name in self.models}
        #: per-model batch-fill EWMA — the autoscaler's width-vs-count
        #: saturation signal (0..1 of the model's batch budget)
        self._fill_ewma: Dict[str, float] = {}
        self._reshape_log: List[Dict[str, Any]] = []
        self.requests: List[Request] = []      # every request ever submitted
        self._rid = itertools.count()
        self._spans: Dict[int, Dict[str, Any]] = {}   # rid -> open spans
        self._window_shed = 0
        self._window_shed_by: Dict[str, int] = {}
        self._dispatch_idx = 0                 # chaos serving-fault index
        self._since_decision = 0
        # incremental accounting (exact at any retention mode): every
        # terminal transition flows through the runtime, so the counters
        # stay correct when retain_requests=False drops the objects
        self._submitted = 0
        self._by_state: Dict[str, int] = {}
        # streaming sessions: sid -> {model, replica, open, chunks} for
        # LIVE sessions only — entries are released when the final
        # chunk reaches a terminal state (or the session is killed), so
        # session bookkeeping stays O(active sessions), not O(ever
        # opened); aggregate history lives in the int counters below
        self._sessions: Dict[int, Dict[str, Any]] = {}
        self._next_sid = 0
        self._sessions_opened = 0
        self._sessions_failed = 0
        self._open_sessions = 0
        #: open/in-flight session count per replica rid — the
        #: open_session placement input and the shrink-protection set
        self._session_load: Dict[int, int] = {}

        self.queue = AdmissionQueue(queue_capacity, self.clock,
                                    on_shed=self._on_shed,
                                    shed_expired=shed_expired)
        if self._multi:
            plans = {name: cfg.plan() for name, cfg in self.models.items()}
            self.batcher = DeadlineBatcher(
                self.queue, max_batch, service_time=service_time,
                slack_margin_s=slack_margin_s, plans=plans)
        else:
            self.batcher = DeadlineBatcher(
                self.queue, max_batch, bucket_edges=bucket_edges,
                pad_key=pad_key, length_key=length_key,
                service_time=service_time, slack_margin_s=slack_margin_s)
        self._service_time = service_time
        virtual = service_time is not None

        def service_hook(batch: AssembledBatch, rid: int) -> float:
            if self._multi:
                s = service_time(batch.model, batch.edge,
                                 batch.n_valid, batch.tier)
            else:
                s = service_time(batch.edge, batch.n_valid, batch.tier)
            w = self._model_width.get(batch.model, 1)
            if w > 1:
                # a width-w slice serves the batch w-way sharded, but
                # only as fast as per-device occupancy allows — below
                # the knee the shards starve and width buys nothing
                s = s / self._width_speedup(batch.n_valid, w)
            return s

        self._service_hook = service_hook if virtual else None
        self.pool = ReplicaPool(
            [self._make_replica(r) for r in range(n_replicas)],
            self.clock, restart_s=restart_s,
            observer=self._on_pool_event,
            fence_budget_s=fence_budget_s,
            replica_factory=self._make_replica,
            prewarm_keys=self._geometry_plan(),
            compile_s=compile_s,
            device_budget=device_budget)
        self.ladders: Dict[str, DegradationLadder] = {
            name: DegradationLadder(
                len(cfg.tiers), cfg.ladder_policy or ladder_policy)
            for name, cfg in self.models.items()}
        #: single-model alias — the PR-5 API surface
        self.ladder = (self.ladders[DEFAULT_MODEL]
                       if not self._multi else None)

    # -- construction helpers ------------------------------------------------
    def _geometry_plan(self) -> List[Tuple[str, Any, int]]:
        """Every (model, edge, tier) program a replica must hold warm —
        what pre-warm compiles before a growth replica joins dispatch."""
        keys: List[Tuple[str, Any, int]] = []
        for name, cfg in self.models.items():
            edges = cfg.bucket_edges or [FIXED]
            for edge in edges:
                for tier in range(len(cfg.tiers)):
                    keys.append((name, edge, tier))
        return keys

    def _make_replica(self, rid: int) -> Replica:
        """Build one replica (also the pool's growth factory): the
        per-model tier table, with per-replica tier INSTANCES when a
        model declares a ``tier_factory`` (streaming session stores
        live per replica).  Warmth is the POOL's business: replicas
        built here are fully warm (PR 5 compiles serving programs at
        startup) and ``resize`` re-marks growth replicas warming/cold."""
        fwd: Dict[str, List[Callable]] = {}
        tier_objs: Dict[str, List[ServingTier]] = {}
        for name, cfg in self.models.items():
            t = cfg.tier_factory(rid) if cfg.tier_factory else cfg.tiers
            if len(t) != len(cfg.tiers):
                raise ValueError(
                    f"model {name!r}: tier_factory built {len(t)} tiers, "
                    f"template declares {len(cfg.tiers)}")
            fwd[name] = [tier.forward for tier in t]
            tier_objs[name] = list(t)
        if self.slice_width > 1:
            # the replica IS a mesh slice (ISSUE 19): its programs are
            # jitted against the tier SpecSet's width-w sub-mesh — the
            # same declaration the elastic trainer re-places — and the
            # pool accounts it as ``width`` devices
            slice_specs = self.specs
            if slice_specs is not None \
                    and slice_specs.data_axis_size != self.slice_width:
                from analytics_zoo_tpu.parallel import mesh as mesh_lib

                devs = list(
                    slice_specs.mesh.devices.reshape(-1)
                    [: self.slice_width])
                sub = mesh_lib.create_mesh(
                    (self.slice_width,),
                    (mesh_lib.data_axis(slice_specs.mesh),),
                    devices=devs)
                slice_specs = slice_specs.replace_mesh(sub)
            replica = ReplicaSlice(
                rid, fwd, self.clock, self.wedge_timeout_s,
                width=self.slice_width, specs=slice_specs,
                service_hook=self._service_hook)
        else:
            replica = Replica(rid, fwd, self.clock, self.wedge_timeout_s,
                              service_hook=self._service_hook)
        replica.tier_objs = tier_objs
        return replica

    @staticmethod
    def _width_speedup(n_valid: int, width: int) -> float:
        """Occupancy-limited service speedup of a width-``width`` slice
        on a batch of ``n_valid``: each of the ``width`` shards serves
        ``n_valid/width`` at ``min(1, (n/w)/knee)`` occupancy, so the
        slice delivers ``w`` × that against the width-1 baseline's
        ``min(1, n/knee)``.  Saturated (n ≥ w·knee) → exactly
        ``width``; below the knee (n ≤ knee) → exactly 1.0 — width
        buys NOTHING until the model is batch-saturated, which is the
        whole width-vs-count policy (docs/MFU_CEILING.md)."""
        n = max(float(n_valid), 1.0)
        base = min(1.0, n / OCCUPANCY_KNEE)
        wide = min(1.0, (n / width) / OCCUPANCY_KNEE) * width
        return wide / base

    # -- telemetry -----------------------------------------------------------
    def _on_pool_event(self, ev: Dict[str, Any]) -> None:
        """Every pool event (fence / failover / restart / resize /
        cold compile) lands in the flight recorder; a FENCE is a
        terminal condition — it trips the black-box dump when one is
        armed.  Cold compiles also count into the registry (the
        pre-warm drill's tax counter)."""
        if ev["kind"] == "cold_compile":
            self.metrics.registry.counter("serve/cold_compiles").inc()
        if self.obs is None:
            return
        self.obs.recorder.record(ev)
        if ev["kind"] == "replica_fenced" and self.obs.dump_path:
            self.obs.dump("replica_fenced")

    def _end_request_spans(self, req: Request, status: str,
                           at: Optional[float] = None,
                           **attrs: Any) -> None:
        if self.obs is None:
            return
        spans = self._spans.pop(req.rid, None)
        if spans is None:
            return
        d = spans.get("dispatch")
        if d is not None:
            d.end(status=status, at=at, **attrs)
        spans["root"].end(status=status, at=at)

    # -- shed observer -------------------------------------------------------
    def _on_shed(self, req: Request, cause: str) -> None:
        self.metrics.on_shed(cause, model=req.model if self._multi
                             else None)
        self._window_shed += 1
        self._window_shed_by[req.model] = \
            self._window_shed_by.get(req.model, 0) + 1
        self._account_terminal(req)
        if req.session is not None:
            # a gap in the chunk stream silently corrupts the session's
            # carry — a shed chunk fails the WHOLE session honestly
            self._kill_session(req, f"chunk shed ({cause})")
        if self.obs is not None:
            spans = self._spans.pop(req.rid, None)
            if spans is not None:
                q = spans.get("queue")
                if q is not None:
                    q.end(status=cause)
                spans["root"].end(status=req.state, cause=cause)

    def _account_terminal(self, req: Request) -> None:
        self._by_state[req.state] = self._by_state.get(req.state, 0) + 1

    # -- client API ----------------------------------------------------------
    def _resolve_model(self, model: Optional[str]) -> ModelConfig:
        if model is None:
            if self._multi and len(self.models) > 1:
                raise ValueError(
                    f"multiplexed runtime serves "
                    f"{sorted(self.models)} — submit(model=...) is "
                    f"required")
            return next(iter(self.models.values()))
        try:
            return self.models[model]
        except KeyError:
            raise KeyError(f"unknown model {model!r} (registered: "
                           f"{sorted(self.models)})") from None

    def submit(self, payload: Any, deadline_s: Optional[float] = None,
               length: Optional[int] = None,
               model: Optional[str] = None) -> Request:
        """Admit one request; raises
        :class:`~analytics_zoo_tpu.resilience.errors.ServerOverloaded`
        on a full queue (the request is still accounted, state
        ``shed``).  ``length``: variable-axis length for bucket
        assignment.  ``model``: which multiplexed model (required when
        the runtime serves more than one)."""
        cfg = self._resolve_model(model)
        if cfg.streaming:
            raise ValueError(
                f"model {cfg.name!r} is a streaming session model — use "
                f"open_session()/submit_chunk()")
        if deadline_s is None:
            deadline_s = (cfg.default_deadline_s
                          if cfg.default_deadline_s is not None
                          else self.default_deadline_s)
        return self._submit(payload, deadline_s, length, cfg.name)

    def _submit(self, payload: Any, deadline_s: float,
                length: Optional[int], model: str,
                session: Optional[int] = None,
                affinity: Optional[int] = None,
                final: bool = False) -> Request:
        now = self.clock.now()
        req = Request(rid=next(self._rid), payload=payload, arrival_t=now,
                      deadline_t=now + deadline_s, length=length,
                      model=model, session=session, affinity=affinity,
                      final=final)
        self._submitted += 1
        if self.retain_requests:
            self.requests.append(req)
        self.metrics.on_submit(model=model if self._multi else None)
        if self.obs is not None:
            # root span of this request's trace: opened here, closed at
            # whatever terminal state the request reaches
            root = self.obs.tracer.start(
                "request", REQ_TRACE.format(rid=req.rid), rid=req.rid,
                deadline_s=round(req.deadline_t - now, 6))
            self._spans[req.rid] = {"root": root}
        self.queue.submit(req)   # may raise; _on_shed closes the spans
        if self.obs is not None and req.rid in self._spans:
            spans = self._spans[req.rid]
            spans["queue"] = self.obs.tracer.start(
                "queue", spans["root"].trace_id, parent=spans["root"])
        return req

    # -- streaming sessions --------------------------------------------------
    def open_session(self, model: Optional[str] = None) -> int:
        """Open a streaming session on its least-loaded healthy replica
        (session-affine: every chunk of this session dispatches THERE —
        the model's carry state lives on that replica).  Raises
        :class:`ServerOverloaded` when no replica is dispatchable."""
        cfg = self._resolve_model(model)
        if not cfg.streaming:
            raise ValueError(f"model {cfg.name!r} is not a streaming "
                             f"session model")
        healthy = self.pool.healthy()
        if not healthy:
            raise ServerOverloaded("no healthy replica to pin a "
                                   "session to; retry with backoff")
        rid = min((r.rid for r in healthy),
                  key=lambda r: (self._session_load.get(r, 0), r))
        sid = self._next_sid
        self._next_sid += 1
        self._sessions[sid] = {"model": cfg.name, "replica": rid,
                               "open": True, "chunks": 0}
        self._sessions_opened += 1
        self._open_sessions += 1
        self._session_load[rid] = self._session_load.get(rid, 0) + 1
        self.metrics.registry.counter("serve/sessions/opened").inc()
        self.metrics.registry.gauge("serve/sessions_open").set(
            float(self._open_sessions))
        if self.obs is not None:
            self.obs.recorder.note("session_opened", session=sid,
                                   model=cfg.name, replica=rid,
                                   t=round(self.clock.now(), 6))
        return sid

    def submit_chunk(self, sid: int, payload: Any,
                     length: Optional[int] = None,
                     deadline_s: Optional[float] = None,
                     final: bool = False) -> Request:
        """Feed one chunk of an open session.  The chunk's deadline is
        INCREMENTAL — anchored at this submit instant (``deadline_s`` or
        the model's ``chunk_deadline_s``), so a long-lived stream never
        accumulates slack debt and chunk deadlines stay monotone — EDF
        therefore preserves chunk order within the session's single
        (model, affinity, edge) group (``ModelConfig`` rejects
        multi-edge streaming plans for exactly this reason).
        ``final=True`` flushes the
        session (the stateful forward emits the tail) and closes it on
        successful admission — a final chunk shed at the door kills the
        session instead (the flush tail is unrecoverable)."""
        sess = self._sessions.get(sid)
        if sess is None:
            if 0 <= sid < self._next_sid:
                raise RuntimeError(f"session {sid} is closed")
            raise KeyError(f"unknown session {sid}")
        if not sess["open"]:
            raise RuntimeError(f"session {sid} is closed")
        cfg = self.models[sess["model"]]
        if deadline_s is None:
            deadline_s = cfg.chunk_deadline_s
        # chunk deadlines must stay MONOTONE within the session — EDF
        # order IS chunk order, so a custom deadline_s earlier than a
        # previous chunk's would reorder the decode; clamp up to the
        # session's deadline high-water mark
        now = self.clock.now()
        deadline_s = max(deadline_s,
                         sess.get("last_deadline_t", 0.0) - now)
        # submit FIRST: a queue-full shed routes through _on_shed which
        # kills the session (a gap in the chunk stream would silently
        # corrupt the carry); only a successfully admitted final chunk
        # marks the session closed
        req = self._submit(
            payload, deadline_s, length, cfg.name, session=sid,
            affinity=sess["replica"], final=final)
        sess["chunks"] += 1
        sess["last_deadline_t"] = req.deadline_t
        if final:
            self._close_session_books(sess)
        return req

    def close_session(self, sid: int) -> None:
        """Client-initiated abort of an open session WITHOUT a flush
        chunk (the stream was abandoned): books close, the live entry
        and its replica pin release, and the pinned replica's store
        entry is evicted — so an abandoned session doesn't hold its
        replica hostage against autoscaler shrink or leak carry state.
        (An idle-session TTL that does this automatically is ROADMAP
        item-1 follow-up work; until then abandonment is the caller's
        contract.)  No-op if the session is already closed/released."""
        sess = self._sessions.get(sid)
        if sess is None:
            return
        self._close_session_books(sess)
        replica = self.pool.replica_by_rid(sess["replica"])
        self._release_session(sid)
        if replica is not None:
            for tier in replica.tier_objs.get(sess["model"], []):
                if tier.evict_session is not None:
                    tier.evict_session(sid)
        if self.obs is not None:
            self.obs.recorder.note("session_closed", session=sid,
                                   t=round(self.clock.now(), 6))

    def _close_session_books(self, sess: Dict[str, Any]) -> None:
        if not sess["open"]:
            return
        sess["open"] = False
        self._open_sessions -= 1
        self.metrics.registry.counter("serve/sessions/closed").inc()
        self.metrics.registry.gauge("serve/sessions_open").set(
            float(self._open_sessions))

    def _session_rids(self) -> Set[int]:
        """Replicas pinned by sessions with work outstanding (open, or
        closed with the final chunk still in flight) — protected from
        the autoscaler's drain-then-retire."""
        return {rid for rid, n in self._session_load.items() if n > 0}

    def _release_session(self, sid: int) -> None:
        """The session's last outcome landed (final chunk terminal, or
        killed): drop the live entry and its replica pin."""
        sess = self._sessions.pop(sid, None)
        if sess is None:
            return
        rid = sess["replica"]
        n = self._session_load.get(rid, 0) - 1
        if n > 0:
            self._session_load[rid] = n
        else:
            self._session_load.pop(rid, None)

    def _kill_session(self, req: Request, reason: str) -> None:
        """A chunk died without being served (shed, dispatch failure,
        replica loss): the session's carry now has a gap, so the whole
        session fails honestly — books closed, live entry released, and
        the pinned replica's store entry evicted
        (``ServingTier.evict_session``) so dead sessions don't leak
        state.  Chunks of this session still queued are failed before
        their dispatch (``_scrub_dead_session_rows``) — they never
        serve from recreated-empty state."""
        sid = req.session
        sess = self._sessions.get(sid)
        if sess is not None:
            self._close_session_books(sess)
            self._release_session(sid)
            self._sessions_failed += 1
            replica = self.pool.replica_by_rid(req.affinity) \
                if req.affinity is not None else None
            if replica is not None:
                for tier in replica.tier_objs.get(req.model, []):
                    if tier.evict_session is not None:
                        tier.evict_session(sid)
            if self.obs is not None:
                self.obs.recorder.note("session_failed", session=sid,
                                       reason=reason[:160],
                                       t=round(self.clock.now(), 6))

    def _scrub_dead_session_rows(self, batch: AssembledBatch) -> None:
        """A killed session's chunks may still be queued (admitted
        before the kill): fail them BEFORE the forward and mask their
        rows (session −1, final 0), so they neither return garbage
        marked ``done`` nor recreate the evicted store entry on the
        replica."""
        if batch.affinity is None:
            return
        for i, req in enumerate(batch.requests):
            if req.session is None or req.session in self._sessions:
                continue
            req.finish("failed", self.clock.now(), error=ReplicaWedged(
                f"session {req.session} already failed"))
            self._account_terminal(req)
            self.metrics.on_fail(model=batch.model if self._multi
                                 else None)
            self._end_request_spans(req, "failed", attempts=req.attempts)
            batch.batch["session"][i] = -1
            batch.batch["final"][i] = 0

    # -- scheduler -----------------------------------------------------------
    def _tier_arg(self):
        if self._multi:
            return {name: ladder.tier
                    for name, ladder in self.ladders.items()}
        return self.ladder.tier

    def pump(self, force: bool = False) -> int:
        """Run all currently due scheduling work: shed expired requests,
        assemble and dispatch every flush-ready batch.  Returns the
        number of batches dispatched.  Call after submits and after
        advancing the clock."""
        self._swap_tick()
        dispatched = 0
        while True:
            if self.parallel and not force \
                    and not self.pool.any_free(self.clock.now()):
                # every replica is serving concurrently — assembling a
                # batch now would only burn its members' slack; expiry
                # still ran on the previous iteration's next_batch
                self.queue.expire()
                break
            batch = self.batcher.next_batch(self._tier_arg(), force=force)
            if batch is None:
                # no batch is flush-ready; expiry may still have shed —
                # that counts toward the current decision window
                break
            self._dispatch(batch)
            dispatched += 1
        return dispatched

    def next_event_t(self) -> Optional[float]:
        """Parallel mode: the next virtual instant the pool changes
        state (a replica frees / restarts / finishes pre-warming) — an
        event-driven load loop advances the clock to ``min(this, next
        arrival)`` when :meth:`pump` has nothing to do."""
        return self.pool.next_event_t(self.clock.now())

    def drain(self, max_batches: int = 10_000_000) -> None:
        """Force-flush everything still queued (shutdown / end of drill):
        every pending request reaches a terminal state."""
        for _ in range(max_batches):
            if self.pump(force=True) == 0 and len(self.queue) == 0:
                return
        raise RuntimeError("drain did not converge")

    # -- live weights: hot-swap with canary + rollback (ISSUE 18) ------------
    def hot_swap(self, checkpoint_path: str,
                 model: Optional[str] = None, *,
                 canary_fraction: float = 0.25,
                 canary_min: int = 32,
                 divergence_budget: float = 1e-3,
                 latency_budget_s: Optional[float] = None,
                 canary_seed: int = 0,
                 lkg_after: int = 2,
                 warm_s: Optional[float] = None) -> Dict[str, Any]:
        """Start a zero-downtime weight rollout from a published
        checkpoint snapshot:

        1. **verify + load + place** — the snapshot's sha256 manifest is
           verified, the pytree restored, and placed through the
           pipeline's declared :class:`~analytics_zoo_tpu.parallel.
           specs.SpecSet` (``place_state``) so the swap is mesh-correct
           by construction;
        2. **canary** — a seeded ``canary_fraction`` of this model's
           live requests is MIRRORED to the new weights (one extra
           forward per touched batch; the mirror never enters
           ``accounting()``), per-row output divergence and modeled
           latency land in rollout-labeled ``serve/canary/*`` metrics,
           and a dedicated :class:`~analytics_zoo_tpu.obs.slo.
           SloEvaluator` trips the stage the moment either crosses its
           budget;
        3. **rollout** — after ``canary_min`` clean mirrored requests
           the pool's one-replica-at-a-time drain → install → re-warm →
           rejoin machine takes over (session-pinned replicas last);
        4. **rollback** — a tripped canary or a mid-rollout SLO trip
           reverts to the previous weights (the ``serve-lkg`` tier's
           content) EXACTLY once; a fully-healthy rollout instead
           promotes this snapshot to ``serve-lkg`` after ``lkg_after``
           clean decision windows (PR-3's hysteresis, serving twin).

        Returns the rollout record (also appended to the swap log).
        Raises :class:`CheckpointCorrupt` on a bad manifest — a
        truncated publish never drains a replica."""
        from analytics_zoo_tpu.parallel import checkpoint as ckpt
        from analytics_zoo_tpu.resilience.errors import CheckpointCorrupt

        cfg = self._resolve_model(model)
        if cfg.weights_to_tiers is None:
            raise ValueError(
                f"model {cfg.name!r} declares no weights_to_tiers — the "
                f"runtime cannot build its tier stack from a checkpoint")
        if self._swap_ctl is not None \
                and self._swap_ctl["phase"] in ("canary", "rolling"):
            raise RuntimeError(
                f"hot_swap: rollout of "
                f"{self._swap_ctl['checkpoint']!r} still in progress")
        now = self.clock.now()
        try:
            ckpt.verify_snapshot(checkpoint_path)
            state = ckpt.load(checkpoint_path, verify=True)
        except CheckpointCorrupt as e:
            if self.obs is not None:
                self.obs.recorder.note(
                    "swap_rejected", checkpoint=checkpoint_path,
                    error=str(e)[:160], t=round(now, 6))
            raise
        placed = self.specs.place_state(state) \
            if self.specs is not None else state
        mirror = list(cfg.weights_to_tiers(placed, -1))
        if len(mirror) != len(cfg.tiers):
            raise ValueError(
                f"model {cfg.name!r}: weights_to_tiers built "
                f"{len(mirror)} tiers, template declares "
                f"{len(cfg.tiers)}")
        k = self._swap_counter
        self._swap_counter += 1
        from analytics_zoo_tpu.obs.slo import SloEvaluator, canary_slos

        window_params = {key: v for key, v in self._slo_params.items()
                         if key in ("fast_window_s", "slow_window_s",
                                    "time_scale", "timeline_cap")}
        evaluator = SloEvaluator(
            slos=canary_slos(cfg.name, divergence_budget,
                             latency_budget_s, rollout=k),
            registry=self.metrics.registry,
            fast_burn=1.0, slow_burn=1.0, **window_params)
        self._lkg = None   # a new rollout supersedes a pending promotion
        self._swap_ctl = {
            "phase": "canary", "model": cfg.name, "rollout": k,
            "checkpoint": checkpoint_path, "placed": placed,
            "mirror": mirror, "fraction": float(canary_fraction),
            "min": int(canary_min), "seed": int(canary_seed),
            "mirrored": 0, "evaluator": evaluator,
            "lkg_after": int(lkg_after), "warm_s": warm_s,
            "rolled_back": False, "stash": {}, "t_started": now,
        }
        self.metrics.registry.counter("serve/swap/rollouts").inc()
        if self.autoscaler is not None:
            # canary verdicts must not be masked by fresh capacity —
            # the loop keeps observing but actuations are swallowed
            self.autoscaler.hold = True
        if self.obs is not None:
            self.obs.recorder.note(
                "swap_started", model=cfg.name, rollout=k,
                checkpoint=checkpoint_path,
                canary_fraction=float(canary_fraction),
                canary_min=int(canary_min),
                divergence_budget=divergence_budget, t=round(now, 6))
        record = {"rollout": k, "model": cfg.name,
                  "checkpoint": checkpoint_path, "outcome": None,
                  "t_started": round(now, 6)}
        self._swap_log.append(record)
        if canary_fraction <= 0 or canary_min <= 0:
            self._begin_roll()   # canary explicitly disabled
        return record

    @property
    def swap_active(self) -> bool:
        """Whether a rollout is in flight (canary or rolling) — the
        gate a checkpoint-watching driver checks before starting the
        next ``hot_swap`` (one rollout at a time; a newly-published
        snapshot waits its turn)."""
        return (self._swap_ctl is not None
                and self._swap_ctl["phase"] in ("canary", "rolling"))

    @property
    def lkg_pending(self) -> bool:
        """Whether a completed rollout is still inside its serve-LKG
        hysteresis (clean decision windows not yet accumulated).  A
        driver that starts the next ``hot_swap`` now supersedes the
        pending promotion — waiting for this to clear is how each
        fully-healthy rollout actually lands in the ``serve-lkg``
        tier."""
        return self._lkg is not None

    def _swap_install(self, replica: Replica) -> None:
        """The pool rollout's install hook: stash the replica's live
        tier stack for this model (the rollback inventory — still
        jit-warm), then mount the new-weights tiers built for THIS
        rid (per-replica stores stay per-replica)."""
        ctl = self._swap_ctl
        name = ctl["model"]
        ctl["stash"][replica.rid] = (replica.forward_fns.get(name),
                                     replica.tier_objs.get(name))
        tiers = list(self.models[name].weights_to_tiers(
            ctl["placed"], replica.rid))
        replica.forward_fns[name] = [t.forward for t in tiers]
        replica.tier_objs[name] = tiers
        self.metrics.registry.counter("serve/swap/replicas_swapped").inc()

    def _begin_roll(self) -> None:
        ctl = self._swap_ctl
        ctl["phase"] = "rolling"
        self.pool.swap_defer = set(self._session_rids())
        self.pool.hot_swap(ctl["checkpoint"], install=self._swap_install,
                           warm_s=ctl["warm_s"],
                           last=sorted(self._session_rids()))
        if self.autoscaler is not None:
            self.autoscaler.hold = False
        if self.obs is not None:
            self.obs.recorder.note(
                "swap_rolling", model=ctl["model"],
                rollout=ctl["rollout"], mirrored=ctl["mirrored"],
                t=round(self.clock.now(), 6))

    def _swap_tick(self) -> None:
        """Advance swap bookkeeping once per pump: refresh the deferred
        (session-pinned) rid set, let the pool machine step, and detect
        rollout completion (which arms the serve-LKG hysteresis)."""
        ctl = self._swap_ctl
        if ctl is None or ctl["phase"] != "rolling":
            return
        self.pool.swap_defer = set(self._session_rids())
        self.pool.healthy()          # runs _revive → _step_rollout
        if self.pool.rollout_active:
            return
        ctl["phase"] = "complete"
        ctl["stash"] = {}            # old weights no longer needed
        self._swap_stats["completed"] += 1
        self._swap_log[-1]["outcome"] = "complete"
        swapped = (self.pool.last_rollout or {}).get("swapped", [])
        self._lkg = {"ctl": ctl, "clean": 0,
                     "after": ctl["lkg_after"]}
        if self.obs is not None:
            self.obs.recorder.note(
                "swap_complete", model=ctl["model"],
                rollout=ctl["rollout"], replicas=list(swapped),
                t=round(self.clock.now(), 6))

    def _maybe_canary(self, batch: AssembledBatch, rows,
                      now: float) -> None:
        """Canary mirroring on the live dispatch path: a seeded
        fraction of this model's requests also runs on the new-weights
        mirror tier; per-row divergence + modeled latency land in the
        rollout-labeled registry names and the canary evaluator trips
        the stage on budget.  The mirror NEVER touches the request
        lifecycle — ``accounting()`` is conserved by construction."""
        ctl = self._swap_ctl
        if ctl is None or ctl["phase"] != "canary" \
                or batch.model != ctl["model"]:
            return
        gate = int(ctl["fraction"] * 1000)
        sel = [i for i, r in enumerate(batch.requests)
               if not r.finished
               and (r.rid * 1_000_003 + ctl["seed"]) % 1000 < gate]
        if not sel:
            return
        m, k = ctl["model"], ctl["rollout"]
        reg = self.metrics.registry
        reg.counter(f"serve/canary/mirrored/model={m}").inc(len(sel))
        ctl["mirrored"] += len(sel)
        div_h = reg.histogram(
            f"serve/canary/divergence/model={m}/swap={k}")
        mirror_tier = ctl["mirror"][batch.tier]
        try:
            mrows = np.asarray(mirror_tier.forward(batch.batch))
            for i in sel:
                a, b = rows[i], mrows[i]
                if isinstance(a, (str, bytes, np.str_)):
                    div = 0.0 if a == b else 1.0
                else:
                    d = np.abs(np.asarray(a, dtype=np.float64)
                               - np.asarray(b, dtype=np.float64))
                    div = float(np.max(d)) if d.size else 0.0
                div_h.observe(div)
        except Exception as err:
            # a crashing canary forward is itself a tripworthy signal
            div_h.observe(float("inf"))
            if self.obs is not None:
                self.obs.recorder.note(
                    "canary_error", model=m, rollout=k,
                    error=f"{type(err).__name__}: {err}"[:160],
                    t=round(now, 6))
        if self._service_time is not None:
            live = float(self._service_hook(batch, -1))
            template = self.models[m].tiers[batch.tier]
            ratio = (template.speed / mirror_tier.speed
                     if getattr(mirror_tier, "speed", 0) else 1.0)
            reg.histogram(
                f"serve/canary/latency_s/model={m}/swap={k}"
            ).observe(live * ratio)
        ev = ctl["evaluator"]
        ev.observe_registry(reg, now)
        decision = ev.decide(now)
        if decision.burning:
            self._swap_stats["trips"] += 1
            reg.counter("serve/canary/trips").inc()
            if self.obs is not None:
                self.obs.recorder.note(
                    "canary_trip", model=m, rollout=k,
                    burning=list(decision.burning),
                    mirrored=ctl["mirrored"], t=round(now, 6))
            self._swap_rollback("canary_trip: "
                                + ",".join(decision.burning))
        elif ctl["mirrored"] >= ctl["min"]:
            self._begin_roll()

    def _swap_rollback(self, reason: str) -> None:
        """Revert the rollout to the previous weights (the content of
        the ``serve-lkg`` tier) EXACTLY once — the ``rolled_back``
        latch makes a canary trip racing a mid-rollout anomaly
        idempotent.  Already-swapped replicas get their stashed (still
        jit-warm) tier stacks back instantly; a replica with no stash
        (grown mid-rollout) is rebuilt from the verified ``serve-lkg``
        snapshot when one exists."""
        ctl = self._swap_ctl
        if ctl is None or ctl["rolled_back"]:
            return
        ctl["rolled_back"] = True
        now = self.clock.now()
        swapped = self.pool.abort_rollout()
        missing: List[int] = []
        for rid in swapped:
            r = self.pool.replica_by_rid(rid)
            if r is None:
                continue
            stash = ctl["stash"].get(rid)
            if stash is not None and stash[0] is not None:
                r.forward_fns[ctl["model"]] = stash[0]
                r.tier_objs[ctl["model"]] = stash[1]
            else:
                missing.append(rid)
        lkg_path = None
        if missing:
            from analytics_zoo_tpu.parallel import checkpoint as ckpt

            base = os.path.dirname(os.path.abspath(ctl["checkpoint"]))
            found = ckpt.tier_snapshot(base, "serve-lkg")
            if found is not None:
                lkg_path = found[0]
                state = ckpt.load(lkg_path, verify=False)
                placed = self.specs.place_state(state) \
                    if self.specs is not None else state
                for rid in missing:
                    r = self.pool.replica_by_rid(rid)
                    tiers = list(self.models[ctl["model"]]
                                 .weights_to_tiers(placed, rid))
                    r.forward_fns[ctl["model"]] = [t.forward
                                                   for t in tiers]
                    r.tier_objs[ctl["model"]] = tiers
        ctl["phase"] = "rolled_back"
        ctl["stash"] = {}
        self._swap_stats["rollbacks"] += 1
        self._swap_log[-1]["outcome"] = "rolled_back"
        self._swap_log[-1]["reason"] = reason[:160]
        self.metrics.registry.counter("serve/swap/rollbacks").inc()
        self._lkg = None
        if self.autoscaler is not None:
            self.autoscaler.hold = False
        if self.obs is not None:
            self.obs.recorder.note(
                "swap_rollback", model=ctl["model"],
                rollout=ctl["rollout"], reason=reason[:160],
                reverted=list(swapped), lkg=lkg_path,
                t=round(now, 6))
            if self.obs.dump_path:
                self.obs.dump("swap_rollback")

    def _maybe_promote_lkg(self, decision) -> None:
        """Serve-LKG hysteresis (the PR-3 pattern): after a completed
        rollout, ``lkg_after`` consecutive clean decision windows
        promote the swapped snapshot into the ``serve-lkg`` tier; a
        trip resets the streak (and a mid-rollout trip rolls back via
        ``_decide_window`` before ever reaching here)."""
        pend = self._lkg
        if pend is None:
            return
        model = pend["ctl"]["model"]
        dirty = any(self._slo_model.get(s) == model
                    for s in decision.burning)
        if dirty:
            pend["clean"] = 0
            return
        pend["clean"] += 1
        if pend["clean"] < pend["after"]:
            return
        from analytics_zoo_tpu.parallel import checkpoint as ckpt
        from analytics_zoo_tpu.resilience.errors import CheckpointCorrupt

        snap = pend["ctl"]["checkpoint"]
        base = os.path.dirname(os.path.abspath(snap))
        self._lkg = None
        try:
            target = ckpt.promote_tier(base, snap, "serve-lkg")
        except (CheckpointCorrupt, OSError) as e:
            # the trainer may have GC'd the step snapshot already —
            # a missed promotion is not a serving fault
            if self.obs is not None:
                self.obs.recorder.note(
                    "swap_lkg_failed", checkpoint=snap,
                    error=str(e)[:160],
                    t=round(self.clock.now(), 6))
            return
        self._swap_stats["lkg_promotions"] += 1
        self.metrics.registry.counter("serve/swap/lkg_promotions").inc()
        if self.obs is not None:
            self.obs.recorder.note(
                "swap_lkg_promoted", checkpoint=snap, tier=target,
                rollout=pend["ctl"]["rollout"],
                t=round(self.clock.now(), 6))

    # -- internals -----------------------------------------------------------
    def _fault_for(self, replica: Replica) -> Optional[Callable]:
        """Compose the chaos hooks targeting ``replica`` at the current
        dispatch index (None when nothing is due)."""
        if self.chaos is None:
            return None
        idx = self._dispatch_idx
        hooks: List[Callable] = []
        spec = self.chaos.serving_active("slow_forward", idx, consume=False)
        if spec is not None and spec.detail.get(
                "replica", replica.rid) == replica.rid:
            self.chaos.serving_active("slow_forward", idx)  # record+consume
            delay = float(spec.detail.get("delay_s", 2.0))
            # the wedge advances time THROUGH the replica's budget guard:
            # with a fence budget armed the pool observes the wedge at
            # the fence instant; without one this is a plain sleep (the
            # PR-5 return-then-check path, byte-identical)
            hooks.append(lambda r: r.sleep_guarded(delay))
        spec = self.chaos.serving_active("replica_crash", idx, consume=False)
        if spec is not None and spec.detail.get(
                "replica", replica.rid) == replica.rid:
            self.chaos.serving_active("replica_crash", idx)

            def crash(r):
                from analytics_zoo_tpu.resilience.errors import InjectedFault

                raise InjectedFault(
                    f"chaos: replica {r.rid} killed mid-batch")

            hooks.append(crash)
        if not hooks:
            return None

        def fault(r):
            for h in hooks:
                h(r)

        return fault

    def _note_fill(self, batch: AssembledBatch) -> None:
        """Per-model batch-fill EWMA — the autoscaler's width-vs-count
        saturation signal: sustained fill ≈ 1.0 means the model is
        batch-saturated and count-growth would split full batches below
        the occupancy knee (see :meth:`_width_speedup`)."""
        cap = max(self.batcher.model_batch(batch.model), 1)
        fill = min(1.0, batch.n_valid / cap)
        prev = self._fill_ewma.get(batch.model)
        self._fill_ewma[batch.model] = (
            fill if prev is None else 0.8 * prev + 0.2 * fill)

    def _dispatch(self, batch: AssembledBatch) -> None:
        self._scrub_dead_session_rows(batch)
        if self.parallel:
            self._dispatch_parallel(batch)
            return
        self._dispatch_idx += 1
        self.metrics.on_batch(batch.n_valid,
                              self.batcher.model_batch(batch.model),
                              self.queue.depth)
        self._note_fill(batch)
        model_label = batch.model if self._multi else None
        t0 = self.clock.now()
        batch_span = None
        if self.obs is not None:
            # the batch gets its own trace (it belongs to N requests at
            # once); each member request's queue span closes here and a
            # per-request dispatch child opens under its root
            batch_span = self.obs.tracer.start(
                "batch", f"batch-{self._dispatch_idx}",
                requests=[r.rid for r in batch.requests],
                edge=str(batch.edge), n_valid=batch.n_valid,
                tier=batch.tier)
            for req in batch.requests:
                spans = self._spans.get(req.rid)
                if spans is None:
                    continue
                q = spans.pop("queue", None)
                if q is not None:
                    q.end(status="assembled", edge=str(batch.edge))
                spans["dispatch"] = self.obs.tracer.start(
                    "dispatch", spans["root"].trace_id,
                    parent=spans["root"], tier=batch.tier,
                    batch=self._dispatch_idx)
        try:
            out = self.pool.dispatch(batch, fault_for=self._fault_for)
        except ReplicaWedged as err:
            now = self.clock.now()
            for req in batch.requests:
                if req.finished:        # scrubbed dead-session row
                    continue
                req.finish("failed", now, error=err)
                self._account_terminal(req)
                self.metrics.on_fail(model=model_label)
                self._end_request_spans(req, "failed",
                                        attempts=req.attempts)
                if req.session is not None:
                    # affine dispatch lost its replica (or wedged): the
                    # session's carry state is gone — honest state loss
                    self._kill_session(req, str(err))
            if batch_span is not None:
                batch_span.end(status="failed",
                               redispatched=batch.redispatched)
            self._after_dispatch(batch, t0, failed=True)
            return
        now = self.clock.now()
        rows = np.asarray(out)
        self._maybe_canary(batch, rows, now)
        for i, req in enumerate(batch.requests):
            if req.finished:            # scrubbed dead-session row
                continue
            req.tier = batch.tier
            req.finish("done", now,
                       result=rows[i] if self.retain_requests else None)
            self._account_terminal(req)
            missed = now > req.deadline_t
            self.metrics.on_complete(now - req.arrival_t, batch.tier,
                                     missed=missed, model=model_label)
            self._end_request_spans(req, "done", attempts=req.attempts,
                                    missed=missed)
            if req.final and req.session is not None:
                self._release_session(req.session)
        if batch_span is not None:
            batch_span.end(status="done", redispatched=batch.redispatched)
        self._after_dispatch(batch, t0, failed=False)

    def _parallel_fault(self, replica: Replica) -> Tuple[bool, float, float]:
        """Chaos windows for the current dispatch index against
        ``replica`` under the parallel service model: ``(crash,
        delay_s, slow_x)``.  The windows are the same ``serving_active``
        queries the serial ``_fault_for`` composes; here the effects are
        applied to the replica's OWN busy horizon instead of the shared
        clock.  ``slow_x`` (the ``slow_device`` kind) multiplies the
        SERVICE time — a persistently slow-but-correct device, which
        deliberately does NOT count as chaotic: it must slip past the
        wedge/fence checks, because catching it is the straggler
        detector's job, not the watchdog's."""
        if self.chaos is None:
            return False, 0.0, 1.0
        idx = self._dispatch_idx
        delay = 0.0
        spec = self.chaos.serving_active("slow_forward", idx, consume=False)
        if spec is not None and spec.detail.get(
                "replica", replica.rid) == replica.rid:
            self.chaos.serving_active("slow_forward", idx)  # record+consume
            delay = float(spec.detail.get("delay_s", 2.0))
        crash = False
        spec = self.chaos.serving_active("replica_crash", idx, consume=False)
        if spec is not None and spec.detail.get(
                "replica", replica.rid) == replica.rid:
            self.chaos.serving_active("replica_crash", idx)
            crash = True
        slow_x = 1.0
        spec = self.chaos.serving_active("slow_device", idx, consume=False)
        if spec is not None and spec.detail.get(
                "replica", replica.rid) == replica.rid:
            self.chaos.serving_active("slow_device", idx)
            slow_x = float(spec.detail.get("slow_x", 4.0))
        return crash, delay, slow_x

    def _dispatch_parallel(self, batch: AssembledBatch) -> None:
        """Parallel-service dispatch: assign the batch to a free (or,
        for sessions/force-drain, the pinned/least-busy) replica; its
        completion lands at ``start + cold_tax + service`` on THAT
        replica's busy horizon while the shared clock stands still —
        replicas serve concurrently, so resizing the pool really
        changes capacity (what the fleet drill measures).

        Chaos + failover compose here too (ISSUE 18): an injected crash
        fences the replica at the instant the batch would have started
        on its horizon, a ``slow_forward`` wedge is detected at the
        fence budget (or, without one, when the slow forward returns) —
        and the batch re-dispatches EXACTLY once through the same
        ``redispatched`` latch as serial mode.  Request spans thread
        through unchanged: dispatch/root spans end AT the computed
        completion instant (``Span.end(at=)``), so az-trace tail
        attribution covers fleet drills."""
        self._dispatch_idx += 1
        self.metrics.on_batch(batch.n_valid,
                              self.batcher.model_batch(batch.model),
                              self.queue.depth)
        self._note_fill(batch)
        now = self.clock.now()
        model_label = batch.model if self._multi else None
        batch_span = None
        if self.obs is not None:
            batch_span = self.obs.tracer.start(
                "batch", f"batch-{self._dispatch_idx}",
                requests=[r.rid for r in batch.requests],
                edge=str(batch.edge), n_valid=batch.n_valid,
                tier=batch.tier)
            for req in batch.requests:
                spans = self._spans.get(req.rid)
                if spans is None:
                    continue
                q = spans.pop("queue", None)
                if q is not None:
                    q.end(status="assembled", edge=str(batch.edge))
                spans["dispatch"] = self.obs.tracer.start(
                    "dispatch", spans["root"].trace_id,
                    parent=spans["root"], tier=batch.tier,
                    batch=self._dispatch_idx)

        def fail_batch(err: BaseException, at: float) -> None:
            for req in batch.requests:
                if req.finished:        # scrubbed dead-session row
                    continue
                req.finish("failed", at, error=err)
                self._account_terminal(req)
                self.metrics.on_fail(model=model_label)
                self._end_request_spans(req, "failed", at=at,
                                        attempts=req.attempts)
                if req.session is not None:
                    self._kill_session(req, str(err))
            if batch_span is not None:
                batch_span.end(status="failed", at=at,
                               redispatched=batch.redispatched)
            if batch.redispatched:
                self.metrics.redispatches += 1
            self._since_decision += 1
            if self._since_decision >= self.decision_every:
                self._decide_window()

        def complete(replica: Replica, out: Any, start: float,
                     elapsed: float, service: float) -> None:
            completion = start + elapsed
            replica.busy_until = completion
            if self.health is not None:
                # only the SERVICE component feeds the straggler EWMA:
                # injected slow_forward delay and cold-start warm tax
                # are not the silicon's speed, and eviction is
                # irreversible — a replica paying warm taxes for new
                # (model, edge, tier) keys must not be flagged for it
                self._note_device_health(replica, service)
            rows = np.asarray(out)
            self._maybe_canary(batch, rows, now)
            for i, req in enumerate(batch.requests):
                if req.finished:        # scrubbed dead-session row
                    continue
                req.tier = batch.tier
                req.finish("done", completion,
                           result=rows[i] if self.retain_requests
                           else None)
                self._account_terminal(req)
                missed = completion > req.deadline_t
                self.metrics.on_complete(completion - req.arrival_t,
                                         batch.tier, missed=missed,
                                         model=model_label)
                self._end_request_spans(req, "done", at=completion,
                                        attempts=req.attempts,
                                        missed=missed)
                if req.final and req.session is not None:
                    self._release_session(req.session)
            if batch_span is not None:
                batch_span.end(status="done", at=completion,
                               redispatched=batch.redispatched)
            if batch.redispatched:
                self.metrics.redispatches += 1
            self._since_decision += 1
            if self._since_decision >= self.decision_every:
                self._decide_window()

        def wedge(replica: Replica, err: ReplicaWedged, at: float,
                  is_backup: bool) -> None:
            replica.busy_until = at
            self.pool._fence(replica, err, at=at)
            failover(replica, err, at, is_backup)

        def serve_on(replica: Replica, t_avail: float,
                     is_backup: bool) -> None:
            """One service attempt on ``replica``'s busy horizon,
            mirroring the serial ``Replica.forward`` time order —
            injected delay, cold compile, model fn, service — with the
            fence budget cutting the cumulative elapsed exactly where
            ``sleep_guarded`` would.  A chaos crash/wedge fences the
            replica at the computed instant and (for the primary, on a
            non-affine batch) falls through to failover."""
            for req in batch.requests:
                req.attempts += 1
            replica.dispatches += 1
            crash, delay, slow_x = self._parallel_fault(replica)
            start = max(t_avail, replica.busy_until)
            budget = replica.fence_budget_s
            chaotic = crash or delay > 0
            if chaotic and budget is not None and delay > budget:
                # the injected stall alone crosses the budget: fenced
                # mid-delay, before compile/fn would even run
                wedge(replica, ReplicaWedged(
                    f"replica {replica.rid}: forward wedged mid-flight "
                    f"— fenced at the {budget:.3f}s fence budget"),
                    start + budget, is_backup)
                return
            if crash:
                # serial ordering: the slow_forward hook sleeps first,
                # then the crash hook raises — the kill lands at
                # start + delay on this replica's horizon
                wedge(replica, ReplicaWedged(
                    f"replica {replica.rid}: forward crashed mid-batch "
                    f"(InjectedFault: chaos: replica {replica.rid} "
                    f"killed mid-batch)"), start + delay, is_backup)
                return
            try:
                out = replica._fn_for(batch)(batch.batch)
            except Exception as e:
                err = e if isinstance(e, ReplicaWedged) else ReplicaWedged(
                    f"replica {replica.rid}: forward crashed mid-batch "
                    f"({type(e).__name__}: {e})")
                fail_batch(err, start)
                return
            tax = replica.cold_tax(batch, mark=False)
            if chaotic and budget is not None and delay + tax > budget:
                # fenced mid-compile: the geometry stays COLD for the
                # restarted replica (mirrors _maybe_cold_compile)
                wedge(replica, ReplicaWedged(
                    f"replica {replica.rid}: forward wedged mid-flight "
                    f"— fenced at the {budget:.3f}s fence budget"),
                    start + budget, is_backup)
                return
            if tax > 0 and replica.warm_keys is not None:
                replica.warm_keys.add((batch.model, batch.edge,
                                       batch.tier))
            # slow_device stretches the service itself (the device
            # computes correctly, just slowly) and stays OUT of
            # `chaotic`: no wedge, no fence — only the straggler EWMA
            # sees it, through the health feed in complete()
            service = float(self._service_hook(batch, replica.rid)) * slow_x
            elapsed = delay + tax + service
            if chaotic and budget is not None and elapsed > budget:
                # fence-budget semantics on the replica's OWN busy
                # horizon: the wedge is observed at start + budget
                wedge(replica, ReplicaWedged(
                    f"replica {replica.rid}: forward wedged mid-flight "
                    f"— fenced at the {budget:.3f}s fence budget"),
                    start + budget, is_backup)
                return
            if chaotic and elapsed > replica.watchdog.timeout_s:
                # no budget: return-then-check — the wedge rides out
                # the whole stall before it is observed
                wedge(replica, ReplicaWedged(
                    f"replica {replica.rid}: forward wedged "
                    f"({elapsed:.3f}s > "
                    f"{replica.watchdog.timeout_s:.3f}s deadline)"),
                    start + elapsed, is_backup)
                return
            complete(replica, out, start, elapsed, service)

        def failover(failed: Replica, err: ReplicaWedged,
                     t_detect: float, is_backup: bool) -> None:
            if is_backup or batch.redispatched \
                    or batch.affinity is not None:
                # latch spent, or a session batch (its carry lives on
                # the failed replica — honest state loss)
                fail_batch(err, t_detect)
                return
            batch.redispatched = True
            backup = self.pool.pick_free(t_detect, exclude=failed.rid)
            if backup is None:
                backup = self.pool.least_busy()
            if backup is None:
                fail_batch(ReplicaWedged(
                    f"batch failover from replica {failed.rid}: no "
                    f"healthy replica left"), t_detect)
                return
            self.pool._event({"kind": "failover", "from": failed.rid,
                              "to": backup.rid,
                              "t": round(t_detect, 6),
                              "requests": [r.rid
                                           for r in batch.requests]})
            serve_on(backup, t_detect, is_backup=True)

        if batch.affinity is not None:
            self.pool._revive()
            replica = self.pool.replica_by_rid(batch.affinity)
            if replica is None or replica.state != "healthy":
                replica = None
        else:
            replica = self.pool.pick_free(now)
            if replica is None:
                # force-drain path: queue the batch on the least-busy
                # replica (starts when it frees)
                replica = self.pool.least_busy()
        if replica is None:
            fail_batch(ReplicaWedged(
                f"no replica available for model {batch.model!r}"
                + (f" (session pinned to {batch.affinity})"
                   if batch.affinity is not None else "")), now)
            return
        serve_on(replica, now, is_backup=False)

    def _note_device_health(self, replica: Replica, elapsed: float) -> None:
        """Feed one completed dispatch's per-replica SERVICE time (the
        post-``slow_x`` compute component only — excluding injected
        ``slow_forward`` delay and cold-start warm tax, which would
        falsely flag healthy silicon) into the straggler EWMA ladder;
        when the ladder flags the replica
        (persistently over ``straggler_factor`` × the fleet median for
        ``flag_after`` windows), quarantine it: drain-then-retire with
        ``device_budget`` decremented, so capacity recovers on healthy
        silicon and nothing re-seats on the slow device."""
        flagged = self.health.observe_step_time(replica.rid, float(elapsed))
        if flagged is None:
            return
        pol = self.health.policy
        if not (pol.evict and self.health.eviction_budget_left):
            logger.warning("health: replica %d flagged as straggler but "
                           "eviction is %s — serving continues degraded",
                           flagged,
                           "off" if not pol.evict else "budget-exhausted")
            return
        victim = self.pool.replica_by_rid(flagged)
        width = victim.width if victim is not None else 1
        if self.pool.quarantine(flagged, reason="straggler"):
            self.health.note_quarantine(flagged, "straggler")
            if self.autoscaler is not None:
                self.autoscaler.note_quarantine(flagged, width)

    def _after_dispatch(self, batch: AssembledBatch, t0: float,
                        failed: bool) -> None:
        dt = self.clock.now() - t0
        if not failed and self.batcher.service_time is None:
            # the EWMA is only ever read when no explicit service model
            # is configured — don't maintain it for nobody
            self.batcher.observe_service_s(batch.edge, dt, tier=batch.tier,
                                           model=batch.model)
        if batch.redispatched:
            self.metrics.redispatches += 1
        self._since_decision += 1
        if self._since_decision >= self.decision_every:
            self._decide_window()

    def _decide_window(self) -> None:
        detail = {"shed_in_window": self._window_shed,
                  "queue_depth": self.queue.depth}
        if self.slo is not None:
            # SLO-driven path: window verdicts come from multi-window
            # burn rates over registry snapshots, not the raw flag —
            # the decision itself lands in the black box (Clockwork:
            # the action log explains the action)
            now = self.clock.now()
            self.slo.observe_registry(self.metrics.registry, now)
            decision = self.slo.decide(now)
            if self.obs is not None:
                self.obs.recorder.note(
                    "slo_decision", t=round(now, 6),
                    overloaded=decision.overloaded,
                    burning=list(decision.burning),
                    new_trips=list(decision.new_trips),
                    recovered=list(decision.recovered),
                    scale_hint=decision.scale_hint)
            if self._multi:
                self._observe_multi(decision, detail)
            else:
                self.ladder.observe_decision(decision, detail=detail)
            # mid-rollout anomaly: a fresh trip of the swapped model's
            # SLOs while replicas are still being swapped rolls back
            ctl = self._swap_ctl
            if ctl is not None and ctl["phase"] == "rolling" \
                    and decision.new_trips:
                hit = [s for s in decision.new_trips
                       if self._slo_model.get(s) == ctl["model"]]
                if hit:
                    self._swap_rollback(
                        "mid_rollout_anomaly: " + ",".join(hit))
            self._maybe_promote_lkg(decision)
            if self.autoscaler is not None:
                self._actuate(decision)
        else:
            if self._multi:
                for name, ladder in self.ladders.items():
                    depth_high = ladder.policy.depth_high * self.max_batch
                    overloaded = (
                        self._window_shed_by.get(name, 0) > 0
                        or self.queue.depth > depth_high)
                    ladder.observe_window(overloaded, detail=dict(detail))
            else:
                depth_high = self.ladder.policy.depth_high * self.max_batch
                overloaded = (self._window_shed > 0
                              or self.queue.depth > depth_high)
                self.ladder.observe_window(overloaded, detail=detail)
        self._window_shed = 0
        self._window_shed_by = {}
        self._since_decision = 0

    def _observe_multi(self, decision, detail: Dict[str, Any]) -> None:
        """Fan one SLO decision out to the per-model ladders and refresh
        the weighted-EDF weights: each model's ladder sees only ITS
        SLOs' burn, and its dispatch weight follows its worst
        fast-window burn (capped) — deadline weighted by how fast that
        model's error budget is being spent."""
        burning_by_model: Dict[str, List[str]] = {}
        for slo_name in decision.burning:
            m = self._slo_model.get(slo_name)
            if m is not None:
                burning_by_model.setdefault(m, []).append(slo_name)
        for name, ladder in self.ladders.items():
            cfg = self.models[name]
            if cfg.slos:
                burning = burning_by_model.get(name, [])
                d = {"slo_burning": burning,
                     "scale_hint": decision.scale_hint, **detail}
                ladder.observe_window(bool(burning), detail=d)
            else:
                # a model with no declared SLOs falls back to its raw
                # per-model shed flag
                ladder.observe_window(
                    self._window_shed_by.get(name, 0) > 0,
                    detail=dict(detail))
            if cfg.slos:
                worst = max((decision.per_slo[s.name]["fast"]["burn"]
                             for s in cfg.slos
                             if s.name in decision.per_slo),
                            default=0.0)
                w = min(max(1.0, 1.0 + worst), self.weight_cap)
                self.batcher.set_model_weight(name, w)
                self.metrics.registry.gauge(
                    f"serve/model_weight/model={name}").set(w)

    def _actuate(self, decision) -> None:
        """The autoscaler's policy loop, then the ACTUATION: a due
        target resizes the pool — growth pre-warms compiled geometries
        before the replica joins dispatch, shrink drains-then-retires
        (session-pinned replicas protected).  A :class:`Reshape`
        decision (the width-vs-count path, armed by
        ``policy.reshape_width``) instead swaps the saturated model's
        ladder onto wider slices — pool COUNT unchanged."""
        target = self.autoscaler.observe_decision(
            decision, self.pool.size,
            saturation=dict(self._fill_ewma) or None,
            widths=dict(self._model_width))
        if target is None:
            return
        if isinstance(target, Reshape):
            self._do_reshape(target)
            return
        protected = self._session_rids()
        if self.pool._swap is not None \
                and self.pool._swap["current"] is not None:
            # the rollout's current victim is mid-drain/warm: retiring
            # it would silently skip its swap step
            protected.add(self.pool._swap["current"])
        actions = self.pool.resize(target,
                                   prewarm=self.autoscaler.policy.prewarm,
                                   protected=sorted(protected))
        if self.obs is not None:
            self.obs.recorder.note(
                "autoscale", t=round(self.clock.now(), 6),
                target=target, grown=actions["grown"],
                drained=actions["drained"],
                burning=list(decision.burning))

    def _do_reshape(self, decision: Reshape) -> None:
        """Actuate a width reshape: the model's service model moves to
        ``to_width``-way sharded slices, and every replica's warm keys
        for that model are DROPPED — wider geometry means new compiled
        programs, so the next dispatch per geometry pays the cold-
        compile tax on the hot path (a reshape must not hide its
        recompile cost the way pre-warm hides growth's)."""
        self._model_width[decision.model] = decision.to_width
        dropped = 0
        for r in self.pool.replicas:
            if r.warm_keys:
                before = len(r.warm_keys)
                r.warm_keys = {k for k in r.warm_keys
                               if k[0] != decision.model}
                dropped += before - len(r.warm_keys)
        ev = {"kind": "autoscale_reshape", "model": decision.model,
              "from_width": decision.from_width,
              "to_width": decision.to_width,
              "fill": round(decision.fill, 6),
              "geometries_dropped": dropped,
              "t": round(self.clock.now(), 6),
              "rationale": decision.rationale}
        self._reshape_log.append(ev)
        self.pool._event(ev)
        if self.obs is not None:
            self.obs.recorder.note(
                "autoscale", t=round(self.clock.now(), 6),
                reshape=decision.model, to_width=decision.to_width,
                fill=round(decision.fill, 6),
                burning=list(decision.burning)
                if hasattr(decision, "burning") else [])

    # -- observability -------------------------------------------------------
    def accounting(self) -> Dict[str, Any]:
        """Request-conservation check: every submitted request is in
        exactly one terminal state once the runtime is drained —
        ``unaccounted == 0`` is the drill's hard invariant.  Exact in
        both retention modes: with ``retain_requests`` the states are
        recounted from the objects; without, the incrementally
        maintained terminal counters ARE the ledger (every terminal
        transition flows through the runtime)."""
        if self.retain_requests:
            by_state: Dict[str, int] = {}
            for r in self.requests:
                by_state[r.state] = by_state.get(r.state, 0) + 1
        else:
            by_state = dict(sorted(self._by_state.items()))
        terminal = sum(v for k, v in by_state.items()
                       if k in ("done", "shed", "timeout", "failed"))
        return {"submitted": self._submitted, "by_state": by_state,
                "terminal": terminal,
                "unaccounted": self._submitted - terminal}

    def snapshot(self) -> Dict[str, Any]:
        mesh_info = None
        if self.specs is not None:
            mesh_info = {
                "axes": dict(self.specs.mesh.shape),
                "data_axis_size": self.specs.data_axis_size,
            }
        out = {
            "mesh": mesh_info,
            "metrics": self.metrics.snapshot(),
            "queue": self.queue.snapshot(),
            "replicas": self.pool.snapshot(),
            "accounting": self.accounting(),
        }
        if self._multi:
            out["models"] = {
                name: {
                    "ladder": self.ladders[name].snapshot(),
                    "weight": self.batcher.model_weight(name),
                    "outcomes": self.metrics.model_snapshot(name),
                    "tiers": [{"name": t.name, "speed": t.speed}
                              for t in cfg.tiers],
                }
                for name, cfg in self.models.items()}
            out["sessions"] = {
                "opened": self._sessions_opened,
                "open": self._open_sessions,
                "failed": self._sessions_failed,
            }
            if self.autoscaler is not None:
                out["autoscale"] = self.autoscaler.snapshot()
                out["pool_size"] = self.pool.size
                out["cold_compiles"] = self.pool.cold_compiles
        else:
            out["ladder"] = self.ladder.snapshot()
            out["tiers"] = [{"name": t.name, "speed": t.speed,
                             "quality_note": t.quality_note}
                            for t in self.tiers]
        if self.slice_width > 1 or self._reshape_log:
            # keyed in only when replicas are slices or a reshape fired
            # (legacy snapshots byte-identical)
            out["slices"] = {
                "slice_width": self.slice_width,
                "devices_used": self.pool.devices_used,
                "device_budget": self.pool.device_budget,
                "model_width": dict(sorted(self._model_width.items())),
                "reshapes": [dict(e) for e in self._reshape_log],
            }
        if self.slo is not None:
            # keyed in only when armed, so pre-PR-11 snapshots (and the
            # banked RESILIENCE_r03/OBS_r01 replays) are byte-unchanged
            r = self.slo.report()
            out["slo"] = {k: r[k] for k in
                          ("slos", "windows", "decisions", "trips",
                           "peak_burns")}
        if self._swap_counter:
            # keyed in only once hot_swap was used (legacy snapshots
            # byte-identical)
            out["swap"] = {
                "rollouts": self._swap_counter,
                "completed": self._swap_stats["completed"],
                "rollbacks": self._swap_stats["rollbacks"],
                "trips": self._swap_stats["trips"],
                "lkg_promotions": self._swap_stats["lkg_promotions"],
                "history": [dict(h) for h in self._swap_log],
            }
        return out
