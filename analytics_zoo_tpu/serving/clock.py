"""Compatibility shim: the clock moved to ``analytics_zoo_tpu.utils.clock``.

The serving runtime grew the injected-clock abstraction first (PR 5);
PR 7's telemetry spine and the :class:`~analytics_zoo_tpu.resilience.
watchdog.StallWatchdog` need the same time source, so the classes now
live in :mod:`analytics_zoo_tpu.utils.clock` and are re-exported here
unchanged for existing imports (``from analytics_zoo_tpu.serving.clock
import VirtualClock`` keeps working)."""

from analytics_zoo_tpu.utils.clock import (  # noqa: F401
    Clock,
    MonotonicClock,
    VirtualClock,
    as_now_fn,
)

__all__ = ["Clock", "MonotonicClock", "VirtualClock", "as_now_fn"]
