"""Clock abstraction for the serving runtime.

Every scheduling decision in :mod:`analytics_zoo_tpu.serving` — deadline
slack, shed-before-dispatch, replica restart timers, degradation-ladder
windows — reads time through one injected clock object instead of
``time.monotonic`` directly.  Production uses :class:`MonotonicClock`;
tests and the committed drill use :class:`VirtualClock`, where time only
moves when the harness says so: a 4× overload burst with a mid-batch
replica crash then replays bit-identically in milliseconds of real CPU,
which is what lets ``RESILIENCE_r03.json`` pin exact shed counts and
tier transitions.

The same clock's ``now`` is handed to each replica's
:class:`~analytics_zoo_tpu.resilience.watchdog.StallWatchdog` (its
``clock=`` parameter), so stall supervision follows virtual time too.
"""

from __future__ import annotations

import time


class Clock:
    """Interface: ``now()`` seconds (monotonic), ``sleep(s)``."""

    def now(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError


class MonotonicClock(Clock):
    """Real wall time (``time.monotonic``)."""

    def now(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        time.sleep(max(0.0, seconds))


class VirtualClock(Clock):
    """Deterministic manual time: ``now()`` returns the current virtual
    instant; ``advance``/``sleep`` move it forward.  Single-threaded by
    design — the serving runtime's scheduler is synchronous, so nothing
    ever blocks waiting for another thread to advance the clock."""

    def __init__(self, start: float = 0.0):
        self._t = float(start)

    def now(self) -> float:
        return self._t

    def advance(self, seconds: float) -> float:
        if seconds < 0:
            raise ValueError(f"cannot advance time backwards ({seconds})")
        self._t += float(seconds)
        return self._t

    def sleep(self, seconds: float) -> None:
        self.advance(seconds)
