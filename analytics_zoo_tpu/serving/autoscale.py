"""Closed-loop autoscaling: the SLO burn-rate signal finally actuates.

PR 11 built the decision input — :class:`~analytics_zoo_tpu.obs.slo.
SloEvaluator` turns registry-snapshot windows into multi-window burn
rates and ``SloDecision.scale_hint`` (+1/0/−1) — and mirrored the burns
into ``slo/*`` gauges precisely so an autoscaler could consume them.
Nothing did.  This module is the actuator half of ROADMAP item 1:

- :class:`AutoscalePolicy` — the knobs: pool bounds, how many
  consecutive burning decisions trigger growth, how many consecutive
  well-under-budget decisions (``scale_hint == −1``) trigger a shrink,
  and a post-actuation cooldown.  The asymmetry deliberately mirrors
  the :class:`~analytics_zoo_tpu.serving.ladder.DegradationLadder`
  hysteresis: growing is cheap and urgent (capacity arrives warm via
  pre-warm), shrinking into still-marginal load re-creates the burn and
  flaps, so the shrink streak is long and any non-shrink hint resets
  it.

- :class:`Autoscaler` — the pure policy loop: feed it each decision
  window's :class:`~analytics_zoo_tpu.obs.slo.SloDecision` (what
  ``ServingRuntime`` does) or a raw registry snapshot's ``slo/*``
  gauges (:meth:`observe_registry` — the snapshot-only consumer the
  PR-11 mirroring promised), get back the target pool size when an
  actuation is due.  The RUNTIME executes the action through
  :meth:`~analytics_zoo_tpu.serving.replica.ReplicaPool.resize` —
  growth pre-warms compiled geometries before the replica joins
  dispatch, shrink drains-then-retires — so the policy here stays
  testable on hand-fed decision streams with no pool at all.

Semantics of the multi-window hint (``obs/slo.py``): ``+1`` only while
an SLO burns on BOTH windows (fast reacts, slow confirms) — a fast-
window-only spike holds rather than grows, exactly the blip the SRE
multi-window discipline exists to ignore; ``−1`` only when every SLO is
far under budget on both windows.  The policy adds streaks + cooldown
on top so a single noisy decision never bounces the pool.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Union

#: gauge-name prefix the snapshot-only observer reads (the PR-11
#: mirror: ``slo/fast_burn/slo=<name>`` / ``slo/slow_burn/slo=<name>``)
_FAST_PREFIX = "slo/fast_burn/slo="
_SLOW_PREFIX = "slo/slow_burn/slo="


@dataclasses.dataclass
class AutoscalePolicy:
    """Bounds + hysteresis for the policy loop.

    ``grow_after`` consecutive burning decisions (``scale_hint == +1``)
    → grow by ``step``; ``shrink_after`` consecutive clean-and-idle
    decisions (``scale_hint == −1``) → shrink by ``step``;
    ``cooldown`` decisions after any actuation ignore the streaks (the
    new capacity needs a window to move the burn rates before the loop
    reacts again).  ``prewarm``: whether growth pre-warms compiled
    geometries before joining dispatch (the drill's A/B knob).

    **Slice units** (ISSUE 19): when replicas are mesh slices
    (:class:`~analytics_zoo_tpu.serving.replica.ReplicaSlice`),
    ``min_replicas``/``max_replicas``/``step`` count SLICES of
    ``slice_width`` devices each, and ``device_budget`` (when set) is
    the hard device ceiling the bounds must fit inside — validated at
    construction, so a width-4 grow can never exceed the budget
    *silently*: a policy whose ``max_replicas × slice_width`` would
    over-subscribe the fleet is rejected up front rather than clamped
    at actuation time.

    **Width-vs-count** (the reshape path): ``reshape_width`` arms the
    alternative actuation — when growth is due AND a model's batch-fill
    EWMA shows it batch-saturated (``fill ≥ reshape_fill``), adding
    more width-``slice_width`` slices just splits an already-full batch
    across more replicas, each landing further below the ≈B/128
    occupancy knee (docs/MFU_CEILING.md) where the per-device matmuls
    starve.  The loop then returns a :class:`Reshape` (swap that
    model's tier ladder onto width-``reshape_width`` slices) instead of
    a count target.  ``None`` (default) disables the path entirely —
    the pre-ISSUE-19 decision stream is byte-identical.
    """

    min_replicas: int = 1
    max_replicas: int = 8
    grow_after: int = 1
    shrink_after: int = 6
    cooldown: int = 2
    step: int = 1
    prewarm: bool = True
    slice_width: int = 1
    device_budget: Optional[int] = None
    reshape_width: Optional[int] = None
    reshape_fill: float = 0.9

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.grow_after < 1 or self.shrink_after < 1 or self.step < 1:
            raise ValueError("grow_after/shrink_after/step must be >= 1")
        if self.cooldown < 0:
            raise ValueError("cooldown must be >= 0")
        if self.slice_width < 1:
            raise ValueError("slice_width must be >= 1")
        if self.device_budget is not None:
            if self.min_replicas * self.slice_width > self.device_budget:
                raise ValueError(
                    f"min_replicas={self.min_replicas} slices of width "
                    f"{self.slice_width} need "
                    f"{self.min_replicas * self.slice_width} devices — "
                    f"over device_budget={self.device_budget}: the "
                    f"floor itself does not fit")
            if self.max_replicas * self.slice_width > self.device_budget:
                raise ValueError(
                    f"max_replicas={self.max_replicas} × slice_width="
                    f"{self.slice_width} = "
                    f"{self.max_replicas * self.slice_width} devices "
                    f"exceeds device_budget={self.device_budget} — "
                    f"bounds are in SLICE units; set max_replicas <= "
                    f"device_budget // slice_width so a width-"
                    f"{self.slice_width} grow cannot over-subscribe "
                    f"the fleet silently")
        if not (0.0 < self.reshape_fill <= 1.0):
            raise ValueError("reshape_fill must be in (0, 1]")
        if self.reshape_width is not None:
            if self.reshape_width <= self.slice_width:
                raise ValueError(
                    f"reshape_width={self.reshape_width} must exceed "
                    f"slice_width={self.slice_width} — a reshape swaps "
                    f"a saturated model onto WIDER slices")
            if self.device_budget is not None \
                    and self.reshape_width > self.device_budget:
                raise ValueError(
                    f"reshape_width={self.reshape_width} exceeds "
                    f"device_budget={self.device_budget}: one reshaped "
                    f"slice would not fit the fleet")

    @property
    def max_devices(self) -> int:
        """The pool ceiling in DEVICE units — what the bounds actually
        spend (``device_budget`` when set, else max_replicas slices)."""
        if self.device_budget is not None:
            return self.device_budget
        return self.max_replicas * self.slice_width


#: occupancy knee the width-vs-count rationale references: per-device
#: batch ≈ B/128 is where the serving matmuls stop gaining from more
#: batch (docs/MFU_CEILING.md) — BELOW it, width-w splits the batch w
#: ways and each shard idles; AT it, width buys ~w× service.
OCCUPANCY_KNEE = 128


@dataclasses.dataclass(frozen=True)
class Reshape:
    """The width-grow decision (ISSUE 19): swap ``model``'s tier ladder
    from width-``from_width`` slices onto width-``to_width`` slices
    instead of adding more narrow replicas.  Returned by the policy
    loop only when the model's batch-fill EWMA (``fill``) shows it
    batch-saturated — the regime where count-growth splits a full batch
    below the occupancy knee and buys nothing.  ``rationale`` records
    the occupancy math the decision banked."""

    model: str
    from_width: int
    to_width: int
    fill: float
    rationale: str


class Autoscaler:
    """The policy loop: decisions in, target pool sizes out.

    ``registry`` (optional): actuations and the current/target sizes
    are mirrored into it (``autoscale/*`` — see ``obs/names.py``) so a
    scrape shows what the loop did and why-shaped counters
    (grow/shrink/hold) accumulate.  ``events`` is the deterministic
    action log the drill banks.
    """

    def __init__(self, policy: Optional[AutoscalePolicy] = None,
                 registry=None):
        self.policy = policy or AutoscalePolicy()
        self.registry = registry
        self.grow_streak = 0
        self.shrink_streak = 0
        self.cooldown_left = 0
        self.decisions = 0
        self.grows = 0
        self.shrinks = 0
        self.holds = 0
        self.reshapes = 0
        #: actuation freeze (the hot-swap canary stage sets this): the
        #: loop keeps observing — streaks and cooldown advance normally —
        #: but no target is returned while held.  A canary burn must
        #: trip the ROLLBACK, not mask itself behind fresh capacity.
        self.hold = False
        #: devices lost to health quarantines (note_quarantine) — the
        #: scaler's record of why its ceiling shrank: the pool's
        #: device_budget decrement is the enforcement, this is the log
        self.evicted_devices = 0
        self.events: List[Dict[str, Any]] = []

    # -- feed ----------------------------------------------------------------
    def observe_decision(self, decision, current_size: int,
                         t: Optional[float] = None,
                         saturation: Optional[Dict[str, float]] = None,
                         widths: Optional[Dict[str, int]] = None,
                         ) -> Union[int, Reshape, None]:
        """Feed one :class:`~analytics_zoo_tpu.obs.slo.SloDecision`;
        returns the new TARGET pool size when an actuation is due,
        else ``None`` (hold).  ``saturation``/``widths`` (per-model
        batch-fill EWMA and current slice width — fed by the runtime)
        enable the :class:`Reshape` alternative when the policy arms
        ``reshape_width``."""
        return self.observe_hint(decision.scale_hint, current_size,
                                 t=decision.t if t is None else t,
                                 burning=list(decision.burning),
                                 saturation=saturation, widths=widths)

    def observe_registry(self, snapshot: Dict[str, Any],
                         current_size: int,
                         t: float,
                         fast_burn: float = 2.0, slow_burn: float = 1.0,
                         recover_burn: float = 0.5) -> Optional[int]:
        """Snapshot-only path: reconstruct the hint from the mirrored
        ``slo/*_burn`` gauges of one ``MetricRegistry.snapshot()`` —
        the consumer shape PR 11 promised (no evaluator object needed).
        Burning = fast ≥ ``fast_burn`` AND slow ≥ ``slow_burn`` per
        SLO; idle = every burn ≤ ``recover_burn`` on both windows."""
        gauges = snapshot.get("gauges", {})
        fast = {k[len(_FAST_PREFIX):]: float(v)
                for k, v in gauges.items() if k.startswith(_FAST_PREFIX)}
        slow = {k[len(_SLOW_PREFIX):]: float(v)
                for k, v in gauges.items() if k.startswith(_SLOW_PREFIX)}
        burning = [name for name in fast
                   if fast[name] >= fast_burn
                   and slow.get(name, 0.0) >= slow_burn]
        if burning:
            hint = 1
        elif fast and all(v <= recover_burn for v in fast.values()) \
                and all(v <= recover_burn for v in slow.values()):
            hint = -1
        else:
            hint = 0
        return self.observe_hint(hint, current_size, t=t, burning=burning)

    def observe_hint(self, hint: int, current_size: int, t: float = 0.0,
                     burning: Optional[List[str]] = None,
                     saturation: Optional[Dict[str, float]] = None,
                     widths: Optional[Dict[str, int]] = None,
                     ) -> Union[int, Reshape, None]:
        """The core loop on a bare ``scale_hint``.  Streak discipline:
        +1 grows the grow streak and kills the shrink streak; −1 the
        inverse; 0 (a fast-only spike, or mixed signals) kills BOTH —
        holding is the correct response to an unconfirmed burn.

        With ``reshape_width`` armed and ``saturation`` provided, a due
        grow first checks width-vs-count: a model whose batch-fill EWMA
        is at/above ``reshape_fill`` (and not yet at ``reshape_width``)
        gets a :class:`Reshape` instead of a count target — more narrow
        replicas would split its already-full batches below the ≈B/128
        occupancy knee (docs/MFU_CEILING.md, :data:`OCCUPANCY_KNEE`),
        while one wider slice serves the full batch at knee occupancy.
        """
        self.decisions += 1
        p = self.policy
        if self.cooldown_left > 0:
            self.cooldown_left -= 1
            self._export(current_size)
            return None
        if hint > 0:
            self.shrink_streak = 0
            self.grow_streak += 1
        elif hint < 0:
            self.grow_streak = 0
            self.shrink_streak += 1
        else:
            self.grow_streak = 0
            self.shrink_streak = 0
        target: Optional[int] = None
        action = None
        if self.grow_streak >= p.grow_after \
                and current_size < p.max_replicas:
            target = min(current_size + p.step, p.max_replicas)
            action = "grow"
        elif self.shrink_streak >= p.shrink_after \
                and current_size > p.min_replicas:
            target = max(current_size - p.step, p.min_replicas)
            action = "shrink"
        if target is not None and self.hold:
            # held (mid-canary): swallow the actuation, keep the streak
            # reset + cooldown so release doesn't fire a stale decision
            self.holds += 1
            self.events.append({
                "kind": "scale_held", "t": round(t, 6),
                "from": current_size, "would": target,
                "action": action, "burning": list(burning or [])})
            self.grow_streak = 0
            self.shrink_streak = 0
            self.cooldown_left = p.cooldown
            self._export(current_size)
            return None
        if action == "grow" and p.reshape_width is not None \
                and saturation:
            # width-vs-count: the most batch-saturated model decides.
            # At/above the fill bar, count-growth splits a full batch
            # below the occupancy knee — swap THIS model onto wider
            # slices instead (the runtime actuates via its reshape
            # path; pool size is unchanged, so no count target).
            model = max(sorted(saturation), key=lambda m: saturation[m])
            fill = float(saturation[model])
            from_w = int((widths or {}).get(model, p.slice_width))
            if fill >= p.reshape_fill and from_w < p.reshape_width:
                self.reshapes += 1
                self.grow_streak = 0
                self.shrink_streak = 0
                self.cooldown_left = p.cooldown
                rationale = (
                    f"batch-fill EWMA {fill:.3f} >= {p.reshape_fill:.2f}"
                    f": {model!r} is batch-saturated — +{p.step} width-"
                    f"{from_w} replica(s) would split full batches "
                    f"below the ~B/{OCCUPANCY_KNEE} occupancy knee "
                    f"(docs/MFU_CEILING.md), while a width-"
                    f"{p.reshape_width} slice serves them at knee "
                    f"occupancy for ~{p.reshape_width / from_w:.0f}x "
                    f"service")
                self.events.append({
                    "kind": "scale_reshape", "t": round(t, 6),
                    "model": model, "from_width": from_w,
                    "to_width": p.reshape_width,
                    "fill": round(fill, 6),
                    "burning": list(burning or []),
                    "rationale": rationale})
                if self.registry is not None:
                    self.registry.counter("autoscale/reshape").inc()
                self._export(current_size)
                return Reshape(model=model, from_width=from_w,
                               to_width=p.reshape_width, fill=fill,
                               rationale=rationale)
        if target is not None:
            if action == "grow":
                self.grows += 1
            else:
                self.shrinks += 1
            self.grow_streak = 0
            self.shrink_streak = 0
            self.cooldown_left = p.cooldown
            self.events.append({
                "kind": f"scale_{action}", "t": round(t, 6),
                "from": current_size, "to": target,
                "burning": list(burning or []),
                "prewarm": p.prewarm})
            if self.registry is not None:
                if action == "grow":
                    self.registry.counter("autoscale/grow").inc()
                else:
                    self.registry.counter("autoscale/shrink").inc()
        self._export(current_size if target is None else target)
        return target

    def note_quarantine(self, replica: int, width: int = 1) -> None:
        """The runtime quarantined ``replica`` (health eviction): its
        ``width`` devices left the fleet permanently, unlike a scale-in
        the next grow could reverse.  Logged so a postmortem can tell an
        autoscaler decision from a health eviction; the hard ceiling
        lives in the pool's decremented ``device_budget``."""
        self.evicted_devices += int(width)
        self.events.append({"kind": "quarantine", "replica": int(replica),
                            "width": int(width),
                            "evicted_devices": self.evicted_devices})

    def _export(self, size: int) -> None:
        if self.registry is not None:
            self.registry.gauge("autoscale/replicas").set(float(size))

    # -- read ----------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        return {
            "policy": dataclasses.asdict(self.policy),
            "decisions": self.decisions,
            "grows": self.grows,
            "shrinks": self.shrinks,
            "holds": self.holds,
            "reshapes": self.reshapes,
            "evicted_devices": self.evicted_devices,
            "actions": list(self.events),
        }
