"""Closed-loop autoscaling: the SLO burn-rate signal finally actuates.

PR 11 built the decision input — :class:`~analytics_zoo_tpu.obs.slo.
SloEvaluator` turns registry-snapshot windows into multi-window burn
rates and ``SloDecision.scale_hint`` (+1/0/−1) — and mirrored the burns
into ``slo/*`` gauges precisely so an autoscaler could consume them.
Nothing did.  This module is the actuator half of ROADMAP item 1:

- :class:`AutoscalePolicy` — the knobs: pool bounds, how many
  consecutive burning decisions trigger growth, how many consecutive
  well-under-budget decisions (``scale_hint == −1``) trigger a shrink,
  and a post-actuation cooldown.  The asymmetry deliberately mirrors
  the :class:`~analytics_zoo_tpu.serving.ladder.DegradationLadder`
  hysteresis: growing is cheap and urgent (capacity arrives warm via
  pre-warm), shrinking into still-marginal load re-creates the burn and
  flaps, so the shrink streak is long and any non-shrink hint resets
  it.

- :class:`Autoscaler` — the pure policy loop: feed it each decision
  window's :class:`~analytics_zoo_tpu.obs.slo.SloDecision` (what
  ``ServingRuntime`` does) or a raw registry snapshot's ``slo/*``
  gauges (:meth:`observe_registry` — the snapshot-only consumer the
  PR-11 mirroring promised), get back the target pool size when an
  actuation is due.  The RUNTIME executes the action through
  :meth:`~analytics_zoo_tpu.serving.replica.ReplicaPool.resize` —
  growth pre-warms compiled geometries before the replica joins
  dispatch, shrink drains-then-retires — so the policy here stays
  testable on hand-fed decision streams with no pool at all.

Semantics of the multi-window hint (``obs/slo.py``): ``+1`` only while
an SLO burns on BOTH windows (fast reacts, slow confirms) — a fast-
window-only spike holds rather than grows, exactly the blip the SRE
multi-window discipline exists to ignore; ``−1`` only when every SLO is
far under budget on both windows.  The policy adds streaks + cooldown
on top so a single noisy decision never bounces the pool.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

#: gauge-name prefix the snapshot-only observer reads (the PR-11
#: mirror: ``slo/fast_burn/slo=<name>`` / ``slo/slow_burn/slo=<name>``)
_FAST_PREFIX = "slo/fast_burn/slo="
_SLOW_PREFIX = "slo/slow_burn/slo="


@dataclasses.dataclass
class AutoscalePolicy:
    """Bounds + hysteresis for the policy loop.

    ``grow_after`` consecutive burning decisions (``scale_hint == +1``)
    → grow by ``step``; ``shrink_after`` consecutive clean-and-idle
    decisions (``scale_hint == −1``) → shrink by ``step``;
    ``cooldown`` decisions after any actuation ignore the streaks (the
    new capacity needs a window to move the burn rates before the loop
    reacts again).  ``prewarm``: whether growth pre-warms compiled
    geometries before joining dispatch (the drill's A/B knob).
    """

    min_replicas: int = 1
    max_replicas: int = 8
    grow_after: int = 1
    shrink_after: int = 6
    cooldown: int = 2
    step: int = 1
    prewarm: bool = True

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.grow_after < 1 or self.shrink_after < 1 or self.step < 1:
            raise ValueError("grow_after/shrink_after/step must be >= 1")
        if self.cooldown < 0:
            raise ValueError("cooldown must be >= 0")


class Autoscaler:
    """The policy loop: decisions in, target pool sizes out.

    ``registry`` (optional): actuations and the current/target sizes
    are mirrored into it (``autoscale/*`` — see ``obs/names.py``) so a
    scrape shows what the loop did and why-shaped counters
    (grow/shrink/hold) accumulate.  ``events`` is the deterministic
    action log the drill banks.
    """

    def __init__(self, policy: Optional[AutoscalePolicy] = None,
                 registry=None):
        self.policy = policy or AutoscalePolicy()
        self.registry = registry
        self.grow_streak = 0
        self.shrink_streak = 0
        self.cooldown_left = 0
        self.decisions = 0
        self.grows = 0
        self.shrinks = 0
        self.holds = 0
        #: actuation freeze (the hot-swap canary stage sets this): the
        #: loop keeps observing — streaks and cooldown advance normally —
        #: but no target is returned while held.  A canary burn must
        #: trip the ROLLBACK, not mask itself behind fresh capacity.
        self.hold = False
        self.events: List[Dict[str, Any]] = []

    # -- feed ----------------------------------------------------------------
    def observe_decision(self, decision, current_size: int,
                         t: Optional[float] = None) -> Optional[int]:
        """Feed one :class:`~analytics_zoo_tpu.obs.slo.SloDecision`;
        returns the new TARGET pool size when an actuation is due,
        else ``None`` (hold)."""
        return self.observe_hint(decision.scale_hint, current_size,
                                 t=decision.t if t is None else t,
                                 burning=list(decision.burning))

    def observe_registry(self, snapshot: Dict[str, Any],
                         current_size: int,
                         t: float,
                         fast_burn: float = 2.0, slow_burn: float = 1.0,
                         recover_burn: float = 0.5) -> Optional[int]:
        """Snapshot-only path: reconstruct the hint from the mirrored
        ``slo/*_burn`` gauges of one ``MetricRegistry.snapshot()`` —
        the consumer shape PR 11 promised (no evaluator object needed).
        Burning = fast ≥ ``fast_burn`` AND slow ≥ ``slow_burn`` per
        SLO; idle = every burn ≤ ``recover_burn`` on both windows."""
        gauges = snapshot.get("gauges", {})
        fast = {k[len(_FAST_PREFIX):]: float(v)
                for k, v in gauges.items() if k.startswith(_FAST_PREFIX)}
        slow = {k[len(_SLOW_PREFIX):]: float(v)
                for k, v in gauges.items() if k.startswith(_SLOW_PREFIX)}
        burning = [name for name in fast
                   if fast[name] >= fast_burn
                   and slow.get(name, 0.0) >= slow_burn]
        if burning:
            hint = 1
        elif fast and all(v <= recover_burn for v in fast.values()) \
                and all(v <= recover_burn for v in slow.values()):
            hint = -1
        else:
            hint = 0
        return self.observe_hint(hint, current_size, t=t, burning=burning)

    def observe_hint(self, hint: int, current_size: int, t: float = 0.0,
                     burning: Optional[List[str]] = None) -> Optional[int]:
        """The core loop on a bare ``scale_hint``.  Streak discipline:
        +1 grows the grow streak and kills the shrink streak; −1 the
        inverse; 0 (a fast-only spike, or mixed signals) kills BOTH —
        holding is the correct response to an unconfirmed burn."""
        self.decisions += 1
        p = self.policy
        if self.cooldown_left > 0:
            self.cooldown_left -= 1
            self._export(current_size)
            return None
        if hint > 0:
            self.shrink_streak = 0
            self.grow_streak += 1
        elif hint < 0:
            self.grow_streak = 0
            self.shrink_streak += 1
        else:
            self.grow_streak = 0
            self.shrink_streak = 0
        target: Optional[int] = None
        action = None
        if self.grow_streak >= p.grow_after \
                and current_size < p.max_replicas:
            target = min(current_size + p.step, p.max_replicas)
            action = "grow"
        elif self.shrink_streak >= p.shrink_after \
                and current_size > p.min_replicas:
            target = max(current_size - p.step, p.min_replicas)
            action = "shrink"
        if target is not None and self.hold:
            # held (mid-canary): swallow the actuation, keep the streak
            # reset + cooldown so release doesn't fire a stale decision
            self.holds += 1
            self.events.append({
                "kind": "scale_held", "t": round(t, 6),
                "from": current_size, "would": target,
                "action": action, "burning": list(burning or [])})
            self.grow_streak = 0
            self.shrink_streak = 0
            self.cooldown_left = p.cooldown
            self._export(current_size)
            return None
        if target is not None:
            if action == "grow":
                self.grows += 1
            else:
                self.shrinks += 1
            self.grow_streak = 0
            self.shrink_streak = 0
            self.cooldown_left = p.cooldown
            self.events.append({
                "kind": f"scale_{action}", "t": round(t, 6),
                "from": current_size, "to": target,
                "burning": list(burning or []),
                "prewarm": p.prewarm})
            if self.registry is not None:
                if action == "grow":
                    self.registry.counter("autoscale/grow").inc()
                else:
                    self.registry.counter("autoscale/shrink").inc()
        self._export(current_size if target is None else target)
        return target

    def _export(self, size: int) -> None:
        if self.registry is not None:
            self.registry.gauge("autoscale/replicas").set(float(size))

    # -- read ----------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        return {
            "policy": dataclasses.asdict(self.policy),
            "decisions": self.decisions,
            "grows": self.grows,
            "shrinks": self.shrinks,
            "holds": self.holds,
            "actions": list(self.events),
        }
