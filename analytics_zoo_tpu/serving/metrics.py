"""Serving metrics: the numbers an operator (and the drill) reads.

Counters and reservoirs only — no wall-clock reads of its own; every
timestamp comes from the runtime's injected clock, so a virtual-clock
run produces a bit-deterministic snapshot.  Exported as one plain dict
(:meth:`ServingMetrics.snapshot`) the drill dumps into
``RESILIENCE_r03.json`` and an operator would scrape.

Since PR 7 the distributions live in a central
:class:`~analytics_zoo_tpu.obs.registry.MetricRegistry` (bounded
reservoir histograms): per-tier latency, batch fill, and queue depth
used to be unbounded Python lists full-sorted on every snapshot — a
million-request drill now costs O(1) memory per tier and the registry
is directly scrapeable (``obs.render_prometheus``) / bridgeable to
TensorBoard (``obs.SummaryBridge``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from analytics_zoo_tpu.obs.registry import (MetricRegistry, nearest_rank)


def percentile(xs: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (deterministic, no interpolation noise
    across numpy versions); None on empty.  Kept as the public helper;
    the per-tier snapshots now come from bounded reservoirs instead of
    sorting full histories."""
    return nearest_rank(sorted(float(x) for x in xs), q)


class ServingMetrics:
    """Aggregates per-request outcomes and per-dispatch observations.

    ``registry``: the central :class:`MetricRegistry` everything
    registers into (one is created when not supplied — the runtime
    passes the session's, so serving metrics land beside train/data
    metrics in the same snapshot).  Metric names: ``serve/submitted``,
    ``serve/shed/cause=...``, ``serve/latency_s/tier=N``,
    ``serve/batch_fill``, ``serve/queue_depth``, ``serve/redispatches``.
    """

    def __init__(self, registry: Optional[MetricRegistry] = None,
                 reservoir: int = 2048):
        self.registry = registry if registry is not None else MetricRegistry()
        self.reservoir = int(reservoir)
        self._r = self.registry
        self.deadline_misses = 0        # completed but late
        self._tiers: List[int] = []     # tiers with ≥1 completion, sorted

    # -- feed ----------------------------------------------------------------
    # ``model`` (multiplexed runtimes, ISSUE 14): outcomes additionally
    # land under model-labeled names (``serve/<metric>/model=<m>...``) so
    # per-model SLOs and the fleet drill read per-model rates; the
    # unlabeled totals are always maintained, so single-model snapshots
    # (and the banked RESILIENCE_r03 / OBS_r01 replays) are unchanged.
    def on_submit(self, model: Optional[str] = None) -> None:
        self._r.counter("serve/submitted").inc()
        if model is not None:
            self._r.counter(f"serve/submitted/model={model}").inc()

    def on_shed(self, cause: str, model: Optional[str] = None) -> None:
        self._r.counter(f"serve/shed/cause={cause}").inc()
        if model is not None:
            self._r.counter(f"serve/shed/model={model}/cause={cause}").inc()

    def on_complete(self, latency_s: float, tier: int, missed: bool,
                    model: Optional[str] = None) -> None:
        self._r.counter("serve/completed").inc()
        tier = int(tier)
        if tier not in self._tiers:
            self._tiers = sorted(self._tiers + [tier])
        self._r.histogram(f"serve/latency_s/tier={tier}",
                          max_samples=self.reservoir).observe(latency_s)
        if model is not None:
            self._r.counter(f"serve/completed/model={model}").inc()
            self._r.histogram(f"serve/latency_s/model={model}/tier={tier}",
                              max_samples=self.reservoir).observe(latency_s)
        if missed:
            self.deadline_misses += 1
            self._r.counter("serve/deadline_misses_completed_late").inc()
            if model is not None:
                self._r.counter(
                    f"serve/deadline_misses_completed_late/model={model}"
                ).inc()

    def on_fail(self, model: Optional[str] = None) -> None:
        self._r.counter("serve/failed").inc()
        if model is not None:
            self._r.counter(f"serve/failed/model={model}").inc()

    def on_batch(self, n_valid: int, max_batch: int,
                 queue_depth: int) -> None:
        # redispatches are counted post-dispatch by the runtime (the
        # failover latch is unknown before the pool runs the batch)
        self._r.counter("serve/batches").inc()
        self._r.histogram("serve/batch_fill",
                          max_samples=self.reservoir).observe(
            n_valid / max(max_batch, 1))
        self._r.histogram("serve/queue_depth",
                          max_samples=self.reservoir).observe(
            float(queue_depth))

    # -- read ----------------------------------------------------------------
    def _count(self, name: str) -> int:
        # az-allow: registered-metric-names — read-side accessor over names this class itself registered (all declared serve/* entries)
        return self._r.counter(name).value

    @property
    def submitted(self) -> int:
        return self._count("serve/submitted")

    @property
    def completed(self) -> int:
        return self._count("serve/completed")

    @property
    def failed(self) -> int:
        return self._count("serve/failed")

    @property
    def batches(self) -> int:
        return self._count("serve/batches")

    @property
    def redispatches(self) -> int:
        return self._count("serve/redispatches")

    @redispatches.setter
    def redispatches(self, v: int) -> None:
        c = self._r.counter("serve/redispatches")
        if v < c.value:
            raise ValueError("redispatches is monotonic")
        c.inc(v - c.value)

    @property
    def shed_by_cause(self) -> Dict[str, int]:
        prefix = "serve/shed/cause="
        return {name[len(prefix):]: m.value
                for name, m in self._r.metrics().items()
                if name.startswith(prefix)}

    @property
    def shed_total(self) -> int:
        return sum(self.shed_by_cause.values())

    def miss_rate(self, model: Optional[str] = None) -> Optional[float]:
        """Deadline-miss rate over all requests with a terminal state:
        a shed/timed-out request missed its deadline by definition, a
        completed-late one missed it in the client's hands.  THE number
        the shedding-vs-baseline comparison uses.  ``model`` narrows it
        to one multiplexed model's requests."""
        if model is None:
            completed, failed, shed = (self.completed, self.failed,
                                       self.shed_total)
            late = self.deadline_misses
        else:
            completed = self._count(f"serve/completed/model={model}")
            failed = self._count(f"serve/failed/model={model}")
            prefix = f"serve/shed/model={model}/cause="
            shed = sum(m.value for name, m in self._r.metrics().items()
                       if name.startswith(prefix))
            late = self._count(
                f"serve/deadline_misses_completed_late/model={model}")
        terminal = completed + failed + shed
        if terminal == 0:
            return None
        return (late + failed + shed) / terminal

    def model_snapshot(self, model: str) -> Dict[str, Any]:
        """Per-model outcome summary for a multiplexed runtime's
        snapshot (counts + miss rate; latency stays in the registry's
        model-labeled reservoirs)."""
        prefix = f"serve/shed/model={model}/cause="
        return {
            "submitted": self._count(f"serve/submitted/model={model}"),
            "completed": self._count(f"serve/completed/model={model}"),
            "failed": self._count(f"serve/failed/model={model}"),
            "shed": sum(m.value for name, m in self._r.metrics().items()
                        if name.startswith(prefix)),
            "completed_late": self._count(
                f"serve/deadline_misses_completed_late/model={model}"),
            "deadline_miss_rate": self.miss_rate(model=model),
        }

    def snapshot(self) -> Dict[str, Any]:
        lat = {}
        for tier in self._tiers:
            h = self._r.histogram(f"serve/latency_s/tier={tier}",
                                  max_samples=self.reservoir)
            hs = h.snapshot()
            lat[str(tier)] = {
                "n": hs["count"],
                "p50_s": hs["p50"],
                "p99_s": hs["p99"],
                "max_s": hs["max"],
                "sampled": hs["sampled"],
            }
        fill = self._r.histogram("serve/batch_fill",
                                 max_samples=self.reservoir).snapshot()
        depth = self._r.histogram("serve/queue_depth",
                                  max_samples=self.reservoir).snapshot()
        return {
            "submitted": self.submitted,
            "completed": self.completed,
            "failed": self.failed,
            "shed_by_cause": dict(sorted(self.shed_by_cause.items())),
            "shed_total": self.shed_total,
            "deadline_misses_completed_late": self.deadline_misses,
            "deadline_miss_rate": self.miss_rate(),
            "batches": self.batches,
            "redispatched_batches": self.redispatches,
            "mean_batch_fill": fill["mean"],
            "queue_depth_p50": depth["p50"],
            "queue_depth_max": (int(depth["max"])
                                if depth["max"] is not None else None),
            "latency_by_tier": lat,
        }
