"""Serving metrics: the numbers an operator (and the drill) reads.

Counters and reservoirs only — no wall-clock reads of its own; every
timestamp comes from the runtime's injected clock, so a virtual-clock
run produces a bit-deterministic snapshot.  Exported as one plain dict
(:meth:`ServingMetrics.snapshot`) the drill dumps into
``RESILIENCE_r03.json`` and an operator would scrape.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


def percentile(xs: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (deterministic, no interpolation noise
    across numpy versions); None on empty."""
    if not xs:
        return None
    s = sorted(xs)
    k = min(len(s) - 1, max(0, int(np.ceil(q / 100.0 * len(s))) - 1))
    return float(s[k])


class ServingMetrics:
    """Aggregates per-request outcomes and per-dispatch observations."""

    def __init__(self):
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.shed_by_cause: Dict[str, int] = {}
        self.deadline_misses = 0        # completed but late
        self.batches = 0
        self.batch_fill: List[float] = []       # n_valid / max_batch
        self.queue_depth_samples: List[int] = []
        self.latency_by_tier: Dict[int, List[float]] = {}
        self.redispatches = 0

    # -- feed ----------------------------------------------------------------
    def on_submit(self) -> None:
        self.submitted += 1

    def on_shed(self, cause: str) -> None:
        self.shed_by_cause[cause] = self.shed_by_cause.get(cause, 0) + 1

    def on_complete(self, latency_s: float, tier: int, missed: bool) -> None:
        self.completed += 1
        self.latency_by_tier.setdefault(int(tier), []).append(
            float(latency_s))
        if missed:
            self.deadline_misses += 1

    def on_fail(self) -> None:
        self.failed += 1

    def on_batch(self, n_valid: int, max_batch: int,
                 queue_depth: int) -> None:
        # redispatches are counted post-dispatch by the runtime (the
        # failover latch is unknown before the pool runs the batch)
        self.batches += 1
        self.batch_fill.append(n_valid / max(max_batch, 1))
        self.queue_depth_samples.append(int(queue_depth))

    # -- read ----------------------------------------------------------------
    @property
    def shed_total(self) -> int:
        return sum(self.shed_by_cause.values())

    def miss_rate(self) -> Optional[float]:
        """Deadline-miss rate over all requests with a terminal state:
        a shed/timed-out request missed its deadline by definition, a
        completed-late one missed it in the client's hands.  THE number
        the shedding-vs-baseline comparison uses."""
        terminal = self.completed + self.failed + self.shed_total
        if terminal == 0:
            return None
        missed = self.deadline_misses + self.failed + self.shed_total
        return missed / terminal

    def snapshot(self) -> Dict[str, Any]:
        lat = {
            str(tier): {
                "n": len(xs),
                "p50_s": percentile(xs, 50),
                "p99_s": percentile(xs, 99),
                "max_s": max(xs) if xs else None,
            }
            for tier, xs in sorted(self.latency_by_tier.items())
        }
        return {
            "submitted": self.submitted,
            "completed": self.completed,
            "failed": self.failed,
            "shed_by_cause": dict(sorted(self.shed_by_cause.items())),
            "shed_total": self.shed_total,
            "deadline_misses_completed_late": self.deadline_misses,
            "deadline_miss_rate": self.miss_rate(),
            "batches": self.batches,
            "redispatched_batches": self.redispatches,
            "mean_batch_fill": (float(np.mean(self.batch_fill))
                                if self.batch_fill else None),
            "queue_depth_p50": percentile(
                [float(x) for x in self.queue_depth_samples], 50),
            "queue_depth_max": (max(self.queue_depth_samples)
                                if self.queue_depth_samples else None),
            "latency_by_tier": lat,
        }
