"""Replica health supervision, exactly-once batch failover, and the
elastic pool the autoscaler actuates.

A serving cell runs N replicas of the model (N devices, or N mesh
shards each presenting as one replica).  A replica can fail two ways
mid-batch: its forward *raises* (device lost, injected crash), or it
*wedges* — makes no progress past its
:class:`~analytics_zoo_tpu.resilience.watchdog.StallWatchdog` deadline
(the PR-1 failure mode that otherwise blocks the host loop silently).
Either way the pool

1. **fences** the replica — state ``fenced``, no further dispatches;
2. **re-dispatches** the in-flight batch to a healthy replica EXACTLY
   once (``AssembledBatch.redispatched`` latch — a batch that fails its
   second replica fails its requests with
   :class:`~analytics_zoo_tpu.resilience.errors.ReplicaWedged` rather
   than ping-ponging through the whole pool and amplifying overload);
3. **restarts** the fenced replica in the background — modeled as a
   ``restart_s`` cooldown on the runtime clock; once it elapses the
   next dispatch cycle re-admits the replica (and its jit cache is
   assumed cold, which is why restarts must not be free).

**Fence budget** (ISSUE 14 satellite — the OBS_r02 p99 fix): by default
a wedged forward is only *observed* when it finally returns, so its
batch rides out the whole stall before re-dispatch — exactly the
``failover_redispatch`` segment the banked tail attribution blamed for
95 % of the p99 cohort gap.  ``ReplicaPool(fence_budget_s=...)`` bounds
that: every virtual sleep inside a supervised forward goes through the
budget guard, and the moment the forward's elapsed time would cross the
budget the replica raises :class:`ReplicaWedged` *at the fence instant*
— the pool fences and re-dispatches **on the fence**, not on the wedged
forward's eventual return, so the redispatch segment is bounded by the
knob.  ``None`` keeps the PR-5 return-then-check behavior (the banked
RESILIENCE_r03 / OBS_r01 / OBS_r02 replays are byte-identical).

**Elasticity** (ISSUE 14 tentpole): :meth:`ReplicaPool.resize` is the
autoscaler's actuator.  Growth builds replicas through the pool's
``replica_factory`` and — when compiled-geometry modeling is armed
(``compile_s`` > 0 with a ``prewarm_keys`` plan) — **pre-warms** them:
the new replica sits in state ``warming`` while its per-(model, edge,
tier) programs compile, joining dispatch only once every planned
geometry is resident, so a burst-driven scale-up never serves a cold
jit cache.  With ``prewarm=False`` the replica joins immediately cold
and its first dispatch of each geometry pays the ``compile_s`` tax on
the hot path (a ``cold_compile`` event per geometry) — the serving-
scale drill banks exactly that delta.  Shrink is **drain-then-retire**:
the victim stops receiving batches (state ``draining``), any in-flight
batch finishes or re-dispatches exactly once through the ordinary
failover latch, and the replica is removed once idle — never with work
on it.

Supervision is PULL-mode :class:`StallWatchdog` on the runtime's clock:
``beat`` when the forward starts, ``check`` when it returns.  A forward
whose (possibly virtual) duration exceeds ``wedge_timeout_s`` is a
wedge even though it eventually returned — in production the push-mode
monitor thread would have interrupted it mid-flight; on the virtual
clock the pull check observes the same deadline deterministically (and
the fence budget models the push-mode interrupt itself).
"""

from __future__ import annotations

import logging
from typing import (Any, Callable, Dict, List, Optional, Sequence, Set,
                    Tuple)

from analytics_zoo_tpu.resilience.errors import ReplicaWedged, StallError
from analytics_zoo_tpu.resilience.watchdog import StallWatchdog
from analytics_zoo_tpu.serving.batcher import AssembledBatch
from analytics_zoo_tpu.serving.request import DEFAULT_MODEL

logger = logging.getLogger("analytics_zoo_tpu")

#: a (model, edge, tier) compiled-geometry key — what pre-warm plans
#: enumerate and ``warm_keys`` tracks
GeometryKey = Tuple[str, Any, int]


class Replica:
    """One supervised model replica.

    ``forward_fns`` maps degradation-tier index → callable
    ``batch_dict -> outputs`` (every tier's geometry pre-compiled on
    this replica's device) — a list for single-model runtimes, or a
    ``{model: [tier fns]}`` dict for a multiplexed one (ISSUE 14).
    ``service_hook`` (optional) returns the simulated service seconds
    for a dispatch — the virtual-clock path; when ``None`` the real
    forward's wall time is what the watchdog sees.

    ``warm_keys``: the compiled-geometry set this replica holds.
    ``None`` (default) disables compile modeling — everything is warm,
    the PR-5 behavior.  A set (possibly empty) arms it: dispatching a
    (model, edge, tier) not in the set pays ``compile_s`` on the hot
    path first (a *cold compile*), exactly the latency cliff pre-warm
    exists to delete.
    """

    #: devices this replica occupies — a plain replica is one device;
    #: :class:`ReplicaSlice` overrides it with its sub-mesh width.  The
    #: pool's ``device_budget`` clamp and the autoscaler's slice-unit
    #: bounds both reason in these units (ISSUE 19).
    width: int = 1

    def __init__(self, rid: int, forward_fns, clock,
                 wedge_timeout_s: float,
                 service_hook: Optional[Callable[..., float]] = None,
                 fence_budget_s: Optional[float] = None,
                 compile_s: float = 0.0,
                 warm_keys: Optional[Set[GeometryKey]] = None):
        self.rid = rid
        if isinstance(forward_fns, dict):
            self.forward_fns: Dict[str, List[Callable]] = {
                m: list(fns) for m, fns in forward_fns.items()}
        else:
            self.forward_fns = {DEFAULT_MODEL: list(forward_fns)}
        self.clock = clock
        self.service_hook = service_hook
        self.fence_budget_s = fence_budget_s
        self.compile_s = float(compile_s)
        self.warm_keys = warm_keys
        self.state = "healthy"       # healthy|fenced|warming|draining
        #: draining for a live-weight swap, NOT for retirement — the
        #: rollout machine re-admits this replica after installing the
        #: new weights instead of ``_revive`` removing it
        self.swap_drain = False
        self.restart_at: Optional[float] = None
        self.warm_ready_at: Optional[float] = None
        self._warm_plan: Sequence[GeometryKey] = ()
        self.dispatches = 0
        self.wedges = 0
        self.cold_compiles = 0
        self.inflight = 0            # batches currently on this replica
        #: parallel-service mode (ISSUE 14, the fleet drill's capacity
        #: model): the virtual instant this replica's last assigned
        #: batch completes — replicas serve CONCURRENTLY, each
        #: sequentially, and the runtime only assigns to free ones
        self.busy_until = 0.0
        self.observer: Optional[Callable[[Dict[str, Any]], None]] = None
        #: per-model ServingTier instances this replica serves (set by
        #: the runtime) — how session state eviction reaches the tier's
        #: per-replica store (``ServingTier.evict_session``)
        self.tier_objs: Dict[str, List[Any]] = {}
        self._fence_t: Optional[float] = None
        # one time-source convention (utils.clock): the watchdog takes
        # the Clock object itself since PR 7, no .now unwrapping
        self.watchdog = StallWatchdog(
            timeout_s=wedge_timeout_s, name=f"replica-{rid}",
            clock=clock)

    def _fn_for(self, batch: AssembledBatch) -> Callable:
        try:
            return self.forward_fns[batch.model][batch.tier]
        except (KeyError, IndexError):
            raise ReplicaWedged(
                f"replica {self.rid}: no forward for model "
                f"{batch.model!r} tier {batch.tier}") from None

    def sleep_guarded(self, seconds: float) -> None:
        """Advance virtual time inside a supervised forward, bounded by
        the fence budget: crossing it sleeps only UP TO the fence
        instant and raises :class:`ReplicaWedged` there — the push-mode
        monitor interrupting the wedge mid-flight, modeled exactly on
        the pull-mode clock.  With no budget this is a plain sleep (the
        PR-5 return-then-check path, byte-identical)."""
        if self._fence_t is None:
            self.clock.sleep(seconds)
            return
        now = self.clock.now()
        if now + seconds > self._fence_t:
            self.clock.sleep(max(self._fence_t - now, 0.0))
            raise ReplicaWedged(
                f"replica {self.rid}: forward wedged mid-flight — fenced "
                f"at the {self.fence_budget_s:.3f}s fence budget")
        self.clock.sleep(seconds)

    def cold_tax(self, batch: AssembledBatch, mark: bool = True) -> float:
        """The cold-compile tax this dispatch pays: ``compile_s`` when
        the replica has never compiled the batch's geometry (pre-warm's
        counterfactual), else 0.  Records the ``cold_compile`` event and
        (with ``mark``) the now-resident key."""
        if self.warm_keys is None or self.compile_s <= 0:
            return 0.0
        key = (batch.model, batch.edge, batch.tier)
        if key in self.warm_keys:
            return 0.0
        self.cold_compiles += 1
        if self.observer is not None:
            self.observer({"kind": "cold_compile", "replica": self.rid,
                           "model": batch.model, "edge": str(batch.edge),
                           "tier": batch.tier,
                           "t": round(self.clock.now(), 6)})
        if mark:
            self.warm_keys.add(key)
        return self.compile_s

    def _maybe_cold_compile(self, batch: AssembledBatch) -> None:
        tax = self.cold_tax(batch, mark=False)
        if tax <= 0:
            return
        self.sleep_guarded(tax)
        # marked warm only once the compile completed (a fence mid-
        # compile leaves the geometry cold for the restarted replica)
        self.warm_keys.add((batch.model, batch.edge, batch.tier))

    def forward(self, batch: AssembledBatch,
                fault: Optional[Callable[["Replica"], None]] = None) -> Any:
        """Run one batch under stall supervision.  ``fault`` (chaos) runs
        just before the model fn — it may raise (crash) or advance the
        virtual clock (slow forward).  Raises :class:`ReplicaWedged` on
        crash or deadline overrun; the POOL owns fencing/failover."""
        self.watchdog.beat()
        self.dispatches += 1
        self.inflight += 1
        t0 = self.clock.now()
        self._fence_t = (t0 + self.fence_budget_s
                         if self.fence_budget_s is not None else None)
        try:
            if fault is not None:
                fault(self)
            self._maybe_cold_compile(batch)
            out = self._fn_for(batch)(batch.batch)
            if self.service_hook is not None:
                # virtual time: the hook says how long this forward took
                self.sleep_guarded(float(self.service_hook(batch,
                                                           self.rid)))
        except ReplicaWedged:
            raise
        except Exception as e:
            raise ReplicaWedged(
                f"replica {self.rid}: forward crashed mid-batch "
                f"({type(e).__name__}: {e})") from e
        finally:
            self.inflight -= 1
            self._fence_t = None
        try:
            self.watchdog.check()
        except StallError as e:
            raise ReplicaWedged(
                f"replica {self.rid}: forward wedged "
                f"({self.clock.now() - t0:.3f}s > "
                f"{self.watchdog.timeout_s:.3f}s deadline)") from e
        return out

    # -- lifecycle ----------------------------------------------------------
    def fence(self, restart_at: float) -> None:
        self.state = "fenced"
        self.wedges += 1
        self.restart_at = restart_at

    def maybe_restart(self, now: float) -> bool:
        """Re-admit the replica once its background restart completed."""
        if self.state == "fenced" and self.restart_at is not None \
                and now >= self.restart_at:
            self.state = "healthy"
            self.restart_at = None
            # clear the latched stall verdict + the age accumulated while
            # fenced, or the revived replica would instantly re-wedge
            self.watchdog.reset()
            return True
        return False

    def begin_warming(self, plan: Sequence[GeometryKey],
                      ready_at: float) -> None:
        """Enter the pre-warm phase: compile every planned geometry OFF
        the dispatch path; :meth:`maybe_warm` admits the replica once
        they are all resident."""
        self.state = "warming"
        self._warm_plan = tuple(plan)
        self.warm_ready_at = ready_at
        self.warm_keys = set()

    def maybe_warm(self, now: float) -> bool:
        """Join dispatch once the pre-warm compiles completed — the
        replica becomes eligible with every planned geometry warm."""
        if self.state == "warming" and self.warm_ready_at is not None \
                and now >= self.warm_ready_at:
            self.state = "healthy"
            self.warm_ready_at = None
            self.warm_keys = set(self._warm_plan)
            self._warm_plan = ()
            self.watchdog.reset()
            return True
        return False


class ReplicaSlice(Replica):
    """A replica that IS a mesh slice (ISSUE 19 tentpole): its tier
    programs are jitted against a width-``w`` sub-mesh rather than a
    single device, so one pool entry occupies ``w`` devices and serves
    each batch with ``w``-way sharded compute.

    ``specs`` is the tier ladder's
    :class:`~analytics_zoo_tpu.parallel.specs.SpecSet` rebased onto the
    slice's sub-mesh (``SpecSet.replace_mesh``) — the same declaration
    the training side elastically re-places, which is what makes a
    serving replica and a training shard the same artifact.  The
    runtime's replica factory jits the tier forwards under
    ``specs.mesh``; this class only carries the width (for the pool's
    device accounting) and the specs (for audit/debug surfaces).  A
    width-1 slice is behaviorally a plain :class:`Replica`.
    """

    def __init__(self, rid: int, forward_fns, clock,
                 wedge_timeout_s: float, width: int = 1,
                 specs: Optional[Any] = None, **kwargs):
        if width < 1:
            raise ValueError(f"slice width must be >= 1, got {width}")
        super().__init__(rid, forward_fns, clock, wedge_timeout_s,
                         **kwargs)
        self.width = int(width)
        self.specs = specs


class ReplicaPool:
    """Round-robin dispatch over healthy replicas with fence + exactly-
    once failover, plus the resize actuator the autoscaler drives.
    ``events`` is the deterministic log the drill banks (no wall-clock
    entries beyond the runtime clock's virtual time).  ``observer``
    (optional, set by the runtime) sees every event as it is appended —
    the telemetry spine's flight recorder hangs off it, and a fence
    event is one of the black box's dump triggers.

    ``fence_budget_s``: the wedge-detection bound (see the module
    docstring) — assigned to every replica that doesn't carry its own.
    ``replica_factory(rid) -> Replica``: how :meth:`resize` builds
    growth replicas (the runtime wires one that mirrors its own replica
    construction).  ``prewarm_keys``/``compile_s``: the compiled-
    geometry plan and per-program compile cost the pre-warm/cold-
    compile modeling uses (``compile_s == 0`` disables it — the PR-5
    behavior)."""

    def __init__(self, replicas: Sequence[Replica], clock,
                 restart_s: float = 5.0,
                 observer: Optional[Callable[[Dict[str, Any]], None]] = None,
                 fence_budget_s: Optional[float] = None,
                 replica_factory: Optional[Callable[[int], Replica]] = None,
                 prewarm_keys: Optional[Sequence[GeometryKey]] = None,
                 compile_s: float = 0.0,
                 device_budget: Optional[int] = None):
        if not replicas:
            raise ValueError("need at least one replica")
        self.replicas = list(replicas)
        self.clock = clock
        self.restart_s = float(restart_s)
        self.events: List[Dict[str, Any]] = []
        self.observer = observer
        self.fence_budget_s = fence_budget_s
        self.replica_factory = replica_factory
        self.prewarm_keys = tuple(prewarm_keys) if prewarm_keys else ()
        self.compile_s = float(compile_s)
        #: hard device ceiling (ISSUE 19 satellite): replica growth is
        #: clamped so Σ width over non-draining replicas never exceeds
        #: it — a width-4 slice grow can't silently over-subscribe the
        #: fleet the way a replica-count bound alone would allow.
        self.device_budget = device_budget
        self._rr = 0
        self._rid_counter = max(r.rid for r in self.replicas) + 1
        #: active hot-swap rollout (None between rollouts) — see hot_swap
        self._swap: Optional[Dict[str, Any]] = None
        #: rids the rollout must NOT drain yet (the runtime refreshes
        #: this with the session-pinned set every pump: session-affine
        #: replicas are swapped LAST, after their sessions close)
        self.swap_defer: Set[int] = set()
        self.swaps_completed = 0
        self.swaps_started = 0
        self.last_rollout: Optional[Dict[str, Any]] = None
        for r in self.replicas:
            self._adopt(r)

    def _adopt(self, r: Replica) -> None:
        if r.fence_budget_s is None:
            r.fence_budget_s = self.fence_budget_s
        r.observer = self._event

    def _event(self, ev: Dict[str, Any]) -> None:
        self.events.append(ev)
        if self.observer is not None:
            self.observer(ev)

    # -- selection -----------------------------------------------------------
    def _revive(self) -> None:
        now = self.clock.now()
        retired: List[Replica] = []
        for r in self.replicas:
            if r.maybe_restart(now):
                self._event({"kind": "replica_restarted",
                             "replica": r.rid, "t": round(now, 6)})
            elif r.maybe_warm(now):
                self._event({"kind": "replica_prewarmed",
                             "replica": r.rid, "t": round(now, 6),
                             "geometries": len(r.warm_keys or ())})
            elif r.state == "draining" and not r.swap_drain \
                    and r.inflight == 0 and r.busy_until <= now:
                retired.append(r)
        for r in retired:
            self.replicas.remove(r)
            self._event({"kind": "replica_retired", "replica": r.rid,
                         "t": round(now, 6)})
        self._step_rollout(now)

    def healthy(self) -> List[Replica]:
        self._revive()
        return [r for r in self.replicas if r.state == "healthy"]

    @property
    def size(self) -> int:
        """Pool size the autoscaler reasons about: every replica that
        is, or will come back as, dispatchable (healthy, fenced-with-
        restart-pending, warming) — draining replicas are already on
        their way out."""
        return sum(r.state != "draining" for r in self.replicas)

    @property
    def devices_used(self) -> int:
        """Devices occupied by non-draining replicas — Σ ``width``, the
        unit the ``device_budget`` clamp and the autoscaler's slice-unit
        bounds reason in (a plain replica is width 1)."""
        return sum(r.width for r in self.replicas
                   if r.state != "draining")

    @property
    def cold_compiles(self) -> int:
        return sum(r.cold_compiles for r in self.replicas)

    def pick(self, exclude: Optional[int] = None) -> Optional[Replica]:
        """Deterministic round-robin over healthy replicas (skipping
        ``exclude`` — the replica that just failed this batch)."""
        ready = [r for r in self.healthy() if r.rid != exclude]
        if not ready:
            return None
        r = ready[self._rr % len(ready)]
        self._rr += 1
        return r

    def replica_by_rid(self, rid: int) -> Optional[Replica]:
        for r in self.replicas:
            if r.rid == rid:
                return r
        return None

    def quarantine(self, rid: int, reason: str = "device_health") -> bool:
        """Evict one replica's devices from the fleet: drain-then-retire
        (the ordinary ``resize`` shrink path — in-flight work finishes or
        re-dispatches once through the failover latch) AND decrement
        ``device_budget`` by the replica's width, so neither a later
        ``resize`` grow nor the autoscaler can re-seat anything on the
        quarantined silicon.  Returns False when ``rid`` is unknown or
        already draining (idempotent — the health sentinel may flag the
        same device from several windows)."""
        r = self.replica_by_rid(rid)
        if r is None or r.state == "draining":
            return False
        width = r.width
        r.state = "draining"
        if self.device_budget is not None:
            self.device_budget = max(self.device_budget - width, 0)
        self._event({"kind": "replica_quarantined", "replica": rid,
                     "reason": reason, "width": width,
                     "device_budget": self.device_budget,
                     "t": round(self.clock.now(), 6)})
        logger.warning("pool: replica %d quarantined (%s) — draining; "
                       "device budget now %s", rid, reason,
                       self.device_budget)
        return True

    # -- parallel service (the fleet capacity model) --------------------------
    def any_free(self, now: float) -> bool:
        return any(r.busy_until <= now for r in self.healthy())

    def pick_free(self, now: float,
                  exclude: Optional[int] = None) -> Optional[Replica]:
        """Round-robin over healthy replicas that are FREE at ``now`` —
        parallel-service mode's assignment rule (a busy replica is
        serving its previous batch concurrently)."""
        ready = [r for r in self.healthy()
                 if r.busy_until <= now and r.rid != exclude]
        if not ready:
            return None
        r = ready[self._rr % len(ready)]
        self._rr += 1
        return r

    def least_busy(self) -> Optional[Replica]:
        """Healthy replica with the earliest busy horizon — the force-
        drain path queues work there when nobody is free."""
        ready = self.healthy()
        if not ready:
            return None
        return min(ready, key=lambda r: (r.busy_until, r.rid))

    def next_event_t(self, now: float) -> Optional[float]:
        """The next virtual instant pool state changes (a busy replica
        frees, a restart completes, a pre-warm finishes) — what an
        event-driven load loop advances the clock to."""
        ts: List[float] = []
        for r in self.replicas:
            if r.busy_until > now:
                ts.append(r.busy_until)
            if r.state == "fenced" and r.restart_at is not None \
                    and r.restart_at > now:
                ts.append(r.restart_at)
            if r.state == "warming" and r.warm_ready_at is not None \
                    and r.warm_ready_at > now:
                ts.append(r.warm_ready_at)
        return min(ts) if ts else None

    # -- resize (the autoscaler's actuator) ----------------------------------
    def resize(self, n: int, prewarm: bool = True,
               protected: Sequence[int] = ()) -> Dict[str, List[int]]:
        """Grow or shrink the pool to ``n`` non-draining replicas.

        Growth builds replicas through ``replica_factory``; with
        compile modeling armed they **pre-warm** first (state
        ``warming`` for ``compile_s × len(prewarm_keys)`` of clock
        time, then join with every planned geometry warm) unless
        ``prewarm=False`` — then they join immediately cold and pay the
        tax per first dispatch.  Shrink is drain-then-retire: victims
        (fenced first, then the highest-rid healthy replica not in
        ``protected`` — session-pinned replicas are never drained while
        an alternative exists) stop receiving batches at once and are
        removed when idle; in-flight work finishes or re-dispatches
        exactly once through the ordinary failover latch.  Returns the
        rids acted on."""
        if n < 1:
            raise ValueError(f"pool size must be >= 1, got {n}")
        self._revive()
        protected_set = set(protected)
        actions: Dict[str, List[int]] = {"grown": [], "drained": []}
        while self.size < n:
            if self.replica_factory is None:
                raise RuntimeError("pool growth needs a replica_factory")
            rid = self._rid_counter
            self._rid_counter += 1
            r = self.replica_factory(rid)
            if self.device_budget is not None \
                    and self.devices_used + r.width > self.device_budget:
                # grow clamped AT THE ACTUATOR: the pool refuses to
                # over-subscribe devices even if a policy bug asks it to
                self._rid_counter -= 1
                self._event({"kind": "resize_budget_clamped",
                             "t": round(self.clock.now(), 6),
                             "requested": int(n), "size": self.size,
                             "devices_used": self.devices_used,
                             "width": r.width,
                             "device_budget": self.device_budget})
                break
            r.compile_s = self.compile_s
            self._adopt(r)
            now = self.clock.now()
            modeled = self.compile_s > 0 and self.prewarm_keys
            if modeled and prewarm:
                r.begin_warming(
                    self.prewarm_keys,
                    now + self.compile_s * len(self.prewarm_keys))
            elif modeled:
                r.warm_keys = set()     # joins cold: pays per-dispatch tax
            self.replicas.append(r)
            if self._swap is not None:
                # growth mid-rollout joins with the NEW weights already
                # installed — it must not serve the retiring checkpoint,
                # and the rollout must not re-drain it
                self._swap["install"](r)
                self._swap["swapped"].append(rid)
                self._event({"kind": "swap_installed", "replica": rid,
                             "t": round(now, 6),
                             "checkpoint": self._swap["checkpoint"],
                             "grown": True})
            self._event({"kind": "replica_joined", "replica": rid,
                         "t": round(now, 6), "prewarm": bool(prewarm),
                         "state": r.state})
            actions["grown"].append(rid)
        while self.size > n:
            # a fenced replica is the cheapest victim — UNLESS sessions
            # are pinned to it: it restarts with their state intact,
            # while retiring it would lose them permanently
            victims = [r for r in self.replicas if r.state == "fenced"
                       and r.rid not in protected_set]
            if not victims:
                victims = sorted(
                    (r for r in self.replicas
                     if r.state in ("healthy", "warming")
                     and r.rid not in protected_set),
                    key=lambda r: -r.rid)
            if not victims:
                break                   # everything left is protected
            victim = victims[0]
            victim.state = "draining"
            self._event({"kind": "replica_draining",
                         "replica": victim.rid,
                         "t": round(self.clock.now(), 6),
                         "inflight": victim.inflight})
            actions["drained"].append(victim.rid)
        self._revive()                  # idle victims retire immediately
        return actions

    # -- live-weight hot-swap (the rollout state machine) ---------------------
    @property
    def rollout_active(self) -> bool:
        return self._swap is not None

    def hot_swap(self, checkpoint: str,
                 install: Callable[[Replica], None],
                 warm_s: Optional[float] = None,
                 last: Sequence[int] = ()) -> Dict[str, Any]:
        """Start a zero-downtime weight rollout: one replica at a time is
        drained (state ``draining`` with the ``swap_drain`` mark — never
        retired), ``install(replica)`` swaps its weights once idle, the
        replica re-warms its compiled geometries (when compile modeling
        is armed) and rejoins dispatch before the next victim drains.
        The rollout advances from :meth:`_revive`, i.e. on every ordinary
        dispatch cycle — no extra driver needed.

        ``checkpoint`` is the snapshot directory the new weights came
        from; its sha256 manifest is verified HERE too (defense in depth
        — the runtime already verified at load), so a truncated publish
        can never start draining replicas.  ``last`` rids are queued at
        the tail (session-pinned replicas swap last); rids in
        ``swap_defer`` are additionally held until the runtime clears
        them.  In-flight batches on the draining replica finish or ride
        the ordinary exactly-once failover latch — ``accounting()``
        conserves every request across the rollout."""
        if self._swap is not None:
            raise RuntimeError(
                f"hot_swap: rollout of {self._swap['checkpoint']!r} "
                f"still in progress")
        from analytics_zoo_tpu.parallel import checkpoint as ckpt

        ckpt.verify_snapshot(checkpoint)
        last_set = set(last)
        order = sorted(r.rid for r in self.replicas
                       if r.state != "draining" and r.rid not in last_set)
        order += sorted(r.rid for r in self.replicas
                        if r.state != "draining" and r.rid in last_set)
        self._swap = {"checkpoint": checkpoint, "install": install,
                      "warm_s": warm_s, "pending": order,
                      "current": None, "phase": None, "swapped": []}
        self.swaps_started += 1
        self._event({"kind": "swap_rollout_started",
                     "checkpoint": checkpoint, "order": list(order),
                     "t": round(self.clock.now(), 6)})
        self._step_rollout(self.clock.now())
        return dict(self._swap, install=None)

    def _step_rollout(self, now: float) -> None:
        """Advance the active rollout one step.  Idempotent; called from
        ``_revive`` so the machine moves whenever pool state is read."""
        sw = self._swap
        if sw is None:
            return
        cur = self.replica_by_rid(sw["current"]) \
            if sw["current"] is not None else None
        if sw["current"] is not None and cur is None:
            sw["current"] = None     # victim retired mid-drain (resize)
        if cur is not None:
            if sw["phase"] == "drain":
                if cur.state == "healthy":
                    # fenced mid-drain and restarted: resume the drain
                    cur.state = "draining"
                if cur.state == "draining" and cur.inflight == 0 \
                        and cur.busy_until <= now:
                    sw["install"](cur)
                    cur.swap_drain = False
                    sw["swapped"].append(cur.rid)
                    self._event({"kind": "swap_installed",
                                 "replica": cur.rid, "t": round(now, 6),
                                 "checkpoint": sw["checkpoint"]})
                    if self.compile_s > 0 and self.prewarm_keys:
                        warm = sw["warm_s"] if sw["warm_s"] is not None \
                            else self.compile_s * len(self.prewarm_keys)
                        cur.begin_warming(self.prewarm_keys, now + warm)
                        sw["phase"] = "warm"
                    else:
                        cur.state = "healthy"
                        cur.watchdog.reset()
                        self._event({"kind": "swap_rejoined",
                                     "replica": cur.rid,
                                     "t": round(now, 6)})
                        sw["current"] = None
                return  # one replica at a time: wait for drain/warm
            if sw["phase"] == "warm":
                if cur.state == "warming":
                    return
                self._event({"kind": "swap_rejoined", "replica": cur.rid,
                             "t": round(now, 6)})
                sw["current"] = None
        # pick the next victim (deferred/retired rids skipped or dropped)
        while sw["pending"]:
            rid = sw["pending"][0]
            r = self.replica_by_rid(rid)
            if r is None or (r.state == "draining" and not r.swap_drain):
                sw["pending"].pop(0)    # retired or retiring: nothing to swap
                continue
            if rid in self.swap_defer:
                # deferred (session-pinned): try a later non-deferred rid
                later = [x for x in sw["pending"]
                         if x not in self.swap_defer
                         and self.replica_by_rid(x) is not None]
                if not later:
                    return              # everything left is deferred: wait
                rid = later[0]
                r = self.replica_by_rid(rid)
                sw["pending"].remove(rid)
            else:
                sw["pending"].pop(0)
            if r.state != "healthy":
                # fenced/warming: queue it back and wait for this cycle
                sw["pending"].insert(0, rid)
                return
            r.state = "draining"
            r.swap_drain = True
            sw["current"], sw["phase"] = rid, "drain"
            self._event({"kind": "swap_drain", "replica": rid,
                         "t": round(now, 6), "inflight": r.inflight})
            self._step_rollout(now)      # an idle victim installs at once
            return
        # pending empty and no current: the rollout is complete
        self.swaps_completed += 1
        self.last_rollout = {"checkpoint": sw["checkpoint"],
                             "swapped": list(sw["swapped"])}
        self._event({"kind": "swap_rollout_complete",
                     "checkpoint": sw["checkpoint"],
                     "swapped": list(sw["swapped"]),
                     "t": round(now, 6)})
        self._swap = None

    def abort_rollout(self) -> List[int]:
        """Stop an in-progress rollout (the rollback path): the
        currently-draining victim is re-admitted un-swapped, and the
        rids that already received new weights are returned so the
        caller can reinstall the rollback tier on them.  No-op (empty
        list) when no rollout is active — the exactly-once rollback
        latch lives in the runtime, this is just the actuator."""
        sw = self._swap
        if sw is None:
            return []
        cur = self.replica_by_rid(sw["current"]) \
            if sw["current"] is not None else None
        if cur is not None and cur.swap_drain:
            cur.swap_drain = False
            if cur.state == "draining":
                cur.state = "healthy"
                cur.watchdog.reset()
        swapped = list(sw["swapped"])
        self._event({"kind": "swap_rollout_aborted",
                     "checkpoint": sw["checkpoint"],
                     "swapped": swapped,
                     "t": round(self.clock.now(), 6)})
        self._swap = None
        return swapped

    # -- dispatch with failover ----------------------------------------------
    def _fence(self, replica: Replica, err: ReplicaWedged,
               at: Optional[float] = None) -> None:
        """Fence ``replica``.  ``at`` pins the fence instant explicitly —
        the parallel service model detects a crash/wedge at an instant it
        computed on the replica's busy horizon, which the shared clock
        has not reached yet."""
        t = self.clock.now() if at is None else float(at)
        restart_at = t + self.restart_s
        replica.fence(restart_at)
        self._event({"kind": "replica_fenced", "replica": replica.rid,
                     "t": round(t, 6),
                     "restart_at": round(restart_at, 6),
                     "error": str(err).split("\n")[0][:160]})
        logger.warning("serving: fenced replica %d (%s); restart at t=%.3f",
                       replica.rid, err, restart_at)

    def dispatch(self, batch: AssembledBatch,
                 fault_for: Optional[Callable[[Replica], Optional[
                     Callable[[Replica], None]]]] = None) -> Any:
        """Run ``batch`` on a healthy replica; on :class:`ReplicaWedged`
        fence the replica and re-dispatch EXACTLY once.  Returns the
        forward outputs; raises :class:`ReplicaWedged` when the retry is
        spent or no healthy replica remains (the runtime fails the
        batch's requests — retryable from the client's side).

        A batch with ``affinity`` set (a streaming-session batch) MUST
        run on that replica — its RNN carry lives there, so failover to
        another replica would silently decode from zeroed state; if the
        pinned replica is gone or unhealthy the batch fails instead
        (honest state loss, the runtime fails its requests)."""
        if batch.affinity is not None:
            self._revive()
            replica = self.replica_by_rid(batch.affinity)
            if replica is None or replica.state != "healthy":
                raise ReplicaWedged(
                    f"session replica {batch.affinity} unavailable "
                    f"(state: "
                    f"{replica.state if replica else 'retired'}) — "
                    f"session state lost")
            fault = fault_for(replica) if fault_for is not None else None
            try:
                return self.dispatch_on(replica, batch, fault)
            except ReplicaWedged as err:
                self._fence(replica, err)
                raise
        replica = self.pick()
        if replica is None:
            raise ReplicaWedged("no healthy replica available")
        try:
            fault = fault_for(replica) if fault_for is not None else None
            return self.dispatch_on(replica, batch, fault)
        except ReplicaWedged as err:
            self._fence(replica, err)
            if batch.redispatched:
                raise
            batch.redispatched = True
            backup = self.pick(exclude=replica.rid)
            if backup is None:
                raise ReplicaWedged(
                    f"batch failover from replica {replica.rid}: no healthy "
                    f"replica left") from err
            self._event({"kind": "failover", "from": replica.rid,
                         "to": backup.rid,
                         "t": round(self.clock.now(), 6),
                         "requests": [r.rid for r in batch.requests]})
            fault = fault_for(backup) if fault_for is not None else None
            try:
                return self.dispatch_on(backup, batch, fault)
            except ReplicaWedged as err2:
                self._fence(backup, err2)
                raise

    def dispatch_on(self, replica: Replica, batch: AssembledBatch,
                    fault: Optional[Callable[[Replica], None]]) -> Any:
        for req in batch.requests:
            req.attempts += 1
        return replica.forward(batch, fault=fault)

    def snapshot(self) -> Dict[str, Any]:
        out = {
            "replicas": [{"rid": r.rid, "state": r.state,
                          "dispatches": r.dispatches, "wedges": r.wedges}
                         for r in self.replicas],
            "healthy": sum(r.state == "healthy" for r in self.replicas),
        }
        if self.swaps_started:    # legacy snapshots stay byte-identical
            out["rollouts"] = {
                "started": self.swaps_started,
                "completed": self.swaps_completed,
                "active": self._swap is not None,
                "last": dict(self.last_rollout) if self.last_rollout
                else None,
            }
        return out
