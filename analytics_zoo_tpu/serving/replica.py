"""Replica health supervision and exactly-once batch failover.

A serving cell runs N replicas of the model (N devices, or N mesh
shards each presenting as one replica).  A replica can fail two ways
mid-batch: its forward *raises* (device lost, injected crash), or it
*wedges* — makes no progress past its
:class:`~analytics_zoo_tpu.resilience.watchdog.StallWatchdog` deadline
(the PR-1 failure mode that otherwise blocks the host loop silently).
Either way the pool

1. **fences** the replica — state ``fenced``, no further dispatches;
2. **re-dispatches** the in-flight batch to a healthy replica EXACTLY
   once (``AssembledBatch.redispatched`` latch — a batch that fails its
   second replica fails its requests with
   :class:`~analytics_zoo_tpu.resilience.errors.ReplicaWedged` rather
   than ping-ponging through the whole pool and amplifying overload);
3. **restarts** the fenced replica in the background — modeled as a
   ``restart_s`` cooldown on the runtime clock; once it elapses the
   next dispatch cycle re-admits the replica (and its jit cache is
   assumed cold, which is why restarts must not be free).

Supervision is PULL-mode :class:`StallWatchdog` on the runtime's clock:
``beat`` when the forward starts, ``check`` when it returns.  A forward
whose (possibly virtual) duration exceeds ``wedge_timeout_s`` is a
wedge even though it eventually returned — in production the push-mode
monitor thread would have interrupted it mid-flight; on the virtual
clock the pull check observes the same deadline deterministically.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Sequence

from analytics_zoo_tpu.resilience.errors import ReplicaWedged, StallError
from analytics_zoo_tpu.resilience.watchdog import StallWatchdog
from analytics_zoo_tpu.serving.batcher import AssembledBatch

logger = logging.getLogger("analytics_zoo_tpu")


class Replica:
    """One supervised model replica.

    ``forward_fns`` maps degradation-tier index → callable
    ``batch_dict -> outputs`` (every tier's geometry pre-compiled on
    this replica's device).  ``service_hook`` (optional) returns the
    simulated service seconds for a dispatch — the virtual-clock path;
    when ``None`` the real forward's wall time is what the watchdog
    sees.
    """

    def __init__(self, rid: int, forward_fns: Sequence[Callable],
                 clock, wedge_timeout_s: float,
                 service_hook: Optional[Callable[..., float]] = None):
        self.rid = rid
        self.forward_fns = list(forward_fns)
        self.clock = clock
        self.service_hook = service_hook
        self.state = "healthy"          # healthy|fenced
        self.restart_at: Optional[float] = None
        self.dispatches = 0
        self.wedges = 0
        # one time-source convention (utils.clock): the watchdog takes
        # the Clock object itself since PR 7, no .now unwrapping
        self.watchdog = StallWatchdog(
            timeout_s=wedge_timeout_s, name=f"replica-{rid}",
            clock=clock)

    def forward(self, batch: AssembledBatch,
                fault: Optional[Callable[["Replica"], None]] = None) -> Any:
        """Run one batch under stall supervision.  ``fault`` (chaos) runs
        just before the model fn — it may raise (crash) or advance the
        virtual clock (slow forward).  Raises :class:`ReplicaWedged` on
        crash or deadline overrun; the POOL owns fencing/failover."""
        self.watchdog.beat()
        self.dispatches += 1
        t0 = self.clock.now()
        try:
            if fault is not None:
                fault(self)
            out = self.forward_fns[batch.tier](batch.batch)
        except ReplicaWedged:
            raise
        except Exception as e:
            raise ReplicaWedged(
                f"replica {self.rid}: forward crashed mid-batch "
                f"({type(e).__name__}: {e})") from e
        if self.service_hook is not None:
            # virtual time: the hook says how long this forward took
            self.clock.sleep(float(self.service_hook(
                batch.edge, batch.n_valid, batch.tier, self.rid)))
        try:
            self.watchdog.check()
        except StallError as e:
            raise ReplicaWedged(
                f"replica {self.rid}: forward wedged "
                f"({self.clock.now() - t0:.3f}s > "
                f"{self.watchdog.timeout_s:.3f}s deadline)") from e
        return out

    # -- lifecycle ----------------------------------------------------------
    def fence(self, restart_at: float) -> None:
        self.state = "fenced"
        self.wedges += 1
        self.restart_at = restart_at

    def maybe_restart(self, now: float) -> bool:
        """Re-admit the replica once its background restart completed."""
        if self.state == "fenced" and self.restart_at is not None \
                and now >= self.restart_at:
            self.state = "healthy"
            self.restart_at = None
            # clear the latched stall verdict + the age accumulated while
            # fenced, or the revived replica would instantly re-wedge
            self.watchdog.reset()
            return True
        return False


class ReplicaPool:
    """Round-robin dispatch over healthy replicas with fence + exactly-
    once failover.  ``events`` is the deterministic log the drill banks
    (no wall-clock entries beyond the runtime clock's virtual time).
    ``observer`` (optional, set by the runtime) sees every event as it
    is appended — the telemetry spine's flight recorder hangs off it,
    and a fence event is one of the black box's dump triggers."""

    def __init__(self, replicas: Sequence[Replica], clock,
                 restart_s: float = 5.0,
                 observer: Optional[Callable[[Dict[str, Any]], None]] = None):
        if not replicas:
            raise ValueError("need at least one replica")
        self.replicas = list(replicas)
        self.clock = clock
        self.restart_s = float(restart_s)
        self.events: List[Dict[str, Any]] = []
        self.observer = observer
        self._rr = 0

    def _event(self, ev: Dict[str, Any]) -> None:
        self.events.append(ev)
        if self.observer is not None:
            self.observer(ev)

    # -- selection -----------------------------------------------------------
    def _revive(self) -> None:
        now = self.clock.now()
        for r in self.replicas:
            if r.maybe_restart(now):
                self._event({"kind": "replica_restarted",
                             "replica": r.rid, "t": round(now, 6)})

    def healthy(self) -> List[Replica]:
        self._revive()
        return [r for r in self.replicas if r.state == "healthy"]

    def pick(self, exclude: Optional[int] = None) -> Optional[Replica]:
        """Deterministic round-robin over healthy replicas (skipping
        ``exclude`` — the replica that just failed this batch)."""
        ready = [r for r in self.healthy() if r.rid != exclude]
        if not ready:
            return None
        r = ready[self._rr % len(ready)]
        self._rr += 1
        return r

    # -- dispatch with failover ----------------------------------------------
    def _fence(self, replica: Replica, err: ReplicaWedged) -> None:
        restart_at = self.clock.now() + self.restart_s
        replica.fence(restart_at)
        self._event({"kind": "replica_fenced", "replica": replica.rid,
                     "t": round(self.clock.now(), 6),
                     "restart_at": round(restart_at, 6),
                     "error": str(err).split("\n")[0][:160]})
        logger.warning("serving: fenced replica %d (%s); restart at t=%.3f",
                       replica.rid, err, restart_at)

    def dispatch(self, batch: AssembledBatch,
                 fault_for: Optional[Callable[[Replica], Optional[
                     Callable[[Replica], None]]]] = None) -> Any:
        """Run ``batch`` on a healthy replica; on :class:`ReplicaWedged`
        fence the replica and re-dispatch EXACTLY once.  Returns the
        forward outputs; raises :class:`ReplicaWedged` when the retry is
        spent or no healthy replica remains (the runtime fails the
        batch's requests — retryable from the client's side)."""
        replica = self.pick()
        if replica is None:
            raise ReplicaWedged("no healthy replica available")
        try:
            fault = fault_for(replica) if fault_for is not None else None
            return self.dispatch_on(replica, batch, fault)
        except ReplicaWedged as err:
            self._fence(replica, err)
            if batch.redispatched:
                raise
            batch.redispatched = True
            backup = self.pick(exclude=replica.rid)
            if backup is None:
                raise ReplicaWedged(
                    f"batch failover from replica {replica.rid}: no healthy "
                    f"replica left") from err
            self._event({"kind": "failover", "from": replica.rid,
                         "to": backup.rid,
                         "t": round(self.clock.now(), 6),
                         "requests": [r.rid for r in batch.requests]})
            fault = fault_for(backup) if fault_for is not None else None
            try:
                return self.dispatch_on(backup, batch, fault)
            except ReplicaWedged as err2:
                self._fence(backup, err2)
                raise

    def dispatch_on(self, replica: Replica, batch: AssembledBatch,
                    fault: Optional[Callable[[Replica], None]]) -> Any:
        for req in batch.requests:
            req.attempts += 1
        return replica.forward(batch, fault=fault)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "replicas": [{"rid": r.rid, "state": r.state,
                          "dispatches": r.dispatches, "wedges": r.wedges}
                         for r in self.replicas],
            "healthy": sum(r.state == "healthy" for r in self.replicas),
        }
