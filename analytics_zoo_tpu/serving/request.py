"""Request objects and the bounded earliest-deadline-first admission queue.

The offline predictors (``SSDPredictor.predict``,
``DeepSpeech2Pipeline.transcribe_samples``) iterate a dataset they own;
online serving inverts that: requests arrive when they arrive, each with
a deadline, and the system must decide *per request* whether serving it
is still worth device time.  Two overload behaviors, both explicit:

- **queue full** → the submit raises
  :class:`~analytics_zoo_tpu.resilience.errors.ServerOverloaded`
  (retryable with backoff) instead of buffering without bound — a
  client that keeps its queue position honest can hedge elsewhere;
- **deadline passed while queued** → the request is shed *before* it
  ever reaches a device (:class:`~analytics_zoo_tpu.resilience.errors.
  RequestTimeout`), because a late answer costs the same device time as
  a useful one (the Clipper/Clockwork argument for shedding at the
  frontier, not after the forward).

Ordering is earliest-deadline-first (EDF): under load the batcher drains
the requests with the least slack first, which is the order that
maximizes the number of deadlines met when service times are roughly
uniform within a shape bucket.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
from typing import Any, Callable, Dict, List, Optional

from analytics_zoo_tpu.resilience.errors import (RequestTimeout,
                                                 ServerOverloaded)

#: terminal request states — the drill's accounting invariant is that
#: every submitted request ends in exactly one of these (none lost)
TERMINAL_STATES = ("done", "shed", "timeout", "failed")

#: the model name single-model runtimes serve under — multiplexed
#: runtimes (``ServingRuntime(models=...)``) key everything per model
DEFAULT_MODEL = "default"


@dataclasses.dataclass
class Request:
    """One inference request.

    ``payload`` is a single sample (e.g. ``{"input": (n, D) array}``).
    ``length`` is the sample's variable-axis length for bucket
    assignment (``None`` for fixed-shape models).  ``deadline_t`` is
    ABSOLUTE clock time; slack = ``deadline_t - now``.

    Multiplexing (ISSUE 14): ``model`` names which registered model the
    request is for — the batcher never mixes models in one batch and
    the replica dispatches the (model, tier) forward.  Streaming
    sessions additionally carry ``session`` (the session id) and
    ``affinity`` (the replica rid the session's carry state lives on —
    the batcher only groups equal-affinity requests and the pool
    dispatches to exactly that replica); ``final`` marks the session's
    flush chunk.
    """

    rid: int
    payload: Any
    arrival_t: float
    deadline_t: float
    length: Optional[int] = None
    state: str = "pending"          # pending|inflight|<terminal>
    result: Any = None
    error: Optional[BaseException] = None
    completed_t: Optional[float] = None
    tier: Optional[int] = None      # degradation tier that served it
    attempts: int = 0               # device dispatches (failover ≤ 2)
    model: str = DEFAULT_MODEL      # which multiplexed model (ISSUE 14)
    session: Optional[int] = None   # streaming session id
    affinity: Optional[int] = None  # replica rid the session is pinned to
    final: bool = False             # session flush chunk

    @property
    def finished(self) -> bool:
        return self.state in TERMINAL_STATES

    def finish(self, state: str, now: float, result: Any = None,
               error: Optional[BaseException] = None) -> None:
        if self.finished:
            raise RuntimeError(f"request {self.rid} already terminal "
                               f"({self.state})")
        if state not in TERMINAL_STATES:
            raise ValueError(f"not a terminal state: {state!r}")
        self.state = state
        self.result = result
        self.error = error
        self.completed_t = now


class AdmissionQueue:
    """Bounded EDF priority queue with shed-before-dispatch.

    ``capacity`` bounds queued (not yet dispatched) requests; on a full
    queue :meth:`submit` sheds the arriving request and raises
    :class:`ServerOverloaded` — after first expiring anything already
    past its deadline, so a burst arriving behind stale work is not
    rejected spuriously.  ``on_shed(request, cause)`` observes every
    shed for metrics.  ``shed_expired=False`` (the drill's no-shedding
    baseline) disables deadline shedding; the bound still holds.
    """

    def __init__(self, capacity: int, clock,
                 on_shed: Optional[Callable[[Request, str], None]] = None,
                 shed_expired: bool = True):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.clock = clock
        self.on_shed = on_shed
        self.shed_expired = shed_expired
        self._heap: List[Any] = []     # (deadline_t, seq, Request)
        self._seq = itertools.count()  # FIFO tiebreak for equal deadlines

    def __len__(self) -> int:
        return len(self._heap)

    @property
    def depth(self) -> int:
        return len(self._heap)

    def _shed(self, req: Request, cause: str,
              error: BaseException) -> None:
        req.finish("shed" if cause == "queue_full" else "timeout",
                   self.clock.now(), error=error)
        if self.on_shed is not None:
            self.on_shed(req, cause)

    def expire(self) -> int:
        """Shed every queued request whose deadline has already passed
        (it can no longer be served in time; device dispatch would be
        pure waste).  Called by the batcher before every assembly.
        Returns the number shed."""
        if not self.shed_expired:
            return 0
        now = self.clock.now()
        shed = 0
        # EDF heap ⇒ expired requests are a prefix of the pop order
        while self._heap and self._heap[0][0] <= now:
            _, _, req = heapq.heappop(self._heap)
            self._shed(req, "deadline", RequestTimeout(
                f"request {req.rid}: deadline passed while queued "
                f"(deadline_t={req.deadline_t:.3f}, now={now:.3f})"))
            shed += 1
        return shed

    def submit(self, req: Request) -> None:
        """Admit ``req`` or raise :class:`ServerOverloaded` (the request
        is marked shed with cause ``queue_full`` first, so accounting
        still sees it)."""
        self.expire()
        if len(self._heap) >= self.capacity:
            err = ServerOverloaded(
                f"admission queue full ({self.capacity} queued); "
                f"retry with backoff")
            self._shed(req, "queue_full", err)
            raise err
        heapq.heappush(self._heap, (req.deadline_t, next(self._seq), req))

    def iter_queued(self):
        """Queued requests in ARBITRARY order — the batcher's O(Q)
        group-stats scan (no sort, no mutation; use :meth:`queued_edf`
        when order matters)."""
        for entry in self._heap:
            yield entry[2]

    def queued_edf(self) -> List[Request]:
        """Queued requests in EDF order — a read-only view for the
        batcher's flush decision (the heap is not mutated; seq uniquely
        tiebreaks equal deadlines so tuple sort never compares Requests)."""
        return [entry[2] for entry in sorted(self._heap)]

    def pop_edf(self, predicate: Optional[Callable[[Request], bool]] = None,
                limit: Optional[int] = None) -> List[Request]:
        """Pop up to ``limit`` requests in EDF order matching
        ``predicate`` (non-matching requests are kept, order preserved).
        With no predicate/limit, drains the queue in EDF order."""
        taken: List[Request] = []
        kept: List[Any] = []
        while self._heap and (limit is None or len(taken) < limit):
            entry = heapq.heappop(self._heap)
            if predicate is None or predicate(entry[2]):
                taken.append(entry[2])
            else:
                kept.append(entry)
        for entry in kept:
            heapq.heappush(self._heap, entry)
        return taken

    def peek_deadline(self) -> Optional[float]:
        """Earliest queued deadline (None when empty)."""
        return self._heap[0][0] if self._heap else None

    def snapshot(self) -> Dict[str, Any]:
        return {"depth": len(self._heap), "capacity": self.capacity,
                "earliest_deadline": self.peek_deadline()}
