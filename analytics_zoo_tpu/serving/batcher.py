"""Deadline-aware dynamic batch assembly over compiled geometries.

Batching amortizes dispatch overhead — decisive on a remote accelerator
where every dispatch pays a fixed round-trip — but waiting to fill a
batch spends the queued requests' deadline slack.  The classic dynamic-
batching compromise (Clipper's adaptive batch sizing): flush a bucket
when it is FULL, or when its most urgent request can no longer afford
to wait for more arrivals.

Geometry discipline: an online path must never hand XLA a shape it has
not compiled — a surprise compile is a multi-second latency cliff that
blows every deadline in the queue.  So assembled batches only ever use

- a time axis from the configured ``bucket_edges`` (the same
  :func:`analytics_zoo_tpu.data.bucket.edge_for` rule the train-side
  ``BucketBatcher`` uses, so serving reuses training's compiled
  geometries), and
- a batch axis of exactly ``max_batch`` — partial flushes are padded
  with zero rows and carry ``n_valid`` (the ``Uint8ToBatch`` convention;
  the runtime slices outputs back).

Flush rule per bucket: let ``t_est`` be the estimated service time of
that bucket's geometry at the current tier.  Flush when
``len(bucket) >= max_batch``, or when the earliest deadline in the
bucket satisfies ``deadline - now <= t_est + slack_margin`` — i.e. the
urgent request would miss if we waited any longer.  Estimation comes
from ``service_time(edge, n, tier)``, the same model the drill uses, or
from an online EWMA of observed service times when none is given.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.data.bucket import edge_for
from analytics_zoo_tpu.serving.request import AdmissionQueue, Request

#: bucket key for fixed-shape models (no variable axis)
FIXED = "fixed"


@dataclasses.dataclass
class AssembledBatch:
    """One device-ready batch: ``requests`` in EDF order, padded
    ``batch`` dict, the geometry it compiled under, and the dispatch
    bookkeeping the failover path reads (``redispatched``)."""

    requests: List[Request]
    batch: Dict[str, Any]
    edge: Any                       # bucket edge or FIXED
    n_valid: int
    tier: int = 0
    redispatched: bool = False      # exactly-once failover latch

    @property
    def earliest_deadline(self) -> float:
        return min(r.deadline_t for r in self.requests)


class DeadlineBatcher:
    """Assemble :class:`AssembledBatch` es from an :class:`AdmissionQueue`.

    ``pad_key`` names the payload leaf padded to the bucket edge; other
    payload leaves must share a shape within a bucket and are stacked
    as-is.  ``length_key`` (when set) adds the per-row valid-length
    vector to the batch — the same contract ``BucketBatcher`` gives the
    train step.
    """

    def __init__(self, queue: AdmissionQueue, max_batch: int,
                 bucket_edges: Optional[Sequence[int]] = None,
                 pad_key: str = "input",
                 length_key: Optional[str] = "n_frames",
                 service_time: Optional[
                     Callable[[Any, int, int], float]] = None,
                 slack_margin_s: float = 0.0):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.queue = queue
        self.max_batch = int(max_batch)
        self.bucket_edges = (sorted(int(e) for e in bucket_edges)
                             if bucket_edges else None)
        self.pad_key = pad_key
        self.length_key = length_key
        self.service_time = service_time
        self.slack_margin_s = float(slack_margin_s)
        # online EWMA of observed per-(geometry, tier) service time, used
        # when no explicit model is configured; a geometry with no
        # observation yet estimates +inf ⇒ always-urgent, so a cold
        # runtime flushes the first (possibly singleton) batch at once
        # and bootstraps the estimate from its observed service time
        self._ewma: Dict[Any, float] = {}

    # -- service-time estimate --------------------------------------------
    def estimate_s(self, edge: Any, n: int, tier: int) -> float:
        if self.service_time is not None:
            return float(self.service_time(edge, n, tier))
        return self._ewma.get((edge, tier), float("inf"))

    def observe_service_s(self, edge: Any, seconds: float, tier: int = 0,
                          alpha: float = 0.3) -> None:
        prev = self._ewma.get((edge, tier))
        self._ewma[(edge, tier)] = (seconds if prev is None
                                    else (1 - alpha) * prev + alpha * seconds)

    # -- bucket assignment -------------------------------------------------
    def bucket_of(self, req: Request) -> Any:
        if self.bucket_edges is None or req.length is None:
            return FIXED
        return edge_for(int(req.length), self.bucket_edges)

    # -- assembly ----------------------------------------------------------
    def _grouped(self) -> Dict[Any, List[Request]]:
        """Queued requests grouped by bucket, EDF order within each —
        a read-only view (requests are NOT popped)."""
        groups: Dict[Any, List[Request]] = {}
        for r in self.queue.queued_edf():
            groups.setdefault(self.bucket_of(r), []).append(r)
        return groups

    def next_batch(self, tier: int, force: bool = False
                   ) -> Optional[AssembledBatch]:
        """Assemble the most urgent flush-ready batch, or ``None`` when
        every bucket can still afford to wait.  ``force=True`` (drain)
        flushes the most urgent non-empty bucket regardless of slack.
        Expired requests are shed first — never dispatched."""
        self.queue.expire()
        groups = self._grouped()
        if not groups:
            return None
        now = self.queue.clock.now()
        ready: List[Any] = []       # (earliest_deadline, edge)
        for edge, reqs in groups.items():
            full = len(reqs) >= self.max_batch
            est = self.estimate_s(edge, min(len(reqs), self.max_batch),
                                  tier)
            urgent = (reqs[0].deadline_t - now
                      <= est + self.slack_margin_s)
            if full or urgent or force:
                ready.append((reqs[0].deadline_t, edge))
        if not ready:
            return None
        _, edge = min(ready, key=lambda t: (t[0], str(t[1])))
        taken = self.queue.pop_edf(
            predicate=lambda r: self.bucket_of(r) == edge,
            limit=self.max_batch)
        return self._collate(taken, edge, tier)

    def _collate(self, reqs: List[Request], edge: Any,
                 tier: int) -> AssembledBatch:
        """Pad rows to the bucket edge and the batch axis to
        ``max_batch`` — both geometries already compiled."""
        rows, lengths = [], []
        for r in reqs:
            arr = np.asarray(r.payload[self.pad_key]
                             if isinstance(r.payload, dict) else r.payload)
            if edge is not FIXED:
                n = min(int(r.length if r.length is not None
                            else arr.shape[0]), int(edge), arr.shape[0])
                padded = np.zeros((int(edge),) + arr.shape[1:], arr.dtype)
                padded[:n] = arr[:n]
                rows.append(padded)
                lengths.append(n)
            else:
                rows.append(arr)
                lengths.append(arr.shape[0] if arr.ndim else 0)
        n_valid = len(rows)
        pad = self.max_batch - n_valid
        if pad:
            rows.extend(np.zeros_like(rows[0]) for _ in range(pad))
            lengths.extend(0 for _ in range(pad))
        batch: Dict[str, Any] = {self.pad_key: np.stack(rows)}
        if edge is not FIXED and self.length_key:
            batch[self.length_key] = np.asarray(lengths, np.int32)
        return AssembledBatch(requests=reqs, batch=batch, edge=edge,
                              n_valid=n_valid, tier=tier)
