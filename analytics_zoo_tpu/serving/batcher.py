"""Deadline-aware dynamic batch assembly over compiled geometries.

Batching amortizes dispatch overhead — decisive on a remote accelerator
where every dispatch pays a fixed round-trip — but waiting to fill a
batch spends the queued requests' deadline slack.  The classic dynamic-
batching compromise (Clipper's adaptive batch sizing): flush a bucket
when it is FULL, or when its most urgent request can no longer afford
to wait for more arrivals.

Geometry discipline: an online path must never hand XLA a shape it has
not compiled — a surprise compile is a multi-second latency cliff that
blows every deadline in the queue.  So assembled batches only ever use

- a time axis from the configured ``bucket_edges`` (the same
  :func:`analytics_zoo_tpu.data.bucket.edge_for` rule the train-side
  ``BucketBatcher`` uses, so serving reuses training's compiled
  geometries), and
- a batch axis of exactly ``max_batch`` — partial flushes are padded
  with zero rows and carry ``n_valid`` (the ``Uint8ToBatch`` convention;
  the runtime slices outputs back).

Flush rule per bucket: let ``t_est`` be the estimated service time of
that bucket's geometry at the current tier.  Flush when
``len(bucket) >= max_batch``, or when the earliest deadline in the
bucket satisfies ``deadline - now <= t_est + slack_margin`` — i.e. the
urgent request would miss if we waited any longer.  Estimation comes
from ``service_time(edge, n, tier)``, the same model the drill uses, or
from an online EWMA of observed service times when none is given.

Multiplexing (ISSUE 14, the Clipper frontend pattern): a batcher given
``plans`` (one :class:`ModelPlan` per registered model) keeps a bucket
per **(model, affinity, edge)** — models never share a batch, a
streaming session's chunks only group with chunks pinned to the same
replica — and the service-time EWMA keys per **(model, edge, tier)**
with the PR-5 always-urgent cold seed *per key*, so one model's learned
estimate never flushes (or starves) another model's batches.  Flush-
ready buckets are drained in **weighted-EDF** order: the runtime feeds
per-model weights from the SLO burn rates (``set_model_weight``) and a
burning model's slack is divided by its weight, so its buckets win the
next dispatch — deadline-weighted by how fast that model's error
budget is being spent.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.data.bucket import edge_for
from analytics_zoo_tpu.serving.request import (DEFAULT_MODEL,
                                               AdmissionQueue, Request)

#: bucket key for fixed-shape models (no variable axis)
FIXED = "fixed"


@dataclasses.dataclass
class ModelPlan:
    """Per-model batching geometry for a multiplexed runtime.

    ``bucket_edges``: variable-axis edges (``None`` = fixed shape);
    ``pad_key``/``length_key``: the payload leaf padded to the edge and
    the per-row valid-length vector's batch key; ``max_batch``: per-
    model batch axis (``None`` = the batcher's global ``max_batch``);
    ``streaming``: session-type model — assembled batches additionally
    carry ``session`` (int64, padding rows −1) and ``final`` (int8)
    vectors so the stateful forward can route each row to its session
    carry and flush on the last chunk.
    """

    bucket_edges: Optional[Sequence[int]] = None
    pad_key: str = "input"
    length_key: Optional[str] = "n_frames"
    max_batch: Optional[int] = None
    streaming: bool = False


@dataclasses.dataclass
class AssembledBatch:
    """One device-ready batch: ``requests`` in EDF order, padded
    ``batch`` dict, the geometry it compiled under, and the dispatch
    bookkeeping the failover path reads (``redispatched``).  ``model``
    keys the replica's per-model forward table; ``affinity`` (set for
    session batches) pins the dispatch to one replica."""

    requests: List[Request]
    batch: Dict[str, Any]
    edge: Any                       # bucket edge or FIXED
    n_valid: int
    tier: int = 0
    redispatched: bool = False      # exactly-once failover latch
    model: str = DEFAULT_MODEL
    affinity: Optional[int] = None

    @property
    def earliest_deadline(self) -> float:
        return min(r.deadline_t for r in self.requests)


class DeadlineBatcher:
    """Assemble :class:`AssembledBatch` es from an :class:`AdmissionQueue`.

    ``pad_key`` names the payload leaf padded to the bucket edge; other
    payload leaves must share a shape within a bucket and are stacked
    as-is.  ``length_key`` (when set) adds the per-row valid-length
    vector to the batch — the same contract ``BucketBatcher`` gives the
    train step.

    ``plans`` (multiplexed mode): model name → :class:`ModelPlan`; the
    legacy ``bucket_edges``/``pad_key``/``length_key`` arguments then
    only seed the ``DEFAULT_MODEL`` plan when none is declared.  With
    plans, ``service_time`` takes ``(model, edge, n, tier)``; without,
    the PR-5 ``(edge, n, tier)`` signature is unchanged.
    """

    def __init__(self, queue: AdmissionQueue, max_batch: int,
                 bucket_edges: Optional[Sequence[int]] = None,
                 pad_key: str = "input",
                 length_key: Optional[str] = "n_frames",
                 service_time: Optional[Callable[..., float]] = None,
                 slack_margin_s: float = 0.0,
                 plans: Optional[Dict[str, ModelPlan]] = None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.queue = queue
        self.max_batch = int(max_batch)
        self.multiplexed = plans is not None
        if plans is None:
            plans = {DEFAULT_MODEL: ModelPlan(
                bucket_edges=bucket_edges, pad_key=pad_key,
                length_key=length_key)}
        self.plans: Dict[str, ModelPlan] = {}
        for name, plan in plans.items():
            edges = (sorted(int(e) for e in plan.bucket_edges)
                     if plan.bucket_edges else None)
            self.plans[name] = dataclasses.replace(plan, bucket_edges=edges)
        self.service_time = service_time
        self.slack_margin_s = float(slack_margin_s)
        # online EWMA of observed per-(model, geometry, tier) service
        # time, used when no explicit model is configured; a key with no
        # observation yet estimates +inf ⇒ always-urgent, so a cold
        # runtime flushes the first (possibly singleton) batch at once
        # and bootstraps the estimate from its observed service time.
        # The MODEL dimension is load-bearing under multiplexing: a
        # freshly registered model must re-earn its own estimate instead
        # of inheriting another model's service time (ISSUE 14 satellite
        # — the cold-start seed applies PER KEY).
        self._ewma: Dict[Tuple[str, Any, int], float] = {}
        #: per-model weighted-EDF weights (1.0 = plain EDF); the runtime
        #: feeds these from the SLO burn rates each decision window
        self._weights: Dict[str, float] = {}
        self._weighted = False

    def _plan(self, model: str) -> ModelPlan:
        try:
            return self.plans[model]
        except KeyError:
            raise KeyError(f"no batching plan for model {model!r} "
                           f"(registered: {sorted(self.plans)})") from None

    def model_batch(self, model: str) -> int:
        plan = self._plan(model)
        return plan.max_batch if plan.max_batch else self.max_batch

    # -- weighted EDF ------------------------------------------------------
    def set_model_weight(self, model: str, weight: float) -> None:
        """Set ``model``'s dispatch weight (≥ 1 boosts, the runtime
        derives it from the model's SLO burn rate).  Slack is divided
        by the weight in the ready-bucket ordering, so a burning
        model's buckets win the next dispatch."""
        if weight <= 0:
            raise ValueError(f"weight must be > 0, got {weight}")
        self._weights[model] = float(weight)
        self._weighted = any(w != 1.0 for w in self._weights.values())

    def model_weight(self, model: str) -> float:
        return self._weights.get(model, 1.0)

    # -- service-time estimate --------------------------------------------
    def estimate_s(self, edge: Any, n: int, tier: int,
                   model: str = DEFAULT_MODEL) -> float:
        if self.service_time is not None:
            if self.multiplexed:
                return float(self.service_time(model, edge, n, tier))
            return float(self.service_time(edge, n, tier))
        return self._ewma.get((model, edge, tier), float("inf"))

    def observe_service_s(self, edge: Any, seconds: float, tier: int = 0,
                          model: str = DEFAULT_MODEL,
                          alpha: float = 0.3) -> None:
        key = (model, edge, tier)
        prev = self._ewma.get(key)
        self._ewma[key] = (seconds if prev is None
                           else (1 - alpha) * prev + alpha * seconds)

    # -- bucket assignment -------------------------------------------------
    def bucket_of(self, req: Request) -> Any:
        plan = self._plan(req.model)
        if plan.bucket_edges is None or req.length is None:
            return FIXED
        return edge_for(int(req.length), plan.bucket_edges)

    # -- assembly ----------------------------------------------------------
    def _group_stats(self) -> Dict[Tuple[str, Optional[int], Any],
                                   Tuple[int, float]]:
        """One O(Q) pass over the queued requests: per (model, affinity,
        edge) group → (count, earliest deadline).  The flush decision
        needs nothing else, so the heap is neither sorted nor mutated —
        this is the scan the million-request drill pays per pump."""
        stats: Dict[Tuple[str, Optional[int], Any], Tuple[int, float]] = {}
        for r in self.queue.iter_queued():
            key = (r.model, r.affinity, self.bucket_of(r))
            cur = stats.get(key)
            if cur is None:
                stats[key] = (1, r.deadline_t)
            else:
                stats[key] = (cur[0] + 1, min(cur[1], r.deadline_t))
        return stats

    def next_batch(self, tier, force: bool = False
                   ) -> Optional[AssembledBatch]:
        """Assemble the most urgent flush-ready batch, or ``None`` when
        every bucket can still afford to wait.  ``tier`` is the current
        degradation rung — an int, or a ``{model: tier}`` map in
        multiplexed mode (each model rides its own ladder).
        ``force=True`` (drain) flushes the most urgent non-empty bucket
        regardless of slack.  Expired requests are shed first — never
        dispatched."""
        self.queue.expire()
        stats = self._group_stats()
        if not stats:
            return None
        tiers = tier if isinstance(tier, dict) else None
        now = self.queue.clock.now()
        ready: List[Tuple[float, str, Tuple[str, Optional[int], Any]]] = []
        for key, (count, earliest) in stats.items():
            model, _affinity, edge = key
            cap = self.model_batch(model)
            m_tier = (tiers.get(model, 0) if tiers is not None
                      else int(tier))
            full = count >= cap
            est = self.estimate_s(edge, min(count, cap), m_tier,
                                  model=model)
            urgent = earliest - now <= est + self.slack_margin_s
            if full or urgent or force:
                if self._weighted:
                    # weighted EDF: positive slack shrinks by the
                    # model's burn-rate weight; NEGATIVE slack (an
                    # overdue bucket — possible under
                    # shed_expired=False) grows in magnitude instead,
                    # so a burning model ranks more urgent in both
                    # regimes (division would invert it exactly when
                    # the bucket is latest).  Equal weights reduce to
                    # plain EDF either way.
                    slack = earliest - now
                    w = self.model_weight(model)
                    rank = slack / w if slack >= 0 else slack * w
                else:
                    rank = earliest
                ready.append((rank, f"{model}/{_affinity}/{edge}", key))
        if not ready:
            return None
        _, _, key = min(ready, key=lambda t: (t[0], t[1]))
        model, affinity, edge = key
        taken = self.queue.pop_edf(
            predicate=lambda r: (r.model == model
                                 and r.affinity == affinity
                                 and self.bucket_of(r) == edge),
            limit=self.model_batch(model))
        m_tier = tiers.get(model, 0) if tiers is not None else int(tier)
        return self._collate(taken, edge, m_tier, model=model,
                             affinity=affinity)

    def _collate(self, reqs: List[Request], edge: Any, tier: int,
                 model: str = DEFAULT_MODEL,
                 affinity: Optional[int] = None) -> AssembledBatch:
        """Pad rows to the bucket edge and the batch axis to the model's
        batch size — both geometries already compiled."""
        plan = self._plan(model)
        cap = self.model_batch(model)
        rows, lengths = [], []
        for r in reqs:
            arr = np.asarray(r.payload[plan.pad_key]
                             if isinstance(r.payload, dict) else r.payload)
            if edge is not FIXED:
                n = min(int(r.length if r.length is not None
                            else arr.shape[0]), int(edge), arr.shape[0])
                padded = np.zeros((int(edge),) + arr.shape[1:], arr.dtype)
                padded[:n] = arr[:n]
                rows.append(padded)
                lengths.append(n)
            else:
                rows.append(arr)
                lengths.append(arr.shape[0] if arr.ndim else 0)
        n_valid = len(rows)
        pad = cap - n_valid
        if pad:
            rows.extend(np.zeros_like(rows[0]) for _ in range(pad))
            lengths.extend(0 for _ in range(pad))
        batch: Dict[str, Any] = {plan.pad_key: np.stack(rows)}
        if edge is not FIXED and plan.length_key:
            batch[plan.length_key] = np.asarray(lengths, np.int32)
        if plan.streaming:
            sess = [(-1 if r.session is None else int(r.session))
                    for r in reqs] + [-1] * pad
            fin = [int(bool(r.final)) for r in reqs] + [0] * pad
            batch["session"] = np.asarray(sess, np.int64)
            batch["final"] = np.asarray(fin, np.int8)
        return AssembledBatch(requests=reqs, batch=batch, edge=edge,
                              n_valid=n_valid, tier=tier, model=model,
                              affinity=affinity)
