"""Online serving resilience runtime.

The training side got its resilience ladder in PRs 1 and 3 (chaos
drills, anomaly rollback); this package is the serving twin — the
ROADMAP's "heavy traffic from millions of users" story.  The reference's
only serving mechanism is a Spark broadcast predictor
(``common/Predictor.scala``); here the existing offline predictors
(``SSDPredictor``, ``FrcnnPredictor``, ``DeepSpeech2Pipeline``,
``StreamingDS2``) are wrapped behind a request-level API with explicit
overload behavior, in the spirit of Clipper's adaptive batching /
load-shedding frontier (Crankshaw et al., NSDI'17) and Clockwork's
predictable-latency discipline (Gujarati et al., OSDI'20):

- :mod:`clock` — injected time (:class:`VirtualClock` for deterministic
  tests/drills, :class:`MonotonicClock` for production; since PR 7 a
  re-export of the shared :mod:`analytics_zoo_tpu.utils.clock`);
- :mod:`request` — :class:`Request`, bounded EDF :class:`AdmissionQueue`
  with shed-before-dispatch;
- :mod:`batcher` — :class:`DeadlineBatcher`, flush-on-full-or-urgent
  over pre-compiled bucket geometries (``data.bucket.edge_for``);
- :mod:`replica` — :class:`Replica`/:class:`ReplicaPool`: StallWatchdog
  supervision, fencing, exactly-once failover, background restart;
- :mod:`ladder` — :class:`DegradationLadder`: bf16 → int8 → reduced
  top-K tier steps with promote-style hysteresis;
- :mod:`metrics` — :class:`ServingMetrics` snapshot dict;
- :mod:`runtime` — :class:`ServingRuntime`, the synchronous clock-driven
  scheduler gluing them together; ``models=[ModelConfig(...)]`` turns
  it into the fleet control plane (ISSUE 14): multi-model multiplexing
  with per-model SLOs/ladders/EWMAs, weighted-EDF admission, and
  session-affine streaming scheduling;
- :mod:`autoscale` — :class:`Autoscaler`: the closed policy loop that
  turns SLO burn rates into ``ReplicaPool.resize`` actuations, growth
  pre-warmed so a scale-up never serves a cold jit cache.

Drill: ``python tools/serve_drill.py`` (committed artifact
``RESILIENCE_r03.json``).  Docs: docs/SERVING.md "Operating under
load"; failure semantics in docs/RESILIENCE.md.
"""

from analytics_zoo_tpu.serving.autoscale import (OCCUPANCY_KNEE,
                                                 Autoscaler,
                                                 AutoscalePolicy,
                                                 Reshape)
from analytics_zoo_tpu.serving.batcher import (FIXED, AssembledBatch,
                                               DeadlineBatcher, ModelPlan)
from analytics_zoo_tpu.serving.clock import (Clock, MonotonicClock,
                                             VirtualClock)
from analytics_zoo_tpu.serving.ladder import (DegradationLadder,
                                              LadderPolicy, ServingTier)
from analytics_zoo_tpu.serving.metrics import ServingMetrics, percentile
from analytics_zoo_tpu.serving.replica import (Replica, ReplicaPool,
                                               ReplicaSlice)
from analytics_zoo_tpu.serving.request import (DEFAULT_MODEL,
                                               TERMINAL_STATES,
                                               AdmissionQueue, Request)
from analytics_zoo_tpu.serving.runtime import ModelConfig, ServingRuntime

__all__ = [k for k in dir() if not k.startswith("_")]
