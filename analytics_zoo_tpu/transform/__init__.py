"""Domain transform libraries (vision, audio) over the generic data layer."""
