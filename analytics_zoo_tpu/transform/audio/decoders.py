"""CTC decoders + ASR metrics.

Port of the reference's decoder stack (``pipeline/deepspeech2/.../
Decoder.scala:13,70`` greedy best-path, ``VocabDecoder.scala:37`` per-word
vocab snap by edit distance, ``NGramDecoder.scala:36`` bigram rerank,
``ArgMaxDecoder.scala:28`` alphabet) and the WER/CER evaluator
(``ASREvaluator.scala:29,41-68``).

Alphabet: 29 chars, blank at index 0 (reference ``InferenceExample.scala:
17-23``): ``_'A-Z<space>``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

ALPHABET = "_'ABCDEFGHIJKLMNOPQRSTUVWXYZ "
BLANK_ID = 0


def ids_to_text(ids, alphabet: str = ALPHABET,
                blank_id: int = BLANK_ID) -> str:
    """CTC collapse: repeat-merge + blank-strip over per-frame argmax ids.

    Split out of :func:`best_path_decode` so the argmax can run ON DEVICE
    (the fused ASR serving path reads back (T,) int ids — ~30× fewer
    bytes than the full (T, C) log-probs)."""
    out: List[str] = []
    prev = -1
    for i in np.asarray(ids):
        if i != prev and i != blank_id:
            out.append(alphabet[int(i)])
        prev = i
    return "".join(out)


def best_path_decode(log_probs: np.ndarray, alphabet: str = ALPHABET,
                     blank_id: int = BLANK_ID) -> str:
    """Greedy CTC: per-frame argmax → collapse repeats → strip blanks
    (reference ``BestPathDecoder``)."""
    return ids_to_text(np.asarray(log_probs).argmax(axis=-1),
                       alphabet, blank_id)


def beam_search_decode(log_probs: np.ndarray, beam_width: int = 16,
                       alphabet: str = ALPHABET, blank_id: int = BLANK_ID,
                       prune_log_prob: float = -18.0) -> str:
    """CTC prefix beam search (Hannun et al. 2014) — sums probability over
    ALL alignments of each prefix instead of following one per-frame
    argmax path, so it recovers transcripts greedy decoding loses when
    probability mass is split across alignments.  Net-new over the
    reference's decoder stack (greedy / vocab-snap / bigram rerank).

    Per prefix two scores are tracked in log space: ``p_b`` (alignments
    ending in blank) and ``p_nb`` (ending in the prefix's last char).
    ``prune_log_prob`` skips symbols below the threshold per frame (beam
    quality is insensitive; cost drops ~|A|-fold).  Exact for
    ``beam_width`` ≥ the number of reachable prefixes (the oracle bound
    the tests use).
    """
    lp = np.asarray(log_probs, np.float32)
    NEG = -np.inf
    lse = np.logaddexp                     # handles -inf operands exactly

    # beams: {prefix tuple: (p_blank, p_nonblank)}
    beams = {(): (0.0, NEG)}
    for t in range(lp.shape[0]):
        frame = lp[t]
        blank_lp = float(frame[blank_id])
        kept = [(s, float(frame[s]))
                for s in np.flatnonzero(frame >= prune_log_prob)
                if s != blank_id]
        nxt: dict = {}

        def add(prefix, pb, pnb):
            opb, opnb = nxt.get(prefix, (NEG, NEG))
            nxt[prefix] = (lse(opb, pb), lse(opnb, pnb))

        for prefix, (p_b, p_nb) in beams.items():
            p_tot = lse(p_b, p_nb)
            # blank extends both paths, prefix unchanged
            add(prefix, p_tot + blank_lp, NEG)
            for s, p_s in kept:
                if prefix and prefix[-1] == s:
                    # repeat char: only a blank-separated path extends the
                    # prefix; the non-blank path merges into the SAME prefix
                    add(prefix + (s,), NEG, p_b + p_s)
                    add(prefix, NEG, p_nb + p_s)
                else:
                    add(prefix + (s,), NEG, p_tot + p_s)
        beams = dict(sorted(
            nxt.items(),
            key=lambda kv: -lse(*kv[1]))[:beam_width])

    best = max(beams.items(), key=lambda kv: lse(*kv[1]))[0]
    return "".join(alphabet[s] for s in best)


def levenshtein(a: Sequence, b: Sequence) -> int:
    """Edit distance (reference ``ASREvaluator`` distance kernel)."""
    if len(a) < len(b):
        a, b = b, a
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                           prev[j - 1] + (ca != cb)))
        prev = cur
    return prev[-1]


def wer(reference: str, hypothesis: str) -> float:
    """Word error rate (reference ``ASREvaluator.scala:41``)."""
    ref_words = reference.split()
    if not ref_words:
        return 0.0 if not hypothesis.split() else 1.0
    return levenshtein(ref_words, hypothesis.split()) / len(ref_words)


def cer(reference: str, hypothesis: str) -> float:
    """Character error rate."""
    if not reference:
        return 0.0 if not hypothesis else 1.0
    return levenshtein(reference, hypothesis) / len(reference)


class VocabDecoder:
    """Snap each decoded word to the nearest vocabulary word by edit
    distance (reference ``VocabDecoder.scala:37``); words already in vocab
    pass through."""

    def __init__(self, vocab: Sequence[str], max_distance: int = 2):
        self.vocab = [v.upper() for v in vocab]
        self.vocab_set = set(self.vocab)
        self.max_distance = max_distance

    def decode_word(self, word: str) -> str:
        if not word or word in self.vocab_set:
            return word
        best, best_d = word, self.max_distance + 1
        for v in self.vocab:
            d = levenshtein(word, v)
            if d < best_d:
                best, best_d = v, d
        return best if best_d <= self.max_distance else word

    def __call__(self, text: str) -> str:
        return " ".join(self.decode_word(w) for w in text.split())


class NGramDecoder:
    """Bigram-context candidate rerank (reference ``NGramDecoder.scala:36``):
    among near-vocab candidates for each word, prefer the one whose bigram
    with the previous decoded word was seen in the corpus."""

    def __init__(self, vocab: Sequence[str], bigrams: Sequence[Sequence[str]],
                 max_distance: int = 2):
        self.inner = VocabDecoder(vocab, max_distance)
        self.bigrams = {(a.upper(), b.upper()) for a, b in bigrams}
        self.max_distance = max_distance

    def _candidates(self, word: str):
        """(candidate, distance) pairs within max_distance — one vocab scan
        reused for both the bigram pick and the fallback min-distance pick."""
        cands = [(v, levenshtein(word, v)) for v in self.inner.vocab]
        cands = [(v, d) for v, d in cands if d <= self.max_distance]
        return cands or [(word, 0)]

    def __call__(self, text: str) -> str:
        out: List[str] = []
        for w in text.split():
            cands = self._candidates(w)
            pick = None
            if out:
                for c, _ in cands:
                    if (out[-1], c) in self.bigrams:
                        pick = c
                        break
            if pick is None:
                pick = min(cands, key=lambda cd: cd[1])[0]
            out.append(pick)
        return " ".join(out)


class TranscriptVectorizer:
    """transcript → padded label-id vector for CTC training (reference
    ``acoustic/TranscriptVectorizer.scala:11``, net-enabled here since this
    framework trains DS2, not just serves it)."""

    def __init__(self, alphabet: str = ALPHABET, max_length: int = 200):
        self.alphabet = alphabet
        self.index = {c: i for i, c in enumerate(alphabet)}
        self.max_length = max_length

    def __call__(self, transcript: str):
        """Returns (ids (max_length,) int32, mask (max_length,) float32)."""
        import numpy as _np

        ids = [self.index[c] for c in transcript.upper() if c in self.index]
        ids = ids[: self.max_length]
        out = _np.zeros(self.max_length, _np.int32)
        mask = _np.zeros(self.max_length, _np.float32)
        out[: len(ids)] = ids
        mask[: len(ids)] = 1.0
        return out, mask


class ASREvaluator:
    """Accumulating WER/CER over utterances (reference ``ASREvaluator``)."""

    def __init__(self):
        self.word_errors = 0
        self.words = 0
        self.char_errors = 0
        self.chars = 0

    def add(self, reference: str, hypothesis: str) -> None:
        self.word_errors += levenshtein(reference.split(), hypothesis.split())
        self.words += len(reference.split())
        self.char_errors += levenshtein(reference, hypothesis)
        self.chars += len(reference)

    @property
    def wer(self) -> float:
        return self.word_errors / max(self.words, 1)

    @property
    def cer(self) -> float:
        return self.char_errors / max(self.chars, 1)


def evaluate_ctc_decoders(forward_fn, batches,
                          alphabet: str = ALPHABET) -> dict:
    """Held-out CER / exact-sequence accuracy with BOTH the greedy and
    prefix-beam decoders — the shared evaluation block of
    ``examples/train_ds2.py`` and ``examples/train_attention_asr.py``
    (one implementation so the two reports can never drift).

    ``forward_fn(inputs) → (B, T, n_alphabet) log-probs``; ``batches``
    yield ``{"input", "labels"}`` with 0 = padding in labels.
    """
    import numpy as np

    stats = {"greedy": [0, 0], "beam": [0, 0]}    # [edit distance, exact]
    total_len = n_seq = 0
    for hb in batches:
        log_probs = forward_fn(hb["input"])
        for i in range(hb["input"].shape[0]):
            ref = "".join(alphabet[t] for t in hb["labels"][i] if t > 0)
            lp = np.asarray(log_probs[i])
            for name, hyp in (("greedy", best_path_decode(lp, alphabet)),
                              ("beam", beam_search_decode(lp,
                                                          alphabet=alphabet))):
                stats[name][0] += levenshtein(hyp, ref)
                stats[name][1] += int(hyp == ref)
            total_len += max(len(ref), 1)
            n_seq += 1
    g, b = stats["greedy"], stats["beam"]
    return {
        "cer": round(g[0] / max(total_len, 1), 4),
        "exact_sequence_acc": round(g[1] / max(n_seq, 1), 4),
        "beam_cer": round(b[0] / max(total_len, 1), 4),
        "beam_exact_sequence_acc": round(b[1] / max(n_seq, 1), 4),
        "sequences": n_seq,
    }
